"""CTA001 — guarded-by lock discipline.

An attribute declared ``guarded-by: <lock>`` in a class body may only
be touched (read, written, deleted, or used as a call receiver)
lexically inside ``with self.<lock>:`` — the go-deadlock-adjacent
half of upstream's lockdebug CI tag, checked statically.  Exemptions:

- ``__init__`` (no concurrent readers exist during construction);
- methods annotated ``# holds: <lock>`` (callers hold the lock —
  the lexical contract moves to the call sites, which the runtime
  DebugLock still verifies under CILIUM_TPU_LOCKDEBUG=1);
- lambda / nested-def bodies hold NOTHING (deferred execution: a
  closure built under the lock runs after it is released).

Lock identity goes through the class's alias map: a
``threading.Condition(self._lock)`` attribute and the runtime name
given to ``make_lock("<name>")`` both resolve to the wrapped lock, so
``with self._nonempty:`` satisfies ``guarded-by: _lock``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .annotations import extract_guarded, extract_holds
from .core import FileCtx, Finding, Repo

CODE = "CTA001"
NAME = "guarded-by"


def _with_locks(node: ast.With, locks) -> Set[str]:
    """Canonical lock identities a ``with`` statement acquires."""
    out: Set[str] = set()
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) \
                and isinstance(e.value, ast.Name) \
                and e.value.id == "self":
            canon = locks.resolve(e.attr)
            if canon is not None:
                out.add(canon)
    return out


class _MethodVisitor(ast.NodeVisitor):
    def __init__(self, checker: "_ClassChecker", held: Set[str]):
        self.c = checker
        self.held = held

    def visit_With(self, node: ast.With) -> None:
        got = _with_locks(node, self.c.gc.locks)
        added = got - self.held
        self.held |= added
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    def visit_Lambda(self, node: ast.Lambda) -> None:
        _MethodVisitor(self.c, set()).generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        _MethodVisitor(self.c, set()).generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            lock = self.c.gc.guarded.get(node.attr)
            if lock is not None and lock not in self.held:
                self.c.report(node, node.attr, lock)
        self.generic_visit(node)


class _ClassChecker:
    def __init__(self, gc, findings: List[Finding]):
        self.gc = gc
        self.findings = findings

    def report(self, node: ast.AST, attr: str, lock: str) -> None:
        ctx: FileCtx = self.gc.ctx
        line = node.lineno
        if ctx.suppressed(CODE, line):
            return
        self.findings.append(Finding(
            CODE, ctx.rel, line,
            f"{self.gc.cls.name}.{attr} is guarded by self.{lock} "
            f"but touched outside `with self.{lock}:` (annotate the "
            f"method `# holds: {lock}` if every caller holds it)",
            checker=NAME))

    def run(self) -> None:
        for node in self.gc.cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name == "__init__":
                continue
            holds = extract_holds(node, self.gc.ctx, self.gc.locks,
                                  self.findings)
            _MethodVisitor(self, set(holds)).generic_visit(node)


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        for node in ctx.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            gc = extract_guarded(node, ctx)
            findings.extend(gc.findings)
            if gc.guarded:
                _ClassChecker(gc, findings).run()
    return findings


def guarded_map(repo: Repo) -> dict:
    """{(rel, class): {attr: lock}} — the test surface proving the
    repo-wide annotation pass is in place."""
    out = {}
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                gc = extract_guarded(node, ctx)
                if gc.guarded:
                    out[(ctx.rel, node.name)] = dict(gc.guarded)
    return out
