"""Analyzer plumbing: findings, suppressions, baseline, file contexts.

Everything here is pure stdlib (``ast`` + ``tokenize``) — the
analyzer must import and run on a box with no jax at all, because it
IS the gate that runs before anything else does.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

BASELINE_NAME = "ANALYSIS_BASELINE.json"

# the one suppression grammar every checker shares:
#   # lint: disable=CTA003[,CTA004] -- reason
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=(?P<codes>[A-Z0-9, ]+?)"
    r"(?:\s*--\s*(?P<reason>.*))?$")
_CODE_RE = re.compile(r"^CTA\d{3}$")


@dataclass(frozen=True)
class Finding:
    """One structured finding: ``file:line: CODE message``."""

    code: str  # stable CTAnnn code
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    message: str
    checker: str = ""  # human checker name

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_dict(self) -> dict:
        return {"code": self.code, "file": self.path,
                "line": self.line, "message": self.message,
                "checker": self.checker}

    def fingerprint(self, line_text: str, occurrence: int = 0) -> str:
        """Stable identity for baselining: survives line-number drift
        (keyed on the flagged line's stripped text, not its number);
        ``occurrence`` disambiguates identical lines in one file."""
        h = hashlib.sha1()
        h.update(self.code.encode())
        h.update(b"\0")
        h.update(self.path.encode())
        h.update(b"\0")
        h.update(line_text.strip().encode())
        h.update(b"\0")
        h.update(str(occurrence).encode())
        return h.hexdigest()[:16]


@dataclass
class Suppression:
    line: int  # line the suppression applies to
    codes: Tuple[str, ...]
    reason: str
    comment_line: int  # where the comment itself sits
    used: bool = False


class FileCtx:
    """One parsed source file: tree + per-line comments + source."""

    def __init__(self, path: str, rel: str):
        self.path = path
        self.rel = rel
        with open(path, "rb") as f:
            raw = f.read()
        self.source = raw.decode("utf-8", errors="replace")
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=rel)
        except SyntaxError as e:
            self.parse_error = str(e)
        # line -> [comment text] (text includes the leading '#')
        self.comments: Dict[int, List[str]] = {}
        # line -> True when the line holds ONLY a comment
        self.comment_only: Dict[int, bool] = {}
        try:
            for tok in tokenize.tokenize(io.BytesIO(raw).readline):
                if tok.type == tokenize.COMMENT:
                    ln = tok.start[0]
                    self.comments.setdefault(ln, []).append(tok.string)
                    before = (self.lines[ln - 1][:tok.start[1]]
                              if ln - 1 < len(self.lines) else "")
                    self.comment_only[ln] = not before.strip()
        except tokenize.TokenError:
            pass
        self.suppressions: List[Suppression] = []
        self.config_findings: List[Finding] = []  # CTA000s found here
        self._parse_suppressions()
        # line -> reason for `# hot-path-ok: reason`
        self.hotpath_ok: Dict[int, str] = {}
        for ln, comments in self.comments.items():
            for c in comments:
                m = re.search(r"#\s*hot-path-ok:\s*(.*)$", c)
                if m:
                    self.hotpath_ok[ln] = m.group(1).strip()

    def _parse_suppressions(self) -> None:
        for ln in sorted(self.comments):
            for c in self.comments[ln]:
                m = _SUPPRESS_RE.search(c)
                if m is None:
                    continue
                codes = tuple(
                    x.strip() for x in m.group("codes").split(",")
                    if x.strip())
                reason = (m.group("reason") or "").strip()
                bad = [x for x in codes if not _CODE_RE.match(x)]
                if bad or not codes:
                    self.config_findings.append(Finding(
                        "CTA000", self.rel, ln,
                        f"malformed suppression (bad code "
                        f"{', '.join(bad) or '<none>'}): {c.strip()!r}",
                        checker="config"))
                    continue
                if not reason:
                    self.config_findings.append(Finding(
                        "CTA000", self.rel, ln,
                        "suppression without a reason (want "
                        "`# lint: disable=CODE -- reason`)",
                        checker="config"))
                    continue
                target = ln + 1 if self.comment_only.get(ln) else ln
                self.suppressions.append(
                    Suppression(target, codes, reason, ln))

    def suppressed(self, code: str, line: int) -> bool:
        for s in self.suppressions:
            if s.line == line and code in s.codes:
                s.used = True
                return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def comments_in(self, lo: int, hi: int) -> List[Tuple[int, str]]:
        """All (line, text) comments with lo <= line < hi."""
        out = []
        for ln in sorted(self.comments):
            if lo <= ln < hi:
                for c in self.comments[ln]:
                    out.append((ln, c))
        return out


def repo_root() -> str:
    """The directory containing the ``cilium_tpu`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


class Repo:
    """Every parsed .py file under the package, plus shared indexes."""

    def __init__(self, root: Optional[str] = None,
                 package: str = "cilium_tpu"):
        self.root = root or repo_root()
        self.package = package
        self.files: List[FileCtx] = []
        pkg_dir = os.path.join(self.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, self.root).replace(
                    os.sep, "/")
                self.files.append(FileCtx(path, rel))

    def by_rel(self, rel: str) -> Optional[FileCtx]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None


class Baseline:
    """The committed grandfather list: findings present here are
    reported as baselined (informational) instead of failing the
    run."""

    def __init__(self, path: str):
        self.path = path
        self.fingerprints: Dict[str, dict] = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                for e in data.get("findings", []):
                    self.fingerprints[e["fingerprint"]] = e
            except (OSError, ValueError, KeyError, TypeError):
                # an unreadable baseline grandfathers nothing —
                # the safe direction
                self.fingerprints = {}

    @staticmethod
    def _fingerprint_all(findings: Iterable[Finding],
                         repo: Repo) -> List[Tuple[Finding, str]]:
        seen: Dict[tuple, int] = {}
        out = []
        for f in findings:
            ctx = repo.by_rel(f.path)
            text = ctx.line_text(f.line) if ctx is not None else ""
            key = (f.code, f.path, text.strip())
            occ = seen.get(key, 0)
            seen[key] = occ + 1
            out.append((f, f.fingerprint(text, occ)))
        return out

    def split(self, findings: List[Finding], repo: Repo
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new, baselined)."""
        new: List[Finding] = []
        old: List[Finding] = []
        for f, fp in self._fingerprint_all(findings, repo):
            (old if fp in self.fingerprints else new).append(f)
        return new, old

    def write(self, findings: List[Finding], repo: Repo) -> None:
        entries = [
            {"fingerprint": fp, "code": f.code, "file": f.path,
             "message": f.message}
            for f, fp in self._fingerprint_all(findings, repo)]
        with open(self.path, "w") as f:
            json.dump({"comment": "grandfathered static-analysis "
                       "findings; refresh with `python -m "
                       "cilium_tpu.analysis --write-baseline`",
                       "findings": entries}, f, indent=1)
            f.write("\n")
