"""CTA006 — metrics-registry exposition scatter (the former
``scripts/check_metrics_registry.py``, now a registered checker
sharing the finding/suppression/baseline machinery; the script
remains as a thin delegating shim).

Prometheus exposition text may only be built in
``cilium_tpu/obs/registry.py`` and ``cilium_tpu/obs/relay.py`` (the
cluster relay merges per-node expositions and renders its own scrape
meta-series — ISSUE 14).  Flagged anywhere else:

1. a TYPE exposition header inside a string literal;
2. a labelled metric sample literal (a metric-suffixed name opening
   an inline label brace).

Additionally, every REQUIRED_SERIES name (the operator-contract
floor) must stay registered in the registry module, and every
RELAY_REQUIRED_SERIES name (the cluster relay's meta-series floor)
must stay rendered in the relay module.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import List

from .core import Finding, Repo

CODE = "CTA006"
NAME = "metrics-registry"

REGISTRY_MODULE = "cilium_tpu/obs/registry.py"
# the cluster observability relay also builds exposition text (the
# merged per-node view + its own scrape meta-series)
RELAY_MODULE = "cilium_tpu/obs/relay.py"
ALLOWED_MODULES = (REGISTRY_MODULE, RELAY_MODULE)

# the relay's meta-series floor: these must stay rendered in the
# relay module — a cluster whose scrape plane cannot say which node
# went dark is the ISSUE 14 failure mode
RELAY_REQUIRED_SERIES = (
    "cilium_cluster_node_scrape_ok",
    "cilium_cluster_node_scrape_age_seconds",
    "cilium_cluster_scrapes_total",
    "cilium_cluster_scrape_errors_total",
    "cilium_cluster_scrape_rtt_us",
)

# series that must be REGISTERED (their name literal present in the
# registry module) — the operator-contract floor
REQUIRED_SERIES = (
    # flow analytics plane + incident flight recorder
    "cilium_flow_agg_windows_total",
    "cilium_flow_agg_batches_dropped_total",
    "cilium_top_talkers_evictions_total",
    "cilium_incidents_total",
    "cilium_sysdump_writes_total",
    # clustermesh serving tier (every router drop site's series —
    # CTA008 enforces the site -> counter mapping, this floor keeps
    # the counters registered)
    "cilium_cluster_router_overflow_total",
    "cilium_cluster_failover_dropped_total",
    "cilium_cluster_crash_dropped_total",
    "cilium_cluster_failovers_total",
    "cilium_cluster_forward_latency_us",
    # live policy churn (datapath/tables.py table versioning): the
    # published generation and its swap plane must stay scrapeable —
    # an invisible generation means churn incidents cannot be
    # correlated with policy updates
    "cilium_policy_generation",
    "cilium_policy_swaps_total",
    "cilium_policy_swap_latency_us",
    "cilium_policy_update_visible_us",
    # map-pressure graceful degradation (datapath/pressure.py): the
    # CT/NAT pressure floor — an invisible pressure state means the
    # accelerated-GC response cannot be correlated with its cause
    "cilium_ct_occupancy",
    "cilium_ct_insert_drops_total",
    "cilium_nat_pool_failures_total",
    # the L7 proxy plane (serving/l7plane.py): every leg of the
    # redirect ledger — redirected == allowed + denied + shed +
    # failed — must stay scrapeable, or shed/failed redirect rows
    # become invisible loss (CTA012 owns the deeper ledger checks;
    # this floor keeps the series registered)
    "cilium_l7_redirected_total",
    "cilium_l7_allowed_total",
    "cilium_l7_denied_total",
    "cilium_l7_shed_total",
    "cilium_l7_failed_total",
    "cilium_l7_worker_restarts_total",
    "cilium_l7_dns_answers_total",
    "cilium_l7_parse_lag_us",
    # map-pressure breadth (ISSUE 19): the SLO plane's map-headroom
    # verdict reads lpm + policy occupancy alongside ct — losing
    # either blinds the headroom SLO for that map
    "cilium_lpm_occupancy",
    "cilium_policy_map_occupancy",
    # long-standing anchors (a registry rewrite that loses these
    # fails here, not on a dashboard)
    "cilium_datapath_packets_total",
    "cilium_serving_verdicts_total",
    "cilium_ring_lost_total",
)

_TYPE_LINE = re.compile(r"#\s*TYPE\s+\w+\s+(counter|gauge|histogram)")
_SAMPLE = re.compile(r"\b[a-z][a-z0-9_]*_(total|bucket|sum|count|"
                     r"seconds|bytes|info)\{[^}]*=")
_GENERIC_SAMPLE = re.compile(r"\b(cilium|hubble)_[a-z0-9_]+\{")


def scan_file(path: str) -> list:
    """-> [(line, what, snippet)] exposition-text hits in one file.
    (The shim script re-exports this; tests call it directly.)"""
    with open(path, "rb") as f:
        src = f.read()
    out = []
    try:
        toks = tokenize.tokenize(io.BytesIO(src).readline)
        for tok in toks:
            if tok.type not in (tokenize.STRING,
                                getattr(tokenize, "FSTRING_MIDDLE",
                                        -1)):
                continue
            s = tok.string
            for pat, what in ((_TYPE_LINE, "# TYPE exposition line"),
                              (_SAMPLE, "labelled metric sample"),
                              (_GENERIC_SAMPLE,
                               "labelled metric sample")):
                if pat.search(s):
                    out.append((tok.start[0], what, s.strip()[:70]))
                    break
    except tokenize.TokenError:
        pass
    return out


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    reg = repo.by_rel(REGISTRY_MODULE)
    if reg is None:
        findings.append(Finding(
            CODE, REGISTRY_MODULE, 1,
            "registry module missing", checker=NAME))
    else:
        for name in REQUIRED_SERIES:
            if f'"{name}"' not in reg.source:
                findings.append(Finding(
                    CODE, reg.rel, 1,
                    f"required series {name!r} is not registered "
                    f"(operator-contract floor)", checker=NAME))
    relay = repo.by_rel(RELAY_MODULE)
    if relay is None:
        findings.append(Finding(
            CODE, RELAY_MODULE, 1,
            "cluster relay module missing", checker=NAME))
    else:
        for name in RELAY_REQUIRED_SERIES:
            if name not in relay.source:
                findings.append(Finding(
                    CODE, relay.rel, 1,
                    f"required relay series {name!r} is not rendered "
                    f"(cluster scrape-plane floor)", checker=NAME))
    for ctx in repo.files:
        if ctx.rel in ALLOWED_MODULES:
            continue
        for line, what, snippet in scan_file(ctx.path):
            if ctx.suppressed(CODE, line):
                continue
            findings.append(Finding(
                CODE, ctx.rel, line,
                f"{what} outside the metrics registry (register a "
                f"collector in obs/registry.py instead): "
                f"{snippet!r}", checker=NAME))
    return findings
