"""Annotation extraction: guarded-by / holds / thread-affinity
comments attached to classes and functions, plus the per-class
lock-alias map (Condition wrappers and ``make_lock`` runtime names
resolve to one identity — the same identity ``infra/lockdebug.py``
uses at runtime)."""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import FileCtx, Finding

# "router" = the cluster serving tier's routing plane: the front-end
# enqueue path plus the per-node forwarder threads
# (cilium_tpu/cluster/router.py) — a hot-path domain like "drain"
# (see hotpath.HOT_DOMAINS).  "transport" = the threads that move
# cluster socket frames (cluster/transport.py helpers, the node
# host's data-channel reader, the forwarders' socket legs) — also a
# hot domain: a forward frame's round trip sits on the cluster's
# admission path.  "api" covers the control-plane thread family: API
# handlers, CLI, tests' main thread, and the cluster
# membership/failover orchestration threads.  "l7" = the L7 proxy
# worker pool (proxy/worker.py): redirected rows' parse + verdict
# threads — a hot domain (see hotpath.HOT_DOMAINS): a redirect's
# detour latency is that flow's serving latency.  "ackflush" = the
# worker-side ack-coalescer flush timer (cluster/nodehost.py
# _ack_flush_loop, ISSUE 17): a sleepy periodic thread that only
# flushes the pending cumulative ack — NOT a hot domain (the data
# thread flushes inline at the ack_every stride; the timer bounds
# idle-tail latency only).  "slo" = the SLO plane's sampler thread
# (obs/slo.py ``slo-sampler``, ISSUE 19): samples the registry
# subset into the history rings and evaluates burn rates — NOT a hot
# domain (it reads lock-guarded ledgers on its own duty-governed
# cadence; by construction never the drain thread).
AFFINITIES = ("drain", "event-worker", "watchdog", "capture", "api",
              "cli", "offline", "router", "transport", "l7",
              "ackflush", "slo", "any")

_GUARDED_LIST_RE = re.compile(
    r"#\s*guarded-by:\s*(?P<lock>[\w.-]+)\s*:\s*(?P<attrs>[\w,\s]+)$")
_GUARDED_TRAIL_RE = re.compile(
    r"#\s*guarded-by:\s*(?P<lock>[\w.-]+)\s*$")
_HOLDS_RE = re.compile(r"#\s*holds:\s*(?P<locks>[\w.,\s-]+?)"
                       r"(?:\s+--.*)?$")
_AFFINITY_RE = re.compile(
    r"#\s*thread-affinity:\s*(?P<affs>[\w,\s-]+?)(?:\s+--.*)?$")


def _def_comment_range(node: ast.AST, ctx: FileCtx
                       ) -> List[Tuple[int, str]]:
    """Comments attached to a def/class: trailing comments anywhere in
    the signature (def line .. first body statement), plus the
    contiguous comment block immediately above the def/decorators."""
    first_stmt = node.body[0].lineno if node.body else node.lineno + 1
    start = node.lineno
    if getattr(node, "decorator_list", None):
        start = min(d.lineno for d in node.decorator_list)
    out = ctx.comments_in(node.lineno, first_stmt)
    ln = start - 1
    above: List[Tuple[int, str]] = []
    while ln >= 1 and ctx.comment_only.get(ln):
        for c in ctx.comments[ln]:
            above.append((ln, c))
        ln -= 1
    return above + out


@dataclass
class LockMap:
    """Per-class lock identities.  ``canon`` maps every way a lock
    can be named — its attribute, a Condition-wrapper attribute, or
    its ``make_lock`` runtime name — onto one canonical attribute."""

    canon: Dict[str, str] = field(default_factory=dict)

    def resolve(self, name: str) -> Optional[str]:
        return self.canon.get(name)


def extract_lock_map(cls: ast.ClassDef) -> LockMap:
    lm = LockMap()
    aliases: List[Tuple[str, str]] = []  # (alias attr, inner attr)
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        attr = tgt.attr
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        fn = call.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if fname in ("Lock", "RLock"):
            lm.canon[attr] = attr
        elif fname == "Condition":
            inner = None
            if call.args and isinstance(call.args[0], ast.Attribute) \
                    and isinstance(call.args[0].value, ast.Name) \
                    and call.args[0].value.id == "self":
                inner = call.args[0].attr
            if inner is not None:
                aliases.append((attr, inner))
            else:
                lm.canon[attr] = attr
        elif fname == "make_lock":
            lm.canon[attr] = attr
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                # the runtime lockdebug name IS a valid static alias:
                # `guarded-by: datapath-loader` == `guarded-by: _lock`
                lm.canon[call.args[0].value] = attr
    for alias, inner in aliases:
        lm.canon[alias] = lm.canon.get(inner, inner)
    return lm


@dataclass
class GuardedClass:
    cls: ast.ClassDef
    ctx: FileCtx
    locks: LockMap
    guarded: Dict[str, str] = field(default_factory=dict)  # attr->lock
    findings: List[Finding] = field(default_factory=list)


def extract_guarded(cls: ast.ClassDef, ctx: FileCtx) -> GuardedClass:
    """Parse both guarded-by forms within one class body."""
    gc = GuardedClass(cls, ctx, extract_lock_map(cls))
    end = max((getattr(n, "end_lineno", None) or n.lineno
               for n in ast.walk(cls)
               if getattr(n, "lineno", None) is not None),
              default=cls.lineno)
    # list form, anywhere in the class span
    for ln, c in ctx.comments_in(cls.lineno, end + 1):
        m = _GUARDED_LIST_RE.search(c)
        if m is None:
            continue
        lock = gc.locks.resolve(m.group("lock"))
        if lock is None:
            gc.findings.append(Finding(
                "CTA000", ctx.rel, ln,
                f"guarded-by names unknown lock "
                f"{m.group('lock')!r} (no matching Lock/RLock/"
                f"Condition/make_lock attribute in "
                f"{cls.name})", checker="config"))
            continue
        for attr in m.group("attrs").split(","):
            attr = attr.strip()
            if attr:
                gc.guarded[attr] = lock
    # trailing form on __init__ self.X = ... lines
    for fn in cls.body:
        if isinstance(fn, ast.FunctionDef) and fn.name == "__init__":
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for ln, c in ctx.comments_in(node.lineno,
                                             (node.end_lineno
                                              or node.lineno) + 1):
                    m = _GUARDED_TRAIL_RE.search(c)
                    if m is None:
                        continue
                    lock = gc.locks.resolve(m.group("lock"))
                    if lock is None:
                        gc.findings.append(Finding(
                            "CTA000", ctx.rel, ln,
                            f"guarded-by names unknown lock "
                            f"{m.group('lock')!r} in {cls.name}",
                            checker="config"))
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            gc.guarded[tgt.attr] = lock
    return gc


def extract_holds(node: ast.FunctionDef, ctx: FileCtx,
                  locks: LockMap,
                  findings: List[Finding]) -> Set[str]:
    """Locks a method declares as held by every caller."""
    held: Set[str] = set()
    for ln, c in _def_comment_range(node, ctx):
        m = _HOLDS_RE.search(c)
        if m is None:
            continue
        for name in m.group("locks").split(","):
            name = name.strip()
            if not name:
                continue
            lock = locks.resolve(name)
            if lock is None:
                findings.append(Finding(
                    "CTA000", ctx.rel, ln,
                    f"holds names unknown lock {name!r}",
                    checker="config"))
                continue
            held.add(lock)
    return held


def extract_affinity(node: ast.FunctionDef, ctx: FileCtx,
                     findings: List[Finding]
                     ) -> Optional[Tuple[str, ...]]:
    """The function's declared thread-affinity set, or None."""
    for ln, c in _def_comment_range(node, ctx):
        m = _AFFINITY_RE.search(c)
        if m is None:
            continue
        affs = tuple(a.strip() for a in m.group("affs").split(",")
                     if a.strip())
        bad = [a for a in affs if a not in AFFINITIES]
        if bad or not affs:
            findings.append(Finding(
                "CTA000", ctx.rel, ln,
                f"unknown thread-affinity {', '.join(bad)!r} "
                f"(vocabulary: {', '.join(AFFINITIES)})",
                checker="config"))
            return None
        return affs
    return None
