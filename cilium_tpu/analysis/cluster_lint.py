"""CTA008 — cluster-ledger: every cluster-router drop site is
counted, surfaced, and decodable; the cluster bench artifact keeps
its schema.

The cluster-wide no-silent-loss ledger (``submitted == per-node
accounted + router_overflow + failover_dropped``) is only as strong
as the discipline that every drop site in ``cilium_tpu/cluster/``
feeds a declared counter.  Statically enforced:

1. ``router.DROP_COUNTERS`` exists (the declared drop-counter
   vocabulary), and every ``self.<name> += ...`` in cluster/ whose
   name ends ``_overflow`` / ``_dropped`` uses a DECLARED name — an
   undeclared increment is a drop site the ledger (and the registry)
   cannot see;
2. every declared counter has its prometheus series
   (``cilium_cluster_<name>_total``) registered in the metrics
   registry module — counted must also mean scrapeable; likewise
   every :data:`REQUIRED_SERIES` entry (the pipelined-window
   credit-loop gauges/counters, ISSUE 17);
3. ``REASON_CLUSTER_OVERFLOW`` exists in the reason space and every
   ``DROP_REASON_*`` decode table covers it (CTA005 enforces this
   generically; CTA008 names the cluster code specifically so a
   botched renumber fails with a cluster-shaped message);
4. when ``BENCH_cluster.json`` exists at the repo root, it carries
   every :data:`BENCH_CLUSTER_KEYS` entry — the bench-schema wire
   for the cluster artifact (``check_bench`` is the importable
   validator the shim CLI and tests share).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from .core import FileCtx, Finding, Repo

CODE = "CTA008"
NAME = "cluster-ledger"

CLUSTER_DIR = "cilium_tpu/cluster/"
ROUTER_MODULE = "cilium_tpu/cluster/router.py"
REGISTRY_MODULE = "cilium_tpu/obs/registry.py"
VERDICT_MODULE = "cilium_tpu/datapath/verdict.py"
CLUSTER_REASON = "REASON_CLUSTER_OVERFLOW"
# decode tables that must name the cluster reason (module -> dict)
DECODE_TABLES = (
    ("cilium_tpu/monitor/api.py", "DROP_REASON_NAMES"),
    ("cilium_tpu/flow/flow.py", "DROP_REASON_DESC"),
    ("cilium_tpu/flow/proto.py", "DROP_REASON_WIRE"),
)

BENCH_NAME = "BENCH_cluster.json"
# the cluster bench artifact's schema floor (bench.py --cluster).
# v2 (ISSUE 13): headline keys are the PROCESS-mode curve; `modes`
# carries both per-mode curves (paired-leg ratios + spread + forward
# latency percentiles), `host_cores` is the honesty floor (a 1-core
# host cannot show N-core speedups in any mode), and the failover
# leg is a real SIGKILL with crash_dropped in the ledger.
# v3 (ISSUE 17): adds the pipelined-transport legs — paired
# interleaved sync(window=1) vs pipelined(window>=8) forward
# throughput (per-pair ratios + spread), the low-load forward-latency
# p50 comparison, the SIGKILL-mid-window ledger leg, and the live
# scale-in leg (zero survivor recompiles)
# v4 (ISSUE 18): adds the encrypted-channel legs — paired
# interleaved encrypted vs plaintext forward throughput (per-pair
# ratios + spread: the AEAD toll, honestly measured), seal/open
# latency percentiles, and the SIGKILL-mid-rotation ledger leg
BENCH_CLUSTER_KEYS = (
    "schema", "best_of", "host_cores", "mode", "modes",
    "sustained_pps_n1", "sustained_pps_n2", "sustained_pps_n3",
    "scaling_n2", "scaling_n3",
    "forward_latency_us",
    "failover_blackout_ms", "failover_detect_ms",
    "failover_ct_entries", "failover_dropped",
    "failover_crash_dropped", "failover_mode",
    "scale_out",
    "ledger_exact",
    # -- v3: pipelined data channel --
    "forward_window",
    "pipelined_speedup", "pipelined_speedup_pairs",
    "pipelined_speedup_spread",
    "latency_p50_sync_us", "latency_p50_pipelined_us",
    "latency_p50_ratio",
    "sigkill_mid_window",
    "scale_in",
    # -- v4: encrypted data channel --
    "encrypted_pps", "plaintext_pps",
    "encrypted_ratio", "encrypted_ratio_pairs",
    "encrypted_ratio_spread",
    "seal_latency_us", "open_latency_us",
    "sigkill_mid_rotation",
)
BENCH_SCHEMA = "bench-cluster-v4"
# pipelined-transport series the registry must export (checked the
# same way as the drop-counter series: the literal name appears in
# the registry module).  The window counters are the observable half
# of the credit loop — without them an operator cannot see a stalled
# window or how much coalescing is buying.
REQUIRED_SERIES = (
    "cilium_cluster_inflight_frames",
    "cilium_cluster_acks_coalesced_total",
    "cilium_cluster_window_stalls_total",
    # the encrypted channel's observable half (ISSUE 18): rejects,
    # replays, and rotations must be scrapeable or the crypto plane
    # fails silently from the operator's seat.  crypto_dropped_total
    # is enforced separately via DROP_COUNTERS (it is a ledger term).
    "cilium_cluster_crypto_rejected_total",
    "cilium_cluster_crypto_replays_total",
    "cilium_cluster_crypto_rotations_total",
)
# per-mode sub-dict floor (both entries of `modes`)
BENCH_MODE_KEYS = (
    "sustained_pps_n1", "sustained_pps_n2", "sustained_pps_n3",
    "scaling_n2", "scaling_n3", "scaling_n2_pairs",
    "scaling_n3_pairs", "forward_latency_us",
)


def _module_tuple(ctx: FileCtx, name: str) -> Optional[List[str]]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)]
    return None


def _module_const(ctx: FileCtx, name: str) -> Optional[int]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Constant):
            return node.value.value
    return None


def _dict_keys(ctx: FileCtx, name: str) -> Optional[Dict[int, bool]]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            return {k.value: True for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, int)}
    return None


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    router = repo.by_rel(ROUTER_MODULE)
    if router is None or router.tree is None:
        return [Finding(CODE, ROUTER_MODULE, 1,
                        "cluster router module missing",
                        checker=NAME)]
    declared = _module_tuple(router, "DROP_COUNTERS")
    if declared is None:
        findings.append(Finding(
            CODE, router.rel, 1,
            "DROP_COUNTERS literal not found (the declared "
            "drop-counter vocabulary the ledger checks against)",
            checker=NAME))
        declared = []

    # 1. undeclared drop-site increments anywhere in cluster/
    for ctx in repo.files:
        if not ctx.rel.startswith(CLUSTER_DIR) or ctx.tree is None:
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AugAssign) \
                    or not isinstance(node.op, ast.Add):
                continue
            tgt = node.target
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            name = tgt.attr
            if not (name.endswith("_overflow")
                    or name.endswith("_dropped")):
                continue
            if name in declared:
                continue
            if ctx.suppressed(CODE, node.lineno):
                continue
            findings.append(Finding(
                CODE, ctx.rel, node.lineno,
                f"drop counter {name!r} incremented but not declared "
                f"in router.DROP_COUNTERS — an uncounted (registry-"
                f"invisible) router drop site", checker=NAME))

    # 2. one registered series per declared counter
    reg = repo.by_rel(REGISTRY_MODULE)
    for name in declared:
        series = f"cilium_cluster_{name}_total"  # lint: disable=CTA006 -- series-NAME construction for the presence check, not exposition text
        if reg is None or f'"{series}"' not in reg.source:
            findings.append(Finding(
                CODE, REGISTRY_MODULE, 1,
                f"router drop counter {name!r} has no registered "
                f"series {series!r}", checker=NAME))

    # 2b. pipelined-window series floor (ISSUE 17): the credit-loop
    # gauges/counters must be registered just like the drop counters
    for series in REQUIRED_SERIES:
        if reg is None or f'"{series}"' not in reg.source:
            findings.append(Finding(
                CODE, REGISTRY_MODULE, 1,
                f"pipelined-transport series {series!r} is not "
                f"registered — the credit window would be "
                f"unobservable", checker=NAME))

    # 3. the cluster reason code decodes everywhere
    verdict = repo.by_rel(VERDICT_MODULE)
    reason = (_module_const(verdict, CLUSTER_REASON)
              if verdict is not None and verdict.tree is not None
              else None)
    if reason is None:
        findings.append(Finding(
            CODE, VERDICT_MODULE, 1,
            f"{CLUSTER_REASON} is not defined in the reason space",
            checker=NAME))
    else:
        for rel, table in DECODE_TABLES:
            ctx = repo.by_rel(rel)
            keys = (_dict_keys(ctx, table)
                    if ctx is not None and ctx.tree is not None
                    else None)
            if keys is None or reason not in keys:
                findings.append(Finding(
                    CODE, rel, 1,
                    f"{table} does not decode {CLUSTER_REASON} "
                    f"({reason}) — the cluster router's drops would "
                    f"render as 'reason {reason}'", checker=NAME))

    # 4. bench artifact schema (only when the artifact exists)
    bench_path = os.path.join(repo.root, BENCH_NAME)
    if os.path.exists(bench_path):
        for msg in check_bench(bench_path):
            findings.append(Finding(CODE, BENCH_NAME, 1, msg,
                                    checker=NAME))
    return findings


# -- bench artifact validation (shim CLI + tests) ----------------------
def check_bench(path: str) -> List[str]:
    """-> list of violation strings (empty = clean)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: does not load as JSON ({e})"]
    if not isinstance(data, dict):
        return [f"{path}: top level is {type(data).__name__}, "
                f"not an object"]
    bad = []
    if data.get("schema") != BENCH_SCHEMA:
        bad.append(f"{path}: schema {data.get('schema')!r} != "
                   f"{BENCH_SCHEMA}")
    for key in BENCH_CLUSTER_KEYS:
        if key not in data:
            bad.append(f"{path}: missing required key {key!r}")
    modes = data.get("modes")
    if not isinstance(modes, dict) or set(modes) != {"thread",
                                                     "process"}:
        bad.append(f"{path}: 'modes' must carry exactly the thread "
                   f"and process curves")
    else:
        for mode, curve in modes.items():
            for key in BENCH_MODE_KEYS:
                if key not in curve:
                    bad.append(f"{path}: modes[{mode!r}] missing "
                               f"{key!r}")
    return bad
