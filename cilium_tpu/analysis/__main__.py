"""``python -m cilium_tpu.analysis`` — the static-analysis CLI.

Exit status: 0 clean, 1 findings, 2 usage error.

Bundle files/dirs passed as positional arguments are additionally
validated against the sysdump schema (CTA007's bundle half)."""

from __future__ import annotations

import argparse
import os
import sys

from .core import BASELINE_NAME, Baseline, repo_root
from .driver import CHECKERS, render_human, render_json, run_analysis
from .sysdump_lint import check_bundle


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cilium_tpu.analysis",
        description="concurrency & invariant static analyzer")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--checker", action="append", default=None,
                    choices=sorted(CHECKERS),
                    help="run only the named checker(s)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: "
                         f"<root>/{BASELINE_NAME})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into "
                         "the baseline and exit 0")
    ap.add_argument("--list-checkers", action="store_true")
    ap.add_argument("bundles", nargs="*",
                    help="sysdump bundle files/dirs to validate")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for name, (code, _fn) in sorted(CHECKERS.items()):
            print(f"{code}  {name}")
        return 0

    result = run_analysis(root=args.root, checkers=args.checker,
                          baseline_path=args.baseline)

    bundle_bad = []
    for a in args.bundles:
        if os.path.isdir(a):
            for n in sorted(os.listdir(a)):
                if n.startswith("sysdump-") and n.endswith(".json"):
                    bundle_bad.extend(
                        check_bundle(os.path.join(a, n)))
        else:
            bundle_bad.extend(check_bundle(a))

    if args.write_baseline:
        root = args.root or repo_root()
        path = args.baseline or os.path.join(root, BASELINE_NAME)
        all_findings = result["findings"] + result["baselined"]
        Baseline(path).write(all_findings, result["repo"])
        print(f"wrote {len(all_findings)} finding(s) to {path}")
        return 0

    if args.json:
        print(render_json(result))
    else:
        print(render_human(result))
    for b in bundle_bad:
        print(f"sysdump: {b}", file=sys.stderr)
    return 1 if (result["findings"] or bundle_bad) else 0


if __name__ == "__main__":
    sys.exit(main())
