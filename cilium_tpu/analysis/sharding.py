"""CTA004 — sharding-spec spelling.

``P(axis)`` and ``P(axis, None)`` place identically, but jax's
compilation cache keys on the SPELLING: jit normalizes output specs
by trimming trailing ``None``s, so a fresh array ``device_put`` with
the trailing-``None`` spelling mismatches the executable's cached
layout key and retraces the serve step on every window swap — the
trap PR 2 fixed once (``parallel/mesh.py`` ``make_sharded_ring``)
and nothing but this checker prevents reintroducing.

Rule: a ``P(...)``/``PartitionSpec(...)`` call whose LAST positional
argument is the literal ``None`` is flagged, unless it appears where
the rank-explicit spelling is the convention:

- inside the value of an ``in_specs=`` / ``out_specs=`` keyword
  (``shard_map`` specs are rank-matched by position), or
- in an assignment to a name containing ``spec`` (the
  ``state_specs = (P(), P(axis, None), ...)`` staging idiom).
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Repo

CODE = "CTA004"
NAME = "sharding-spec"

_SPEC_NAMES = {"P", "PartitionSpec"}
_SPEC_KEYWORDS = {"in_specs", "out_specs"}


def _trailing_none_p_calls(tree: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name not in _SPEC_NAMES or not node.args:
            continue
        last = node.args[-1]
        if isinstance(last, ast.Constant) and last.value is None:
            out.append(node)
    return out


def _allowed_spans(tree: ast.AST) -> Set[int]:
    """ids of every AST node inside an in_specs/out_specs keyword
    value or a ``*spec*``-named assignment."""
    allowed: Set[int] = set()

    def mark(node: ast.AST) -> None:
        for sub in ast.walk(node):
            allowed.add(id(sub))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in _SPEC_KEYWORDS:
                    mark(kw.value)
        elif isinstance(node, ast.Assign):
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if any("spec" in n.lower() for n in names):
                mark(node.value)
    return allowed


def check(repo: Repo, graph=None) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in repo.files:
        if ctx.tree is None:
            continue
        allowed = _allowed_spans(ctx.tree)
        for call in _trailing_none_p_calls(ctx.tree):
            if id(call) in allowed:
                continue
            line = call.lineno
            if ctx.suppressed(CODE, line):
                continue
            findings.append(Finding(
                CODE, ctx.rel, line,
                "trailing-None PartitionSpec spelling (P(axis, None) "
                "places like P(axis) but keys the compile cache "
                "differently — the window-swap retrace trap); trim "
                "the trailing None outside shard_map in_specs/"
                "out_specs", checker=NAME))
    return findings
