"""k8s watchers: Service/Endpoints, Pod, CiliumIdentity,
CiliumEndpoint, CiliumNode event handlers.

Reference: upstream cilium ``pkg/k8s/watchers`` — informer callbacks
translating k8s objects into agent mutations:

- ``service.go`` + ``endpoints.go``: Service + Endpoints objects
  reconcile into the ServiceManager (frontend = clusterIP:port,
  backends = ready endpoint addresses x matching port);
- ``pod.go``: local pods become endpoints (labels -> identity, pod IP
  -> ipcache host route, container ports -> named ports);
- ``cilium_identity.go`` (CRD identity mode): CiliumIdentity objects
  replay into the local allocator exactly like kvstore watch events;
- ``cilium_endpoint.go``: REMOTE CiliumEndpoints feed ipcache (pod IP
  -> identity) — the CRD-mode replacement for kvstore ipcache sync;
- ``cilium_node.go``: node lifecycle into the node registry the
  operator/health mesh read.

Like :class:`~cilium_tpu.k8s.CNPWatcher`, each watcher is the
translation half only: drive it from fake event streams in tests
(SURVEY.md §4 fake-clientset pattern) or a real informer in
deployment.  All handlers are idempotent — k8s informers re-deliver.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..labels import LabelSet
from . import NS_LABEL, NS_LABELS_PREFIX

_PROTO_NUM = {"TCP": 6, "UDP": 17, "SCTP": 132}

# k8s resource.Quantity suffixes, CASE-SENSITIVE ("m" is milli, "M"
# mega — upstream parses the annotation as a Quantity of bits/s);
# "K"/"k" both accepted (common operator typo for the canonical "k")
_BW_UNITS = {"": 1, "m": 1e-3, "k": 10 ** 3, "K": 10 ** 3,
             "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
             "P": 10 ** 15, "E": 10 ** 18,
             "Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30,
             "Ti": 1 << 40, "Pi": 1 << 50, "Ei": 1 << 60}


def parse_bandwidth(spec) -> int:
    """``kubernetes.io/egress-bandwidth`` quantity -> BYTES/s (0 =
    none/invalid; the annotation is a k8s resource.Quantity in
    bits/s — upstream pkg/bandwidth parses it the same way)."""
    if not spec:
        return 0
    s = str(spec).strip()
    for suffix in sorted(_BW_UNITS, key=len, reverse=True):
        if suffix and s.endswith(suffix):
            num = s[: -len(suffix)]
            break
    else:
        suffix, num = "", s
    try:
        bits = float(num) * _BW_UNITS[suffix]
        return max(int(bits / 8), 0)
    except (ValueError, OverflowError):
        # covers non-numeric specs AND inf/nan/1e400, whose float()
        # succeeds but whose int() raises — one malformed annotation
        # must read as "no limit", never crash the watcher
        return 0


def _meta_key(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    return f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"


def _k8s_selector_matches(sel: dict, labels: dict) -> bool:
    """Plain k8s LabelSelector over an object's metadata.labels:
    matchLabels AND every matchExpression (In/NotIn/Exists/
    DoesNotExist) must hold.  Unknown operators fail CLOSED (match
    nothing) — silently ignoring a constraint would widen a policy."""
    for k, v in (sel.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for e in sel.get("matchExpressions") or ():
        key, op = e.get("key", ""), e.get("operator", "")
        vals = e.get("values") or ()
        if op == "In":
            if labels.get(key) not in vals:
                return False
        elif op == "NotIn":
            if key in labels and labels[key] in vals:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            return False
    return True


class ServiceWatcher:
    """Service + Endpoints objects -> ServiceManager entries.

    One LB entry per (k8s service, port, frontend): registry name
    ``<ns>/<name>:<portname-or-number>`` for the clusterIP frontend,
    with ``/nodeport``, ``/external/<ip>`` and ``/lb/<ip>`` suffixes
    for the external frontend classes (reference: pkg/k8s/watchers
    service+endpoints caches feeding pkg/service's frontend set).

    Frontend classes (reference pkg/loadbalancer SVCType):

    - ClusterIP (spec.clusterIP) — always, unless headless;
    - NodePort (``node_ip``:spec.ports[].nodePort) for
      type NodePort/LoadBalancer.  Divergence vs upstream: upstream
      matches a nodePort on EVERY local address; here the frontend
      compiles at the agent's configured ``node_ip`` only;
    - ExternalIP (spec.externalIPs[]);
    - LoadBalancer (status.loadBalancer.ingress[].ip).

    ``externalTrafficPolicy: Local`` filters external frontends to
    node-LOCAL backends, ``internalTrafficPolicy: Local`` does the
    same for the clusterIP frontend (``is_local_ip`` decides — wired
    to the endpoint registry).  A frontend whose filtered backend set
    is EMPTY still installs: matching traffic must drop with
    NO_SERVICE (upstream DROP_NO_SERVICE), not fall through to
    routing.  ``sessionAffinity: ClientIP`` carries its timeout onto
    every frontend of the service."""

    def __init__(self, services, node_ip=None, local_ips=None,
                 nodeport_addresses=()):
        self.services = services  # ServiceManager
        self.node_ip = node_ip
        # extra addresses nodePort frontends bind (reference:
        # --nodeport-addresses; narrows DIVERGENCES #21 — upstream's
        # catch-all binds every local address)
        self.nodeport_addresses = tuple(nodeport_addresses)
        # () -> set of node-local pod IPs, snapshotted ONCE per
        # reconcile (a per-ip predicate would rescan the endpoint
        # registry ports x backends times per event)
        self.local_ips = local_ips
        self._svc: Dict[str, dict] = {}
        self._eps: Dict[str, dict] = {}
        self._installed: Dict[str, set] = {}  # key -> LB names
        # fired with the changed "<ns>/<name>" after every service/
        # endpoints event (the hub wires CNPWatcher.resync_services
        # here so toServices re-expands only affected CNPs)
        self.on_change = None

    def _changed(self, key: str) -> None:
        if self.on_change is not None:
            self.on_change(key)

    # -- Service objects ---------------------------------------------
    def on_service_add(self, obj: dict) -> None:
        key = _meta_key(obj)
        self._svc[key] = obj
        self._reconcile(key)
        self._changed(key)

    on_service_update = on_service_add

    def on_service_delete(self, obj: dict) -> None:
        key = _meta_key(obj)
        self._svc.pop(key, None)
        self._reconcile(key)
        self._changed(key)

    # -- Endpoints objects -------------------------------------------
    def on_endpoints_add(self, obj: dict) -> None:
        key = _meta_key(obj)
        self._eps[key] = obj
        self._reconcile(key)
        self._changed(key)

    on_endpoints_update = on_endpoints_add

    def on_endpoints_delete(self, obj: dict) -> None:
        key = _meta_key(obj)
        self._eps.pop(key, None)
        self._reconcile(key)
        self._changed(key)

    def _reconcile(self, key: str) -> None:
        svc = self._svc.get(key)
        eps = self._eps.get(key)
        wanted: Dict[str, Tuple[str, List[str], int, str, int]] = {}
        local_set = None
        if svc is not None:
            spec = svc.get("spec") or {}
            stype = spec.get("type") or "ClusterIP"
            cluster_ip = spec.get("clusterIP")
            ext_local = spec.get("externalTrafficPolicy") == "Local"
            int_local = spec.get("internalTrafficPolicy") == "Local"
            if (ext_local or int_local) and self.local_ips is not None:
                local_set = set(self.local_ips())
            aff = 0
            if spec.get("sessionAffinity") == "ClientIP":
                aff = int(((spec.get("sessionAffinityConfig") or {})
                           .get("clientIP") or {})
                          .get("timeoutSeconds", 10800))
            lb_ips = [ing.get("ip")
                      for ing in ((svc.get("status") or {})
                                  .get("loadBalancer") or {})
                      .get("ingress") or () if ing.get("ip")]
            for p in spec.get("ports") or ():
                pname = p.get("name") or str(p.get("port"))
                proto = _PROTO_NUM.get(p.get("protocol", "TCP"), 6)
                backends = (self._backends(eps, p)
                            if eps is not None else [])
                local = (backends if local_set is None else
                         [b for b in backends
                          if b.rsplit(":", 1)[0] in local_set])
                # dual-stack: spec.clusterIPs may add a second-family
                # VIP beyond the primary spec.clusterIP
                cips: List[str] = []
                for c in ([cluster_ip]
                          + list(spec.get("clusterIPs") or ())):
                    if c and c != "None" and c not in cips:
                        cips.append(c)
                for j, cip in enumerate(cips):
                    suffix = "" if j == 0 else f"/ip{j}"
                    wanted[f"{key}:{pname}{suffix}"] = (
                        f"{cip}:{p.get('port')}",
                        local if int_local else backends,
                        proto, "ClusterIP", aff)
                ext_be = local if ext_local else backends
                node_port = p.get("nodePort")
                if stype in ("NodePort", "LoadBalancer") and node_port:
                    addrs: List[str] = []
                    for a in (self.node_ip,) + self.nodeport_addresses:
                        if a and a not in addrs:  # dedup vs node_ip
                            addrs.append(a)
                    for i, addr in enumerate(addrs):
                        suffix = "" if i == 0 else f"/{addr}"
                        wanted[f"{key}:{pname}/nodeport{suffix}"] = (
                            f"{addr}:{node_port}", ext_be,
                            proto, "NodePort", aff)
                for eip in spec.get("externalIPs") or ():
                    wanted[f"{key}:{pname}/external/{eip}"] = (
                        f"{eip}:{p.get('port')}", ext_be,
                        proto, "ExternalIP", aff)
                if stype == "LoadBalancer":
                    for lip in lb_ips:
                        wanted[f"{key}:{pname}/lb/{lip}"] = (
                            f"{lip}:{p.get('port')}", ext_be,
                            proto, "LoadBalancer", aff)
        have = self._installed.get(key, set())
        for name in have - set(wanted):
            self.services.delete(name)
        cur = {s.name: s for s in self.services.list()}
        for name, (frontend, backends, proto, kind,
                   aff) in wanted.items():
            c = cur.get(name)
            if (c is not None and c.protocol == proto
                    and c.kind == kind and c.affinity_timeout == aff
                    and f"{c.frontend_ip}:{c.frontend_port}" == frontend
                    and [f"{b.ip}:{b.port}" for b in c.backends]
                    == backends):
                continue  # unchanged: keep the compiled LB tensors
            self.services.upsert(name, frontend, backends,
                                 protocol=proto, kind=kind,
                                 affinity_timeout=aff)
        if wanted:
            self._installed[key] = set(wanted)
        else:  # fully withdrawn: don't grow an empty entry per
            self._installed.pop(key, None)  # ever-seen service

    def resync(self) -> None:
        """Endpoint churn: Local traffic policies re-filter their
        backend sets against the endpoints now on this node (a pod
        attaching after its Endpoints event must start receiving,
        and vice versa)."""
        for key, svc in list(self._svc.items()):
            spec = svc.get("spec") or {}
            if (spec.get("externalTrafficPolicy") == "Local"
                    or spec.get("internalTrafficPolicy") == "Local"):
                self._reconcile(key)

    # -- toServices peer views (pkg/k8s TranslateToServicesRule) ------
    def service_peer_ips(self, ns: str, name: str) -> set:
        """The IP peer set a ``k8sService`` reference expands to:
        clusterIP + every ready backend address (upstream translates
        to the endpoints' IPs; the frontend rides along so socket-LB'd
        connects to the VIP are judged consistently)."""
        key = f"{ns}/{name}"
        out: set = set()
        svc = self._svc.get(key)
        if svc is not None:
            cip = (svc.get("spec") or {}).get("clusterIP")
            if cip and cip != "None":
                out.add(cip)
        eps = self._eps.get(key)
        if eps is not None:
            for subset in eps.get("subsets") or ():
                for a in subset.get("addresses") or ():
                    if a.get("ip"):
                        out.add(a["ip"])
        return out

    def select_peer_ips(self, selector: dict,
                        ns: Optional[str] = None) -> set:
        """``k8sServiceSelector`` expansion: services whose OBJECT
        labels match the full k8s LabelSelector grammar (matchLabels
        AND matchExpressions), all namespaces unless ``ns`` given."""
        out: set = set()
        for key, svc in self._svc.items():
            sns, name = key.split("/", 1)
            if ns and sns != ns:
                continue
            labels = (svc.get("metadata") or {}).get("labels") or {}
            if _k8s_selector_matches(selector or {}, labels):
                out |= self.service_peer_ips(sns, name)
        return out

    @staticmethod
    def _backends(eps: dict, svc_port: dict) -> List[str]:
        """Ready addresses x the subset port matching this service
        port (by name, or the single unnamed port)."""
        pname = svc_port.get("name")
        out = []
        for subset in eps.get("subsets") or ():
            ports = subset.get("ports") or ()
            target = None
            for sp in ports:
                if (pname and sp.get("name") == pname) or (
                        not pname and len(ports) == 1):
                    target = sp.get("port")
                    break
            if target is None:
                continue
            for addr in subset.get("addresses") or ():
                ip = addr.get("ip")
                if ip:
                    out.append(f"{ip}:{target}")
        return sorted(out)


def pod_labels(obj: dict,
               ns_labels: Optional[Dict[str, str]] = None) -> List[str]:
    """Pod metadata labels -> cilium identity labels (``k8s:`` source
    + the namespace label + the NAMESPACE's own labels under the
    ``io.cilium.k8s.namespace.labels.`` prefix, reference:
    k8s.GetPodMetadata — that prefix is what namespaceSelector peers
    compile to)."""
    meta = obj.get("metadata") or {}
    ns = meta.get("namespace", "default")
    out = [f"k8s:{k}={v}" for k, v in (meta.get("labels") or {}).items()]
    out.append(f"k8s:{NS_LABEL}={ns}")
    for k, v in (ns_labels or {}).items():
        out.append(f"k8s:{NS_LABELS_PREFIX}{k}={v}")
    return sorted(out)


class PodWatcher:
    """Local pods -> endpoint lifecycle (reference: pod.go).

    Only pods scheduled on THIS node become endpoints (remote pods
    reach the ipcache via CiliumEndpoint objects).  A label change
    re-registers the endpoint (identity change = new endpoint policy,
    like upstream's UpdateLabels regeneration)."""

    def __init__(self, daemon, node_name: Optional[str] = None,
                 namespaces: Optional["NamespaceWatcher"] = None):
        self.daemon = daemon
        self.node_name = node_name or daemon.config.node_name
        self.namespaces = namespaces
        self._eps: Dict[str, int] = {}  # ns/name -> endpoint id
        self._sig: Dict[str, tuple] = {}  # ns/name -> (labels,ips,ports)
        self._objs: Dict[str, dict] = {}  # ns/name -> last pod object

    def _pod_ips(self, obj: dict) -> Tuple[str, ...]:
        st = obj.get("status") or {}
        ips = [e.get("ip") for e in st.get("podIPs") or () if e.get("ip")]
        if not ips and st.get("podIP"):
            ips = [st["podIP"]]
        return tuple(ips)

    @staticmethod
    def _named_ports(obj: dict) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in (obj.get("spec") or {}).get("containers") or ():
            for p in c.get("ports") or ():
                if p.get("name") and p.get("containerPort"):
                    out[p["name"]] = int(p["containerPort"])
        return out

    def on_add(self, obj: dict) -> Optional[int]:
        key = _meta_key(obj)
        if (obj.get("spec") or {}).get("nodeName") != self.node_name:
            return None
        ips = self._pod_ips(obj)
        if not ips:
            return None  # not yet scheduled/IP'd; a later update fires
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        ns_labels = (self.namespaces.labels_of(ns)
                     if self.namespaces else None)
        labels = pod_labels(obj, ns_labels)
        ports = self._named_ports(obj)
        bw = parse_bandwidth(((obj.get("metadata") or {}).get(
            "annotations") or {}).get("kubernetes.io/egress-bandwidth"))
        # idempotency covers EVERYTHING the endpoint derives from the
        # pod: an IP change (sandbox restart) or port change with
        # unchanged labels must still re-register
        sig = (tuple(labels), ips, tuple(sorted(ports.items())), bw)
        if key in self._eps:
            if sig == self._sig.get(key):
                return self._eps[key]  # idempotent re-deliver
            self.on_delete(obj)  # pod changed: re-register
        ep = self.daemon.add_endpoint(key, ips, labels,
                                      named_ports=ports)
        if bw:
            # reference: pkg/bandwidth reads the pod annotation and
            # programs the endpoint's EDT aggregate
            self.daemon.set_bandwidth(ep.id, bw)
        self._eps[key] = ep.id
        self._sig[key] = sig
        self._objs[key] = obj
        return ep.id

    on_update = on_add

    def on_delete(self, obj: dict) -> bool:
        key = _meta_key(obj)
        ep_id = self._eps.pop(key, None)
        self._sig.pop(key, None)
        self._objs.pop(key, None)
        if ep_id is None:
            return False
        self.daemon.set_bandwidth(ep_id, None)
        return self.daemon.endpoints.remove(ep_id)

    def reregister_namespace(self, ns: str) -> int:
        """Namespace labels changed: replay every known pod of that
        namespace so identities pick up the new
        ``io.cilium.k8s.namespace.labels.*`` set."""
        n = 0
        for key, obj in list(self._objs.items()):
            if key.split("/", 1)[0] == ns:
                self.on_add(obj)
                n += 1
        return n


class NamespaceWatcher:
    """Namespace objects -> namespace-label registry (reference:
    pkg/k8s watcher for Namespace; upstream folds namespace labels
    into pod identity labels under ``io.cilium.k8s.namespace.labels.``
    so namespaceSelector peers can match them)."""

    def __init__(self, pods: Optional[PodWatcher] = None):
        self.pods = pods
        self._labels: Dict[str, Dict[str, str]] = {}

    def labels_of(self, ns: str) -> Dict[str, str]:
        return self._labels.get(ns, {})

    def on_add(self, obj: dict):
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        labels = dict(meta.get("labels") or {})
        if self._labels.get(name) == labels:
            return
        self._labels[name] = labels
        if self.pods is not None:
            self.pods.reregister_namespace(name)

    on_update = on_add

    def on_delete(self, obj: dict):
        name = (obj.get("metadata") or {}).get("name", "")
        if self._labels.pop(name, None) is not None and self.pods:
            self.pods.reregister_namespace(name)


class CiliumIdentityWatcher:
    """CiliumIdentity CRD objects -> local allocator replay
    (reference: CRD identity allocation mode).  Same semantics as the
    kvstore id/ watch: creates register/rebind, deletes drop
    unreferenced replicas."""

    def __init__(self, allocator):
        self.allocator = allocator

    @staticmethod
    def _parse(obj: dict) -> Tuple[int, LabelSet]:
        num = int((obj.get("metadata") or {}).get("name"))
        labels = obj.get("security-labels") or {}
        return num, LabelSet.parse(
            *[f"{k}={v}" if v else k for k, v in labels.items()])

    def on_add(self, obj: dict):
        num, labels = self._parse(obj)
        return self.allocator.watch_update(num, labels)

    on_update = on_add

    def on_delete(self, obj: dict) -> bool:
        num = int((obj.get("metadata") or {}).get("name"))
        return self.allocator.watch_remove(num)


def cep_from_endpoint(ep, node_ip: str = "") -> dict:
    """Local endpoint -> CiliumEndpoint object (what the agent would
    publish for remote nodes to consume; reference:
    pkg/k8s/apis/cilium.io/v2 CiliumEndpoint)."""
    ns = "default"
    name = ep.name
    if "/" in ep.name:
        ns, name = ep.name.split("/", 1)
    return {
        "apiVersion": "cilium.io/v2",
        "kind": "CiliumEndpoint",
        "metadata": {"name": name, "namespace": ns},
        "status": {
            "id": ep.id,
            "identity": {
                "id": (ep.identity.numeric_id if ep.identity else 0),
                "labels": sorted(str(l) for l in ep.labels),
            },
            "networking": {
                "addressing": [{"ipv6" if ":" in ip else "ipv4": ip}
                               for ip in ep.ips],
                **({"node": node_ip} if node_ip else {}),
            },
            "state": ep.state.value,
        },
    }


class CiliumEndpointWatcher:
    """REMOTE CiliumEndpoint objects -> ipcache (pod IP -> identity)
    — the CRD-mode ipcache propagation path (reference:
    cilium_endpoint.go endpointUpdated -> ipcache.Upsert)."""

    def __init__(self, daemon):
        self.daemon = daemon
        self._ips: Dict[str, Tuple[str, ...]] = {}

    @staticmethod
    def _addresses(obj: dict) -> Tuple[str, ...]:
        net = ((obj.get("status") or {}).get("networking") or {})
        out = []
        for pair in net.get("addressing") or ():
            for fam in ("ipv4", "ipv6"):
                if pair.get(fam):
                    out.append(pair[fam])
        return tuple(out)

    def _is_local(self, ips) -> bool:
        """A real informer delivers ALL CiliumEndpoints, including the
        ones this agent publishes for its own pods — those must be
        skipped (upstream cilium_endpoint.go does the same) or a CEP
        re-sync/delete would clobber the LOCAL endpoint's ipcache
        entry and misclassify its traffic."""
        return any(self.daemon.endpoints.lookup_by_ip(ip) is not None
                   for ip in ips)

    def on_add(self, obj: dict) -> int:
        key = _meta_key(obj)
        status = obj.get("status") or {}
        ident = int((status.get("identity") or {}).get("id", 0))
        ips = self._addresses(obj)
        if self._is_local(ips):
            return 0
        # remove addresses that disappeared in an update
        for ip in self._ips.get(key, ()):
            if ip not in ips:
                self._del_ip(ip)
        n = 0
        for ip in ips:
            suffix = "/128" if ":" in ip else "/32"
            self.daemon.upsert_ipcache(ip + suffix, ident)
            n += 1
        self._ips[key] = ips
        return n

    on_update = on_add

    def on_delete(self, obj: dict) -> int:
        key = _meta_key(obj)
        ips = self._ips.pop(key, None) or self._addresses(obj)
        if self._is_local(ips):
            return 0
        n = 0
        for ip in ips:
            self._del_ip(ip)
            n += 1
        return n

    def _del_ip(self, ip: str) -> None:
        suffix = "/128" if ":" in ip else "/32"
        self.daemon.delete_ipcache(ip + suffix)


class CiliumEndpointSliceWatcher:
    """CiliumEndpointSlice objects -> the same per-endpoint ipcache
    path as direct CEPs (reference: pkg/k8s/watchers
    ciliumEndpointSliceInit — agents in CES mode watch slices INSTEAD
    of CiliumEndpoints; build the informer with ``CES_RESOURCES``.
    See operator/ces.py for the batching side).

    A slice update diffs against the previous membership so endpoints
    that left the slice are deleted, not leaked — but membership is
    tracked GLOBALLY (key -> owning slice): the operator's FCFS
    refill can migrate an endpoint between slices within one sync
    window, and whichever slice's update lands second must not tear
    down the ipcache entry the other slice still carries."""

    def __init__(self, ceps: "CiliumEndpointWatcher"):
        self.ceps = ceps
        self._members: Dict[str, Dict[str, dict]] = {}  # slice -> key -> cep
        self._owner: Dict[str, str] = {}                # key -> slice name

    def on_add(self, obj: dict) -> int:
        from ..operator.ces import expand_slice

        name = (obj.get("metadata") or {}).get("name", "")
        now = {_meta_key(cep): cep for cep in expand_slice(obj)}
        prev = self._members.get(name, {})
        n = 0
        for key, cep in prev.items():
            # delete only if no OTHER slice has since claimed the key
            if key not in now and self._owner.get(key) == name:
                self.ceps.on_delete(cep)
                del self._owner[key]
                n += 1
        for key, cep in now.items():
            self._owner[key] = name
            if prev.get(key) != cep:  # skip unchanged members
                n += self.ceps.on_add(cep)
        self._members[name] = now
        return n

    on_update = on_add

    def on_delete(self, obj: dict) -> int:
        name = (obj.get("metadata") or {}).get("name", "")
        prev = self._members.pop(name, {})
        n = 0
        for key, cep in prev.items():
            if self._owner.get(key) == name:
                self.ceps.on_delete(cep)
                del self._owner[key]
                n += 1
        return n


class EgressGatewayPolicyWatcher:
    """CiliumEgressGatewayPolicy objects -> the daemon's egress
    gateway table (reference: pkg/egressgateway — pods matching the
    policy's selector SNAT via the designated egress IP toward the
    destination CIDRs)."""

    def __init__(self, daemon):
        self.daemon = daemon

    def on_add(self, obj: dict) -> None:
        name = (obj.get("metadata") or {}).get("name", "")
        spec = obj.get("spec") or {}
        gw = spec.get("egressGateway") or {}
        eip = gw.get("egressIP")
        dests = spec.get("destinationCIDRs") or ()
        # EVERY selector entry participates (pods matching ANY of
        # them); a namespaceSelector translates to the folded
        # namespace-label prefix, same as CNP peers — an entry with
        # neither selector contributes nothing (NOT a wildcard)
        entries = []
        for sel in spec.get("selectors") or ():
            pod = sel.get("podSelector")
            nss = sel.get("namespaceSelector")
            if "podSelector" not in sel and "namespaceSelector" \
                    not in sel:
                continue  # neither key present: contributes nothing
            ml = dict((pod or {}).get("matchLabels") or {})
            me = list((pod or {}).get("matchExpressions") or ())
            for k, v in ((nss or {}).get("matchLabels") or {}).items():
                ml[f"k8s:{NS_LABELS_PREFIX}{k}"] = v
            for e in (nss or {}).get("matchExpressions") or ():
                e = dict(e)
                e["key"] = f"k8s:{NS_LABELS_PREFIX}{e.get('key', '')}"
                me.append(e)
            combined = {}
            if ml:
                combined["matchLabels"] = ml
            if me:
                combined["matchExpressions"] = me
            # an explicitly-present EMPTY podSelector ({}) is the k8s
            # match-all: the entry stays (as the wildcard selector),
            # it is NOT dropped
            entries.append(combined)
        if not (name and eip and dests and entries):
            # the spec was edited into an unusable state (cleared
            # egressIP/CIDRs/selectors): keeping the STALE rules
            # SNATing would be the opposite of the operator's edit
            if name:
                self.daemon.remove_egress_gateway(name)
            return
        try:
            self.daemon.add_egress_gateway(name, entries, dests, eip)
        except (ValueError, OverflowError) as e:
            import logging

            logging.getLogger(__name__).warning(
                "egress gateway policy %s rejected: %s", name, e)
            # fail closed for THIS policy only: drop any prior
            # version rather than keep stale rules
            self.daemon.remove_egress_gateway(name)

    on_update = on_add

    def on_delete(self, obj: dict) -> bool:
        name = (obj.get("metadata") or {}).get("name", "")
        return self.daemon.remove_egress_gateway(name)


class LocalRedirectPolicyWatcher:
    """CiliumLocalRedirectPolicy objects -> node-local service
    redirects (reference: pkg/redirectpolicy — traffic to a frontend
    address redirects to node-LOCAL backends, e.g. the node-local DNS
    cache).  The dataplane is the ordinary service DNAT path; this
    watcher resolves the backend selector over local endpoints and
    re-resolves on endpoint churn."""

    PREFIX = "lrp:"

    def __init__(self, daemon):
        self.daemon = daemon
        self._specs: Dict[str, dict] = {}  # name -> parsed spec
        daemon.endpoints.on_attach(lambda _p: self.resync())

    def on_add(self, obj: dict) -> None:
        name = _meta_key(obj)
        spec = obj.get("spec") or {}
        fe = (spec.get("redirectFrontend") or {}).get(
            "addressMatcher") or {}
        be = spec.get("redirectBackend") or {}
        ip = fe.get("ip")
        ports = [(int(p.get("port", 0)),
                  _PROTO_NUM.get(p.get("protocol", "TCP"), 6))
                 for p in fe.get("toPorts") or ()]
        be_sel = dict(be.get("localEndpointSelector") or {})
        be_ports = [int(p.get("port", 0))
                    for p in be.get("toPorts") or ()]
        if not (ip and ports and be_ports):
            # cleared/unusable spec: drop any prior version's
            # redirects instead of leaving them stale
            self.on_delete(obj)
            return
        # backend selection is scoped to the POLICY's namespace
        # (upstream pkg/redirectpolicy): a matching pod elsewhere
        # must not capture this namespace's traffic
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        ml = dict(be_sel.get("matchLabels") or {})
        ml[f"k8s:{NS_LABEL}"] = ns
        be_sel["matchLabels"] = ml
        # an update may drop frontend ports: uninstall the prior
        # version's services first, then install the new set
        if name in self._specs:
            self._uninstall(name)
        self._specs[name] = {"ip": ip, "ports": ports,
                             "selector": be_sel,
                             "be_ports": be_ports}
        self._install(name)

    on_update = on_add

    def on_delete(self, obj: dict) -> bool:
        name = _meta_key(obj)
        if self._specs.pop(name, None) is None:
            return False
        self._uninstall(name)
        return True

    def resync(self) -> None:
        """Endpoint churn: re-resolve every policy's local backends."""
        for name in list(self._specs):
            self._install(name)

    def _install(self, name: str) -> None:
        from ..policy.api import EndpointSelector

        spec = self._specs[name]
        sel = EndpointSelector.from_dict(spec["selector"])
        local = [ip for ep in self.daemon.endpoints.list()
                 if sel.matches(ep.labels)
                 for ip in ep.ips if ":" not in ip]
        existing = {s.name: s for s in self.daemon.services.list()}
        for i, (fport, proto) in enumerate(spec["ports"]):
            be_port = spec["be_ports"][min(i,
                                           len(spec["be_ports"]) - 1)]
            # proto in the key: the canonical nodelocaldns LRP fronts
            # 53/UDP AND 53/TCP — they must not collide
            svc = f"{self.PREFIX}{name}:{fport}/{proto}"
            if local:
                backends = [f"{b}:{be_port}" for b in sorted(local)]
                cur = existing.get(svc)
                if (cur is not None and cur.protocol == proto
                        and [f"{b.ip}:{b.port}" for b in cur.backends]
                        == backends):
                    continue  # unchanged: keep the compiled tensors
                self.daemon.services.upsert(
                    svc, f"{spec['ip']}:{fport}", backends,
                    protocol=proto)
            elif svc in existing:
                # no local backend (pod gone): withdraw rather than
                # blackhole via a stale address.  Only when actually
                # installed — delete() invalidates the compiled LB
                # tensors even on a no-op
                self.daemon.services.delete(svc)

    def _uninstall(self, name: str) -> None:
        for svc in [s.name for s in self.daemon.services.list()
                    if s.name.startswith(f"{self.PREFIX}{name}:")]:
            self.daemon.services.delete(svc)


class CIDRGroupWatcher:
    """CiliumCIDRGroup objects -> named CIDR sets for policy
    ``cidrGroupRef`` expansion (reference: pkg/policy CIDRGroupRef +
    the CiliumCIDRGroup CRD, cilium 1.13+).  ``on_change`` fires with
    the group name so the CNP watcher re-expands only dependents."""

    def __init__(self):
        self._groups: Dict[str, tuple] = {}
        self.on_change = None

    def _changed(self, name: str) -> None:
        if self.on_change is not None:
            self.on_change(name)

    def on_add(self, obj: dict) -> None:
        name = (obj.get("metadata") or {}).get("name", "")
        spec = obj.get("spec") or {}
        self._groups[name] = tuple(spec.get("externalCIDRs") or ())
        self._changed(name)

    on_update = on_add

    def on_delete(self, obj: dict) -> None:
        name = (obj.get("metadata") or {}).get("name", "")
        self._groups.pop(name, None)
        self._changed(name)

    def get(self, name: str):
        return self._groups.get(name)


class CiliumNodeWatcher:
    """CiliumNode objects -> the kvstore node registry (what the
    health mesh probes and the operator's dead-node sweep reads;
    reference: cilium_node.go + pkg/node/manager)."""

    def __init__(self, kv):
        from ..health import NODES_PREFIX

        self.kv = kv
        self._prefix = NODES_PREFIX

    def on_add(self, obj: dict) -> None:
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        spec = obj.get("spec") or {}
        addrs = spec.get("addresses") or ()
        ip = next((a.get("ip") for a in addrs
                   if a.get("type") == "InternalIP"), None)
        info = {"name": name,
                **({"ip": ip} if ip else {}),
                **({"pod-cidrs": spec["ipam"]["podCIDRs"]}
                   if (spec.get("ipam") or {}).get("podCIDRs") else {})}
        self.kv.update(f"{self._prefix}/{name}",
                       json.dumps(info).encode())

    on_update = on_add

    def on_delete(self, obj: dict) -> None:
        name = (obj.get("metadata") or {}).get("name", "")
        self.kv.delete(f"{self._prefix}/{name}")


class K8sWatcherHub:
    """All watchers wired to one daemon — the pkg/k8s/watchers
    K8sWatcher aggregate.  ``dispatch(kind, event, obj)`` routes a
    fake (or real) informer stream."""

    def __init__(self, daemon):
        from . import CNPWatcher

        self.services = ServiceWatcher(
            daemon.services, node_ip=daemon.config.node_ip,
            nodeport_addresses=daemon.config.nodeport_addresses,
            local_ips=lambda: {ip for ep in daemon.endpoints.list()
                               for ip in ep.ips})
        daemon.endpoints.on_attach(
            lambda _p: self.services.resync())
        self.cidr_groups = CIDRGroupWatcher()
        self.cnp = CNPWatcher(daemon.repo, services=self.services,
                              groups=self.cidr_groups)
        self.services.on_change = self.cnp.resync_services
        self.cidr_groups.on_change = self.cnp.resync_cidr_groups
        self.pods = PodWatcher(daemon)
        self.namespaces = NamespaceWatcher(self.pods)
        self.pods.namespaces = self.namespaces
        self.identities = CiliumIdentityWatcher(daemon.allocator)
        self.ceps = CiliumEndpointWatcher(daemon)
        self.ces = CiliumEndpointSliceWatcher(self.ceps)
        self.egress = EgressGatewayPolicyWatcher(daemon)
        self.lrp = LocalRedirectPolicyWatcher(daemon)
        self.nodes = CiliumNodeWatcher(daemon.kvstore)
        self._routes = {
            "CiliumNetworkPolicy": self.cnp,
            "CiliumClusterwideNetworkPolicy": self.cnp,
            "Service": _Renamed(self.services, "service"),
            "Endpoints": _Renamed(self.services, "endpoints"),
            "Pod": self.pods,
            "Namespace": self.namespaces,
            "CiliumIdentity": self.identities,
            "CiliumEndpoint": self.ceps,
            "CiliumEndpointSlice": self.ces,
            "CiliumCIDRGroup": self.cidr_groups,
            "CiliumEgressGatewayPolicy": self.egress,
            "CiliumLocalRedirectPolicy": self.lrp,
            "CiliumNode": self.nodes,
        }

    def dispatch(self, event: str, obj: dict):
        """``event`` in add|update|delete; ``obj`` any supported
        kind."""
        kind = obj.get("kind", "")
        handler = self._routes.get(kind)
        if handler is None:
            raise ValueError(f"unhandled k8s kind {kind!r}")
        return getattr(handler, f"on_{event}")(obj)

    def replay(self, events) -> int:
        """Apply a fixture stream of (event, obj) pairs."""
        n = 0
        for event, obj in events:
            self.dispatch(event, obj)
            n += 1
        return n


class _Renamed:
    """Adapts ServiceWatcher's per-kind handler names to the generic
    on_add/on_update/on_delete surface."""

    def __init__(self, inner, prefix: str):
        self._inner = inner
        self._prefix = prefix

    def __getattr__(self, name: str):
        if name.startswith("on_"):
            return getattr(self._inner,
                           f"on_{self._prefix}_{name[3:]}")
        raise AttributeError(name)
