"""k8s integration: CiliumNetworkPolicy objects -> repository rules.

Reference: upstream cilium ``pkg/k8s`` — generated CRD clients,
``apis/cilium.io/v2`` (CiliumNetworkPolicy with ``spec``/``specs``),
and the watchers translating k8s objects into ``api.Rule`` lists
(``pkg/k8s/apis/cilium.io/v2.ParseToCiliumRule``).  This module is the
translation layer alone: it accepts CNP-shaped dicts (parsed YAML/
JSON) and produces repository mutations; a fake watcher drives it in
tests the way ``pkg/k8s`` fake clientsets do (SURVEY.md §4).

Namespace semantics (mirroring ParseToCiliumRule):

- the subject endpointSelector gains
  ``k8s:io.kubernetes.pod.namespace=<ns>`` unless it already
  constrains the namespace;
- ``fromEndpoints``/``toEndpoints`` selectors likewise default to the
  policy's namespace unless they name one, carry a
  ``namespaceSelector`` (compiled to namespace-label matches — see
  ``_selector_in_namespace``), or already match namespace labels;
- every derived rule carries identity labels
  ``k8s:io.cilium.k8s.policy.name/namespace/uid`` so delete-by-labels
  removes exactly this CNP's rules.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..policy.api import Rule, rule_from_dict

NS_LABEL = "io.kubernetes.pod.namespace"
# namespace OBJECT labels folded into pod identities (reference:
# k8s.GetPodMetadata + policy.JoinPath) — what namespaceSelector
# peers compile down to
NS_LABELS_PREFIX = "io.cilium.k8s.namespace.labels."
POLICY_NAME_LABEL = "k8s:io.cilium.k8s.policy.name"
POLICY_NS_LABEL = "k8s:io.cilium.k8s.policy.namespace"
POLICY_UID_LABEL = "k8s:io.cilium.k8s.policy.uid"


def _selector_in_namespace(sel: Optional[dict], ns: str) -> dict:
    """Scope a (possibly empty) selector dict to the namespace unless
    it already constrains it.

    A ``namespaceSelector`` key (k8s NetworkPolicyPeer style) compiles
    to ``k8s:io.cilium.k8s.namespace.labels.<key>`` matches — the
    labels the pod watcher folds in from Namespace objects — and lifts
    the default same-namespace scoping (reference:
    parseNetworkPolicyPeer's namespaceSelector handling)."""
    sel = dict(sel or {})
    ml = dict(sel.get("matchLabels") or {})
    me = list(sel.get("matchExpressions") or ())
    nssel = sel.get("namespaceSelector")
    ns_constrained = nssel is not None
    if nssel:
        for k, v in (nssel.get("matchLabels") or {}).items():
            ml[f"k8s:{NS_LABELS_PREFIX}{k}"] = v
        for e in nssel.get("matchExpressions") or ():
            e = dict(e)
            e["key"] = f"k8s:{NS_LABELS_PREFIX}{e.get('key', '')}"
            me.append(e)

    def _ns_key(k: str) -> bool:
        bare = k.split(":", 1)[-1]
        return bare == NS_LABEL or bare.startswith(NS_LABELS_PREFIX)

    constrained = (ns_constrained
                   or any(_ns_key(k) for k in ml)
                   or any(_ns_key(e.get("key", "")) for e in me))
    if not constrained:
        ml[f"k8s:{NS_LABEL}"] = ns
    out: dict = {}
    if ml:
        out["matchLabels"] = ml
    if me:
        out["matchExpressions"] = me
    return out


def _scope_peers(section: dict, ns: str) -> dict:
    """Namespace the peer selectors of one ingress/egress entry."""
    out = dict(section)
    for key in ("fromEndpoints", "toEndpoints"):
        if key in out and out[key]:
            out[key] = [_selector_in_namespace(s, ns) for s in out[key]]
    return out


def rules_from_cnp(obj: dict) -> List[Rule]:
    """One CiliumNetworkPolicy object (parsed YAML/JSON) -> rules.

    Accepts ``spec`` (one rule) or ``specs`` (several); both error if
    absent, matching upstream sanitization."""
    kind = obj.get("kind", "")
    if kind not in ("CiliumNetworkPolicy", "CiliumClusterwideNetworkPolicy"):
        raise ValueError(f"not a CNP object: kind={kind!r}")
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    if not name:
        raise ValueError("CNP metadata.name is required")
    ns = meta.get("namespace", "default")
    clusterwide = kind == "CiliumClusterwideNetworkPolicy"
    specs = []
    if obj.get("spec"):
        specs.append(obj["spec"])
    specs.extend(obj.get("specs") or ())
    if not specs:
        raise ValueError("CNP needs spec or specs")

    derived = [f"{POLICY_NAME_LABEL}={name}"]
    if not clusterwide:
        derived.append(f"{POLICY_NS_LABEL}={ns}")
    if meta.get("uid"):
        derived.append(f"{POLICY_UID_LABEL}={meta['uid']}")

    rules = []
    for spec in specs:
        d = dict(spec)
        if not clusterwide:
            sel_key = ("endpointSelector" if "endpointSelector" in d
                       else "nodeSelector" if "nodeSelector" in d
                       else "endpointSelector")
            d[sel_key] = _selector_in_namespace(d.get(sel_key), ns)
            for section in ("ingress", "ingressDeny", "egress",
                            "egressDeny"):
                if d.get(section):
                    d[section] = [_scope_peers(s, ns)
                                  for s in d[section]]
        d["labels"] = list(d.get("labels") or ()) + derived
        if not d.get("description"):
            d["description"] = f"cnp:{ns}/{name}" if not clusterwide \
                else f"ccnp:{name}"
        rules.append(rule_from_dict(d))
    return rules


def _expand_to_services(section: dict, services_view) -> dict:
    """One egress entry: ``toServices`` -> derived ``toCIDRSet``
    (reference: pkg/k8s TranslateToServicesRule rewrites the rule
    in place against the service/endpoints caches).

    An expansion yielding NO peers inserts the unmatchable
    ``0.0.0.0/32`` instead of leaving the entry peer-less — a
    peer-less egress entry is an L3 wildcard, and a vanished service
    must fail closed, not open."""
    tos = section.get("toServices")
    if not tos:
        return section
    out = dict(section)
    del out["toServices"]
    peers: set = set()
    for ent in tos:
        ks = ent.get("k8sService") or {}
        sel = ent.get("k8sServiceSelector") or {}
        if ks:
            peers |= services_view.service_peer_ips(
                ks.get("namespace", "default"),
                ks.get("serviceName", ""))
        elif sel:
            peers |= services_view.select_peer_ips(
                dict(sel.get("selector") or {}), sel.get("namespace"))
    cidrs = list(out.get("toCIDRSet") or ())
    if peers:
        cidrs.extend({"cidr": (f"{ip}/32" if ":" not in ip
                               else f"{ip}/128")}
                     for ip in sorted(peers))
    else:
        cidrs.append({"cidr": "0.0.0.0/32"})  # matches nothing real
    out["toCIDRSet"] = cidrs
    return out


def expand_cnp_services(obj: dict, services_view) -> dict:
    """Deep-copy a CNP, expanding every egress/egressDeny entry's
    ``toServices`` against the live service view.  Objects without
    toServices return unchanged (same identity — callers use that to
    skip re-imports)."""
    if not cnp_has_to_services(obj):
        return obj
    import copy
    obj = copy.deepcopy(obj)
    specs = ([obj["spec"]] if obj.get("spec") else []) + \
        list(obj.get("specs") or ())
    for spec in specs:
        for section in ("egress", "egressDeny"):
            if spec.get(section):
                spec[section] = [
                    _expand_to_services(s, services_view)
                    for s in spec[section]]
    return obj


def cnp_cidr_group_refs(obj: dict) -> set:
    """Names of every CiliumCIDRGroup the CNP references via
    fromCIDRSet/toCIDRSet ``cidrGroupRef`` entries."""
    refs = set()
    specs = ([obj.get("spec")] if obj.get("spec") else []) + \
        list(obj.get("specs") or ())
    for spec in specs:
        for section in ("ingress", "ingressDeny", "egress",
                        "egressDeny"):
            for e in spec.get(section) or ():
                for key in ("fromCIDRSet", "toCIDRSet"):
                    for c in e.get(key) or ():
                        if isinstance(c, dict) and c.get("cidrGroupRef"):
                            refs.add(c["cidrGroupRef"])
    return refs


def expand_cnp_cidr_groups(obj: dict, groups) -> dict:
    """Deep-copy a CNP, replacing ``cidrGroupRef`` entries with the
    referenced group's CIDRs (reference: pkg/policy CIDRGroupRef
    resolution against CiliumCIDRGroup.spec.externalCIDRs).  A ref to
    a MISSING/empty group expands to the unmatchable ``0.0.0.0/32``
    — fail closed, never widen."""
    if not cnp_cidr_group_refs(obj):
        return obj
    import copy
    obj = copy.deepcopy(obj)
    specs = ([obj["spec"]] if obj.get("spec") else []) + \
        list(obj.get("specs") or ())
    for spec in specs:
        for section in ("ingress", "ingressDeny", "egress",
                        "egressDeny"):
            for e in spec.get(section) or ():
                for key in ("fromCIDRSet", "toCIDRSet"):
                    if not e.get(key):
                        continue
                    out = []
                    for c in e[key]:
                        if not (isinstance(c, dict)
                                and c.get("cidrGroupRef")):
                            out.append(c)
                            continue
                        cidrs = groups.get(c["cidrGroupRef"]) or ()
                        exc = list(c.get("except") or ())
                        if cidrs:
                            # the entry's 'except' carve-outs apply to
                            # every expanded CIDR — dropping them
                            # would WIDEN the policy
                            out.extend(
                                {"cidr": x,
                                 **({"except": exc} if exc else {})}
                                for x in cidrs)
                        else:
                            out.append({"cidr": "0.0.0.0/32"})
                    e[key] = out
    return obj


def cnp_has_to_services(obj: dict) -> bool:
    specs = ([obj.get("spec")] if obj.get("spec") else []) + \
        list(obj.get("specs") or ())
    return any(e.get("toServices")
               for spec in specs
               for section in ("egress", "egressDeny")
               for e in (spec.get(section) or ()))


def cnp_identity_labels(obj: dict) -> List[str]:
    """The derived labels identifying one CNP's rules (for delete)."""
    meta = obj.get("metadata") or {}
    out = [f"{POLICY_NAME_LABEL}={meta.get('name', '')}"]
    if obj.get("kind") != "CiliumClusterwideNetworkPolicy":
        out.append(
            f"{POLICY_NS_LABEL}={meta.get('namespace', 'default')}")
    return out


class CNPWatcher:
    """The watcher half: CNP add/update/delete events -> repository
    mutations (reference: pkg/k8s/watchers cilium_network_policy.go).
    Drive it from a fake event stream in tests, or a real informer in
    deployment.

    ``services`` (a ServiceWatcher, optional) enables ``toServices``
    egress entries: they expand to the referenced services' peer IPs
    at import, and :meth:`resync_services` (wired to service/
    endpoints churn by the hub) re-expands affected CNPs — skipping
    the repository round-trip when the expansion is unchanged.
    ``groups`` (a CIDRGroupWatcher, optional) likewise enables
    ``cidrGroupRef`` entries (CiliumCIDRGroup expansion), re-expanded
    via :meth:`resync_cidr_groups`."""

    def __init__(self, repo, services=None, groups=None):
        self.repo = repo
        self.services = services
        self.groups = groups
        # CNPs carrying toServices:
        #   key -> (raw obj, last expansion, named-ref keys, has_sel)
        # named-ref keys are the "<ns>/<name>" services the CNP names
        # via k8sService; has_sel marks k8sServiceSelector use (those
        # depend on EVERY service's labels, so any change re-expands)
        self._svc_cnps: Dict[str, tuple] = {}
        # CNPs carrying cidrGroupRef: key -> (raw, last, group names)
        self._group_cnps: Dict[str, tuple] = {}

    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        # kind-qualified: a CCNP and a default-ns CNP may share a name
        kind = "ccnp" if obj.get("kind") == \
            "CiliumClusterwideNetworkPolicy" else "cnp"
        return (f"{kind}:{meta.get('namespace', 'default')}"
                f"/{meta.get('name')}")

    @staticmethod
    def _service_refs(obj: dict) -> tuple:
        """-> (named '<ns>/<name>' keys, any-selector flag)."""
        named, has_sel = set(), False
        specs = ([obj.get("spec")] if obj.get("spec") else []) + \
            list(obj.get("specs") or ())
        for spec in specs:
            for section in ("egress", "egressDeny"):
                for e in spec.get(section) or ():
                    for ent in e.get("toServices") or ():
                        ks = ent.get("k8sService") or {}
                        if ks:
                            named.add(
                                f"{ks.get('namespace', 'default')}"
                                f"/{ks.get('serviceName', '')}")
                        elif ent.get("k8sServiceSelector"):
                            has_sel = True
        return named, has_sel

    def _expand(self, obj: dict) -> dict:
        key = self._key(obj)
        has_svc = cnp_has_to_services(obj)
        grefs = cnp_cidr_group_refs(obj)
        if has_svc and self.services is None:
            raise ValueError("toServices needs a service view "
                             "(CNPWatcher(services=...))")
        if grefs and self.groups is None:
            raise ValueError("cidrGroupRef needs a CiliumCIDRGroup "
                             "view (CNPWatcher(groups=...))")
        expanded = obj
        if has_svc:
            expanded = expand_cnp_services(expanded, self.services)
        if grefs:
            expanded = expand_cnp_cidr_groups(expanded, self.groups)
        # both trackers record the FULLY expanded form: the
        # unchanged-skip in either resync compares against
        # _reexpand's full composition
        if has_svc:
            named, has_sel = self._service_refs(obj)
            self._svc_cnps[key] = (obj, expanded, named, has_sel)
        else:
            self._svc_cnps.pop(key, None)
        if grefs:
            self._group_cnps[key] = (obj, expanded, grefs)
        else:
            self._group_cnps.pop(key, None)
        return expanded

    def on_add(self, obj: dict) -> int:
        return self.repo.add_list(rules_from_cnp(self._expand(obj)))

    def on_update(self, obj: dict) -> int:
        expanded = self._expand(obj)
        self.repo.delete_by_labels(cnp_identity_labels(obj))
        return self.repo.add_list(rules_from_cnp(expanded))

    def on_delete(self, obj: dict) -> int:
        self._svc_cnps.pop(self._key(obj), None)
        self._group_cnps.pop(self._key(obj), None)
        return self.repo.delete_by_labels(cnp_identity_labels(obj))

    def resync_services(self, changed: str = None) -> int:
        """Service/Endpoints churn: re-expand the toServices CNPs
        that could see ``changed`` ("<ns>/<name>"; None = all) and
        whose derived peer set actually moved.  Returns CNPs
        re-imported."""
        n = 0
        for key, (raw, last, named, has_sel) in list(
                self._svc_cnps.items()):
            if changed is not None and not has_sel \
                    and changed not in named:
                continue
            fresh = self._reexpand(raw)
            if fresh != last:
                self._svc_cnps[key] = (raw, fresh, named, has_sel)
                self.repo.delete_by_labels(cnp_identity_labels(raw))
                self.repo.add_list(rules_from_cnp(fresh))
                n += 1
        return n

    def _reexpand(self, raw: dict) -> dict:
        """Full re-expansion (services THEN groups — the import-time
        composition order), keeping the group tracking in step when a
        service-driven resync moves a CNP that also carries refs."""
        fresh = raw
        if cnp_has_to_services(raw) and self.services is not None:
            fresh = expand_cnp_services(fresh, self.services)
        grefs = cnp_cidr_group_refs(raw)
        if grefs and self.groups is not None:
            fresh = expand_cnp_cidr_groups(fresh, self.groups)
            self._group_cnps[self._key(raw)] = (raw, fresh, grefs)
        return fresh

    def resync_cidr_groups(self, changed: str = None) -> int:
        """CiliumCIDRGroup churn: re-expand CNPs referencing the
        changed group (None = all)."""
        n = 0
        for key, (raw, last, grefs) in list(self._group_cnps.items()):
            if changed is not None and changed not in grefs:
                continue
            fresh = self._reexpand(raw)
            if fresh != last:
                self._group_cnps[key] = (raw, fresh, grefs)
                if key in self._svc_cnps:
                    named, has_sel = self._service_refs(raw)
                    self._svc_cnps[key] = (raw, fresh, named, has_sel)
                self.repo.delete_by_labels(cnp_identity_labels(raw))
                self.repo.add_list(rules_from_cnp(fresh))
                n += 1
        return n
