"""k8s integration: CiliumNetworkPolicy objects -> repository rules.

Reference: upstream cilium ``pkg/k8s`` — generated CRD clients,
``apis/cilium.io/v2`` (CiliumNetworkPolicy with ``spec``/``specs``),
and the watchers translating k8s objects into ``api.Rule`` lists
(``pkg/k8s/apis/cilium.io/v2.ParseToCiliumRule``).  This module is the
translation layer alone: it accepts CNP-shaped dicts (parsed YAML/
JSON) and produces repository mutations; a fake watcher drives it in
tests the way ``pkg/k8s`` fake clientsets do (SURVEY.md §4).

Namespace semantics (mirroring ParseToCiliumRule):

- the subject endpointSelector gains
  ``k8s:io.kubernetes.pod.namespace=<ns>`` unless it already
  constrains the namespace;
- ``fromEndpoints``/``toEndpoints`` selectors likewise default to the
  policy's namespace unless they name one, carry a
  ``namespaceSelector`` (compiled to namespace-label matches — see
  ``_selector_in_namespace``), or already match namespace labels;
- every derived rule carries identity labels
  ``k8s:io.cilium.k8s.policy.name/namespace/uid`` so delete-by-labels
  removes exactly this CNP's rules.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..policy.api import Rule, rule_from_dict

NS_LABEL = "io.kubernetes.pod.namespace"
# namespace OBJECT labels folded into pod identities (reference:
# k8s.GetPodMetadata + policy.JoinPath) — what namespaceSelector
# peers compile down to
NS_LABELS_PREFIX = "io.cilium.k8s.namespace.labels."
POLICY_NAME_LABEL = "k8s:io.cilium.k8s.policy.name"
POLICY_NS_LABEL = "k8s:io.cilium.k8s.policy.namespace"
POLICY_UID_LABEL = "k8s:io.cilium.k8s.policy.uid"


def _selector_in_namespace(sel: Optional[dict], ns: str) -> dict:
    """Scope a (possibly empty) selector dict to the namespace unless
    it already constrains it.

    A ``namespaceSelector`` key (k8s NetworkPolicyPeer style) compiles
    to ``k8s:io.cilium.k8s.namespace.labels.<key>`` matches — the
    labels the pod watcher folds in from Namespace objects — and lifts
    the default same-namespace scoping (reference:
    parseNetworkPolicyPeer's namespaceSelector handling)."""
    sel = dict(sel or {})
    ml = dict(sel.get("matchLabels") or {})
    me = list(sel.get("matchExpressions") or ())
    nssel = sel.get("namespaceSelector")
    ns_constrained = nssel is not None
    if nssel:
        for k, v in (nssel.get("matchLabels") or {}).items():
            ml[f"k8s:{NS_LABELS_PREFIX}{k}"] = v
        for e in nssel.get("matchExpressions") or ():
            e = dict(e)
            e["key"] = f"k8s:{NS_LABELS_PREFIX}{e.get('key', '')}"
            me.append(e)

    def _ns_key(k: str) -> bool:
        bare = k.split(":", 1)[-1]
        return bare == NS_LABEL or bare.startswith(NS_LABELS_PREFIX)

    constrained = (ns_constrained
                   or any(_ns_key(k) for k in ml)
                   or any(_ns_key(e.get("key", "")) for e in me))
    if not constrained:
        ml[f"k8s:{NS_LABEL}"] = ns
    out: dict = {}
    if ml:
        out["matchLabels"] = ml
    if me:
        out["matchExpressions"] = me
    return out


def _scope_peers(section: dict, ns: str) -> dict:
    """Namespace the peer selectors of one ingress/egress entry."""
    out = dict(section)
    for key in ("fromEndpoints", "toEndpoints"):
        if key in out and out[key]:
            out[key] = [_selector_in_namespace(s, ns) for s in out[key]]
    return out


def rules_from_cnp(obj: dict) -> List[Rule]:
    """One CiliumNetworkPolicy object (parsed YAML/JSON) -> rules.

    Accepts ``spec`` (one rule) or ``specs`` (several); both error if
    absent, matching upstream sanitization."""
    kind = obj.get("kind", "")
    if kind not in ("CiliumNetworkPolicy", "CiliumClusterwideNetworkPolicy"):
        raise ValueError(f"not a CNP object: kind={kind!r}")
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    if not name:
        raise ValueError("CNP metadata.name is required")
    ns = meta.get("namespace", "default")
    clusterwide = kind == "CiliumClusterwideNetworkPolicy"
    specs = []
    if obj.get("spec"):
        specs.append(obj["spec"])
    specs.extend(obj.get("specs") or ())
    if not specs:
        raise ValueError("CNP needs spec or specs")

    derived = [f"{POLICY_NAME_LABEL}={name}"]
    if not clusterwide:
        derived.append(f"{POLICY_NS_LABEL}={ns}")
    if meta.get("uid"):
        derived.append(f"{POLICY_UID_LABEL}={meta['uid']}")

    rules = []
    for spec in specs:
        d = dict(spec)
        if not clusterwide:
            sel_key = ("endpointSelector" if "endpointSelector" in d
                       else "nodeSelector" if "nodeSelector" in d
                       else "endpointSelector")
            d[sel_key] = _selector_in_namespace(d.get(sel_key), ns)
            for section in ("ingress", "ingressDeny", "egress",
                            "egressDeny"):
                if d.get(section):
                    d[section] = [_scope_peers(s, ns)
                                  for s in d[section]]
        d["labels"] = list(d.get("labels") or ()) + derived
        if not d.get("description"):
            d["description"] = f"cnp:{ns}/{name}" if not clusterwide \
                else f"ccnp:{name}"
        rules.append(rule_from_dict(d))
    return rules


def cnp_identity_labels(obj: dict) -> List[str]:
    """The derived labels identifying one CNP's rules (for delete)."""
    meta = obj.get("metadata") or {}
    out = [f"{POLICY_NAME_LABEL}={meta.get('name', '')}"]
    if obj.get("kind") != "CiliumClusterwideNetworkPolicy":
        out.append(
            f"{POLICY_NS_LABEL}={meta.get('namespace', 'default')}")
    return out


class CNPWatcher:
    """The watcher half: CNP add/update/delete events -> repository
    mutations (reference: pkg/k8s/watchers cilium_network_policy.go).
    Drive it from a fake event stream in tests, or a real informer in
    deployment."""

    def __init__(self, repo):
        self.repo = repo

    def on_add(self, obj: dict) -> int:
        return self.repo.add_list(rules_from_cnp(obj))

    def on_update(self, obj: dict) -> int:
        self.repo.delete_by_labels(cnp_identity_labels(obj))
        return self.repo.add_list(rules_from_cnp(obj))

    def on_delete(self, obj: dict) -> int:
        return self.repo.delete_by_labels(cnp_identity_labels(obj))
