"""Minimal k8s informer client: list + streaming watch with resume.

Reference: upstream cilium's ``pkg/k8s`` informers (client-go
reflectors): LIST a resource for its current state + resourceVersion,
then WATCH from that version as a chunked HTTP stream of
``{"type": ADDED|MODIFIED|DELETED|BOOKMARK|ERROR, "object": {...}}``
lines, resuming from the last seen resourceVersion on disconnect and
re-LISTing on 410 Gone (compacted history).  Events drive
:class:`~cilium_tpu.k8s.watchers.K8sWatcherHub` — the translation
layer that was previously fixture-driven only — so an agent can join
a real (or stub) apiserver end to end.

Scope notes (deliberate): no client-side caching beyond the hub's own
state (handlers are idempotent, re-LIST re-delivers as adds), bearer
token + https optional, one thread per resource (nine resources — the
reflector-per-resource shape)."""

from __future__ import annotations

import json
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# kind -> collection path (cluster-wide LIST/WATCH)
DEFAULT_RESOURCES: Tuple[Tuple[str, str], ...] = (
    ("Namespace", "/api/v1/namespaces"),
    ("Pod", "/api/v1/pods"),
    ("Service", "/api/v1/services"),
    ("Endpoints", "/api/v1/endpoints"),
    ("CiliumNetworkPolicy", "/apis/cilium.io/v2/ciliumnetworkpolicies"),
    ("CiliumClusterwideNetworkPolicy",
     "/apis/cilium.io/v2/ciliumclusterwidenetworkpolicies"),
    ("CiliumIdentity", "/apis/cilium.io/v2/ciliumidentities"),
    ("CiliumEndpoint", "/apis/cilium.io/v2/ciliumendpoints"),
    ("CiliumEgressGatewayPolicy",
     "/apis/cilium.io/v2/ciliumegressgatewaypolicies"),
    ("CiliumLocalRedirectPolicy",
     "/apis/cilium.io/v2/ciliumlocalredirectpolicies"),
    ("CiliumNode", "/apis/cilium.io/v2/ciliumnodes"),
)

# CES mode (upstream --enable-cilium-endpoint-slice): agents watch
# operator-batched CiliumEndpointSlices INSTEAD of per-pod
# CiliumEndpoints — both kinds feed the same CiliumEndpointWatcher
# state, so watching both would let a slice shrink clobber an entry a
# live direct CEP still backs (and vice versa).
CES_RESOURCES: Tuple[Tuple[str, str], ...] = tuple(
    r for r in DEFAULT_RESOURCES if r[0] != "CiliumEndpoint"
) + (("CiliumEndpointSlice",
      "/apis/cilium.io/v2alpha1/ciliumendpointslices"),)

# what the OPERATOR's informer watches to drive CES batching (its
# "hub" is a CESBatcher — the operator is the only CEP consumer in
# CES mode; reference: operator/pkg/ciliumendpointslice informer)
OPERATOR_CES_RESOURCES: Tuple[Tuple[str, str], ...] = (
    ("CiliumEndpoint", "/apis/cilium.io/v2/ciliumendpoints"),
)

_EVENT_MAP = {"ADDED": "add", "MODIFIED": "update", "DELETED": "delete"}


class Reflector:
    """LIST + WATCH one resource, dispatching into the hub."""

    def __init__(self, base_url: str, kind: str, path: str,
                 dispatch: Callable[[str, dict], None],
                 token: Optional[str] = None,
                 verify_tls: bool = True,
                 backoff: float = 0.2, max_backoff: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.kind = kind
        self.path = path
        self.dispatch = dispatch
        self.token = token
        self._ctx = None
        if self.base_url.startswith("https") and not verify_tls:
            self._ctx = ssl.create_default_context()
            self._ctx.check_hostname = False
            self._ctx.verify_mode = ssl.CERT_NONE
        self.backoff = backoff
        self.max_backoff = max_backoff
        self.resource_version: Optional[str] = None
        self.lists = 0  # re-LIST count (observability/tests)
        self.events = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- transport -----------------------------------------------------
    def _open(self, url: str, timeout: Optional[float]):
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=timeout,
                                      context=self._ctx)

    def _list(self) -> None:
        with self._open(self.base_url + self.path, timeout=10) as resp:
            body = json.loads(resp.read())
        self.resource_version = str(
            (body.get("metadata") or {}).get("resourceVersion", "0"))
        self.lists += 1
        for item in body.get("items") or ():
            item.setdefault("kind", self.kind)
            self.dispatch("add", item)

    def _watch_once(self) -> None:
        url = (f"{self.base_url}{self.path}?watch=true"
               f"&resourceVersion={self.resource_version}"
               "&allowWatchBookmarks=true")
        # no read timeout: the server holds the stream open; the stop
        # path closes via a short timeout + retry loop instead
        with self._open(url, timeout=30) as resp:
            for line in resp:
                if self._stop.is_set():
                    return
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                typ = ev.get("type", "")
                obj = ev.get("object") or {}
                rv = (obj.get("metadata") or {}).get("resourceVersion")
                if typ == "ERROR":
                    code = (obj.get("code")
                            or (obj.get("status") or {}).get("code"))
                    if code == 410:  # history compacted: re-LIST
                        self.resource_version = None
                        return
                    continue
                if rv is not None:
                    self.resource_version = str(rv)
                if typ == "BOOKMARK":
                    continue
                event = _EVENT_MAP.get(typ)
                if event is None:
                    continue
                obj.setdefault("kind", self.kind)
                self.events += 1
                self.dispatch(event, obj)

    # -- lifecycle -----------------------------------------------------
    def run(self) -> None:
        delay = self.backoff
        while not self._stop.is_set():
            try:
                if self.resource_version is None:
                    self._list()
                self._watch_once()
                delay = self.backoff  # clean return: immediate resume
            except (urllib.error.URLError, urllib.error.HTTPError,
                    ConnectionError, TimeoutError, OSError,
                    ValueError) as exc:
                if self._stop.is_set():
                    return
                if getattr(exc, "code", None) == 410:
                    self.resource_version = None
                    continue
                time.sleep(delay)
                delay = min(delay * 2, self.max_backoff)

    def start(self) -> "Reflector":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"reflector-{self.kind}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()


class K8sClient:
    """The agent's apiserver attachment: one reflector per resource,
    all feeding ``hub.dispatch`` (reference: the k8s watcher startup in
    daemon init — SURVEY §3.1 "k8s watchers start")."""

    def __init__(self, base_url: str, hub,
                 token: Optional[str] = None,
                 resources: Sequence[Tuple[str, str]] = DEFAULT_RESOURCES,
                 verify_tls: bool = True):
        self._lock = threading.Lock()
        self.hub = hub
        self.reflectors = [
            Reflector(base_url, kind, path, self._dispatch, token=token,
                      verify_tls=verify_tls)
            for kind, path in resources
        ]

    def _dispatch(self, event: str, obj: dict) -> None:
        # the hub's handlers mutate daemon state; serialize across
        # reflector threads (client-go delivers per-informer serially;
        # cross-informer races are ours to exclude)
        with self._lock:
            self.hub.dispatch(event, obj)

    def start(self) -> "K8sClient":
        for r in self.reflectors:
            r.start()
        return self

    def stop(self) -> None:
        for r in self.reflectors:
            r.stop()

    def status(self) -> List[dict]:
        return [{
            "kind": r.kind,
            "resourceVersion": r.resource_version,
            "lists": r.lists,
            "events": r.events,
        } for r in self.reflectors]
