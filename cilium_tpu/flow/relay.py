"""Relay: cluster-wide flow aggregation across agents.

Reference: upstream ``hubble-relay`` — fans GetFlows out to every
node's hubble server and merges the streams time-ordered, stamping
each flow with its node of origin.  Peers here are anything with the
Observer ``get_flows`` protocol: in-process Observers, or
:class:`cilium_tpu.flow.grpc_server.ObserverClient` handles to remote
agents' gRPC servers.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .flow import Flow
from .observer import FlowFilter


class Relay:
    def __init__(self, peers: Dict[str, object]):
        """``peers``: node name -> Observer-protocol object."""
        self.peers = dict(peers)

    def add_peer(self, name: str, obs) -> None:
        self.peers[name] = obs

    def remove_peer(self, name: str) -> None:
        self.peers.pop(name, None)

    def get_flows(self, filters: Sequence[FlowFilter] = (),
                  number: int = 100,
                  oldest_first: bool = False,
                  blacklist: Sequence[FlowFilter] = ()) -> List[dict]:
        """Merged, time-ordered flows as dicts with ``node_name``
        stamped (relay adds the node dimension the per-agent API
        lacks)."""
        merged: List[dict] = []
        for name, obs in self.peers.items():
            for f in obs.get_flows(filters=filters, number=number,
                                   oldest_first=oldest_first,
                                   blacklist=blacklist):
                d = f.to_dict() if isinstance(f, Flow) else dict(f)
                d["node_name"] = name
                merged.append(d)
        merged.sort(key=lambda d: d.get("time", 0.0),
                    reverse=not oldest_first)
        return merged[:number]

    def nodes(self) -> List[dict]:
        """The GetNodes surface (``hubble list nodes``): per-peer
        availability + flow counts; a dead peer reports unavailable
        instead of failing the listing."""
        out = []
        for name, obs in sorted(self.peers.items()):
            try:
                st = (obs.server_status()
                      if hasattr(obs, "server_status") else {})
                n = st.get("num_flows",
                           len(obs) if hasattr(obs, "__len__") else 0)
                out.append({"name": name, "state": "connected",
                            "num_flows": int(n),
                            "seen_flows": int(st.get("seen_flows", n))})
            except Exception as e:
                out.append({"name": name, "state": "unavailable",
                            "error": str(e)[:100]})
        return out

    def server_status(self) -> dict:
        """hubble-relay ServerStatus: aggregate over peers."""
        total = seen = 0
        nodes = []
        for name, obs in self.peers.items():
            try:
                n = len(obs) if hasattr(obs, "__len__") else 0
                s = getattr(obs, "seq", n)
                nodes.append({"name": name, "flows": n, "seen": s})
                total += n
                seen += s
            except Exception as e:  # a dead peer must not kill status
                nodes.append({"name": name, "error": str(e)[:100]})
        return {"num_flows": total, "seen_flows": seen,
                "num_connected_nodes": len(self.peers), "nodes": nodes}
