"""Flow metrics: Prometheus-style counters from the flow stream.

Reference: upstream cilium ``pkg/hubble/metrics`` — pluggable handlers
("flow", "drop", "port-distribution", "policy-verdict", ...) turning
flows into Prometheus series, plus ``pkg/metrics``' agent registry.
Vectorized: handlers aggregate whole EventBatches with numpy bincount,
not per-flow callbacks.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Tuple

import numpy as np

from ..core.packets import COL_DIR, COL_DPORT, COL_PROTO
from ..monitor.api import MSG_DROP, MSG_POLICY_VERDICT, EventBatch
from ..policy.mapstate import VERDICT_ALLOW, VERDICT_REDIRECT


class FlowMetrics:
    """Aggregates the monitor stream (a MonitorAgent consumer)."""

    def __init__(self):
        self.flows_total: Dict[Tuple[str, str], int] = defaultdict(int)
        self.drops_total: Dict[Tuple[int, str], int] = defaultdict(int)
        self.port_distribution: Dict[Tuple[int, int], int] = defaultdict(int)
        self.policy_verdicts: Dict[Tuple[str, str], int] = defaultdict(int)

    def consume(self, batch: EventBatch) -> None:
        if len(batch) == 0:
            return
        dirs = batch.hdr[:, COL_DIR]
        fwd = (batch.verdict == VERDICT_ALLOW) | \
              (batch.verdict == VERDICT_REDIRECT)
        for d in (0, 1):
            dname = "ingress" if d == 0 else "egress"
            sel = dirs == d
            self.flows_total[("forwarded", dname)] += int((fwd & sel).sum())
            self.flows_total[("dropped", dname)] += int((~fwd & sel).sum())
        dropped = batch.msg_type == MSG_DROP
        if dropped.any():
            for d in (0, 1):
                dname = "ingress" if d == 0 else "egress"
                sel = dropped & (dirs == d)
                if not sel.any():
                    continue
                reasons, counts = np.unique(batch.reason[sel],
                                            return_counts=True)
                for r, n in zip(reasons.tolist(), counts.tolist()):
                    self.drops_total[(int(r), dname)] += n
        # vectorized (proto, dport) histogram: one bincount per batch
        key = (batch.hdr[:, COL_PROTO].astype(np.int64) << 16) \
            | batch.hdr[:, COL_DPORT].astype(np.int64)
        uniq, counts = np.unique(key, return_counts=True)
        for k, n in zip(uniq.tolist(), counts.tolist()):
            self.port_distribution[(k >> 16, k & 0xFFFF)] += n
        verdict_ev = batch.msg_type == MSG_POLICY_VERDICT
        if verdict_ev.any():
            allowed = fwd & verdict_ev
            self.policy_verdicts[("allowed", "L3_L4")] += int(allowed.sum())
            self.policy_verdicts[("denied", "L3_L4")] += int(
                (verdict_ev & ~fwd).sum())

    def render(self) -> str:
        """Prometheus text exposition of the flow series.  Inside an
        agent the daemon's unified registry serves these (the
        /metrics endpoint body); this standalone render exists for
        tooling that holds a bare FlowMetrics — it goes through the
        SAME registry renderer, so exposition text is built in
        exactly one module (the check_metrics_registry lint)."""
        from ..obs.registry import MetricsRegistry, register_flow_metrics

        reg = MetricsRegistry()
        register_flow_metrics(reg, self)
        return reg.render()
