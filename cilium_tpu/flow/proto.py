"""Hand-encoded protobuf wire format for the Hubble Observer API.

Reference: upstream ``api/v1/flow/flow.proto`` (message ``Flow`` and
friends) and ``api/v1/observer/observer.proto`` (``GetFlowsRequest``,
``GetFlowsResponse``).  The environment has no protoc-gen plugins, so
the wire format is encoded by hand from the proto definitions: field
numbers and enum values below are flow.proto's (provenance caveat:
the reference mount is empty, so they are transcribed from the
upstream schema rather than cited to a file; the golden test pins the
resulting bytes).

Only the subset of fields this framework populates is encoded —
protobuf readers skip unknown fields and default missing ones, so a
stock hubble CLI can consume the stream.

Wire-format primitives implemented: varint (wire type 0) and
length-delimited (wire type 2) — flow.proto uses nothing else.
:func:`decode_message` is a schema-less decoder used by the golden
round-trip test and the binary client.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from .flow import Flow, FlowEndpoint

# --- primitives ------------------------------------------------------


def encode_varint(n: int) -> bytes:
    if n < 0:  # proto int32/enum negatives ride as 10-byte varints
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, off: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = data[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _tag(field: int, wire_type: int) -> bytes:
    return encode_varint((field << 3) | wire_type)


def _varint_field(field: int, value: int) -> bytes:
    if not value:
        return b""  # proto3 default elision
    return _tag(field, 0) + encode_varint(value)


def _bytes_field(field: int, value: bytes) -> bytes:
    if not value:
        return b""
    return _tag(field, 2) + encode_varint(len(value)) + value


def _str_field(field: int, value: str) -> bytes:
    return _bytes_field(field, value.encode())


def _msg_field(field: int, payload: bytes) -> bytes:
    """Submessage: encoded even when empty IF the caller passes
    non-None (presence carries meaning for message fields)."""
    return _tag(field, 2) + encode_varint(len(payload)) + payload


def decode_message(data: bytes) -> Dict[int, list]:
    """Schema-less decode: {field: [value, ...]} where value is an int
    (wire type 0) or bytes (wire type 2).  Fixed32/64 are not used by
    flow.proto and raise."""
    out: Dict[int, list] = {}
    off = 0
    while off < len(data):
        key, off = decode_varint(data, off)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, off = decode_varint(data, off)
        elif wt == 2:
            ln, off = decode_varint(data, off)
            if off + ln > len(data):
                # Python slicing would silently truncate: a corrupt
                # request must error, not decode to partial filters
                raise ValueError("truncated length-delimited field")
            v = data[off:off + ln]
            off += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(field, []).append(v)
    return out


# --- flow.proto enums ------------------------------------------------

# enum Verdict
VERDICT_WIRE = {1: 1, 3: 5, 2: 2, 0: 2}  # ALLOW->FORWARDED,
# REDIRECT->REDIRECTED, DENY/DEFAULT_DENY->DROPPED

# wire Verdict -> internal verdict codes (one wire DROPPED covers two
# internal codes; binary filters expand through this, since FlowFilter
# compares against INTERNAL codes)
VERDICT_WIRE_TO_INTERNAL = {1: (1,), 2: (0, 2), 5: (3,)}

# enum DropReason: internal reason codes -> flow.proto values.  The
# reference's bpf DROP_* space starts at 130; POLICY_DENIED is 133.
# Reasons without an upstream value travel as 0 (UNKNOWN) in the
# field-25 ENUM — but the NATIVE code always rides field 3 (the
# deprecated uint32 ``drop_reason``, numerically below the bpf
# DROP_* floor so it cannot collide with an upstream value), and
# :func:`decode_flow` prefers it, so relay-merged flows decoded from
# the binary wire keep full drop-reason fidelity (the DIVERGENCES
# #15 caveat, closed in ISSUE 14).  A stock hubble reader that only
# looks at field 25 still sees a valid (if generic) enum value.
DROP_REASON_WIRE = {1: 133, 2: 133, 3: 0, 4: 0, 5: 0, 6: 0, 7: 0,
                    8: 0, 9: 0, 10: 0, 11: 0, 12: 0}

# enum FlowType
FLOW_TYPE_L3_L4 = 1
FLOW_TYPE_L7 = 2

# enum TrafficDirection
TRAFFIC_INGRESS = 1
TRAFFIC_EGRESS = 2

# enum IPVersion
IP_V4 = 1
IP_V6 = 2

_TCP_FLAG_FIELDS = (  # message TCPFlags field numbers
    ("FIN", 1, 0x01), ("SYN", 2, 0x02), ("RST", 3, 0x04),
    ("PSH", 4, 0x08), ("ACK", 5, 0x10), ("URG", 6, 0x20),
)


# --- message encoders ------------------------------------------------


def _encode_timestamp(t: float) -> bytes:
    secs = int(t)
    nanos = int(round((t - secs) * 1e9))
    secs += nanos // 1_000_000_000  # rounding can carry a full second
    nanos %= 1_000_000_000
    return _varint_field(1, secs) + _varint_field(2, nanos)


def _encode_endpoint(ep: FlowEndpoint) -> bytes:
    # message Endpoint: ID=1, identity=2, namespace=3, labels=4,
    # pod_name=5
    ns = ""
    pod = ep.pod_name
    if "/" in pod:
        ns, pod = pod.split("/", 1)
    out = _varint_field(1, ep.endpoint_id)
    out += _varint_field(2, ep.identity)
    out += _str_field(3, ns)
    for lab in ep.labels:
        out += _str_field(4, lab)
    out += _str_field(5, pod)
    return out


def _encode_l4(f: Flow) -> Optional[bytes]:
    # message Layer4 oneof protocol: TCP=1, UDP=2, ICMPv4=3, ICMPv6=4,
    # SCTP=5
    sp, dp = f.source.port, f.destination.port
    if f.proto == 6:
        flags = b""
        for _name, field, bit in _TCP_FLAG_FIELDS:
            if f.flags & bit:
                flags += _varint_field(field, 1)
        tcp = (_varint_field(1, sp) + _varint_field(2, dp)
               + (_msg_field(3, flags) if flags else b""))
        return _msg_field(1, tcp)
    if f.proto == 17:
        return _msg_field(2, _varint_field(1, sp) + _varint_field(2, dp))
    if f.proto in (1, 58):
        icmp = _varint_field(1, f.destination.port)  # type=1 (code=2)
        return _msg_field(3 if f.proto == 1 else 4, icmp)
    if f.proto == 132:
        return _msg_field(5, _varint_field(1, sp) + _varint_field(2, dp))
    return None


def _encode_l7(l7: dict) -> bytes:
    # message Layer7: type=1, latency_ns=2, oneof record {dns=100,
    # http=101, kafka=102}
    out = b""
    kind_map = {"REQUEST": 1, "RESPONSE": 2, "SAMPLE": 3}
    out += _varint_field(1, kind_map.get(str(l7.get("type", "")), 0))
    http = l7.get("http")
    if http:
        payload = (_varint_field(1, int(http.get("code", 0)))
                   + _str_field(2, str(http.get("method", "")))
                   + _str_field(3, str(http.get("url", "")))
                   + _str_field(4, str(http.get("protocol", ""))))
        out += _msg_field(101, payload)
    dns = l7.get("dns")
    if dns:
        payload = _str_field(1, str(dns.get("query", "")))
        for ip in dns.get("ips", ()):
            payload += _str_field(2, str(ip))
        payload += _varint_field(3, int(dns.get("ttl", 0)))
        out += _msg_field(100, payload)
    kafka = l7.get("kafka")
    if kafka:
        payload = (_varint_field(1, int(kafka.get("error_code", 0)))
                   + _varint_field(2, int(kafka.get("api_version", 0)))
                   + _str_field(3, str(kafka.get("api_key", "")))
                   + _varint_field(4, int(kafka.get("correlation_id",
                                                    0)))
                   + _str_field(5, str(kafka.get("topic", ""))))
        out += _msg_field(102, payload)
    return out


def encode_flow(f: Flow, node_name: str = "") -> bytes:
    """message Flow: time=1, verdict=2, drop_reason=3, IP=5, l4=6,
    source=8, destination=9, Type=10, node_name=11, l7=15, reply=16
    (deprecated), event_type=19, traffic_direction=22,
    drop_reason_desc=25, is_reply=26 (BoolValue), Summary=100000
    (deprecated), uuid=34."""
    out = _msg_field(1, _encode_timestamp(f.time))
    out += _varint_field(2, VERDICT_WIRE.get(f.verdict, 0))
    if f.drop_reason:
        out += _varint_field(3, f.drop_reason)  # deprecated raw code
    ip = (_str_field(1, f.source.ip) + _str_field(2, f.destination.ip)
          + _varint_field(3, IP_V6 if ":" in f.source.ip else IP_V4))
    out += _msg_field(5, ip)
    l4 = _encode_l4(f)
    if l4 is not None:
        out += _msg_field(6, l4)
    out += _msg_field(8, _encode_endpoint(f.source))
    out += _msg_field(9, _encode_endpoint(f.destination))
    out += _varint_field(10, FLOW_TYPE_L7 if f.l7 else FLOW_TYPE_L3_L4)
    out += _str_field(11, node_name)
    if f.l7:
        out += _msg_field(15, _encode_l7(f.l7))
    out += _varint_field(16, 1 if f.is_reply else 0)
    out += _msg_field(19, _varint_field(1, f.event_type))
    out += _varint_field(
        22, TRAFFIC_EGRESS if f.traffic_direction else TRAFFIC_INGRESS)
    if f.drop_reason:
        out += _varint_field(
            25, DROP_REASON_WIRE.get(f.drop_reason, 0))
    out += _msg_field(26, _varint_field(1, 1 if f.is_reply else 0))
    out += _str_field(34, str(f.uuid))
    out += _str_field(100000, f.summary())
    return out


def encode_get_flows_response(f: Flow, node_name: str = "") -> bytes:
    """observer.proto GetFlowsResponse: oneof {flow=1, ...},
    node_name=1000, time=1001."""
    out = _msg_field(1, encode_flow(f, node_name))
    out += _str_field(1000, node_name)
    out += _msg_field(1001, _encode_timestamp(f.time))
    return out


# FlowFilter wire fields handled (flow.proto): source_ip=1,
# destination_ip=4, verdict=6.  Other filter fields (source_pod=2,
# labels, fqdns, ...) are skipped schema-aware — misreading them as a
# different field would silently mis-filter.
_FILTER_SOURCE_IP = 1
_FILTER_DEST_IP = 4
_FILTER_VERDICT = 6


def encode_get_flows_request(number: int = 0, follow: bool = False,
                             whitelist: Sequence[dict] = (),
                             blacklist: Sequence[dict] = ()) -> bytes:
    """Client-side GetFlowsRequest (for the binary client + tests).
    ``verdict`` values are WIRE enum values (FORWARDED=1, DROPPED=2,
    REDIRECTED=5)."""
    out = _varint_field(1, number)
    out += _varint_field(3, 1 if follow else 0)

    def _filter_payload(f: dict) -> bytes:
        return (_str_field(_FILTER_SOURCE_IP, f.get("source_ip", ""))
                + _str_field(_FILTER_DEST_IP,
                             f.get("destination_ip", ""))
                + _varint_field(_FILTER_VERDICT, f.get("verdict", 0)))

    for f in blacklist:
        out += _msg_field(4, _filter_payload(f))
    for f in whitelist:
        out += _msg_field(5, _filter_payload(f))
    return out


def encode_server_status(num_flows: int, max_flows: int,
                         seen_flows: int) -> bytes:
    """observer.proto ServerStatusResponse: num_flows=1, max_flows=2,
    seen_flows=3."""
    return (_varint_field(1, num_flows) + _varint_field(2, max_flows)
            + _varint_field(3, seen_flows))


def decode_get_flows_request(data: bytes) -> dict:
    """observer.proto GetFlowsRequest subset: number=1, follow=3,
    blacklist=4, whitelist=5.  FlowFilter fields handled:
    source_ip=1, destination_ip=4, verdict=6 (the _FILTER_* constants
    above); other filter fields are skipped rather than misread."""
    msg = decode_message(data)
    out: dict = {}
    if 1 in msg:
        out["number"] = int(msg[1][-1])
    if 3 in msg:
        out["follow"] = bool(msg[3][-1])

    def _filters(raws) -> list:
        supported = {_FILTER_SOURCE_IP, _FILTER_DEST_IP, _FILTER_VERDICT}
        fs = []
        for raw in raws:
            m = decode_message(raw)
            f: dict = {}
            if _FILTER_SOURCE_IP in m:
                f["source_ip"] = m[_FILTER_SOURCE_IP][-1].decode()
            if _FILTER_DEST_IP in m:
                f["destination_ip"] = m[_FILTER_DEST_IP][-1].decode()
            if _FILTER_VERDICT in m:
                f["verdict"] = int(m[_FILTER_VERDICT][-1])
            if set(m) - supported:
                # a condition we cannot evaluate: the filter must match
                # NOTHING (matching everything would turn a narrow
                # blacklist into exclude-all / a whitelist into
                # match-all)
                f["unsupported"] = True
            fs.append(f)
        return fs

    if 4 in msg:
        out["blacklist"] = _filters(msg[4])
    if 5 in msg:
        out["whitelist"] = _filters(msg[5])
    return out


# wire Verdict -> hubble JSON verdict name (decode side)
_VERDICT_WIRE_NAMES = {1: "FORWARDED", 2: "DROPPED", 5: "REDIRECTED"}


def _decode_endpoint(raw: bytes) -> dict:
    m = decode_message(raw)
    out: dict = {"identity": int(m.get(2, [0])[-1])}
    labels = [b.decode() for b in m.get(4, [])]
    if labels:
        out["labels"] = labels
    if 5 in m:
        pod = m[5][-1].decode()
        ns = m[3][-1].decode() if 3 in m else ""
        out["podName"] = f"{ns}/{pod}" if ns else pod
    if 1 in m:
        out["ID"] = int(m[1][-1])
    return out


def decode_flow(raw: bytes) -> dict:
    """One encoded ``Flow`` message -> the hubble-JSON-shaped dict
    ``Flow.to_dict`` produces, with NATIVE drop-reason fidelity: the
    native reason code rides field 3 (the deprecated uint32
    ``drop_reason``) and is preferred over the field-25 enum, so a
    repo-native reason (ingress shed, dispatch timeout, cluster
    overflow, NAT exhaustion...) decoded off the binary wire renders
    its precise name instead of UNKNOWN(0) — the DIVERGENCES #15
    caveat, closed.  Used by ``BinaryObserverClient.get_flow_dicts``
    (the relay-peer surface over the binary wire)."""
    from .flow import DROP_REASON_DESC

    m = decode_message(raw)
    out: dict = {}
    if 1 in m:
        t = decode_message(m[1][-1])
        out["time"] = (int(t.get(1, [0])[-1])
                       + int(t.get(2, [0])[-1]) / 1e9)
    out["verdict"] = _VERDICT_WIRE_NAMES.get(
        int(m.get(2, [0])[-1]), "VERDICT_UNKNOWN")
    if 5 in m:
        ip = decode_message(m[5][-1])
        out["IP"] = {
            "source": (ip[1][-1].decode() if 1 in ip else ""),
            "destination": (ip[2][-1].decode() if 2 in ip else ""),
        }
    if 8 in m:
        out["source"] = _decode_endpoint(m[8][-1])
    if 9 in m:
        out["destination"] = _decode_endpoint(m[9][-1])
    out["Type"] = ("L7" if int(m.get(10, [1])[-1]) == FLOW_TYPE_L7
                   else "L3_L4")
    if 11 in m:
        out["node_name"] = m[11][-1].decode()
    if 19 in m:
        et = decode_message(m[19][-1])
        out["event_type"] = {"type": int(et.get(1, [0])[-1])}
    out["traffic_direction"] = (
        "EGRESS" if int(m.get(22, [TRAFFIC_INGRESS])[-1])
        == TRAFFIC_EGRESS else "INGRESS")
    if 26 in m:
        br = decode_message(m[26][-1])
        out["is_reply"] = bool(int(br.get(1, [0])[-1]))
    else:
        out["is_reply"] = bool(int(m.get(16, [0])[-1]))
    # drop-reason fidelity: field 3 carries the NATIVE code; field 25
    # the (lossy) upstream enum.  Prefer native when present.
    native = int(m.get(3, [0])[-1])
    wire_desc = int(m.get(25, [0])[-1])
    if native:
        out["drop_reason"] = native
        out["drop_reason_desc"] = DROP_REASON_DESC.get(
            native, f"DROP_REASON_{native}")
    elif wire_desc:
        out["drop_reason"] = wire_desc
        out["drop_reason_desc"] = f"DROP_REASON_{wire_desc}"
    if 100000 in m:
        out["Summary"] = m[100000][-1].decode()
    if 34 in m:
        out["uuid"] = m[34][-1].decode()
    return out
