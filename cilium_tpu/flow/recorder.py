"""Recorder: capture matching traffic to pcap.

Reference: upstream ``pkg/hubble/recorder`` (cilium 1.10+) — operators
start a recording with filters; matching packets stream into a pcap
file.  TPU-first: the monitor's EventBatches already carry the header
rows; a recording is a FlowFilter-gated sink that re-renders matched
rows as pcap records (core.pcap.write_pcap's wire format).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.packets import HeaderBatch
from ..monitor.api import EventBatch
from .observer import FlowFilter


@dataclass
class Recording:
    recording_id: int
    path: str
    filters: Sequence[FlowFilter]
    max_packets: int
    captured: int = 0
    started: float = field(default_factory=time.time)
    stopped: Optional[float] = None
    rows: List[np.ndarray] = field(default_factory=list)

    @property
    def active(self) -> bool:
        return self.stopped is None

    def to_dict(self) -> dict:
        return {"id": self.recording_id, "path": self.path,
                "captured": self.captured, "active": self.active,
                "max-packets": self.max_packets}


class Recorder:
    """A MonitorAgent consumer gating batches through recordings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._recordings: Dict[int, Recording] = {}
        self._next = 1

    def start(self, path: str, filters: Sequence[FlowFilter] = (),
              max_packets: int = 65536) -> Recording:
        with self._lock:
            rec = Recording(self._next, path, tuple(filters),
                            max_packets)
            self._recordings[self._next] = rec
            self._next += 1
            return rec

    def stop(self, recording_id: int) -> Optional[Recording]:
        """Finalize: write the pcap and return the recording."""
        from ..core.pcap import write_pcap

        with self._lock:
            rec = self._recordings.get(recording_id)
            if rec is None or not rec.active:
                return rec
            rec.stopped = time.time()
            rows = list(rec.rows)
        hdr = (np.stack(rows) if rows
               else np.zeros((0, 16), dtype=np.uint32))
        write_pcap(rec.path, HeaderBatch(hdr))
        return rec

    def list(self) -> List[dict]:
        with self._lock:
            return [r.to_dict() for r in self._recordings.values()]

    def consume(self, batch: EventBatch) -> None:
        if len(batch) == 0:
            return
        with self._lock:
            active = [r for r in self._recordings.values() if r.active]
        if not active:
            return
        for rec in active:
            if rec.filters:
                # whitelist semantics: a packet matching ANY filter is
                # captured (the observer's get_flows contract)
                keep = np.zeros(len(batch), dtype=bool)
                for f in rec.filters:
                    keep |= _mask_batch(f, batch)
            else:
                keep = np.ones(len(batch), dtype=bool)
            idx = np.nonzero(keep)[0]
            with self._lock:
                room = rec.max_packets - rec.captured
                for i in idx[:room]:
                    rec.rows.append(batch.hdr[i].copy())
                rec.captured += min(len(idx), room)


def _mask_batch(f: FlowFilter, batch: EventBatch) -> np.ndarray:
    """FlowFilter over an EventBatch — EVERY FlowFilter field applies
    (the observer ring implements the same contract over its SoA
    arrays; an ignored field would silently widen a capture)."""
    import ipaddress

    from ..core.packets import (COL_DPORT, COL_DST_IP3, COL_PROTO,
                                COL_SPORT, COL_SRC_IP3)
    from ..datapath.conntrack import CT_REPLY

    m = np.ones(len(batch), dtype=bool)
    hdr = batch.hdr
    if f.verdict is not None:
        m &= batch.verdict == f.verdict
    if f.protocol is not None:
        m &= hdr[:, COL_PROTO] == f.protocol
    if f.port is not None:
        m &= ((hdr[:, COL_DPORT] == f.port)
              | (hdr[:, COL_SPORT] == f.port))
    if f.source_ip:
        m &= hdr[:, COL_SRC_IP3] == int(
            ipaddress.IPv4Address(f.source_ip))
    if f.destination_ip:
        m &= hdr[:, COL_DST_IP3] == int(
            ipaddress.IPv4Address(f.destination_ip))
    if f.source_identity is not None or f.destination_identity \
            is not None:
        # identical side-mapping to FlowFilter.mask: the one identity
        # column holds the REMOTE peer, which sits on the src side for
        # ingress non-reply rows (and flips with reply direction)
        from ..core.packets import COL_DIR

        is_reply = batch.ct_state == CT_REPLY
        ingress = hdr[:, COL_DIR] == 0
        remote_is_src = ingress ^ is_reply
        if f.source_identity is not None:
            m &= np.where(remote_is_src,
                          batch.identity == f.source_identity, True)
        if f.destination_identity is not None:
            m &= np.where(~remote_is_src,
                          batch.identity == f.destination_identity,
                          True)
    if f.reply is not None:
        m &= (batch.ct_state == CT_REPLY) == f.reply
    if f.since is not None:
        m &= np.full(len(batch), batch.timestamp >= f.since)
    if f.until is not None:
        m &= np.full(len(batch), batch.timestamp <= f.until)
    return m
