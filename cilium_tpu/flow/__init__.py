"""Flow plane: Hubble-equivalent observability.

Reference: upstream cilium ``pkg/hubble`` — ``parser/threefour``
decodes monitor events into ``flow.Flow`` records enriched with
identity/endpoint metadata; the observer keeps a ring buffer served
over an API; metrics and exporters consume the same stream.

TPU-first redesign: flows live as struct-of-arrays in a fixed-size
ring (one vectorized append per device batch); typed Flow objects are
materialized only at the query/export edge.
"""

from .flow import Flow, VERDICT_NAMES  # noqa: F401
from .parser import ThreeFourParser  # noqa: F401
from .observer import FlowFilter, Observer  # noqa: F401
from .metrics import FlowMetrics  # noqa: F401
from .exporter import FlowExporter  # noqa: F401
from .seven import SevenParser  # noqa: F401
from .relay import Relay  # noqa: F401
