"""Flow exporter: JSON-lines flow log files.

Reference: upstream cilium ``pkg/hubble/exporter`` — writes flows as
one JSON object per line ({"flow": {...}, "node_name", "time"}), with
size-based rotation.
"""

from __future__ import annotations

import json
import os
from typing import IO, Optional

from ..monitor.api import EventBatch
from .observer import Observer


class FlowExporter:
    """Writes flows from an observer-shaped batch stream to JSONL.

    Registered as a MonitorAgent consumer; uses a private single-batch
    Observer for materialization so enrichment getters apply."""

    def __init__(self, path: str, node_name: str = "node0",
                 max_bytes: int = 64 << 20,
                 identity_getter=None, endpoint_getter=None):
        self.path = path
        self.node_name = node_name
        self.max_bytes = max_bytes
        self._identity_getter = identity_getter
        self._endpoint_getter = endpoint_getter
        self._seq = 0
        self._fh: Optional[IO[str]] = None
        self.written = 0

    def _file(self) -> IO[str]:
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def consume(self, batch: EventBatch) -> None:
        if len(batch) == 0:
            return
        from .observer import materialize_flow

        ident_get = self._identity_getter or (lambda n: ())
        ep_get = self._endpoint_getter or (lambda e: ("", e))
        fh = self._file()
        for i in range(len(batch)):
            fl = materialize_flow(
                batch.hdr[i], batch.timestamp, self._seq + i,
                int(batch.verdict[i]), int(batch.reason[i]),
                int(batch.ct_state[i]), int(batch.msg_type[i]),
                int(batch.identity[i]), ident_get, ep_get,
                proxy_port=int(batch.proxy_port[i]))
            rec = {"flow": fl.to_dict(), "node_name": self.node_name,
                   "time": fl.time}
            fh.write(json.dumps(rec) + "\n")
            self.written += 1
        self._seq += len(batch)
        fh.flush()
        if os.path.getsize(self.path) > self.max_bytes:
            self._rotate()

    def _rotate(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
        os.replace(self.path, self.path + ".1")

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
