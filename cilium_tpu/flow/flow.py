"""The Flow record — the output schema kept from the reference.

Reference: upstream cilium ``api/v1/flow/flow.proto`` (``Flow``
message).  Field names in :meth:`Flow.to_dict` mirror the proto's JSON
rendering (camelCase keys as produced by hubble's JSON exporter) so
downstream consumers of hubble JSON can switch over unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..policy.mapstate import (
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)

# flow.proto Verdict enum names
VERDICT_NAMES = {
    VERDICT_ALLOW: "FORWARDED",
    VERDICT_REDIRECT: "REDIRECTED",
    VERDICT_DENY: "DROPPED",
    VERDICT_DEFAULT_DENY: "DROPPED",
}

PROTO_NAMES = {6: "TCP", 17: "UDP", 1: "ICMPv4", 58: "ICMPv6",
               132: "SCTP"}

EVENT_TYPE_NAMES = {1: "DropNotify", 4: "TraceNotify",
                    9: "PolicyVerdictNotify", 129: "L7"}

# flow.proto DropReason enum-style names (hubble JSON renders strings)
DROP_REASON_DESC = {
    1: "POLICY_DENIED",
    2: "POLICY_DENY_DEFAULT",
    3: "QUEUE_OVERFLOW",
    4: "UNKNOWN_ENDPOINT",  # lxcmap miss (unregistered endpoint id)
    5: "NO_MAPPING_FOR_NAT_MASQUERADING",  # SNAT pool exhausted
    6: "BANDWIDTH_LIMITED",  # egress rate limit (EDT analogue)
    7: "NO_SERVICE",  # frontend with no backend (DROP_NO_SERVICE)
    8: "AUTH_REQUIRED",  # mutual auth missing (pkg/auth)
    9: "INGRESS_QUEUE_OVERFLOW",  # serving admission shed (XDP ring)
    10: "DISPATCH_TIMEOUT",  # serving watchdog deadlined a hung dispatch
    11: "RECOVERY_DROP",  # serving recovery accounted a lost batch
    12: "CLUSTER_ROUTER_OVERFLOW",  # cluster forward queue full
}


@dataclass
class FlowEndpoint:
    """flow.proto Endpoint: one side of a flow."""

    ip: str = ""
    port: int = 0
    identity: int = 0
    labels: Tuple[str, ...] = ()
    pod_name: str = ""
    endpoint_id: int = 0

    def to_dict(self) -> dict:
        d: dict = {"identity": self.identity}
        if self.labels:
            d["labels"] = list(self.labels)
        if self.pod_name:
            d["podName"] = self.pod_name
        if self.endpoint_id:
            d["ID"] = self.endpoint_id
        return d


@dataclass
class Flow:
    time: float
    uuid: int  # monotonically increasing sequence number
    verdict: int
    drop_reason: int
    event_type: int  # monitor MSG_* number
    is_reply: bool
    traffic_direction: int  # 0 ingress / 1 egress
    proto: int
    flags: int
    length: int
    source: FlowEndpoint
    destination: FlowEndpoint
    l7: Optional[dict] = None  # L7 record when proxy-parsed
    # flow.proto proxy_port: the listener a REDIRECTED flow detoured
    # to (0 = no redirect) — without it a redirect row renders
    # indistinguishably from plain ALLOW (ISSUE 16 satellite)
    proxy_port: int = 0

    @property
    def verdict_name(self) -> str:
        return VERDICT_NAMES.get(self.verdict, "VERDICT_UNKNOWN")

    def summary(self) -> str:
        p = PROTO_NAMES.get(self.proto, str(self.proto))
        arrow = "<-" if self.is_reply else "->"
        to_proxy = (f" to-proxy:{self.proxy_port}"
                    if self.verdict == VERDICT_REDIRECT
                    and self.proxy_port else "")
        return (f"{self.source.ip}:{self.source.port} {arrow} "
                f"{self.destination.ip}:{self.destination.port} "
                f"{p} {self.verdict_name}{to_proxy}")

    def to_dict(self) -> dict:
        """hubble-JSON-shaped rendering (flow.proto JSON)."""
        d = {
            "time": self.time,
            "uuid": str(self.uuid),
            "verdict": self.verdict_name,
            "IP": {
                "source": self.source.ip,
                "destination": self.destination.ip,
            },
            "l4": self._l4_dict(),
            "source": self.source.to_dict(),
            "destination": self.destination.to_dict(),
            "Type": "L7" if self.l7 else "L3_L4",
            "event_type": {"type": int(self.event_type)},
            "traffic_direction": ("INGRESS" if self.traffic_direction == 0
                                  else "EGRESS"),
            "is_reply": self.is_reply,
        }
        if self.drop_reason:
            d["drop_reason_desc"] = DROP_REASON_DESC.get(
                self.drop_reason, f"DROP_REASON_{self.drop_reason}")
            d["drop_reason"] = self.drop_reason
            if self.verdict in (VERDICT_ALLOW, VERDICT_REDIRECT):
                # forwarded WITH a would-be deny reason: the
                # policy-audit-mode signature (upstream renders
                # verdict AUDIT)
                d["policy_audit"] = True
        if self.proxy_port:
            d["proxy_port"] = self.proxy_port
        if self.l7:
            d["l7"] = self.l7
        d["Summary"] = self.summary()
        return d

    def _l4_dict(self) -> dict:
        if self.proto == 6:
            return {"TCP": {"source_port": self.source.port,
                            "destination_port": self.destination.port,
                            "flags": self._tcp_flags()}}
        if self.proto == 17:
            return {"UDP": {"source_port": self.source.port,
                            "destination_port": self.destination.port}}
        if self.proto in (1, 58):
            key = "ICMPv4" if self.proto == 1 else "ICMPv6"
            return {key: {"type": self.destination.port}}
        if self.proto == 132:
            return {"SCTP": {"source_port": self.source.port,
                             "destination_port": self.destination.port}}
        return {"proto": self.proto}

    def _tcp_flags(self) -> dict:
        f = self.flags
        out = {}
        for name, bit in (("FIN", 0x01), ("SYN", 0x02), ("RST", 0x04),
                          ("PSH", 0x08), ("ACK", 0x10), ("URG", 0x20)):
            if f & bit:
                out[name] = True
        return out
