"""threefour parser: monitor events -> flow records.

Reference: upstream cilium ``pkg/hubble/parser/threefour/parser.go`` —
``Parser.Decode`` turns a raw monitor payload (DropNotify/TraceNotify/
PolicyVerdictNotify) into a ``flow.Flow``, enriching with the ipcache/
identity/endpoint getters.  TPU-first: batches stay vectorized; this
parser is the thin adapter wiring a MonitorAgent to an Observer, plus
a single-event decode path for wire-format payloads (golden tests,
CLI replay).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..monitor.api import EventBatch, MonitorEvent
from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    N_COLS,
    ip_to_words,
)
from .flow import Flow, FlowEndpoint
from .observer import Observer


class ThreeFourParser:
    """Feeds an Observer from a MonitorAgent (batch path) and decodes
    single wire events (compat path)."""

    def __init__(self, observer: Observer):
        self.observer = observer
        self.decoded = 0
        self.errors = 0

    # -- batch path (the hot loop) ----------------------------------
    def consume(self, batch: EventBatch) -> None:
        self.observer.consume(batch)
        self.decoded += len(batch)

    # -- single-event path (wire payloads) --------------------------
    def decode(self, payload: bytes, timestamp: float = 0.0) -> Flow:
        """Wire-format monitor payload -> Flow (pkg/hubble Decode)."""
        if len(payload) != MonitorEvent.WIRE_SIZE:
            self.errors += 1
            raise ValueError(
                f"bad monitor payload size {len(payload)}, "
                f"want {MonitorEvent.WIRE_SIZE}")
        ev = MonitorEvent.unpack(payload, timestamp)
        batch = self._event_to_batch(ev)
        self.observer.consume(batch)
        self.decoded += 1
        return self.observer.get_flows(number=1)[0]

    @staticmethod
    def _event_to_batch(ev: MonitorEvent) -> EventBatch:
        hdr = np.zeros((1, N_COLS), dtype=np.uint32)
        hdr[0, COL_SRC_IP0:COL_SRC_IP0 + 4] = ip_to_words(ev.src_ip)
        hdr[0, COL_DST_IP0:COL_DST_IP0 + 4] = ip_to_words(ev.dst_ip)
        hdr[0, COL_SPORT] = ev.sport
        hdr[0, COL_DPORT] = ev.dport
        hdr[0, COL_PROTO] = ev.proto
        hdr[0, COL_FLAGS] = ev.flags
        hdr[0, COL_LEN] = ev.length
        hdr[0, COL_FAMILY] = 6 if ":" in ev.src_ip else 4
        hdr[0, COL_EP] = ev.endpoint
        hdr[0, COL_DIR] = ev.direction
        return EventBatch(
            msg_type=np.array([ev.msg_type], dtype=np.uint8),
            verdict=np.array([ev.verdict], dtype=np.uint8),
            reason=np.array([ev.reason], dtype=np.uint8),
            ct_state=np.array([ev.ct_state], dtype=np.uint8),
            identity=np.array([ev.identity], dtype=np.uint32),
            proxy_port=np.array([ev.proxy_port], dtype=np.uint16),
            hdr=hdr,
            timestamp=ev.timestamp,
        )
