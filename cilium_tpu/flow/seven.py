"""The "seven" parser: L7 proxy access records -> Flow records.

Reference: upstream cilium ``pkg/hubble/parser/seven`` — Envoy access
logs become ``flow.Flow`` messages with the ``l7`` field set
(``flow.proto`` Layer7: HTTP/DNS/Kafka) and event type L7 (129).
TPU-first: the proxy's featurizer already produced the structured
record; this parser enriches it (identity labels, endpoint info) and
lands it in the same Observer ring as the threefour flows.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..core.packets import N_COLS, COL_DPORT, COL_PROTO
from ..proxy.featurize import KIND_DNS, KIND_HTTP
from ..proxy.proxy import L7Record
from .flow import VERDICT_ALLOW, VERDICT_DENY

MSG_L7 = 129  # flow event type for proxy records (hubble: L7)


class SevenParser:
    """proxy.on_record consumer -> Observer ring (the seven parser)."""

    def __init__(self, observer,
                 numeric_of_row: Optional[Callable[[int], int]] = None):
        """``numeric_of_row``: identity ROW -> numeric identity (the
        loader row map); rows are what the proxy carries."""
        self.observer = observer
        self.numeric_of_row = numeric_of_row or (lambda r: 0)
        self.parsed = 0

    def consume(self, rec: L7Record) -> None:
        l7 = self._layer7(rec)
        hdr = np.zeros(N_COLS, dtype=np.uint32)
        hdr[COL_PROTO] = 17 if rec.kind == KIND_DNS else 6
        hdr[COL_DPORT] = rec.proxy_port
        verdict = VERDICT_ALLOW if rec.verdict else VERDICT_DENY
        self.observer.append_l7(
            hdr_row=hdr, l7=l7, verdict=verdict,
            identity=self.numeric_of_row(rec.src_row),
            timestamp=rec.timestamp)
        self.parsed += 1

    def _layer7(self, rec: L7Record) -> dict:
        # flow.proto Layer7 JSON shape
        if rec.kind == KIND_HTTP:
            return {
                "type": "REQUEST",
                "http": {
                    "method": rec.method,
                    "url": rec.path,
                    **({"host": rec.host} if rec.host else {}),
                    "protocol": "HTTP/1.1",
                    "code": rec.status,
                },
            }
        if rec.kind == KIND_DNS:
            return {
                "type": "REQUEST",
                "dns": {
                    "query": rec.qname,
                    "rcode": 0 if rec.verdict else 5,  # REFUSED
                },
            }
        return {
            "type": "REQUEST",
            "kafka": {
                "api_key": rec.method,
                "topic": rec.path,
                # 29 = TOPIC_AUTHORIZATION_FAILED (kafka error code)
                "error_code": 0 if rec.verdict else 29,
            },
        }
