"""Flow ring buffer + query API (the Hubble observer).

Reference: upstream cilium ``pkg/hubble/observer`` — a fixed-size ring
of the most recent N flows served via the gRPC ``Observer.GetFlows``
API with flow filters.  TPU-first redesign: the ring is
struct-of-arrays numpy — one vectorized slice-assign per device batch,
vectorized filter evaluation at query time, Flow objects materialized
only for the rows returned.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    N_COLS,
    ip_to_words,
    words_to_ip,
)
from ..datapath.conntrack import CT_REPLY
from ..monitor.api import EventBatch
from .flow import Flow, FlowEndpoint

IdentityGetter = Callable[[int], Tuple[str, ...]]  # numeric -> labels
EndpointGetter = Callable[[int], Tuple[str, int]]  # ep id -> (pod, id)


@dataclass
class FlowFilter:
    """A subset of flow.proto FlowFilter, vectorized.

    All set conditions AND together (one filter); a request passes a
    list of filters that OR (reference: whitelist semantics)."""

    verdict: Optional[int] = None
    source_ip: Optional[str] = None
    destination_ip: Optional[str] = None
    source_identity: Optional[int] = None
    destination_identity: Optional[int] = None
    # the flow's security identity on WHICHEVER side is remote (the
    # ring stores only the remote numeric identity; the local side
    # is an endpoint, not an identity column).  This is what
    # `--identity` / ?identity= mean: "flows involving identity N" —
    # note that OR-ing source_identity with destination_identity
    # does NOT express this (each wildcards the rows the other
    # constrains, so the union matches everything)
    identity: Optional[int] = None
    port: Optional[int] = None
    protocol: Optional[int] = None
    since: Optional[float] = None
    until: Optional[float] = None
    reply: Optional[bool] = None
    # set by the wire decoder when the filter carried a field this
    # implementation cannot evaluate: such a filter matches NOTHING
    # (conservative for both whitelist and blacklist use)
    unsupported: bool = False

    def mask(self, ring: "Observer", idx: np.ndarray) -> np.ndarray:
        if self.unsupported:
            return np.zeros(len(idx), dtype=bool)
        m = np.ones(len(idx), dtype=bool)
        if self.verdict is not None:
            m &= ring.verdict[idx] == self.verdict
        if self.protocol is not None:
            m &= ring.hdr[idx, COL_PROTO] == self.protocol
        if self.port is not None:
            m &= ((ring.hdr[idx, COL_SPORT] == self.port)
                  | (ring.hdr[idx, COL_DPORT] == self.port))
        if self.source_ip is not None:
            w = ip_to_words(self.source_ip)
            for j in range(4):
                m &= ring.hdr[idx, COL_SRC_IP0 + j] == w[j]
        if self.destination_ip is not None:
            w = ip_to_words(self.destination_ip)
            for j in range(4):
                m &= ring.hdr[idx, COL_DST_IP0 + j] == w[j]
        if self.since is not None:
            m &= ring.time[idx] >= self.since
        if self.until is not None:
            m &= ring.time[idx] <= self.until
        if self.reply is not None:
            m &= (ring.ct_state[idx] == CT_REPLY) == self.reply
        if self.identity is not None:
            m &= ring.identity[idx] == self.identity
        if self.source_identity is not None or \
                self.destination_identity is not None:
            is_reply = ring.ct_state[idx] == CT_REPLY
            ingress = ring.hdr[idx, COL_DIR] == 0
            remote_is_src = ingress ^ is_reply
            # remote identity sits on src side for ingress non-reply
            if self.source_identity is not None:
                m &= np.where(remote_is_src,
                              ring.identity[idx] == self.source_identity,
                              True)
            if self.destination_identity is not None:
                m &= np.where(~remote_is_src,
                              ring.identity[idx]
                              == self.destination_identity, True)
        return m


class Observer:
    """Fixed-capacity SoA flow ring (power-of-two capacity).

    Thread-safety contract (audited for the async event plane):
    under live serving ``consume`` runs on the EVENT-JOIN WORKER
    (monitor fan-out), ``append_l7`` on proxy threads, and
    ``get_flows`` on API handler threads — concurrently.  Every ring
    mutation (the vectorized slice-assign + the ``seq`` bump) and
    every read (the oldest-pointer computation, filter masks, and
    row materialization) happens under ``_lock``, so a query
    observes either ALL of a batch's rows or none of them: no torn
    rows (a row whose columns mix two different flows), and ``seq``
    is monotonic across queries.  The seq bump deliberately happens
    LAST inside the locked block, after every column landed.
    ``tests/test_flow_analytics.py`` pins this with a concurrent
    query-during-live-consume test."""

    def __init__(self, capacity: int = 4096,
                 identity_getter: Optional[IdentityGetter] = None,
                 endpoint_getter: Optional[EndpointGetter] = None):
        assert capacity & (capacity - 1) == 0
        self.capacity = capacity
        self.time = np.zeros(capacity, dtype=np.float64)
        self.verdict = np.zeros(capacity, dtype=np.uint8)
        self.reason = np.zeros(capacity, dtype=np.uint8)
        self.ct_state = np.zeros(capacity, dtype=np.uint8)
        self.msg_type = np.zeros(capacity, dtype=np.uint8)
        self.identity = np.zeros(capacity, dtype=np.uint32)
        self.proxy = np.zeros(capacity, dtype=np.uint16)
        self.hdr = np.zeros((capacity, N_COLS), dtype=np.uint32)
        self.flow_seq = np.zeros(capacity, dtype=np.int64)
        # L7 payloads (seven-parser flows); None for L3/L4 rows
        self.l7 = np.empty(capacity, dtype=object)
        self.seq = 0  # total flows ever written
        self.identity_getter = identity_getter or (lambda n: ())
        self.endpoint_getter = endpoint_getter or (lambda e: ("", e))
        self._lock = threading.Lock()
        # guarded-by: _lock: time, verdict, reason, ct_state, msg_type,
        # guarded-by: _lock: identity, proxy, hdr, flow_seq, l7, seq

    def __len__(self) -> int:
        # holds: _lock -- get_flows reads it inside its locked region;
        # external callers use the locked server_status()
        return min(self.seq, self.capacity)

    def server_status(self) -> dict:
        # thread-affinity: any
        """Locked num/seen/max counts (hubble ServerStatus shape).
        The gRPC server and relay prefer this over their fallback
        ``len(obs)``/``obs.seq`` reads, which raced a live consume."""
        with self._lock:
            return {"num_flows": len(self), "seen_flows": self.seq,
                    "max_flows": self.capacity}

    def consume(self, batch: EventBatch) -> None:
        # thread-affinity: any -- publish() fans out on whichever
        # thread published (event-join worker for ring joins, drain
        # thread for host-synthesized shed/recovery drops)
        """Vectorized ring append (a MonitorAgent consumer)."""
        n = len(batch)
        if n == 0:
            return
        with self._lock:
            if n >= self.capacity:  # keep the newest capacity rows
                sl = slice(n - self.capacity, n)
                # land each kept row where a sequential append of all n
                # rows would have put it, so get_flows' oldest-pointer
                # ((seq + n) % capacity) stays meaningful for any n
                pos = (self.seq + n - self.capacity
                       + np.arange(self.capacity)) % self.capacity
            else:
                start = self.seq % self.capacity
                pos = (start + np.arange(n)) % self.capacity
                sl = slice(0, n)
            self.time[pos] = batch.timestamp
            self.verdict[pos] = batch.verdict[sl]
            self.reason[pos] = batch.reason[sl]
            self.ct_state[pos] = batch.ct_state[sl]
            self.msg_type[pos] = batch.msg_type[sl]
            self.identity[pos] = batch.identity[sl]
            self.proxy[pos] = batch.proxy_port[sl]
            self.hdr[pos] = batch.hdr[sl]
            self.flow_seq[pos] = self.seq + np.arange(n)[sl]
            self.l7[pos] = None
            self.seq += n

    def append_l7(self, hdr_row: np.ndarray, l7: dict, verdict: int,
                  identity: int, timestamp: float) -> None:
        # thread-affinity: any
        """One seven-parser flow (proxy access record) into the ring."""
        from ..flow.seven import MSG_L7

        with self._lock:
            pos = self.seq % self.capacity
            self.time[pos] = timestamp
            self.verdict[pos] = verdict
            self.reason[pos] = 0
            self.ct_state[pos] = 0
            self.msg_type[pos] = MSG_L7
            self.identity[pos] = identity
            self.proxy[pos] = 0
            self.hdr[pos] = hdr_row
            self.flow_seq[pos] = self.seq
            self.l7[pos] = l7
            self.seq += 1

    def get_flows(self, filters: Sequence[FlowFilter] = (),
                  number: int = 100, oldest_first: bool = False,
                  blacklist: Sequence[FlowFilter] = ()
                  ) -> List[Flow]:
        # thread-affinity: api, cli, capture, offline
        """The Observer.GetFlows equivalent: ``filters`` (whitelist)
        OR together; ``blacklist`` filters then EXCLUDE (reference:
        GetFlowsRequest whitelist/blacklist semantics)."""
        with self._lock:
            n = len(self)
            if n == 0:
                return []
            # oldest -> newest ring order
            if self.seq <= self.capacity:
                idx = np.arange(n)
            else:
                start = self.seq % self.capacity
                idx = (start + np.arange(self.capacity)) % self.capacity
            if filters:
                keep = np.zeros(len(idx), dtype=bool)
                for f in filters:
                    keep |= f.mask(self, idx)
                idx = idx[keep]
            for f in blacklist:
                idx = idx[~f.mask(self, idx)]
            if not oldest_first:
                idx = idx[::-1]
            idx = idx[:number]
            return [self._materialize(i) for i in idx]

    def flows_since(self, cursor: int, limit: int = 512
                    ) -> Tuple[List[Flow], int]:
        # thread-affinity: api, cli, capture, offline
        """The since-cursor ring TAIL (ISSUE 14 cluster relay): every
        flow whose ``flow_seq`` is >= ``cursor``, oldest first,
        newest ``limit`` kept when the tail outgrew it, plus the new
        cursor (``seq`` high-water — pass it back next time).  Flows
        that lapped out of the ring between scrapes are simply gone
        (the ring's standing newest-wins contract); the cursor jump
        makes the gap visible to the caller."""
        with self._lock:
            new_cursor = self.seq
            n = len(self)
            if n == 0 or cursor >= new_cursor:
                return [], new_cursor
            if self.seq <= self.capacity:
                idx = np.arange(n)
            else:
                start = self.seq % self.capacity
                idx = (start + np.arange(self.capacity)) \
                    % self.capacity
            keep = self.flow_seq[idx] >= cursor
            idx = idx[keep]
            if limit and len(idx) > limit:
                idx = idx[-limit:]  # the newest `limit`, time order
            return [self._materialize(i) for i in idx], new_cursor

    def _materialize(self, i: int) -> Flow:
        # holds: _lock -- called from get_flows' locked region only
        f = materialize_flow(
            self.hdr[i], float(self.time[i]), int(self.flow_seq[i]),
            int(self.verdict[i]), int(self.reason[i]),
            int(self.ct_state[i]), int(self.msg_type[i]),
            int(self.identity[i]), self.identity_getter,
            self.endpoint_getter, proxy_port=int(self.proxy[i]))
        if self.l7[i] is not None:
            f.l7 = self.l7[i]
        return f


def materialize_flow(r: np.ndarray, time: float, seq: int, verdict: int,
                     reason: int, ct_state: int, msg_type: int,
                     remote_ident: int, identity_getter: IdentityGetter,
                     endpoint_getter: EndpointGetter,
                     proxy_port: int = 0) -> Flow:
    """One header row + event fields -> enriched Flow (shared by the
    observer ring and the exporter's direct batch path)."""
    fam = int(r[COL_FAMILY])
    src_ip = words_to_ip(r[COL_SRC_IP0:COL_SRC_IP0 + 4], fam)
    dst_ip = words_to_ip(r[COL_DST_IP0:COL_DST_IP0 + 4], fam)
    is_reply = ct_state == CT_REPLY
    ingress = int(r[COL_DIR]) == 0
    pod, epid = endpoint_getter(int(r[COL_EP]))
    # the LOCAL endpoint sits on dst side for ingress, src for egress
    # (reference: threefour parser's endpoint resolution)
    src = FlowEndpoint(ip=src_ip, port=int(r[COL_SPORT]))
    dst = FlowEndpoint(ip=dst_ip, port=int(r[COL_DPORT]))
    local, remote = (dst, src) if ingress else (src, dst)
    remote.identity = remote_ident
    remote.labels = tuple(identity_getter(remote_ident))
    local.pod_name = pod
    local.endpoint_id = epid
    return Flow(
        time=time,
        uuid=seq,
        verdict=verdict,
        drop_reason=reason,
        event_type=msg_type,
        is_reply=is_reply,
        traffic_direction=int(r[COL_DIR]),
        proto=int(r[COL_PROTO]),
        flags=int(r[COL_FLAGS]),
        length=int(r[COL_LEN]),
        source=src,
        destination=dst,
        proxy_port=proxy_port,
    )
