"""The Observer gRPC API: hubble's external surface.

Reference: upstream hubble serves ``observer.Observer`` over gRPC
(``GetFlows`` server-streaming + ``ServerStatus``; schemas
``api/v1/flow/flow.proto`` + ``api/v1/observer/observer.proto``).

The service speaks BOTH encodings on the same method paths:

- **binary flow.proto** (hand-encoded wire format, ``flow/proto.py``)
  — what a stock hubble CLI with generated stubs sends/expects;
- **flow.proto JSON** (the dicts ``Flow.to_dict`` produces — hubble's
  JSON rendering) — used by the in-repo relay/CLI clients.

Requests are sniffed: JSON starts with ``{`` (0x7b decodes as an
invalid protobuf tag, so the sniff is unambiguous); each response is
serialized in the encoding its request used.

``serve(observer, address)`` -> grpc.Server;
:class:`ObserverClient` is the matching JSON client (used by the
relay for remote peers and by the CLI's ``hubble observe``);
:class:`BinaryObserverClient` drives the binary surface.
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional, Sequence

import grpc

SERVICE = "observer.Observer"

_dumps = lambda d: json.dumps(d).encode()  # noqa: E731
_loads = lambda b: json.loads(b.decode()) if b else {}  # noqa: E731
_ident = lambda b: b  # noqa: E731 — handlers serialize per-request


def _sniff_request(data: bytes) -> dict:
    """bytes -> request dict + ``_wire`` marker ("json" | "proto")."""
    from .proto import decode_get_flows_request

    if not data:
        return {"_wire": "proto"}
    if data[:1] == b"{":
        req = _loads(data)
        req["_wire"] = "json"
        return req
    req = decode_get_flows_request(data)
    req["_wire"] = "proto"
    return req


class _ObserverHandler(grpc.GenericRpcHandler):
    def __init__(self, observer, node_name: str = ""):
        self.observer = observer
        self.node_name = node_name

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/GetFlows":
            return grpc.unary_stream_rpc_method_handler(
                self._get_flows,
                request_deserializer=_sniff_request,
                response_serializer=_ident)
        if method == f"/{SERVICE}/ServerStatus":
            return grpc.unary_unary_rpc_method_handler(
                self._server_status,
                request_deserializer=_sniff_request,
                response_serializer=_ident)
        return None

    def _get_flows(self, request: dict, context) -> Iterator[bytes]:
        from .observer import FlowFilter
        from .proto import encode_get_flows_response

        binary = request.get("_wire") == "proto"
        number = int(request.get("number", 100))

        def to_filters(entries) -> list:
            out = []
            for f in entries:
                if binary and "verdict" in f:
                    # binary filters carry WIRE Verdict enum values;
                    # the ring compares INTERNAL codes (one wire
                    # DROPPED spans two internal codes, so a filter
                    # may expand into several OR'd ones)
                    from .proto import VERDICT_WIRE_TO_INTERNAL

                    f = dict(f)
                    internals = VERDICT_WIRE_TO_INTERNAL.get(
                        f.pop("verdict"), (-1,))  # unknown: none
                    out.extend(FlowFilter(verdict=v, **f)
                               for v in internals)
                else:
                    out.append(FlowFilter(**f))
            return out

        kwargs = dict(
            filters=to_filters(request.get("whitelist", ())),
            number=number,
            oldest_first=bool(request.get("oldest_first", False)))
        blacklist = to_filters(request.get("blacklist", ()))
        if blacklist:
            kwargs["blacklist"] = blacklist
        flows = self.observer.get_flows(**kwargs)
        for f in flows:
            is_flow = hasattr(f, "to_dict")
            if binary:
                if not is_flow:
                    # relay-aggregated dicts carry no Flow object to
                    # re-encode; answering a proto request with JSON
                    # bytes would crash the client's decoder
                    # mid-stream — fail the RPC explicitly instead
                    context.abort(
                        grpc.StatusCode.UNIMPLEMENTED,
                        "binary wire unavailable for relay-aggregated "
                        "flows; use the JSON encoding")
                yield encode_get_flows_response(f, self.node_name)
            else:
                yield _dumps({"flow": f.to_dict() if is_flow
                              else dict(f)})

    def _server_status(self, request: dict, context) -> bytes:
        from .proto import encode_server_status

        obs = self.observer
        if hasattr(obs, "server_status"):
            st = obs.server_status()
        else:
            st = {"num_flows": len(obs), "seen_flows": obs.seq,
                  "max_flows": obs.capacity}
        if request.get("_wire") == "proto":
            return encode_server_status(
                int(st.get("num_flows", 0)), int(st.get("max_flows", 0)),
                int(st.get("seen_flows", 0)))
        return _dumps(st)


def serve(observer, address: str = "unix:///tmp/hubble.sock",
          max_workers: int = 4, node_name: str = "") -> grpc.Server:
    """Start the Observer service (unix:// or host:port address).
    ``observer`` may be an Observer or a Relay (relay exposes the same
    GetFlows protocol, making this the hubble-relay server too)."""
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (_ObserverHandler(observer, node_name),))
    server.add_insecure_port(address)
    server.start()
    return server


class ObserverClient:
    """GetFlows/ServerStatus client; quacks like an Observer for the
    relay (get_flows returns flow dicts)."""

    def __init__(self, address: str = "unix:///tmp/hubble.sock"):
        self.channel = grpc.insecure_channel(address)
        self._get = self.channel.unary_stream(
            f"/{SERVICE}/GetFlows",
            request_serializer=_dumps, response_deserializer=_loads)
        self._status = self.channel.unary_unary(
            f"/{SERVICE}/ServerStatus",
            request_serializer=_dumps, response_deserializer=_loads)

    def get_flows(self, filters: Sequence = (), number: int = 100,
                  oldest_first: bool = False,
                  blacklist: Sequence = ()) -> List[dict]:
        req = {"number": number, "oldest_first": oldest_first}
        if filters:
            req["whitelist"] = [f.__dict__ for f in filters]
        if blacklist:
            req["blacklist"] = [f.__dict__ for f in blacklist]
        return [msg["flow"] for msg in self._get(req)]

    def server_status(self) -> dict:
        return self._status({})

    def close(self) -> None:
        self.channel.close()


class BinaryObserverClient:
    """Binary flow.proto client — what a stock hubble CLI's generated
    stubs put on the wire; responses decode through the schema-less
    decoder (flow/proto.py field numbers)."""

    def __init__(self, address: str = "unix:///tmp/hubble.sock"):
        self.channel = grpc.insecure_channel(address)
        self._get = self.channel.unary_stream(
            f"/{SERVICE}/GetFlows",
            request_serializer=_ident, response_deserializer=_ident)
        self._status = self.channel.unary_unary(
            f"/{SERVICE}/ServerStatus",
            request_serializer=_ident, response_deserializer=_ident)

    def get_flows(self, number: int = 100,
                  whitelist: Sequence[dict] = (),
                  blacklist: Sequence[dict] = ()) -> List[dict]:
        """Returns schema-less decodes of each GetFlowsResponse:
        {field: [values]} with field 1 = the encoded Flow."""
        from .proto import decode_message, encode_get_flows_request

        req = encode_get_flows_request(number=number,
                                       whitelist=whitelist,
                                       blacklist=blacklist)
        return [decode_message(raw) for raw in self._get(req)]

    def get_flow_dicts(self, number: int = 100,
                       whitelist: Sequence[dict] = (),
                       blacklist: Sequence[dict] = ()) -> List[dict]:
        """GetFlows decoded to hubble-JSON-shaped dicts with NATIVE
        drop-reason fidelity (``flow/proto.decode_flow`` prefers the
        field-3 native code over the lossy field-25 enum) — the
        relay-peer surface over the binary wire: a Relay fed these
        merges flows whose repo-native drop reasons survive the
        round trip (DIVERGENCES #15 caveat, closed)."""
        from .proto import decode_flow

        out = []
        for msg in self.get_flows(number=number, whitelist=whitelist,
                                  blacklist=blacklist):
            if 1 in msg:
                out.append(decode_flow(msg[1][-1]))
        return out

    def server_status(self) -> dict:
        from .proto import decode_message

        msg = decode_message(self._status(b""))
        return {"num_flows": int(msg.get(1, [0])[-1]),
                "max_flows": int(msg.get(2, [0])[-1]),
                "seen_flows": int(msg.get(3, [0])[-1])}

    def close(self) -> None:
        self.channel.close()
