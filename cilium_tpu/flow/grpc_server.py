"""The Observer gRPC API: hubble's external surface.

Reference: upstream hubble serves ``observer.Observer`` over gRPC
(``GetFlows`` server-streaming + ``ServerStatus``; schema
``api/v1/flow/flow.proto``).  This environment ships the grpc runtime
but not the protoc-gen-grpc plugin, so the service is registered with
generic method handlers and the messages travel as the flow.proto
JSON rendering (the exact dicts ``Flow.to_dict`` produces — the same
bytes hubble's JSON exporter emits).  A consumer with real hubble
stubs would need the binary proto; the METHOD SHAPE and payload schema
are kept so that swap is mechanical.

``serve(observer, address)`` -> grpc.Server;
:class:`ObserverClient` is the matching client (used by the relay for
remote peers and by the CLI's ``hubble observe``).
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional, Sequence

import grpc

SERVICE = "observer.Observer"

_dumps = lambda d: json.dumps(d).encode()  # noqa: E731
_loads = lambda b: json.loads(b.decode()) if b else {}  # noqa: E731


class _ObserverHandler(grpc.GenericRpcHandler):
    def __init__(self, observer):
        self.observer = observer

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/GetFlows":
            return grpc.unary_stream_rpc_method_handler(
                self._get_flows,
                request_deserializer=_loads,
                response_serializer=_dumps)
        if method == f"/{SERVICE}/ServerStatus":
            return grpc.unary_unary_rpc_method_handler(
                self._server_status,
                request_deserializer=_loads,
                response_serializer=_dumps)
        return None

    def _get_flows(self, request: dict, context) -> Iterator[dict]:
        from .observer import FlowFilter

        number = int(request.get("number", 100))
        filters = [FlowFilter(**f)
                   for f in request.get("whitelist", ())]
        flows = self.observer.get_flows(
            filters=filters, number=number,
            oldest_first=bool(request.get("oldest_first", False)))
        for f in flows:
            yield {"flow": f.to_dict() if hasattr(f, "to_dict")
                   else dict(f)}

    def _server_status(self, request: dict, context) -> dict:
        obs = self.observer
        if hasattr(obs, "server_status"):
            return obs.server_status()
        return {"num_flows": len(obs), "seen_flows": obs.seq,
                "max_flows": obs.capacity}


def serve(observer, address: str = "unix:///tmp/hubble.sock",
          max_workers: int = 4) -> grpc.Server:
    """Start the Observer service (unix:// or host:port address).
    ``observer`` may be an Observer or a Relay (relay exposes the same
    GetFlows protocol, making this the hubble-relay server too)."""
    from concurrent import futures

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_ObserverHandler(observer),))
    server.add_insecure_port(address)
    server.start()
    return server


class ObserverClient:
    """GetFlows/ServerStatus client; quacks like an Observer for the
    relay (get_flows returns flow dicts)."""

    def __init__(self, address: str = "unix:///tmp/hubble.sock"):
        self.channel = grpc.insecure_channel(address)
        self._get = self.channel.unary_stream(
            f"/{SERVICE}/GetFlows",
            request_serializer=_dumps, response_deserializer=_loads)
        self._status = self.channel.unary_unary(
            f"/{SERVICE}/ServerStatus",
            request_serializer=_dumps, response_deserializer=_loads)

    def get_flows(self, filters: Sequence = (), number: int = 100,
                  oldest_first: bool = False) -> List[dict]:
        req = {"number": number, "oldest_first": oldest_first}
        if filters:
            req["whitelist"] = [f.__dict__ for f in filters]
        return [msg["flow"] for msg in self._get(req)]

    def server_status(self) -> dict:
        return self._status({})

    def close(self) -> None:
        self.channel.close()
