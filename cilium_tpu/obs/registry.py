"""The unified metrics registry: every prometheus series the agent
exports, declared in ONE place.

Reference: upstream cilium ``pkg/metrics`` — a single agent registry
every subsystem registers into, backing ``GET /metrics``.  Before
this module the exposition text was hand-assembled in four places
(serving stats, ``flow/metrics.py``, the loader metricsmap render,
the fault/recovery counters), each with its own formatting and its
own chances to drift; ``scripts/check_metrics_registry.py`` lints
that no exposition text is built anywhere else, so the scatter
cannot regrow.

Pull model: a metric is a NAME + TYPE + HELP + a zero-arg COLLECT
callable sampled at render time, so registration costs the hot path
nothing — all reads happen when an operator scrapes.  A collector
returning ``None`` omits its series (e.g. serving counters while no
session is active, matching the pre-registry behavior tests pin).

Histograms render as CUMULATIVE log2 buckets (``_bucket{le=...}`` +
``_sum`` + ``_count``) instead of only p50/95/99 point reads — the
form Prometheus can aggregate across scrapes and nodes.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

from ..serving.stats import N_BUCKETS, LatencyHistogram

# collect() -> None (omit) | scalar | [(labels_dict, value), ...]
Collect = Callable[[], object]

# prometheus metric-name grammar — asserted at registration time so a
# typo'd name fails where it was written, not on a scraper.  \Z, not
# $: a $ matches BEFORE a trailing newline, which is exactly the
# exposition-tearing input this guard exists to reject
_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(round(v, 6))
    return str(v)


def escape_label_value(v) -> str:
    """Prometheus text-exposition label-value escaping (backslash,
    double quote, newline — in that order, per the format spec).
    One definition for the registry's own ``_labels`` AND the
    cluster relay's injected ``node`` label (``obs/relay.py``): node
    names are operator input, and an unescaped quote or newline
    would tear the whole exposition, not one sample."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(d: Dict[str, object]) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in d.items())
    return "{" + inner + "}"


class MetricsRegistry:
    """Self-describing registry.  ``prepare`` (optional) runs once
    per render before any collector — the place to snapshot shared
    state (e.g. one ``serving_stats()`` call feeding a dozen
    collectors) instead of re-snapshotting per metric."""

    def __init__(self, prepare: Optional[Callable[[], None]] = None):
        self._metrics: List[dict] = []
        self._names: set = set()
        self._prepare = prepare

    def _add(self, name: str, mtype: str, help_: str,
             collect: Collect) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not a valid prometheus "
                f"series name ([a-zA-Z_:][a-zA-Z0-9_:]*)")
        if name in self._names:
            raise ValueError(f"metric {name!r} registered twice")
        self._names.add(name)
        self._metrics.append({"name": name, "type": mtype,
                              "help": help_, "collect": collect})

    def counter(self, name: str, help_: str,
                collect: Collect) -> None:
        self._add(name, "counter", help_, collect)

    def gauge(self, name: str, help_: str, collect: Collect) -> None:
        self._add(name, "gauge", help_, collect)

    def histogram(self, name: str, help_: str,
                  collect: Callable[[], Optional[LatencyHistogram]]
                  ) -> None:
        """``collect`` returns the live :class:`LatencyHistogram`
        (log2 µs buckets) or None to omit."""
        self._add(name, "histogram", help_, collect)

    # -- rendering -----------------------------------------------------
    def render(self) -> str:
        """The ``GET /metrics`` body (prometheus text exposition)."""
        if self._prepare is not None:
            self._prepare()
        lines: List[str] = []
        for m in self._metrics:
            try:
                got = m["collect"]()
            except Exception:  # a broken collector must not 500 the
                continue  # whole scrape
            if got is None:
                continue
            name = m["name"]
            lines.append(f"# HELP {name} {m['help']}")
            if m["type"] == "histogram":
                self._render_histogram(lines, name, got)
                continue
            lines.append(f"# TYPE {name} {m['type']}")
            if isinstance(got, (list, tuple)):
                for labels, v in got:
                    lines.append(f"{name}{_labels(labels)} {_fmt(v)}")
            else:
                lines.append(f"{name} {_fmt(got)}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_histogram(lines: List[str], name: str,
                          h: LatencyHistogram) -> None:
        """Cumulative-bucket exposition of a log2 µs histogram.
        Bucket ``i`` holds values in ``[2^(i-1), 2^i)`` (µs), so the
        cumulative count at ``le="2^i"`` includes buckets ``0..i``.
        Trailing empty buckets collapse into ``+Inf`` — cumulative
        semantics survive a partial bound list."""
        lines.append(f"# TYPE {name} histogram")
        # copy the bucket list ONCE and derive +Inf/_count from that
        # copy: re-reading h.count while the drain thread is between
        # its bucket and count increments would emit a non-monotone
        # cumulative series (+Inf below an earlier le bucket)
        buckets = list(h.buckets)
        total = sum(buckets)
        acc = 0
        top = max((i for i, c in enumerate(buckets) if c),
                  default=-1)
        for i in range(min(top + 1, N_BUCKETS)):
            acc += buckets[i]
            lines.append(f'{name}_bucket{{le="{1 << i}"}} {acc}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{name}_sum {_fmt(h.total_us)}")
        lines.append(f"{name}_count {total}")

    def inventory(self) -> List[dict]:
        """The self-description: name/type/help for every registered
        metric (the README metric-inventory table's source)."""
        return [{"name": m["name"], "type": m["type"],
                 "help": m["help"]} for m in self._metrics]

    def kind(self, name: str) -> Optional[str]:
        """counter/gauge/histogram for a registered name, else None
        — the SeriesHistory sampler's reset-vs-passthrough switch."""
        for m in self._metrics:
            if m["name"] == name:
                return m["type"]
        return None

    # -- sampling (the SeriesHistory feed) -----------------------------
    def sample(self, names: "Sequence[str]") -> Dict[str, object]:
        """One NUMERIC sample of a declared subset — the
        ``SeriesHistory`` feed.  Same pull model as :meth:`render`
        (``prepare`` once, then only the requested collectors; a
        broken or None collector omits its series), but values come
        back as numbers, not exposition text: counters/gauges as a
        float (labelled families summed — history retains the
        family total, the live exposition keeps the breakdown),
        histograms as ``{"buckets": [...], "count", "sum"}`` with
        the bucket list copied ONCE (the torn-read discipline of
        ``_render_histogram``: count derives from that copy)."""
        want = set(names)
        if self._prepare is not None:
            self._prepare()
        out: Dict[str, object] = {}
        for m in self._metrics:
            if m["name"] not in want:
                continue
            try:
                got = m["collect"]()
            except Exception:  # noqa: BLE001 — a broken collector
                continue  # must not kill the sampler tick
            if got is None:
                continue
            if m["type"] == "histogram":
                buckets = list(got.buckets)
                out[m["name"]] = {"buckets": buckets,
                                  "count": sum(buckets),
                                  "sum": float(got.total_us)}
            elif isinstance(got, (list, tuple)):
                total = 0.0
                for _labels_d, v in got:
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)):
                        continue
                    total += float(v)
                out[m["name"]] = total
            elif isinstance(got, (int, float)) and not isinstance(
                    got, bool):
                out[m["name"]] = float(got)
        return out


# -- flow metrics (pkg/hubble/metrics analogue) -----------------------
def register_flow_metrics(reg: MetricsRegistry, fm) -> None:
    """Register the flow-stream handlers' series (``FlowMetrics``
    dicts) — the pre-registry ``flow/metrics.py`` render, now behind
    the one registry (satellite: these counters reach the prometheus
    endpoint through the same path as everything else)."""
    reg.counter(
        "hubble_flows_processed_total",
        "flows seen on the monitor stream by verdict/direction",
        lambda: [({"verdict": v, "direction": d}, n)
                 for (v, d), n in sorted(fm.flows_total.items())])
    reg.counter(
        "hubble_drop_total",
        "dropped flows by datapath reason code/direction",
        lambda: [({"reason": r, "direction": d}, n)
                 for (r, d), n in sorted(fm.drops_total.items())])
    reg.counter(
        "hubble_port_distribution_total",
        "destination (protocol, port) histogram over the flow stream",
        lambda: [({"protocol": p, "port": port}, n)
                 for (p, port), n in
                 sorted(fm.port_distribution.items())])
    reg.counter(
        "hubble_policy_verdicts_total",
        "policy-verdict events by verdict/match type",
        lambda: [({"verdict": v, "match": match}, n)
                 for (v, match), n in sorted(fm.policy_verdicts.items())])


def build_daemon_registry(daemon) -> MetricsRegistry:
    """Wire one agent's full metric surface: datapath metricsmap,
    control-plane gauges, serving counters + fault-tolerance plane,
    the NEW registry-backed idle-tick gauges (queue depth, arena
    occupancy, in-flight window) and cumulative latency histograms,
    compile/trace introspection, CT snapshots, and the flow-stream
    handlers."""
    state: Dict[str, object] = {}

    def prepare() -> None:
        state["sv"] = daemon.serving_stats()
        # snapshot the lock-guarded summaries ONCE per scrape — the
        # per-key collectors below index these instead of re-taking
        # the compile-log/tracer locks per metric
        log = getattr(daemon.loader, "compile_log", None)
        state["compile"] = (log.summary() if log is not None
                            else None)
        s = daemon._serving
        tr = s.get("tracer") if s is not None else None
        state["trace"] = tr.stats() if tr is not None else None

    def sv(*keys, active_only: bool = True):
        """Pluck a value out of the serving snapshot (None omits)."""
        cur = state.get("sv") or {}
        if active_only and not cur.get("active"):
            return None
        for k in keys:
            if not isinstance(cur, dict) or k not in cur:
                return None
            cur = cur[k]
        return cur

    def runtime():
        s = daemon._serving
        return s.get("runtime") if s is not None else None

    reg = MetricsRegistry(prepare=prepare)

    # -- datapath + control plane -------------------------------------
    def datapath_packets():
        m = daemon.loader.metrics()
        return [({"reason": r, "direction":
                  "ingress" if d == 0 else "egress"}, int(m[r, d]))
                for r in range(m.shape[0]) for d in (0, 1)
                if m[r, d]]

    reg.counter("cilium_datapath_packets_total",
                "verdicted packets by reason code and direction "
                "(the device metricsmap)", datapath_packets)
    reg.gauge("cilium_policy_revision",
              "policy repository revision",
              lambda: daemon.repo.revision)
    reg.gauge("cilium_endpoint_count", "registered local endpoints",
              lambda: len(daemon.endpoints.list()))
    reg.gauge("cilium_identity_count", "allocated security identities",
              lambda: len(daemon.allocator.all_identities()))

    # -- serving counters (only while a session is active) ------------
    reg.counter("cilium_serving_verdicts_total",
                "real (valid) rows dispatched by the serving plane",
                lambda: sv("verdicts"))
    reg.counter("cilium_serving_shed_total",
                "packets shed at serving admission",
                lambda: sv("shed"))
    reg.counter("cilium_serving_submitted_total",
                "packets offered to serving admission (the "
                "availability SLO denominator: shed + recovery "
                "drops over this)",
                lambda: sv("submitted"))
    reg.counter("cilium_serving_batches_total",
                "serving batches dispatched", lambda: sv("batches"))
    # the K-batch superbatch scoreboard (ISSUE 11): device dispatches
    # vs batches — batches/dispatches > 1 IS the amortization the
    # fused K-batch scan buys; the fill gauge defends the no-empty-
    # steps assembly (real rows / rows shipped in superbatches)
    reg.counter("cilium_serving_dispatches_total",
                "device dispatches (a superbatch carries K batches)",
                lambda: sv("dispatch", "dispatches"))
    reg.counter("cilium_serving_superbatches_total",
                "dispatches that carried K > 1 fused batches",
                lambda: sv("dispatch", "superbatches"))
    reg.gauge("cilium_serving_batches_per_dispatch",
              "batches per device dispatch (superbatch amortization)",
              lambda: sv("dispatch", "batches-per-dispatch"))
    reg.counter("cilium_serving_h2d_bytes_total",
                "host->device header bytes shipped (padding included)",
                lambda: sv("h2d", "bytes"))
    reg.counter("cilium_serving_packed_batches_total",
                "batches shipped in the packed 16 B/packet format",
                lambda: sv("h2d", "packed-batches"))
    reg.counter("cilium_serving_route_overflow_total",
                "packets lost to per-shard block overflow (flow skew)",
                lambda: sv("route-overflow"))

    # -- the async event plane (serving/eventplane.py): the d2h leg's
    # scoreboard.  d2h bytes are counted at SWAP (they crossed the
    # link whatever happens to the window), window drops are the
    # no-silent-loss ledger's monitor-plane side, and ring lap loss
    # is summed over every window — joined or dropped — so a lagging
    # consumer shows up here even when its windows never decode ------
    reg.counter("cilium_serving_d2h_bytes_total",
                "device->host event-window bytes shipped "
                "(occupancy-bounded gather + cursor)",
                lambda: sv("event-plane", "d2h-bytes"))
    reg.counter("cilium_serving_event_windows_dropped_total",
                "drain windows lost by the event plane (queue "
                "overflow, join failure, worker death, stop sweep)",
                lambda: sv("event-plane", "windows-dropped"))
    reg.counter("cilium_serving_event_window_overflows_total",
                "drain windows dropped at the bounded window queue",
                lambda: sv("event-plane", "queue-overflows"))
    reg.counter("cilium_serving_event_worker_restarts_total",
                "event-join worker restarts spent",
                lambda: sv("event-plane", "worker-restarts"))
    reg.counter("cilium_ring_lost_total",
                "ring events lost to lap overrun (appended - "
                "capacity while the consumer lagged a full lap)",
                lambda: sv("event-plane", "ring-lost"))

    def ring_events_total():
        ep = sv("event-plane")
        if not isinstance(ep, dict):
            return None
        return (int(ep.get("events-joined") or 0)
                + int(ep.get("events-dropped") or 0)
                + int(ep.get("ring-lost") or 0))

    reg.counter("cilium_serving_ring_events_total",
                "ring events produced (joined + dropped + lapped) — "
                "the event-plane loss SLO denominator",
                ring_events_total)

    def eventplane():
        s = daemon._serving
        return s.get("eventplane") if s is not None else None

    reg.gauge("cilium_serving_event_windows_pending",
              "drain windows queued or joining on the event-join "
              "worker (live at scrape time)",
              lambda: (w.pending if (w := eventplane()) is not None
                       else None))
    reg.histogram("cilium_serving_event_join_lag_us",
                  "window swap -> events emitted lag on the "
                  "event-join worker (µs, log2 buckets)",
                  lambda: (w.join_lag
                           if (w := eventplane()) is not None
                           else None))

    # -- the L7 proxy plane (serving/l7plane.py + proxy/worker.py):
    # the redirect ledger — redirected == allowed + denied + shed +
    # failed — surfaced leg by leg.  Collectors prefer the LIVE
    # session's snapshot and fall back to the last session's final
    # ledger (daemon._l7_last), so the post-stop scrape still shows
    # where every redirected row went.  CTA012 pins this floor -------
    def l7(*keys):
        cur = sv("l7", *keys)
        if cur is not None:
            return cur
        cur = daemon._l7_last
        for k in keys:
            if not isinstance(cur, dict) or k not in cur:
                return None
            cur = cur[k]
        return cur

    reg.counter("cilium_l7_redirected_total",
                "redirect rows ingested by the L7 proxy plane",
                lambda: l7("redirected"))
    reg.counter("cilium_l7_allowed_total",
                "redirect rows the L7 verdict allowed",
                lambda: l7("l7-allowed"))
    reg.counter("cilium_l7_denied_total",
                "redirect rows the L7 verdict denied",
                lambda: l7("l7-denied"))
    reg.counter("cilium_l7_shed_total",
                "redirect rows shed at the bounded L7 task queue "
                "(overflow, stopped/terminal pool)",
                lambda: l7("l7-shed"))
    reg.counter("cilium_l7_failed_total",
                "redirect rows lost to parse/handler failure or "
                "worker death (counted, never silent)",
                lambda: l7("l7-failed"))
    reg.counter("cilium_l7_worker_restarts_total",
                "L7 worker restarts spent against the pool budget",
                lambda: l7("worker-restarts"))
    reg.counter("cilium_l7_dns_answers_total",
                "DNS answers observed by L7 workers (each feeds a "
                "live FQDN identity mint)",
                lambda: l7("dns-answers"))

    def l7pool():
        p = daemon._l7plane
        return p.pool if p is not None else None

    reg.gauge("cilium_l7_tasks_pending",
              "redirect tasks queued or parsing on the L7 pool "
              "(live at scrape time)",
              lambda: (p.pending if (p := l7pool()) is not None
                       else None))
    reg.histogram("cilium_l7_parse_lag_us",
                  "redirect submit -> L7 verdict lag on the worker "
                  "pool (µs, log2 buckets)",
                  lambda: (p.parse_lag
                           if (p := l7pool()) is not None else None))

    # -- clustermesh serving tier (cilium_tpu/cluster): per-node
    # series for the tier the node belongs to.  Collectors read the
    # daemon's _cluster back reference live — None (not a cluster
    # member) omits the whole family.  CTA008 pins every router drop
    # counter to a series here ------------------------------------------
    def cl(fn):
        c = daemon._cluster
        return None if c is None else fn(c)

    reg.counter("cilium_cluster_submitted_total",
                "packets offered to the cluster front-end router",
                lambda: cl(lambda c: (c.router.submitted
                                      if c.router is not None
                                      else None)))
    reg.counter("cilium_cluster_router_overflow_total",
                "packets shed at the router's bounded per-node "
                "forward queues (REASON_CLUSTER_OVERFLOW)",
                lambda: cl(lambda c: c.router_overflow_total()))
    reg.counter("cilium_cluster_failover_dropped_total",
                "packets lost migrating a dead node's forward queue "
                "onto its failover peer",
                lambda: cl(lambda c: c.failover_dropped_total()))
    reg.counter("cilium_cluster_crash_dropped_total",
                "rows a SIGKILLed worker process admitted (per its "
                "last data-channel ack) but never resolved — the "
                "process-mode crash-loss ledger term",
                lambda: cl(lambda c: c.crash_dropped_total()))
    reg.counter("cilium_cluster_failovers_total",
                "completed node failovers (CT replay + router re-pin)",
                lambda: cl(lambda c: c.failovers_total()))
    reg.counter("cilium_cluster_scale_outs_total",
                "completed live scale-outs (node joined, slot share "
                "re-pinned, moved slots' CT migrated)",
                lambda: cl(lambda c: sum(
                    1 for e in c.scale_events
                    if e.get("kind") != "scale-in")))
    reg.counter("cilium_cluster_scale_ins_total",
                "completed live scale-ins (node retired cleanly: "
                "window drained, slots re-pinned, CT migrated to "
                "each slot's new owner)",
                lambda: cl(lambda c: c.scale_ins_total()))
    reg.counter("cilium_cluster_obs_scrapes_total",
                "successful relay scrapes of worker nodes (the "
                "cluster scrape-health SLO denominator)",
                lambda: cl(lambda c: c.obs.scrape_counts()[0]))
    reg.counter("cilium_cluster_obs_scrape_errors_total",
                "failed relay scrapes of worker nodes",
                lambda: cl(lambda c: c.obs.scrape_counts()[1]))
    reg.gauge("cilium_cluster_inflight_frames",
              "pipelined data-channel frames sent but not yet "
              "cumulatively acked, summed over windowed nodes "
              "(live at scrape time)",
              lambda: cl(lambda c: c.inflight_frames()))
    reg.counter("cilium_cluster_acks_coalesced_total",
                "per-frame acks elided by the worker-side ack "
                "coalescer (a cumulative ack covering k frames "
                "counts k-1)",
                lambda: cl(lambda c: c.acks_coalesced_total()))
    reg.counter("cilium_cluster_window_stalls_total",
                "times a forwarder exhausted its send-window credit "
                "and waited for a cumulative ack",
                lambda: cl(lambda c: c.window_stalls_total()))
    reg.histogram("cilium_cluster_forward_latency_us",
                  "router enqueue -> node delivered (queue wait + "
                  "transport round trip, µs, log2 buckets)",
                  lambda: cl(lambda c: (c.router.forward_latency
                                        if c.router is not None
                                        else None)))
    reg.gauge("cilium_cluster_nodes",
              "cluster node replicas by liveness",
              lambda: cl(lambda c: [
                  ({"state": "live"}, c.live_dead_counts()[0]),
                  ({"state": "dead"}, c.live_dead_counts()[1])]))
    reg.gauge("cilium_cluster_forward_pending",
              "rows queued in the router's forward queues "
              "(live at scrape time)",
              lambda: cl(lambda c: c.forward_pending()))
    # -- encrypted data channel (ISSUE 18) ----------------------------
    reg.counter("cilium_cluster_crypto_rejected_total",
                "sealed cluster frames some channel end refused "
                "(AEAD auth, replay, epoch skew, injected fault) — "
                "each a counted NACK or parent-side open failure, "
                "never a worker crash",
                lambda: cl(lambda c: c.crypto_rejected_total()))
    reg.counter("cilium_cluster_crypto_replays_total",
                "sealed frames refused as REPLAYS specifically "
                "(sequence already seen inside the epoch's replay "
                "window)",
                lambda: cl(lambda c: c.crypto_replays_total()))
    reg.counter("cilium_cluster_crypto_rotations_total",
                "cluster-wide key-epoch rotation operations "
                "completed (rotate_epoch: kvstore-published, every "
                "live channel re-keyed worker-first under grace)",
                lambda: cl(lambda c: c.crypto_rotations_total()))
    reg.counter("cilium_cluster_crypto_dropped_total",
                "rows lost to crypto rejects (the ledger term "
                "paired with crypto_rejected: every refused data "
                "frame's rows land here, exact)",
                lambda: cl(lambda c: c.crypto_dropped_total()))

    # -- fault-tolerance plane ----------------------------------------
    reg.counter("cilium_serving_restarts_total",
                "drain-loop restarts spent by the serving watchdog",
                lambda: sv("fault-tolerance", "restarts"))
    reg.counter("cilium_serving_dispatch_timeouts_total",
                "dispatches declared hung at the deadline",
                lambda: sv("fault-tolerance", "dispatch-timeouts"))
    reg.counter("cilium_serving_recovery_dropped_total",
                "rows accounted by the recovery plane "
                "(dead/hung/failed dispatch + stop sweep)",
                lambda: sv("fault-tolerance", "recovery-dropped"))
    reg.gauge("cilium_serving_degraded",
              "1 while the degraded-mode ladder is below its top rung",
              lambda: ([({"mode": lad["rung"]},
                         1 if lad["degraded"] else 0)]
                       if (lad := sv("ladder")) else None))
    reg.counter("cilium_serving_demotions_total",
                "degraded-mode ladder demotions",
                lambda: (lad["demotions"]
                         if (lad := sv("ladder")) else None))

    # -- registry-backed gauges.  Queue backlog and the in-flight
    # window read LIVE at scrape time (plain attribute / len reads —
    # the idle tick only fires when the queue is EMPTY, so a sampled
    # copy would read ~0 during exactly the overload episodes the
    # backlog gauge exists for); arena occupancy iterates the slot
    # dict, which only the drain thread may do safely, so it stays on
    # the idle-tick sample (ServingRuntime._sample_gauges) ------------
    def live_queue(attr):
        def collect():
            rt = runtime()
            return getattr(rt.queue, attr) if rt is not None else None

        return collect

    def idle_gauge(key):
        def collect():
            rt = runtime()
            if rt is None:
                return None
            return rt.stats.gauges.get(key)

        return collect

    reg.gauge("cilium_serving_queue_pending",
              "admission-queue backlog (live at scrape time)",
              live_queue("pending"))
    reg.gauge("cilium_serving_queue_depth",
              "admission-queue capacity", live_queue("capacity"))
    reg.gauge("cilium_serving_arena_bytes",
              "staging-arena bytes allocated at the last idle tick",
              idle_gauge("arena-bytes"))
    reg.gauge("cilium_serving_arena_shapes",
              "distinct staging-slot shapes allocated",
              idle_gauge("arena-shapes"))

    def inflight_window():
        s = daemon._serving
        if s is None or s.get("runtime") is None:
            return None
        return len(s["window"])

    reg.gauge("cilium_serving_inflight_window",
              "serve_batch header windows retained for the event join "
              "(live at scrape time)", inflight_window)

    # -- cumulative latency histograms --------------------------------
    def hist(attr):
        def collect():
            rt = runtime()
            return getattr(rt.stats, attr) if rt is not None else None

        return collect

    reg.histogram("cilium_serving_queue_wait_us",
                  "admission -> dispatch wait (µs, log2 buckets)",
                  hist("queue_wait"))
    reg.histogram("cilium_serving_latency_us",
                  "admission -> events-emitted end-to-end latency "
                  "(µs, log2 buckets)", hist("latency"))

    # -- live policy churn (datapath/tables.py table versioning):
    # the published table generation and the swap plane's latency.
    # Collectors read the loader's versioner live — single-writer
    # counters/log2-buckets, same torn-read tolerance as every
    # serving histogram ------------------------------------------------
    def tablesv():
        return getattr(daemon.loader, "tables", None)

    reg.gauge("cilium_policy_generation",
              "published device table generation (monotonic; bumps "
              "on every attach/patch publish flip)",
              lambda: (tv.generation
                       if (tv := tablesv()) is not None else None))
    reg.counter("cilium_policy_swaps_total",
                "table generation flips published (full + delta "
                "attaches, identity/ipcache patches, auth grants)",
                lambda: (tv.swaps
                         if (tv := tablesv()) is not None else None))
    reg.histogram("cilium_policy_swap_latency_us",
                  "dispatch-lock hold for one table publish flip "
                  "(µs, log2 buckets) — the drain thread's swap "
                  "stall ceiling",
                  lambda: (tv.swap_stall
                           if (tv := tablesv()) is not None
                           else None))
    reg.histogram("cilium_policy_update_visible_us",
                  "table mutation entry -> published generation "
                  "latency (µs, log2 buckets)",
                  lambda: (tv.update_visible
                           if (tv := tablesv()) is not None
                           else None))

    # -- compile / trace introspection --------------------------------
    def compile_stat(key):
        def collect():
            summ = state.get("compile")
            return summ[key] if summ is not None else None

        return collect

    reg.counter("cilium_serving_compiles_total",
                "XLA executables compiled on the serving path",
                compile_stat("compiles"))
    reg.counter("cilium_serving_compile_violations_total",
                "one-executable-per-(rung, mode) invariant violations",
                compile_stat("violations"))
    reg.gauge("cilium_serving_executables",
              "live serving executables by (mode, shape)",
              compile_stat("executables"))

    def tracer_stat(key):
        def collect():
            st = state.get("trace")
            return st[key] if st is not None else None

        return collect

    reg.counter("cilium_obs_spans_started_total",
                "trace spans allocated at admission (1-in-N sampled)",
                tracer_stat("started"))
    reg.counter("cilium_obs_spans_completed_total",
                "trace spans that reached the verdict-join boundary",
                tracer_stat("completed"))
    reg.counter("cilium_obs_spans_dropped_total",
                "trace spans whose packet died mid-pipeline",
                tracer_stat("dropped"))

    # -- the flow analytics plane + incident flight recorder.  These
    # counters live for the daemon's lifetime (not session-scoped
    # like the serving block): aggregation also runs on the offline
    # process_batch path, and incidents outlive the session that
    # fired them ------------------------------------------------------
    reg.counter("cilium_flow_agg_windows_total",
                "aggregation windows closed by the flow analytics "
                "plane (ring-of-windows roll-overs)",
                lambda: daemon.analytics.windows.windows_closed)
    reg.counter("cilium_top_talkers_evictions_total",
                "space-saving sketch evictions across the 4-tuple "
                "and identity-pair top-K sketches",
                lambda: (daemon.analytics.talkers.evictions
                         + daemon.analytics.pairs.evictions))
    reg.counter("cilium_flow_agg_batches_dropped_total",
                "decoded batches the analytics plane lost (pending-"
                "queue overflow or poisoned ingest)",
                lambda: daemon.analytics.batches_dropped)
    reg.counter("cilium_incidents_total",
                "named incidents recorded by the flight recorder",
                # via stats(): a locked copy — unlocked iteration
                # races first-of-a-kind inserts on worker/watchdog
                # threads and would silently drop the series from
                # the scrape
                lambda: ([({"kind": k}, n) for k, n in sorted(
                    daemon.flightrec.stats()[
                        "incidents-by-kind"].items())]
                    or None))
    reg.counter("cilium_sysdump_writes_total",
                "sysdump bundles written by the flight recorder",
                lambda: daemon.flightrec.writes_total)

    # -- map pressure (datapath/pressure.py).  Collectors read the
    # monitor's CACHED last sample — the periodic controller does the
    # device work; a scrape never touches the device.  None before
    # the first sample (or when the backend cannot measure) omits
    # the series, the standard collector contract ------------------
    def pressure(*keys):
        def collect():
            last = daemon.pressure.last
            if last is None:
                return None
            cur = last
            for k in keys:
                if not isinstance(cur, dict):
                    return None
                cur = cur.get(k)
            return cur

        return collect

    reg.gauge("cilium_ct_occupancy",
              "CT map occupancy fraction (occupied slots / capacity, "
              "live + expired-unswept) at the last pressure sample",
              pressure("ct", "occupancy"))
    reg.counter("cilium_ct_insert_drops_total",
                "CT inserts dropped at a full probe window (map "
                "pressure; restore-time placement drops included)",
                pressure("ct", "insert-drops"))
    reg.counter("cilium_nat_pool_failures_total",
                "SNAT port allocations failed on pool exhaustion "
                "(DROP_NAT_NO_MAPPING pressure)",
                pressure("nat", "failures"))
    reg.gauge("cilium_lpm_occupancy",
              "LPM/ipcache table occupancy fraction (programmed "
              "prefixes / table capacity) at the last pressure "
              "sample",
              pressure("lpm", "occupancy"))
    reg.gauge("cilium_policy_map_occupancy",
              "policy-table occupancy fraction (programmed "
              "identity rows / table capacity) at the last "
              "pressure sample",
              pressure("policy", "occupancy"))
    reg.gauge("cilium_map_pressure",
              "1 while the map-pressure monitor is in the pressure "
              "state (CT aging sweep accelerated)",
              lambda: (1 if daemon.pressure.state == "pressure"
                       else 0))

    # -- CT snapshots (age/entries ride recovery decisions) -----------
    def ct_snap(key):
        def collect():
            snap = daemon.ct_snapshot_info()
            return snap[key] if snap is not None else None

        return collect

    reg.gauge("cilium_ct_snapshot_age_seconds",
              "age of the retained CT snapshot recovery would restore",
              ct_snap("age-seconds"))
    reg.gauge("cilium_ct_snapshot_entries",
              "entries in the retained CT snapshot",
              ct_snap("entries"))

    # -- SLO plane (obs/slo.py).  Collectors read the engine's CACHED
    # last evaluation — the slo-sampler thread does the window math,
    # a scrape never evaluates.  getattr: the engine is constructed
    # AFTER the registry (it samples the registry), so the back
    # reference resolves lazily; None (engine off or not yet ticked)
    # omits the family.  CTA014 pins these three names ---------------
    def slo(fn):
        eng = getattr(daemon, "slo", None)
        return None if eng is None else fn(eng)

    reg.gauge("cilium_slo_budget_remaining",
              "unconsumed fraction of each SLO's slow-window error "
              "budget (1 = untouched, 0 = exhausted)",
              lambda: slo(lambda e: e.budget_series()))
    reg.gauge("cilium_slo_burn_rate",
              "error-budget burn rate per SLO and window (1 = "
              "burning exactly the window's budget)",
              lambda: slo(lambda e: e.burn_series()))
    reg.gauge("cilium_slo_state",
              "SLO state code (0 ok, 1 no-data, 2 warn, 3 page)",
              lambda: slo(lambda e: e.state_series()))

    # -- flow-stream handlers (pkg/hubble/metrics) --------------------
    register_flow_metrics(reg, daemon.flow_metrics)
    return reg
