"""Declarative SLOs + multi-window burn-rate alerting (ISSUE 19,
tentpole part 2).

Reference: upstream cilium's operability story turns counters into
JUDGMENTS — cilium-health says healthy/degraded, Hubble metrics feed
the SRE-workbook burn-rate alerts.  This module is that layer over
the PR 4 registry and the ISSUE 19 history rings: an SLO declares an
OBJECTIVE over a series expression, the engine evaluates each one
over a FAST and a SLOW window, and the pair of burn rates classifies
the moment:

- ``burn = error_rate / error_budget`` where ``error_budget = 1 -
  objective``: burn 1.0 consumes exactly the window's budget; burn
  10 exhausts the slow window's budget in a tenth of it.
- PAGE only when BOTH windows burn past ``page_burn`` — the fast
  window makes the alert responsive, the slow window makes it hold
  evidence (a one-sample blip cannot page; the SRE-workbook
  multi-window rationale).
- A page opens an EPISODE: one ``slo-burn`` flight-recorder incident
  (sysdump auto-capture) at entry, hysteresis on the way out
  (``clear_ticks`` consecutive calm evaluations), the recovery
  recorded on the episode — a storm cannot flap incidents, and the
  operator sees when it healed, not just when it fired.

Three SLO kinds cover the shipped defaults:

- ``ratio``: bad-counter sum over a total counter (availability,
  event-plane loss, L7 parse failures, cluster scrape health);
- ``percentile``: a latency histogram's tail mass over a threshold —
  cumulative log2 buckets are counters, so the window's distribution
  is a bucket difference and "p99 under 100 ms" is the ratio of
  over-threshold mass to total mass;
- ``gauge``: fraction of window samples at/over a threshold (map
  occupancy headroom).

The engine owns the ONE sampler thread (``slo-sampler``, CTA002
domain ``slo`` — never the drain thread) driving both the history
rings and the evaluations on the flow-analytics duty idiom: the
cadence is a ceiling, and on a loaded host the loop stretches its
delay so sampling stays under ``max_duty`` of wall clock.  ``tick``
is callable synchronously with injected clocks, so tests drive the
whole plane deterministically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .flightrec import KIND_SLO_BURN
from .history import SeriesHistory

SLO_KINDS = ("ratio", "percentile", "gauge")

STATE_OK = "ok"
STATE_NO_DATA = "no-data"
STATE_WARN = "warn"
STATE_PAGE = "page"
# cilium_slo_state codes (registry exposition)
STATE_CODES = {STATE_OK: 0, STATE_NO_DATA: 1, STATE_WARN: 2,
               STATE_PAGE: 3}

# dispatch tail bound for the shipped dispatch-p99 SLO (µs): one
# admission-to-events-emitted dispatch should clear in 100 ms
DISPATCH_P99_US = 100_000
# occupancy headroom bound for the shipped map-headroom SLO: a map
# sample at/over this fraction counts against the objective
MAP_HEADROOM_OCCUPANCY = 0.90

MAX_EPISODES = 64


@dataclass(frozen=True)
class SLODef:
    """One declared objective.  ``bad``/``total`` for ratio kinds,
    ``series`` (+ ``threshold``) for percentile/gauge kinds; all
    series names must be registered (validated at engine
    construction, linted by CTA014)."""
    name: str
    description: str
    kind: str
    objective: float
    bad: Tuple[str, ...] = ()
    total: str = ""
    series: Tuple[str, ...] = ()
    threshold: float = 0.0

    def referenced_series(self) -> Tuple[str, ...]:
        return tuple(self.bad) + (
            (self.total,) if self.total else ()) + tuple(self.series)


def default_slos() -> Tuple[SLODef, ...]:
    """The shipped SLO set (ISSUE 19): every objective the serving,
    event, L7, cluster-scrape, and map planes already ledger."""
    return (
        SLODef(
            name="serving-availability",
            description="packets neither shed at admission nor "
                        "dropped in fault recovery",
            kind="ratio", objective=0.999,
            bad=("cilium_serving_shed_total",
                 "cilium_serving_recovery_dropped_total"),
            total="cilium_serving_submitted_total"),
        SLODef(
            name="dispatch-p99",
            description="dispatch latency p99 under 100 ms "
                        "(admission -> events emitted)",
            kind="percentile", objective=0.99,
            series=("cilium_serving_latency_us",),
            threshold=DISPATCH_P99_US),
        SLODef(
            name="event-plane-loss",
            description="ring events neither lapped nor dropped "
                        "with their window",
            kind="ratio", objective=0.999,
            bad=("cilium_ring_lost_total",
                 "cilium_serving_event_windows_dropped_total"),
            total="cilium_serving_ring_events_total"),
        SLODef(
            name="cluster-scrape-health",
            description="relay scrapes of worker nodes succeeding",
            kind="ratio", objective=0.95,
            bad=("cilium_cluster_obs_scrape_errors_total",),
            total="cilium_cluster_obs_scrapes_total"),
        SLODef(
            name="l7-parse-failure",
            description="redirected rows reaching an L7 verdict "
                        "(parse failures burn)",
            kind="ratio", objective=0.995,
            bad=("cilium_l7_failed_total",),
            total="cilium_l7_redirected_total"),
        SLODef(
            name="map-headroom",
            description="datapath map occupancy samples under the "
                        "headroom bound (CT, LPM/ipcache, policy)",
            kind="gauge", objective=0.99,
            series=("cilium_ct_occupancy", "cilium_lpm_occupancy",
                    "cilium_policy_map_occupancy"),
            threshold=MAP_HEADROOM_OCCUPANCY),
    )


# the declared history subset: every series the shipped SLOs
# reference plus the trend gauges operators diff by hand today.
# EXCLUDES device-touching collectors (cilium_datapath_packets_total
# renders the metricsmap) and the cilium_slo_* family itself (the
# engine feeds those; sampling them would read the previous tick).
# CTA014 floors each name against the registry
HISTORY_SERIES = (
    "cilium_serving_submitted_total",
    "cilium_serving_shed_total",
    "cilium_serving_recovery_dropped_total",
    "cilium_serving_verdicts_total",
    "cilium_serving_ring_events_total",
    "cilium_ring_lost_total",
    "cilium_serving_event_windows_dropped_total",
    "cilium_serving_latency_us",
    "cilium_serving_queue_wait_us",
    "cilium_serving_queue_pending",
    "cilium_serving_degraded",
    "cilium_l7_failed_total",
    "cilium_l7_redirected_total",
    "cilium_ct_occupancy",
    "cilium_lpm_occupancy",
    "cilium_policy_map_occupancy",
    "cilium_ct_insert_drops_total",
    "cilium_nat_pool_failures_total",
    "cilium_cluster_obs_scrapes_total",
    "cilium_cluster_obs_scrape_errors_total",
    "cilium_incidents_total",
)


def validate_slo_config(fast_window_s, slow_window_s, page_burn,
                        warn_burn, clear_ticks, max_duty) -> tuple:
    """Validate the SLO DaemonConfig knobs (the
    validate_serving_config contract: fail at construction)."""
    fast_window_s = float(fast_window_s)
    slow_window_s = float(slow_window_s)
    if fast_window_s <= 0:
        raise ValueError("slo_fast_window must be > 0")
    if slow_window_s <= fast_window_s:
        raise ValueError("slo_slow_window must be > slo_fast_window "
                         "(the multi-window premise)")
    page_burn = float(page_burn)
    warn_burn = float(warn_burn)
    if warn_burn <= 0:
        raise ValueError("slo_warn_burn must be > 0")
    if page_burn < warn_burn:
        raise ValueError("slo_page_burn must be >= slo_warn_burn")
    clear_ticks = int(clear_ticks)
    if clear_ticks <= 0:
        raise ValueError("slo_clear_ticks must be > 0")
    max_duty = float(max_duty)
    if not 0.0 <= max_duty < 1.0:
        raise ValueError("slo_max_duty must be in [0, 1) "
                         "(0 disables the governor)")
    return (fast_window_s, slow_window_s, page_burn, warn_burn,
            clear_ticks, max_duty)


class SLOEngine:
    """Owns the sampler cadence (one thread drives history + SLO
    evaluation), the per-SLO episode state machines, and the cached
    last evaluation the registry collectors and ``GET /slo`` read."""

    # guarded-by: _lock: last, ticks, active, episodes, _delay

    def __init__(self, history: SeriesHistory,
                 slos: Sequence[SLODef],
                 record_incident: Optional[Callable] = None,
                 interval_s: float = 10.0,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 page_burn: float = 10.0,
                 warn_burn: float = 2.0,
                 clear_ticks: int = 3,
                 max_duty: float = 0.05):
        self.history = history
        self.slos: Tuple[SLODef, ...] = tuple(slos)
        self._record_incident = record_incident
        self.interval_s = float(interval_s)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self.clear_ticks = int(clear_ticks)
        self.max_duty = float(max_duty)
        seen = set()
        for d in self.slos:
            if d.kind not in SLO_KINDS:
                raise ValueError(f"SLO {d.name!r}: unknown kind "
                                 f"{d.kind!r} (one of {SLO_KINDS})")
            if not 0.0 < d.objective < 1.0:
                raise ValueError(f"SLO {d.name!r}: objective must "
                                 f"be in (0, 1)")
            if d.name in seen:
                raise ValueError(f"SLO {d.name!r} declared twice")
            seen.add(d.name)
            for s in d.referenced_series():
                if s not in history.kinds:
                    raise ValueError(
                        f"SLO {d.name!r} references series {s!r} "
                        f"outside the declared history subset")
        self._lock = threading.Lock()
        self.last: Optional[dict] = None
        self.ticks = 0
        # SLO name -> open episode (page entered, not yet cleared)
        self.active: Dict[str, dict] = {}
        # closed episodes, oldest first, bounded
        self.episodes: List[dict] = []
        self._delay = self.interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        if self.interval_s <= 0 or self._thread is not None:
            return
        # restartable (stop() then start(), the bench's paired
        # armed/off legs): a FRESH event rather than clear() — a
        # straggler thread from a timed-out join still sees its own
        # set event and exits instead of racing the new cadence
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="slo-sampler")
        self._thread.start()

    def stop(self) -> None:
        # thread-affinity: api
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def _loop(self) -> None:
        # thread-affinity: slo -- the engine's own sampler thread;
        # never the drain thread (samples snapshot lock-guarded
        # ledgers, evaluation walks the history rings — all
        # off-hot-path by construction)
        while True:
            with self._lock:
                delay = self._delay
            if self._stop.wait(delay):
                return
            t0 = time.monotonic()
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — one broken tick must
                pass  # not kill the sampler cadence
            if self.max_duty > 0:
                # duty governor: cost/(cost+delay) <= max_duty
                cost = time.monotonic() - t0
                with self._lock:
                    self._delay = max(
                        self.interval_s,
                        cost * (1.0 - self.max_duty) / self.max_duty)

    # -- the evaluator -------------------------------------------------
    def tick(self, now: Optional[float] = None,
             wall: Optional[float] = None) -> dict:
        # thread-affinity: slo, api, cli
        """One sampler tick: append a history sample, evaluate every
        SLO over both windows, walk the episode machines.  Clocks
        are injectable (deterministic chaos tests drive a fake
        timeline through here)."""
        rec = self.history.take_sample(now=now, wall=wall)
        now = rec["t"]
        wall = rec["at"]
        fired: List[dict] = []
        with self._lock:
            evals: Dict[str, dict] = {}
            for d in self.slos:
                ev = self._evaluate(d, now)
                evals[d.name] = ev
                self._episode_step(d, ev, now, wall, fired)
            self.ticks += 1
            self.last = {
                "at": wall,
                "verdict": self._verdict_locked(evals),
                "evals": evals,
            }
            out = self.last
        # incidents fire OUTSIDE the lock: the capture thread's
        # collect calls back into snapshot(), and holding _lock
        # across record_incident would make that wait on this tick
        # for no reason
        for detail in fired:
            if self._record_incident is not None:
                self._record_incident(KIND_SLO_BURN, detail)
        return out

    def _evaluate(self, d: SLODef, now: float) -> dict:
        # holds: _lock
        fast = self._window_error(d, self.fast_window_s, now)
        slow = self._window_error(d, self.slow_window_s, now)
        budget = 1.0 - d.objective
        ev: dict = {
            "kind": d.kind,
            "objective": d.objective,
            "fast-window-s": self.fast_window_s,
            "slow-window-s": self.slow_window_s,
        }
        if fast is None or slow is None:
            ev["state"] = STATE_NO_DATA
            ev["budget-remaining"] = 1.0
            return ev
        fast_burn = fast / budget
        slow_burn = slow / budget
        ev["error-fast"] = round(fast, 6)
        ev["error-slow"] = round(slow, 6)
        ev["fast-burn"] = round(fast_burn, 3)
        ev["slow-burn"] = round(slow_burn, 3)
        # budget remaining: the slow window IS the budget period —
        # burn 1.0 sustained for the whole window exhausts it
        ev["budget-remaining"] = round(
            max(0.0, min(1.0, 1.0 - slow_burn)), 6)
        if fast_burn >= self.page_burn and slow_burn >= self.page_burn:
            ev["state"] = STATE_PAGE
        elif fast_burn >= self.warn_burn and slow_burn >= self.warn_burn:
            ev["state"] = STATE_WARN
        else:
            ev["state"] = STATE_OK
        return ev

    def _window_error(self, d: SLODef, window_s: float,
                      now: float) -> Optional[float]:
        # holds: _lock
        """The window's error fraction, or None when the rings lack
        data.  Zero traffic in the window is burn 0 (an idle plane
        consumes no budget), distinct from no-data (the rings have
        not covered the window for these series yet)."""
        h = self.history
        if d.kind == "ratio":
            total = h.counter_delta(d.total, window_s, now)
            if total is None:
                return None
            if total <= 0:
                return 0.0
            bad = 0.0
            for name in d.bad:
                delta = h.counter_delta(name, window_s, now)
                if delta is not None:
                    bad += delta
            return min(1.0, bad / total)
        if d.kind == "percentile":
            delta = h.hist_delta(d.series[0], window_s, now)
            if delta is None:
                return None
            count = delta["count"]
            if count <= 0:
                return 0.0
            # log2 buckets: bucket i holds [2^(i-1), 2^i) µs, so the
            # mass known under the threshold is the cumulative count
            # through the largest bucket whose upper bound fits
            under = sum(b for i, b in enumerate(delta["buckets"])
                        if (1 << i) <= d.threshold)
            return max(0.0, min(1.0, (count - under) / count))
        # gauge: fraction of window samples at/over the threshold,
        # worst series per sample (one saturated map burns even while
        # its siblings idle)
        rows = [h.gauge_window(name, window_s, now)
                for name in d.series]
        n = max((len(r) for r in rows), default=0)
        if n == 0:
            return None
        over = 0
        for i in range(n):
            worst = max((r[i] for r in rows if i < len(r)),
                        default=0.0)
            if worst >= d.threshold:
                over += 1
        return over / n

    def _episode_step(self, d: SLODef, ev: dict, now: float,
                      wall: float, fired: List[dict]) -> None:
        # holds: _lock
        state = ev["state"]
        ep = self.active.get(d.name)
        if ep is None:
            if state == STATE_PAGE:
                ep = {
                    "slo": d.name,
                    "started-at": wall,
                    "t0": now,
                    "peak-burn": max(ev.get("fast-burn", 0.0),
                                     ev.get("slow-burn", 0.0)),
                    "calm": 0,
                }
                self.active[d.name] = ep
                fired.append({
                    "slo": d.name,
                    "kind": d.kind,
                    "objective": d.objective,
                    "fast-burn": ev.get("fast-burn"),
                    "slow-burn": ev.get("slow-burn"),
                    "budget-remaining": ev.get("budget-remaining"),
                })
            return
        ep["peak-burn"] = max(ep["peak-burn"],
                              ev.get("fast-burn", 0.0),
                              ev.get("slow-burn", 0.0))
        # hysteresis: the episode closes only after clear_ticks
        # consecutive evaluations with BOTH windows calm (under the
        # warn burn) — a storm re-arms the counter, so one episode
        # is one incident however long it flaps
        calm = (state in (STATE_OK, STATE_NO_DATA)
                and ev.get("fast-burn", 0.0) < self.warn_burn
                and ev.get("slow-burn", 0.0) < self.warn_burn)
        if calm:
            ep["calm"] += 1
            if ep["calm"] >= self.clear_ticks:
                del self.active[d.name]
                self.episodes.append({
                    "slo": d.name,
                    "started-at": ep["started-at"],
                    "recovered-at": wall,
                    "duration-s": round(now - ep["t0"], 3),
                    "peak-burn": round(ep["peak-burn"], 3),
                })
                del self.episodes[:-MAX_EPISODES]
        else:
            ep["calm"] = 0

    def _verdict_locked(self, evals: Dict[str, dict]) -> str:
        # holds: _lock
        states = [e["state"] for e in evals.values()]
        if self.active or STATE_PAGE in states:
            return STATE_PAGE
        if STATE_WARN in states:
            return STATE_WARN
        return STATE_OK

    # -- reading --------------------------------------------------------
    def snapshot(self) -> dict:
        # thread-affinity: any
        """``GET /slo`` body + the sysdump ``slo`` section."""
        with self._lock:
            last = self.last
            return {
                "enabled": self.interval_s > 0,
                "interval-s": self.interval_s,
                "effective-interval-s": round(self._delay, 3),
                "fast-window-s": self.fast_window_s,
                "slow-window-s": self.slow_window_s,
                "page-burn": self.page_burn,
                "warn-burn": self.warn_burn,
                "clear-ticks": self.clear_ticks,
                "ticks": self.ticks,
                "verdict": (last["verdict"] if last is not None
                            else STATE_NO_DATA),
                "at": last["at"] if last is not None else None,
                "slos": ({name: dict(ev) for name, ev
                          in last["evals"].items()}
                         if last is not None else {}),
                "active": {name: {k: v for k, v in ep.items()
                                  if k != "t0"}
                           for name, ep in self.active.items()},
                "episodes": [dict(e) for e in self.episodes],
                "resyncs": self.history.resyncs,
            }

    def stats(self) -> dict:
        # thread-affinity: any
        """The serving-stats block: verdict + per-SLO states only
        (the full evaluation rides ``GET /slo``)."""
        with self._lock:
            last = self.last
            out = {
                "enabled": self.interval_s > 0,
                "verdict": (last["verdict"] if last is not None
                            else STATE_NO_DATA),
                "ticks": self.ticks,
                "active-episodes": len(self.active),
                "episodes": len(self.episodes),
            }
            if last is not None:
                out["states"] = {
                    name: ev["state"]
                    for name, ev in last["evals"].items()}
                out["budget-remaining"] = {
                    name: ev.get("budget-remaining")
                    for name, ev in last["evals"].items()}
            return out

    # -- registry collectors (read the cached evaluation) ---------------
    def budget_series(self):
        # thread-affinity: any
        with self._lock:
            if self.last is None:
                return None
            return [({"slo": name}, ev["budget-remaining"])
                    for name, ev in sorted(self.last["evals"].items())
                    if ev.get("budget-remaining") is not None]

    def burn_series(self):
        # thread-affinity: any
        with self._lock:
            if self.last is None:
                return None
            out = []
            for name, ev in sorted(self.last["evals"].items()):
                for window in ("fast", "slow"):
                    v = ev.get(f"{window}-burn")
                    if v is not None:
                        out.append(({"slo": name, "window": window},
                                    v))
            return out

    def state_series(self):
        # thread-affinity: any
        with self._lock:
            if self.last is None:
                return None
            return [({"slo": name}, STATE_CODES[ev["state"]])
                    for name, ev in
                    sorted(self.last["evals"].items())]
