"""In-process metrics history: fixed-memory ring of registry samples
(ISSUE 19, tentpole part 1).

Reference: upstream cilium leans on an external Prometheus for
retention, but cilium-health and Hubble both keep a bounded
in-process window so "trending which way" survives without a scrape
stack.  Here `SeriesHistory` retains a DECLARED subset of registry
series (``MetricsRegistry.sample``) in two downsample tiers — a fast
ring (default 10 s x 360 slots = 1 h) and a slow ring fed every
``slow_every``-th sample (default 5 min x 288 slots = 24 h) — both
``deque(maxlen=...)``, so memory is fixed no matter the uptime.

Counter-reset discipline: a daemon restart zeroes every cumulative
counter.  Emitting the raw values would make every windowed rate go
negative for one window; instead the sampler detects the reset
(:func:`counters_reset` — the ONE definition, shared with the CLI's
``serving stats --follow`` resync) and carries a per-series offset so
the ADJUSTED series stays monotone (the Prometheus
``rate()``-across-restart convention).  The reset is recorded on the
sample (``resync: [names]``) so operators see the restart instead of
a silent splice.  Histograms get the same treatment vectorized over
their cumulative bucket counts.

The ring is a pure data structure — it owns no thread.  The SLO
engine (``obs/slo.py``) owns the sampler cadence and calls
:meth:`take_sample`; queries (``GET /metrics/history``, ``cilium-tpu
history``) only read.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def counters_reset(pairs: "Sequence[Tuple[object, object]]") -> bool:
    """True when any (current, previous) cumulative-counter pair went
    BACKWARD — the one shared definition of "the process restarted"
    (a live counter is monotone; only a restart rewinds it).  Used by
    the CLI follow loop (full-block resync, no negative rates) and
    the history sampler (offset splice, no negative deltas).
    Non-numeric / missing values never signal a reset."""
    for cur, prev in pairs:
        if (isinstance(cur, (int, float))
                and isinstance(prev, (int, float))
                and not isinstance(cur, bool)
                and not isinstance(prev, bool)
                and cur < prev):
            return True
    return False


def validate_history_config(interval_s, slots, slow_every,
                            slow_slots) -> tuple:
    """Validate the history DaemonConfig knobs (the
    validate_serving_config contract: fail at construction)."""
    interval_s = float(interval_s)
    if interval_s < 0:
        raise ValueError("history_interval must be >= 0 "
                         "(0 disables the sampler)")
    slots = int(slots)
    slow_slots = int(slow_slots)
    if slots <= 1 or slow_slots <= 1:
        raise ValueError("history_slots / history_slow_slots must "
                         "be > 1 (a one-slot ring cannot hold a "
                         "rate window)")
    slow_every = int(slow_every)
    if slow_every <= 0:
        raise ValueError("history_slow_every must be > 0")
    return interval_s, slots, slow_every, slow_slots


class SeriesHistory:
    """Two-tier ring of adjusted registry samples.

    ``sample_fn()`` returns ``{name: value}`` in the
    ``MetricsRegistry.sample`` shape; ``kinds`` maps each declared
    name to counter/gauge/histogram (the reset-vs-passthrough
    switch).  All mutation happens in :meth:`take_sample` under one
    lock; readers get copies."""

    # guarded-by: _lock: _fast, _slow, _offset, _prev, samples,
    # guarded-by: _lock: resyncs

    def __init__(self, sample_fn: Callable[[], Dict[str, object]],
                 kinds: Dict[str, str],
                 interval_s: float = 10.0,
                 slots: int = 360,
                 slow_every: int = 30,
                 slow_slots: int = 288):
        self._sample_fn = sample_fn
        self.kinds = dict(kinds)
        self.interval_s = float(interval_s)
        self.slots = int(slots)
        self.slow_every = int(slow_every)
        self.slow_slots = int(slow_slots)
        self._lock = threading.Lock()
        self._fast: deque = deque(maxlen=self.slots)
        self._slow: deque = deque(maxlen=self.slow_slots)
        # per-series reset splice state: _prev holds the last RAW
        # value (scalar for counters, the full dict for histograms),
        # _offset the accumulated pre-restart total the adjusted
        # series continues from
        self._prev: Dict[str, object] = {}
        self._offset: Dict[str, object] = {}
        self.samples = 0
        self.resyncs = 0

    # -- writing (the SLO engine's tick) -------------------------------
    # (named take_sample, not sample: the callgraph's name-match
    # fallback would otherwise bind tick's call here to the
    # api-affine MapPressureMonitor.sample)
    def take_sample(self, now: Optional[float] = None,
                    wall: Optional[float] = None) -> dict:
        # thread-affinity: slo, api, cli
        """One sampler tick: pull the declared subset, splice any
        counter reset, append to the fast ring (and every
        ``slow_every``-th tick to the slow ring).  ``now`` is the
        monotonic timestamp window math uses; ``wall`` the operator-
        facing epoch time — both injectable for deterministic
        tests."""
        raw = self._sample_fn()
        if now is None:
            now = time.monotonic()
        if wall is None:
            wall = time.time()
        with self._lock:
            values: Dict[str, object] = {}
            reset_names: List[str] = []
            for name, v in raw.items():
                kind = self.kinds.get(name)
                if kind == "counter":
                    values[name] = self._adjust_counter(
                        name, v, reset_names)
                elif kind == "histogram":
                    values[name] = self._adjust_hist(
                        name, v, reset_names)
                else:  # gauge (or undeclared kind): pass through
                    values[name] = v
            rec = {"t": now, "at": wall, "v": values}
            if reset_names:
                self.resyncs += 1
                rec["resync"] = sorted(reset_names)
            self._fast.append(rec)
            if self.samples % self.slow_every == 0:
                self._slow.append(rec)
            self.samples += 1
            return rec

    def _adjust_counter(self, name: str, v, reset_names) -> float:
        # holds: _lock
        prev = self._prev.get(name)
        off = self._offset.get(name, 0.0)
        if prev is not None and counters_reset([(v, prev)]):
            # splice: the adjusted series continues from where the
            # dead process left it, the fresh raw counts from there
            off = off + prev
            reset_names.append(name)
        self._prev[name] = v
        self._offset[name] = off
        return float(off) + float(v)

    def _adjust_hist(self, name: str, v: dict, reset_names) -> dict:
        # holds: _lock
        prev = self._prev.get(name)
        off = self._offset.get(name)
        if off is None:
            off = {"buckets": [0] * len(v["buckets"]),
                   "count": 0, "sum": 0.0}
        if (prev is not None
                and counters_reset([(v["count"], prev["count"])])):
            # vectorized splice over the cumulative bucket counts
            off = {"buckets": [o + p for o, p in
                               zip(off["buckets"], prev["buckets"])],
                   "count": off["count"] + prev["count"],
                   "sum": off["sum"] + prev["sum"]}
            reset_names.append(name)
        self._prev[name] = v
        self._offset[name] = off
        return {"buckets": [o + b for o, b in
                            zip(off["buckets"], v["buckets"])],
                "count": off["count"] + v["count"],
                "sum": off["sum"] + v["sum"]}

    # -- reading --------------------------------------------------------
    def _merged(self) -> List[dict]:
        """Both tiers as one time-ordered record list (the slow ring
        extends the window past the fast ring's span; records the
        fast ring still holds dedupe by timestamp)."""
        with self._lock:
            recs = {r["t"]: r for r in self._slow}
            recs.update({r["t"]: r for r in self._fast})
        return [recs[t] for t in sorted(recs)]

    def _window(self, window_s: float, now: Optional[float]
                ) -> Tuple[Optional[dict], List[dict]]:
        """Records inside ``[now - window_s, now]`` plus the baseline
        record just BEFORE the window (rate deltas anchor on it, so a
        window covers its full span instead of losing the first
        sample interval)."""
        if now is None:
            now = time.monotonic()
        cutoff = now - float(window_s)
        base: Optional[dict] = None
        win: List[dict] = []
        for r in self._merged():
            if r["t"] < cutoff:
                base = r
            else:
                win.append(r)
        return base, win

    def counter_delta(self, name: str, window_s: float,
                      now: Optional[float] = None
                      ) -> Optional[float]:
        """Adjusted increase of a counter over the window, or None
        when the ring lacks two datapoints for it (never negative —
        the splice guarantees monotone)."""
        base, win = self._window(window_s, now)
        if not win:
            return None
        first = base if base is not None else win[0]
        last = win[-1]
        if first is last:
            return None
        a, b = first["v"].get(name), last["v"].get(name)
        if not isinstance(a, (int, float)) or not isinstance(
                b, (int, float)):
            return None
        return float(b) - float(a)

    def hist_delta(self, name: str, window_s: float,
                   now: Optional[float] = None) -> Optional[dict]:
        """Adjusted bucket/count increase over the window (the
        percentile-SLO substrate: cumulative log2 buckets are
        counters, so the window's distribution is a difference)."""
        base, win = self._window(window_s, now)
        if not win:
            return None
        first = base if base is not None else win[0]
        last = win[-1]
        if first is last:
            return None
        a, b = first["v"].get(name), last["v"].get(name)
        if not isinstance(a, dict) or not isinstance(b, dict):
            return None
        return {"buckets": [y - x for x, y in
                            zip(a["buckets"], b["buckets"])],
                "count": b["count"] - a["count"],
                "sum": b["sum"] - a["sum"]}

    def gauge_window(self, name: str, window_s: float,
                     now: Optional[float] = None) -> List[float]:
        """Every gauge sample inside the window, oldest first."""
        _base, win = self._window(window_s, now)
        out: List[float] = []
        for r in win:
            v = r["v"].get(name)
            if isinstance(v, (int, float)) and not isinstance(
                    v, bool):
                out.append(float(v))
        return out

    def query(self, series: Optional[Sequence[str]] = None,
              since: float = 0.0) -> dict:
        # thread-affinity: any
        """``GET /metrics/history`` body: both tiers, operator
        (epoch) timestamps, optionally filtered to a series subset
        and to samples at/after ``since``."""
        want = set(series) if series else None

        def emit(ring: Sequence[dict]) -> List[dict]:
            out = []
            for r in ring:
                if r["at"] < since:
                    continue
                v = r["v"]
                if want is not None:
                    v = {k: v[k] for k in want if k in v}
                row = {"at": r["at"], "v": v}
                if "resync" in r:
                    row["resync"] = r["resync"]
                out.append(row)
            return out

        with self._lock:
            fast = list(self._fast)
            slow = list(self._slow)
            samples = self.samples
            resyncs = self.resyncs
        return {
            "interval-s": self.interval_s,
            "slots": self.slots,
            "slow-every": self.slow_every,
            "slow-slots": self.slow_slots,
            "series": (sorted(want & set(self.kinds))
                       if want is not None else sorted(self.kinds)),
            "samples": samples,
            "resyncs": resyncs,
            "fast": emit(fast),
            "slow": emit(slow),
        }

    def stats(self) -> dict:
        # thread-affinity: any
        """The serving-stats / sysdump summary block (counts, not
        the rings themselves)."""
        with self._lock:
            fast_len = len(self._fast)
            slow_len = len(self._slow)
            span = (self._fast[-1]["t"] - self._fast[0]["t"]
                    if fast_len >= 2 else 0.0)
            if slow_len >= 2:
                span = max(span,
                           self._slow[-1]["t"] - self._slow[0]["t"])
            samples = self.samples
            resyncs = self.resyncs
        return {
            "interval-s": self.interval_s,
            "series": len(self.kinds),
            "samples": samples,
            "resyncs": resyncs,
            "fast-len": fast_len,
            "slow-len": slow_len,
            "span-s": round(span, 3),
        }
