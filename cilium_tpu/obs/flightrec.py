"""The incident flight recorder: named incidents + sysdump bundles.

Reference: production cilium ships ``cilium-bugtool`` and
``cilium sysdump`` — when something goes wrong, the FIRST operator
move is capturing the agent's state as one artifact, because by the
time a human looks, the interesting state (ladder position, recent
flows, queue depths) has healed or rolled over.  This module is that
discipline made automatic: the serving plane's failure machinery
(watchdog restart, ladder demotion, terminal event-join worker), the
analytics plane's drop-spike detector, and a manual API/CLI trigger
all RECORD a named incident here, and — when a ``sysdump_dir`` is
configured — each incident captures a bundle at the moment it fired.

The bundle is one JSON file assembled by the owner's ``collect_fn``
(the daemon snapshots DaemonConfig, serving stats + ladder state, the
compile log, the span tracer's slowest/latest traces, the last N
flows from the Observer, the live aggregation windows, the metrics
registry render, and — when relay peers are registered — a
relay-merged flow sample stamped with node names).  Guarantees:

- SECTION-CONTAINED collection: a failing section becomes
  ``{"error": ...}`` in the bundle instead of killing the capture
  (incident time is exactly when subsystems misbehave);
- BOUNDED size: an oversize bundle sheds its largest optional
  sections in a fixed order (metrics text, flows, relay flows,
  traces, aggregation) until it fits ``max_bytes``, recording what
  was truncated — a flight recorder that can fill a disk during an
  incident storm is itself an incident;
- ATOMIC writes (tmp + rename) with a RETENTION cap (oldest bundles
  deleted past ``retention``);
- RATE-LIMITED auto-capture (``min_interval_s``): a restart storm
  records every incident but skips captures inside the interval,
  counted — manual triggers bypass the limit;
- RE-ENTRANCY-SAFE: an incident fired from inside a capture's
  collect (e.g. a spike detected while the capture drains analytics)
  records but never nests a second capture.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

# incident kinds the agent fires (detail payloads differ per kind);
# the registry's cilium_incidents_total{kind=} labels come from here
KIND_SPIKE = "drop-spike"
KIND_RESTART = "watchdog-restart"
KIND_TERMINAL = "watchdog-terminal"
KIND_DEMOTION = "ladder-demotion"
KIND_EVENTWORKER = "eventworker-terminal"
# the L7 worker pool's restart budget exhausted — redirected traffic
# is shedding to the l7_shed ledger leg from here on
KIND_L7POOL = "l7pool-terminal"
# a cluster node replica died and its flows were failed over onto a
# designated peer (CT snapshot replayed, router re-pinned); recorded
# on the PEER — the dead node's recorder died with it
KIND_NODE_FAILOVER = "node-failover"
# a live scale-out completed: a fresh replica joined the serving
# cluster, a slot share re-pinned to it, and the moved slots' CT
# migrated (cluster/scale.py); recorded on the NEW node
KIND_NODE_SCALEOUT = "node-scaleout"
# a live scale-IN completed: a replica retired cleanly — window
# drained, slots re-pinned onto the survivors, its CT migrated to
# each slot's new owner (cluster/scale.py scale_in); recorded on a
# SURVIVOR — the victim's recorder retires with it
KIND_NODE_SCALEIN = "node-scalein"
# an encrypted cluster channel hit CRYPTO_DESYNC_THRESHOLD
# consecutive key-mismatch open failures (wrong peer key: AEAD auth
# fails every frame, both directions) — the channel is broken toward
# the router's requeue/failover path instead of hanging; recorded on
# the WORKER over the (plaintext) control channel, the only leg a
# desync cannot poison (cluster/process.py _note_open_failure)
KIND_CRYPTO_DESYNC = "crypto-desync"
# the map-pressure monitor (datapath/pressure.py) crossed a
# threshold — CT occupancy, insert-drop rate, or NAT pool failures —
# and entered the pressure state (one incident per episode; the
# accelerated CT aging sweep is the paired response)
KIND_MAP_PRESSURE = "map-pressure"
# an SLO's fast AND slow burn rates crossed the page threshold
# (obs/slo.py) — the error budget is burning fast enough to exhaust
# inside the slow window; one incident per episode (hysteresis), the
# recovery recorded on the episode when the burn clears
KIND_SLO_BURN = "slo-burn"
KIND_MANUAL = "manual"

# required top-level bundle keys (scripts/check_sysdump_schema.py
# validates written bundles against this; keep the two in sync via
# the import there)
SYSDUMP_REQUIRED_KEYS = (
    "schema", "node", "taken-at", "trigger", "incident", "config",
    "serving", "compile", "traces", "flows", "flow-aggregation",
    "incidents", "metrics", "pressure", "history", "slo",
)
SYSDUMP_SCHEMA = 1

# oversize bundles shed these sections in order until under the cap
_SHED_ORDER = ("metrics", "flows", "relay-flows", "traces",
               "flow-aggregation")

MAX_INCIDENTS = 128


def validate_flightrec_config(sysdump_dir, retention, max_bytes,
                              min_interval_s, flows) -> tuple:
    """Validate the flight-recorder DaemonConfig knobs (the
    validate_serving_config contract)."""
    if sysdump_dir is not None:
        sysdump_dir = str(sysdump_dir)
        if not sysdump_dir:
            sysdump_dir = None
    retention = int(retention)
    if retention < 1:
        raise ValueError("sysdump_retention must be >= 1")
    max_bytes = int(max_bytes)
    if max_bytes < 4096:
        raise ValueError("sysdump_max_bytes must be >= 4096 (the "
                         "bundle skeleton alone needs that)")
    min_interval_s = float(min_interval_s)
    if min_interval_s < 0:
        raise ValueError("sysdump_min_interval_s must be >= 0")
    flows = int(flows)
    if flows < 0:
        raise ValueError("sysdump_flows must be >= 0")
    return sysdump_dir, retention, max_bytes, min_interval_s, flows


class FlightRecorder:
    """Incident history + bundle capture.  ``collect_fn()`` returns
    the section dict (each value already JSON-safe or str()-able);
    the recorder adds the envelope (schema/trigger/incident/
    incidents) and enforces the size/retention bounds."""

    def __init__(self, collect_fn: Callable[[], Dict[str, object]],
                 sysdump_dir: Optional[str] = None,
                 retention: int = 8, max_bytes: int = 1 << 20,
                 min_interval_s: float = 1.0, node: str = "node0"):
        (sysdump_dir, retention, max_bytes, min_interval_s, _
         ) = validate_flightrec_config(sysdump_dir, retention,
                                       max_bytes, min_interval_s, 0)
        self._collect = collect_fn
        self.sysdump_dir = sysdump_dir
        self.retention = retention
        self.max_bytes = max_bytes
        self.min_interval_s = min_interval_s
        self.node = node
        self._lock = threading.Lock()
        # guarded-by: _lock: _incidents, _seq, _capturing,
        # guarded-by: _lock: _capture_owner, _last_capture,
        # guarded-by: _lock: incidents_total,
        # guarded-by: _lock: writes_total, captures_skipped,
        # guarded-by: _lock: write_errors, last_bundle, last_error
        self._capture_done = threading.Condition(self._lock)
        self._incidents: List[dict] = []
        self._seq = 0
        self._last_capture = 0.0
        self._capturing = False  # re-entrancy guard: an AUTO capture
        # triggered during a capture is skipped, counted — its
        # incident is still recorded; a MANUAL capture waits briefly
        # for the in-flight bundle (an operator's sysdump must not
        # be declined because a burn episode happened to be writing)
        self._capture_owner: Optional[int] = None
        self.incidents_total: Dict[str, int] = {}
        self.writes_total = 0
        self.captures_skipped = 0
        self.write_errors = 0
        self.last_bundle: Optional[str] = None
        self.last_error: Optional[str] = None

    # -- incidents -----------------------------------------------------
    def record_incident(self, kind: str, detail=None,
                        capture: bool = True) -> dict:
        # thread-affinity: any
        """Record one named incident; with ``capture`` (and a
        configured dir, outside the rate limit) also writes a sysdump
        bundle ASYNCHRONOUSLY on a short-lived capture thread.  Safe
        (and cheap) from any thread — the serving DRAIN thread fires
        this on ladder demotion, and a synchronous capture there
        would drag the whole collect (analytics drain, metrics
        render) onto the dispatch path; the watchdog and event-join
        worker likewise must not stall behind a bundle write."""
        with self._lock:
            self._seq += 1
            inc = {
                "seq": self._seq,
                "kind": str(kind),
                "time": time.time(),
                "detail": self._safe_detail(detail),
            }
            self._incidents.append(inc)
            del self._incidents[:-MAX_INCIDENTS]
            self.incidents_total[inc["kind"]] = (
                self.incidents_total.get(inc["kind"], 0) + 1)
        if capture and self.enabled:
            # pre-check the rate limit / re-entrancy under the lock
            # so an incident storm does not spawn a thread per
            # incident just for capture() to decline; capture()
            # re-checks authoritatively (a racing pair costs one
            # wasted thread, never a double bundle)
            with self._lock:
                skip = (self._capturing
                        or (self.min_interval_s > 0
                            and self._last_capture
                            and time.monotonic() - self._last_capture
                            < self.min_interval_s))
                if skip:
                    self.captures_skipped += 1
            if not skip:
                threading.Thread(
                    target=self.capture,
                    kwargs={"trigger": kind, "incident": inc,
                            "manual": False},
                    daemon=True, name="sysdump-capture").start()
        return inc

    @staticmethod
    def _safe_detail(detail):
        # thread-affinity: any
        if detail is None:
            return None
        if isinstance(detail, (str, int, float, bool)):
            return detail
        try:
            # hot-path-ok: probe-serializes a HAND-SIZED incident
            # detail dict (demotion cause, spike summary) — incidents
            # are rare by construction; the bundle write itself runs
            # on the capture thread
            json.dumps(detail)
            return detail
        except (TypeError, ValueError):
            return str(detail)[:500]

    def incidents(self, limit: int = 32) -> List[dict]:
        # thread-affinity: any
        with self._lock:
            return [dict(i) for i in self._incidents[-limit:]]

    # -- bundles -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.sysdump_dir is not None

    def capture(self, trigger: str = KIND_MANUAL,
                incident: Optional[dict] = None,
                manual: bool = True) -> Optional[str]:
        # thread-affinity: capture, api, cli
        """Write one bundle; returns its path, or None when disabled,
        rate-limited (auto only), nested inside another capture on
        the SAME thread, or (manual) when a concurrent capture does
        not finish within the grace period.  A manual request racing
        an auto-capture thread WAITS for it rather than declining:
        with periodic burn evaluation an auto bundle can be mid-write
        at any instant, and the operator asked for a dump, not a
        maybe."""
        if not self.enabled:
            return None
        now = time.monotonic()
        me = threading.get_ident()
        with self._capture_done:
            if self._capturing and (not manual
                                    or self._capture_owner == me):
                self.captures_skipped += 1
                return None
            deadline = now + 5.0
            while self._capturing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.captures_skipped += 1
                    return None
                self._capture_done.wait(remaining)
            if (not manual and self.min_interval_s > 0
                    and self._last_capture
                    and time.monotonic() - self._last_capture
                    < self.min_interval_s):
                self.captures_skipped += 1
                return None
            self._capturing = True
            self._capture_owner = me
            self._last_capture = time.monotonic()
            seq = self._seq
            recent = [dict(i) for i in self._incidents[-32:]]
        try:
            return self._write_bundle(trigger, incident, recent, seq)
        finally:
            with self._capture_done:
                self._capturing = False
                self._capture_owner = None
                self._capture_done.notify_all()

    def collect_bundle(self, trigger: str = KIND_MANUAL,
                       incident: Optional[dict] = None,
                       recent: Optional[List[dict]] = None,
                       bound: bool = True) -> Dict[str, object]:
        # thread-affinity: capture, api, cli
        """Assemble one bundle DICT without writing it — the
        envelope + section collect + (with ``bound``) the
        shed-to-fit pass.  The disk path (:meth:`capture`) passes
        ``bound=False`` and runs the pass itself while serializing
        (one pass total — a longer capture widens the re-entrancy
        skip window for concurrent incidents); the cluster sysdump
        relay (``obs/relay.py``) keeps ``bound=True`` and ships the
        dict over the control channel, so a worker process's bundle
        lands in the parent's archive without touching the worker's
        filesystem.  Works with the recorder DISABLED (no sysdump
        dir): collection never needed one."""
        if recent is None:
            with self._lock:
                recent = [dict(i) for i in self._incidents[-32:]]
        bundle: Dict[str, object] = {
            "schema": SYSDUMP_SCHEMA,
            "node": self.node,
            "taken-at": time.time(),
            "trigger": str(trigger),
            "incident": incident,
            "incidents": recent,
            "max-bytes": self.max_bytes,
        }
        try:
            sections = self._collect() or {}
        except Exception as e:  # noqa: BLE001 — a wholly-failed
            sections = {"collect-error": str(e)}  # collect still
            # yields a bundle: the envelope + incident history alone
            # beat no artifact
        for key, val in sections.items():
            bundle.setdefault(key, val)
        for key in SYSDUMP_REQUIRED_KEYS:
            bundle.setdefault(key, None)
        if bound:
            # shed-to-fit so control-channel consumers honor
            # max_bytes too; mutates in place, stamps `truncated`
            self._bound(bundle)
        return bundle

    def _write_bundle(self, trigger: str, incident: Optional[dict],
                      recent: List[dict], seq: int) -> Optional[str]:
        # thread-affinity: capture, api, cli
        bundle = self.collect_bundle(trigger, incident, recent,
                                     bound=False)
        body, _ = self._bound(bundle)  # shed record rides the body
        name = (f"sysdump-{time.strftime('%Y%m%d-%H%M%S')}"
                f"-{seq:05d}-{_slug(trigger)}.json")
        path = os.path.join(self.sysdump_dir, name)
        try:
            os.makedirs(self.sysdump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(body)
            os.replace(tmp, path)
        except OSError as e:
            with self._lock:
                self.write_errors += 1
                self.last_error = str(e)
            return None
        with self._lock:
            self.writes_total += 1
            self.last_bundle = path
        self._prune()
        return path

    def _bound(self, bundle: Dict[str, object]) -> tuple:
        """Serialize under the size cap, shedding the largest
        optional sections in ``_SHED_ORDER`` until it fits.
        Idempotent: a bundle already bounded (collect_bundle runs
        the pass; the disk path re-checks) keeps its shed record."""
        truncated: List[str] = list(bundle.get("truncated") or [])
        while True:
            bundle["truncated"] = truncated
            body = json.dumps(bundle, indent=1, default=str)
            if len(body.encode()) <= self.max_bytes:
                return body, truncated
            for key in _SHED_ORDER:
                if bundle.get(key) not in (None, "(truncated)"):
                    bundle[key] = "(truncated)"
                    truncated.append(key)
                    break
            else:
                # nothing left to shed: hard-truncate the body (an
                # invalid-JSON tail beats an unbounded file; the
                # schema check treats this as a failed bundle, which
                # is the honest answer)
                return body[:self.max_bytes], truncated + ["(body)"]

    def _prune(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.sysdump_dir)
                           if n.startswith("sysdump-")
                           and n.endswith(".json"))
            for n in names[:-self.retention]:
                os.unlink(os.path.join(self.sysdump_dir, n))
        except OSError:
            pass

    def list_bundles(self) -> List[dict]:
        """``GET /debug/sysdump``'s listing: newest first."""
        if not self.enabled:
            return []
        try:
            names = sorted((n for n in os.listdir(self.sysdump_dir)
                            if n.startswith("sysdump-")
                            and n.endswith(".json")), reverse=True)
        except OSError:
            return []
        out = []
        for n in names:
            path = os.path.join(self.sysdump_dir, n)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"name": n, "path": path,
                        "bytes": int(st.st_size),
                        "modified": round(st.st_mtime, 3)})
        return out

    def stats(self) -> dict:
        # thread-affinity: any
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": self.sysdump_dir,
                "retention": self.retention,
                "max-bytes": self.max_bytes,
                "incidents": sum(self.incidents_total.values()),
                "incidents-by-kind": dict(self.incidents_total),
                "writes": self.writes_total,
                "captures-skipped": self.captures_skipped,
                "write-errors": self.write_errors,
                "last-bundle": self.last_bundle,
                **({"last-error": self.last_error}
                   if self.last_error else {}),
            }


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c == "-" else "-"
                   for c in str(s))[:32] or "incident"
