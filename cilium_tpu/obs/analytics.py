"""The flow analytics plane: streaming aggregation over the decoded
event stream.

Reference: upstream cilium's Hubble does not stop at storing flows —
``pkg/hubble/metrics`` aggregates the stream into per-identity rates
and hubble-ui renders top talkers and a service map from it, and
production operators page on *derived* signals (drop-rate spikes),
not raw flows.  The repo already had the flow ring (an Observer of
the last N flows) and per-label counters (``flow/metrics.py``); what
was missing is the ANALYTICS layer: windowed per-identity-pair
aggregates, heavy-hitter tracking, and a drop-spike detector that
turns the stream into a named incident.

Hot-path discipline (the PR 5 contract, extended):

- ``submit(batch)`` is the only thing any publishing thread pays: an
  O(1) reference append onto a bounded deque (overflow drops the
  OLDEST pending batch, counted).  It is registered as a
  MonitorAgent consumer, so it sees every decoded batch the monitor
  plane sees — ring-event joins from the event-join worker AND the
  host-synthesized drop batches (sheds, recovery drops) the drain
  thread publishes.
- ``drain()`` does the actual work and runs ONLY off the dispatch
  path: the daemon calls it from the event-join worker after each
  window join, from ``process_batch`` (the offline path), and from
  API queries.  A tier-1 test monkeypatch-records the thread
  identity of ``_ingest`` to prove the drain thread never executes
  it.
- ``_ingest`` is vectorized numpy over the batch: ``np.unique`` over
  composite key columns + ``np.add.at`` for byte sums.  Python loops
  run over UNIQUE keys per batch (identity pairs, distinct flows),
  never per packet.

Three aggregates:

- :class:`WindowAggregator` — rolling time windows (``window_s``
  wide, ``retention`` closed windows kept in a ring) of counters
  keyed by ``(src_identity, dst_identity, verdict, drop_reason)``
  with packet + byte sums; the ``GET /flows/aggregate`` verdict
  matrix renders from these.
- :class:`SpaceSavingSketch` — the Metwally et al. space-saving
  top-K heavy-hitters sketch, one instance keyed by flow 4-tuple and
  one by identity pair.  Guarantees (documented, tested): any key
  whose true count exceeds ``N/k`` is in the sketch, and every
  estimate overshoots its true count by at most ``N/k`` (the
  per-key ``error`` field bounds it exactly).
- :class:`SpikeDetector` — drop count per closed window vs the mean
  of the trailing ``baseline_windows`` non-spike windows; crossing
  ``max(min_drops, factor * baseline)`` raises ONE incident and
  enters the spike state, which releases only when drops fall back
  to the baseline (hysteresis: a burst spanning several windows is
  one incident, not one per window).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..core.packets import (COL_DIR, COL_DPORT, COL_DST_IP0, COL_EP,
                            COL_FAMILY, COL_LEN, COL_PROTO, COL_SPORT,
                            COL_SRC_IP0, words_to_ip)
from ..datapath.conntrack import CT_REPLY
from ..monitor.api import MSG_DROP

# ep-id -> local numeric identity (the daemon's endpoint table)
EpIdentityGetter = Callable[[int], int]
# on_incident(kind, detail_dict) — fired from whatever thread drained
IncidentFn = Callable[[str, dict], None]

DEFAULT_QUEUE_DEPTH = 16


def validate_analytics_config(window_s, windows, topk, queue_depth,
                              spike_factor, spike_min_drops,
                              spike_baseline_windows,
                              max_duty=0.5) -> tuple:
    """Validate the flow-analytics DaemonConfig knobs; returns the
    normalized tuple.  Same contract as ``validate_serving_config``:
    a bad knob fails at daemon construction, never as analytics that
    silently aggregates nothing."""
    max_duty = float(max_duty)
    if not 0.0 < max_duty <= 1.0:
        raise ValueError("flow_agg_max_duty must be in (0, 1] (the "
                         "aggregation duty-cycle cap)")
    window_s = float(window_s)
    if window_s <= 0:
        raise ValueError("flow_agg_window_s must be > 0")
    windows = int(windows)
    if windows < 1:
        raise ValueError("flow_agg_windows must be >= 1 (the closed-"
                         "window retention ring)")
    topk = int(topk)
    if topk < 1:
        raise ValueError("flow_agg_topk must be >= 1")
    queue_depth = int(queue_depth)
    if queue_depth < 1:
        raise ValueError("flow_agg_queue_depth must be >= 1")
    spike_factor = float(spike_factor)
    if spike_factor < 1.0:
        raise ValueError("spike_factor must be >= 1 (a spike is "
                         "MORE drops than baseline)")
    spike_min_drops = int(spike_min_drops)
    if spike_min_drops < 1:
        raise ValueError("spike_min_drops must be >= 1")
    spike_baseline_windows = int(spike_baseline_windows)
    if spike_baseline_windows < 1:
        raise ValueError("spike_baseline_windows must be >= 1")
    return (window_s, windows, topk, queue_depth, spike_factor,
            spike_min_drops, spike_baseline_windows, max_duty)


class SpaceSavingSketch:
    """Space-saving top-K (Metwally, Agrawal, El Abbadi 2005),
    extended with a byte sum per key.

    Invariants (the correctness test asserts both on Zipf traffic):

    - every key with true count > N/k is monitored (an elephant can
      never be evicted by mice: eviction replaces the MINIMUM
      counter, and min <= N/k always);
    - ``estimate - error <= true count <= estimate`` per key, with
      ``error <= N/k`` (a key inherits the evicted minimum as its
      error bound).

    Not thread-safe on its own — the owning :class:`FlowAnalytics`
    serializes updates under its aggregation lock."""

    __slots__ = ("k", "counts", "evictions", "total", "_key_hash")

    # fixed odd multipliers for the membership prefilter hash (a
    # wrapped dot product per row — vectorized).  The hash only
    # PREFILTERS: every candidate is confirmed by exact tuple lookup,
    # so a collision costs one wasted dict probe, never a wrong count
    _HASH_MULT = (np.random.default_rng(0xC111).integers(
        1, 1 << 63, size=32, dtype=np.uint64) << np.uint64(1)) \
        | np.uint64(1)

    def __init__(self, k: int):
        self.k = int(k)
        # key -> [count, bytes, error]
        self.counts: Dict[tuple, list] = {}
        self.evictions = 0
        self.total = 0  # sum of true increments ever offered (N)
        # hashes of counts' keys (rebuilt lazily): batch membership
        # prefilters vectorized against this
        self._key_hash: Optional[np.ndarray] = None

    @classmethod
    def _row_hash(cls, arr: np.ndarray) -> np.ndarray:
        w = arr.shape[1]
        return (arr.astype(np.uint64)
                * cls._HASH_MULT[:w]).sum(axis=1, dtype=np.uint64)

    def update(self, key: tuple, pkts: int, byts: int) -> None:
        self.total += pkts
        cur = self.counts.get(key)
        if cur is not None:
            cur[0] += pkts
            cur[1] += byts
            return
        self._key_hash = None
        if len(self.counts) < self.k:
            self.counts[key] = [pkts, byts, 0]
            return
        # evict the minimum-count key; the newcomer inherits its
        # count as the overestimate error (the space-saving step)
        victim = min(self.counts, key=lambda x: self.counts[x][0])
        floor = self.counts.pop(victim)[0]
        self.evictions += 1
        self.counts[key] = [floor + pkts, byts, floor]

    def update_many(self, keys: list, pkts, byts) -> None:
        """List-keyed convenience wrapper over
        :meth:`update_batch`."""
        if not len(keys):
            return
        self.update_batch(
            np.asarray(keys, dtype=np.int64).reshape(len(keys), -1),
            np.asarray(pkts, dtype=np.int64),
            np.asarray(byts, dtype=np.int64))

    def update_batch(self, rows: np.ndarray, pkts: np.ndarray,
                     byts: np.ndarray) -> None:
        """Batch merge — the streaming engine's hot call.  A batch's
        exact per-key counts form a zero-error summary, so this is a
        summary MERGE (Agarwal et al., "Mergeable Summaries"): a key
        absent from the sketch enters floored at the sketch's
        current minimum (that floor is its error), then the union is
        truncated to the top-k by estimate.  Same guarantees as m
        sequential :meth:`update` calls (elephants retained,
        overcount <= N/k), but the python-held work is O(k) per
        batch REGARDLESS of how many distinct keys the batch
        carried: membership runs vectorized against the numpy key
        mirror, and only the k largest fresh keys (by count — the
        only ones that can survive the truncation, since absent keys
        all share the same floor) are ever converted to tuples.  The
        worker thread's GIL time is what the serving drain thread
        contends with on CPU hosts, so this bound is load-bearing."""
        m = len(rows)
        if m == 0:
            return
        self.total += int(pkts.sum())
        counts = self.counts
        s = len(counts)
        if s:
            if self._key_hash is None:
                self._key_hash = self._row_hash(np.array(
                    list(counts.keys()), dtype=np.int64
                ).reshape(s, -1))
            # hash prefilter (vectorized) + exact confirm (python
            # over <= k candidates): a collision only costs a dict
            # probe that misses
            cand = np.flatnonzero(
                np.isin(self._row_hash(rows), self._key_hash))
            fresh_mask = np.ones(m, dtype=bool)
            for j in cand.tolist():
                cur = counts.get(tuple(rows[j].tolist()))
                if cur is not None:
                    cur[0] += int(pkts[j])
                    cur[1] += int(byts[j])
                    fresh_mask[j] = False
            fresh = np.flatnonzero(fresh_mask)
        else:
            fresh = np.arange(m)
        nf = len(fresh)
        if nf == 0:
            return
        if nf > self.k:
            # EXACT preselection: fresh keys all enter at mu + count,
            # so their estimate order is their count order — only
            # the k largest can survive the union truncation below
            order = np.argsort(pkts[fresh], kind="stable")[::-1]
            keep = fresh[order[:self.k]]
        else:
            keep = fresh
        mu = (min(c[0] for c in counts.values())
              if s >= self.k else 0)
        union = list(counts.items()) + [
            (key, [mu + p, b, mu])
            for key, p, b in zip(map(tuple, rows[keep].tolist()),
                                 pkts[keep].tolist(),
                                 byts[keep].tolist())]
        self._key_hash = None
        if len(union) > self.k:
            union.sort(key=lambda kv: -kv[1][0])
            self.evictions += s + nf - self.k
            self.counts = dict(union[:self.k])
        else:
            self.counts = dict(union)

    def top(self, n: Optional[int] = None) -> List[dict]:
        items = sorted(self.counts.items(), key=lambda kv: -kv[1][0])
        if n is not None:
            items = items[:n]
        return [{"key": k, "packets": int(c), "bytes": int(b),
                 "error": int(e)} for k, (c, b, e) in items]

    def error_bound(self) -> int:
        """The analytic overestimate bound: N/k."""
        return self.total // self.k if self.k else 0


class _Window:
    __slots__ = ("wid", "start", "packets", "bytes", "drops",
                 "counters", "opened_at")

    def __init__(self, wid: int, window_s: float):
        self.wid = wid
        self.start = wid * window_s
        self.packets = 0
        self.bytes = 0
        self.drops = 0
        # (src_id, dst_id, verdict, reason) -> [pkts, bytes]
        self.counters: Dict[tuple, list] = {}
        # wall clock at open (monotonic): the age-based roll closes
        # a window that outlived window_s with NO successor batch —
        # keyed on age, not wall window id, so synthetic-timestamp
        # streams (tests, replay) are not force-closed
        self.opened_at = time.monotonic()

    def to_dict(self, top: int = 16) -> dict:
        rows = sorted(self.counters.items(),
                      key=lambda kv: -kv[1][0])[:top]
        return {
            "window": self.wid,
            "start": round(self.start, 3),
            "packets": self.packets,
            "bytes": self.bytes,
            "drops": self.drops,
            "counters": [
                {"src-identity": k[0], "dst-identity": k[1],
                 "verdict": k[2], "reason": k[3],
                 "packets": int(v[0]), "bytes": int(v[1])}
                for k, v in rows],
        }


class WindowAggregator:
    """Ring-of-windows retention: one open window plus the last
    ``retention`` closed ones.  Ingest rolls the window forward when
    a batch's timestamp crosses the boundary; a straggler batch
    stamped before the boundary folds into the open window rather
    than resurrecting a closed one (monotonic enough for rates, and
    it keeps the close callback a one-shot per window)."""

    def __init__(self, window_s: float, retention: int,
                 on_close: Optional[Callable[[_Window], None]] = None):
        self.window_s = float(window_s)
        self.retention = int(retention)
        self.closed: Deque[_Window] = collections.deque(
            maxlen=self.retention)
        self.current: Optional[_Window] = None
        self.windows_closed = 0
        self._on_close = on_close

    def ingest(self, wid: int, keys: np.ndarray, pkts: np.ndarray,
               byts: np.ndarray, drops: int) -> None:
        cur = self.current
        if cur is None:
            cur = self.current = _Window(wid, self.window_s)
        elif wid > cur.wid:
            self.roll(wid)
            cur = self.current
        cur.packets += int(pkts.sum())
        cur.bytes += int(byts.sum())
        cur.drops += int(drops)
        counters = cur.counters
        # tolist() converts rows to native-int tuples in C; the loop
        # body is pure dict ops over UNIQUE keys
        for key, p, b in zip(map(tuple, keys.tolist()),
                             pkts.tolist(), byts.tolist()):
            slot = counters.get(key)
            if slot is None:
                counters[key] = [p, b]
            else:
                slot[0] += p
                slot[1] += b

    def roll(self, wid: int) -> None:
        """Close the open window (fires ``on_close`` exactly once)
        and open a fresh one at ``wid``."""
        cur = self.current
        self.current = _Window(wid, self.window_s)
        if cur is None:
            return
        self.closed.append(cur)
        self.windows_closed += 1
        if self._on_close is not None:
            self._on_close(cur)

    def matrix(self, top: int = 32) -> List[dict]:
        """The verdict matrix: per (src_identity, dst_identity,
        verdict, reason) totals aggregated over the open window plus
        every retained closed one."""
        agg: Dict[tuple, list] = {}
        wins = list(self.closed)
        if self.current is not None:
            wins.append(self.current)
        for w in wins:
            for k, v in w.counters.items():
                slot = agg.get(k)
                if slot is None:
                    agg[k] = [v[0], v[1]]
                else:
                    slot[0] += v[0]
                    slot[1] += v[1]
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]
        return [{"src-identity": k[0], "dst-identity": k[1],
                 "verdict": k[2], "reason": k[3],
                 "packets": v[0], "bytes": v[1]} for k, v in rows]


class SpikeDetector:
    """Drop-spike detection over CLOSED windows, with hysteresis.

    A window whose drop count crosses ``max(min_drops, factor *
    baseline)`` enters the spike state and fires ``on_spike`` ONCE;
    the state releases only when a window's drops fall back to
    ``max(baseline, min_drops / 2)``.  Spike windows are EXCLUDED
    from the baseline — a sustained burst must not teach the
    detector that the burst is normal (which would re-arm flapping
    across window boundaries)."""

    def __init__(self, factor: float, min_drops: int,
                 baseline_windows: int,
                 on_spike: Optional[Callable[[dict], None]] = None):
        self.factor = float(factor)
        self.min_drops = int(min_drops)
        self._baseline: Deque[int] = collections.deque(
            maxlen=int(baseline_windows))
        self.in_spike = False
        self.spikes = 0
        self.last_spike: Optional[dict] = None
        self._on_spike = on_spike

    @property
    def baseline(self) -> float:
        if not self._baseline:
            return 0.0
        return sum(self._baseline) / len(self._baseline)

    def observe(self, window: _Window) -> Optional[dict]:
        base = self.baseline
        threshold = max(float(self.min_drops), self.factor * base)
        fired = None
        if not self.in_spike:
            if window.drops >= threshold:
                self.in_spike = True
                self.spikes += 1
                fired = self.last_spike = {
                    "window": window.wid,
                    "drops": window.drops,
                    "packets": window.packets,
                    "baseline": round(base, 3),
                    "threshold": round(threshold, 3),
                    "detected-at": time.time(),
                }
                if self._on_spike is not None:
                    self._on_spike(fired)
            else:
                self._baseline.append(window.drops)
        else:
            release = max(base, self.min_drops / 2.0)
            if window.drops <= release:
                self.in_spike = False
                self._baseline.append(window.drops)
        return fired

    def to_dict(self) -> dict:
        return {
            "in-spike": self.in_spike,
            "spikes": self.spikes,
            "baseline-drops": round(self.baseline, 3),
            "min-drops": self.min_drops,
            "factor": self.factor,
            "last-spike": self.last_spike,
        }


# columns composing the flow 4-tuple sketch key (family first so the
# renderer knows how to print the ip words)
_TUPLE_COLS = ([COL_FAMILY]
               + list(range(COL_SRC_IP0, COL_SRC_IP0 + 4))
               + list(range(COL_DST_IP0, COL_DST_IP0 + 4))
               + [COL_SPORT, COL_DPORT, COL_PROTO])


def _unique_rows(arr: np.ndarray):
    """Exact ``np.unique(axis=0)`` replacement for integer rows —
    ``(unique_rows, inverse, counts)`` — an order of magnitude
    faster on the wide keys this module aggregates.  ``axis=0``
    unique argsorts a VOID view (per-element memcmp through a
    function pointer: ~15 ms for 8k x 12 rows, measured — which
    would make the analytics worker the serving bottleneck);
    instead, factorize column by column, combining the running code
    as ``code * card + col_code`` and RE-COMPRESSING after every
    combine so values stay < N² (no overflow for any column count,
    and every sort is a plain 1-D int64 sort).  Constant columns
    (most of a real header: family, dst ip, dport, proto) cost one
    cheap unique and no combine."""
    n = len(arr)
    if n == 0:
        return arr, np.zeros(0, dtype=np.int64), np.zeros(
            0, dtype=np.int64)
    code = None
    bound = 1  # exclusive upper bound on code values (python int)
    for j in range(arr.shape[1]):
        u, inv = np.unique(arr[:, j], return_inverse=True)
        card = len(u)
        if card == 1:
            continue
        if code is None:
            code, bound = inv, card
            continue
        if bound * card >= (1 << 62):
            # only re-compress when the combine would overflow —
            # with few varying columns this never fires, so the
            # whole factorization is one sort per varying column
            code = np.unique(code, return_inverse=True)[1]
            bound = n
        code = code * card + inv
        bound *= card
    if code is None:  # every column constant: one unique row
        return (arr[:1], np.zeros(n, dtype=np.int64),
                np.array([n], dtype=np.int64))
    _, code = np.unique(code, return_inverse=True)
    # code is DENSE now: counts and a representative row per code
    # come from O(n) passes, no further sorting
    counts = np.bincount(code)
    rep = np.empty(len(counts), dtype=np.int64)
    rep[code] = np.arange(n)
    return arr[rep], code, counts


class FlowAnalytics:
    """The engine: a bounded pending queue fed by ``submit`` (any
    thread, O(1)) and drained by ``drain`` (worker / API threads
    only).  All aggregation state is guarded by one lock taken only
    in ``drain``/``snapshot`` — never by a publishing thread."""

    def __init__(self, window_s: float = 1.0, retention: int = 8,
                 topk: int = 32,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 spike_factor: float = 4.0, spike_min_drops: int = 64,
                 spike_baseline_windows: int = 4,
                 max_duty: float = 0.1,
                 ep_identity: Optional[EpIdentityGetter] = None,
                 on_incident: Optional[IncidentFn] = None,
                 enabled: bool = True):
        (window_s, retention, topk, queue_depth, spike_factor,
         spike_min_drops, spike_baseline_windows, max_duty
         ) = validate_analytics_config(
            window_s, retention, topk, queue_depth, spike_factor,
            spike_min_drops, spike_baseline_windows, max_duty)
        self.enabled = bool(enabled)
        self.window_s = window_s
        self.topk = topk
        self.queue_depth = queue_depth
        # the duty-cycle governor: aggregation may spend at most
        # max_duty of wall time per rolling second; excess pending
        # batches become COUNTED drops.  This bounds by construction
        # how much CPU the analytics plane can take from anything
        # else (on CPU hosts the XLA datapath shares the cores —
        # "off the dispatch path" must also mean "not eating the
        # dispatch path's machine")
        self.max_duty = max_duty
        self._duty_t0 = 0.0
        self._duty_spent = 0.0
        self._ep_identity = ep_identity or (lambda e: 0)
        self._on_incident = on_incident
        # the pending queue: tiny lock, append/popleft only — this is
        # ALL a publishing thread (incl. the serving drain thread)
        # ever touches
        self._qlock = threading.Lock()
        # guarded-by: _qlock: _pending, batches_submitted,
        # guarded-by: _qlock: batches_ingested, batches_dropped
        self._pending: Deque[object] = collections.deque()
        # the aggregation state: worker/API threads only.  Lock order
        # where both are held: _lock THEN _qlock (drain's ledger
        # updates nest _qlock inside the aggregation lock)
        self._lock = threading.Lock()
        # guarded-by: _lock: windows, talkers, pairs, detector,
        # guarded-by: _lock: _fired_spikes, _duty_t0, _duty_spent,
        # guarded-by: _lock: packets_seen
        self.detector = SpikeDetector(
            spike_factor, spike_min_drops, spike_baseline_windows)
        # spikes detected while the aggregation lock is held are
        # DEFERRED and fired after drain() releases it: the incident
        # callback reaches the flight recorder, whose sysdump capture
        # snapshots this very engine — firing under the lock would
        # deadlock the worker against its own capture
        self._fired_spikes: List[dict] = []
        self.windows = WindowAggregator(window_s, retention,
                                        on_close=self._window_closed)
        self.talkers = SpaceSavingSketch(topk)
        self.pairs = SpaceSavingSketch(topk)
        # the ledger: submitted == ingested + dropped once pending
        # drains (drain() always empties what it saw)
        self.batches_submitted = 0
        self.batches_ingested = 0
        self.batches_dropped = 0
        self.packets_seen = 0

    # -- producer side (ANY thread, including the drain thread) --------
    def submit(self, batch) -> None:
        # thread-affinity: any
        """A MonitorAgent consumer: park one decoded EventBatch by
        reference.  Never aggregates here — the deque append is the
        entire cost on the publishing thread.  While the duty budget
        is exhausted (a shed storm), the batch is dropped HERE
        (counted) instead of parked: retaining references the
        governor will drop anyway extends big drop-batch lifetimes
        across the queue, and that allocator/cache pressure is paid
        by the whole machine."""
        if not self.enabled or len(batch) == 0:
            return
        with self._qlock:
            self.batches_submitted += 1
            # ADVISORY cross-lock read of the _lock-guarded duty
            # clock, racy BY DESIGN: taking _lock on the publishing
            # path would make the drain thread wait out a whole
            # aggregation pass — the exact contention submit() exists
            # to avoid.  Worst case one batch is parked (or dropped)
            # a beat late; drain() re-checks authoritatively.
            # lint: disable=CTA001 -- advisory racy read; drain() re-checks under _lock
            spent, t0 = self._duty_spent, self._duty_t0
            if (spent >= self.max_duty
                    and time.monotonic() - t0 < 1.0):
                self.batches_dropped += 1
                return
            if len(self._pending) >= self.queue_depth:
                self._pending.popleft()
                self.batches_dropped += 1
            self._pending.append(batch)

    @property
    def pending(self) -> int:
        # thread-affinity: any
        with self._qlock:
            return len(self._pending)

    # -- consumer side (event-join worker / API / offline callers) -----
    def drain(self) -> int:
        # thread-affinity: event-worker, capture, api, cli, offline
        """Aggregate everything pending, then roll the open window
        if wall time has crossed its boundary — a drop burst
        followed by SILENCE must still close its window and reach
        the spike detector (the daemon's flow-agg-roll controller
        ticks this on the window cadence, so detection never waits
        for a next batch that may not come).  Runs on the CALLING
        thread — the daemon only calls it off the dispatch path
        (event-join worker, process_batch tail, the roll controller,
        API queries, stop_serving)."""
        with self._qlock:
            batches, self._pending = list(self._pending), \
                collections.deque()
        with self._lock:
            for batch in batches:
                now = time.monotonic()
                if now - self._duty_t0 >= 1.0:
                    self._duty_t0, self._duty_spent = now, 0.0
                if self._duty_spent >= self.max_duty:
                    # duty budget spent this second: shed the batch
                    # (counted) instead of stealing more CPU from
                    # the machine the datapath runs on.  Ledger
                    # counters mutate under _qlock ONLY (submit's
                    # duty-exhausted drop also counts there; split
                    # locks would lose increments and break the
                    # exact submitted == ingested + dropped ledger)
                    with self._qlock:
                        self.batches_dropped += 1
                    continue
                try:
                    self._ingest(batch)
                except Exception:  # noqa: BLE001 — one poisoned
                    # batch must not wedge the analytics plane; the
                    # ledger still counts it (as ingested work that
                    # produced nothing) via batches_dropped
                    with self._qlock:
                        self.batches_dropped += 1
                else:
                    with self._qlock:
                        self.batches_ingested += 1
                self._duty_spent += time.monotonic() - now
            # age-based roll: a window that outlived window_s with
            # no successor batch still closes (and reaches the spike
            # detector) — a drop burst followed by SILENCE is
            # exactly the case the detector must not sleep through.
            # An EMPTY aged window only rolls while the detector is
            # in a spike (the release observation); pure silence
            # does not churn empty windows through the ring
            cur = self.windows.current
            if (cur is not None
                    and time.monotonic() - cur.opened_at
                    >= self.window_s
                    and (cur.packets or cur.drops
                         or self.detector.in_spike)):
                self.windows.roll(cur.wid + 1)
            fired, self._fired_spikes = self._fired_spikes, []
        for spike in fired:  # outside the lock — see _window_closed
            self._spike_incident(spike)
        return len(batches)

    def _window_closed(self, window: _Window) -> None:
        # holds: _lock -- the WindowAggregator close hook fires from
        # drain()'s locked region
        # thread-affinity: event-worker, capture, api, cli, offline
        """WindowAggregator close hook (called under ``_lock``):
        detect, but DEFER the incident callback to drain()'s
        unlocked tail."""
        fired = self.detector.observe(window)
        if fired is not None:
            self._fired_spikes.append(fired)

    def _ingest(self, batch) -> None:
        # holds: _lock -- called from drain()'s locked region only
        # thread-affinity: event-worker, capture, api, cli, offline
        # -- NEVER the drain thread: the static half of the tier-1
        # monkeypatch thread-identity proof
        """Vectorized aggregation of one EventBatch (the monkeypatch
        point for the never-on-the-drain-thread tier-1 proof)."""
        hdr = batch.hdr
        n = len(batch)
        self.packets_seen += n
        lens = hdr[:, COL_LEN].astype(np.int64)
        # local identity per row: python only over UNIQUE endpoints
        eps, inv = np.unique(hdr[:, COL_EP], return_inverse=True)
        local = np.fromiter(
            (self._ep_identity(int(e)) for e in eps),
            dtype=np.int64, count=len(eps))[inv]
        remote = batch.identity.astype(np.int64)
        # remote sits on the src side for ingress non-reply rows
        # (the threefour parser's endpoint resolution, vectorized)
        remote_is_src = ((hdr[:, COL_DIR] == 0)
                         ^ (batch.ct_state == CT_REPLY))
        src_id = np.where(remote_is_src, remote, local)
        dst_id = np.where(remote_is_src, local, remote)
        key4 = np.stack(
            [src_id, dst_id, batch.verdict.astype(np.int64),
             batch.reason.astype(np.int64)], axis=1)
        uniq, inv4, cnt = _unique_rows(key4)
        byts = np.bincount(inv4, weights=lens,
                           minlength=len(uniq)).astype(np.int64)
        drops = int((batch.msg_type == MSG_DROP).sum())
        self.windows.ingest(int(batch.timestamp // self.window_s),
                            uniq, cnt, byts, drops)
        # identity-pair heavy hitters: collapse the window keys
        # (already unique) onto (src, dst) — vectorized, then one
        # batch merge into the sketch
        puniq, pinv, _ = _unique_rows(uniq[:, :2])
        ppkts = np.bincount(pinv, weights=cnt,
                            minlength=len(puniq)).astype(np.int64)
        pbyts = np.bincount(pinv, weights=byts,
                            minlength=len(puniq)).astype(np.int64)
        self.pairs.update_batch(puniq, ppkts, pbyts)
        # flow 4-tuple heavy hitters: unique flows per batch (the
        # sketch's batch merge keeps python work O(k), never per
        # distinct flow)
        tup = hdr[:, _TUPLE_COLS].astype(np.int64)
        tuniq, tinv, tcnt = _unique_rows(tup)
        tbyts = np.bincount(tinv, weights=lens,
                            minlength=len(tuniq)).astype(np.int64)
        self.talkers.update_batch(tuniq, tcnt, tbyts)

    def _spike_incident(self, spike: dict) -> None:
        # thread-affinity: event-worker, capture, api, cli, offline
        if self._on_incident is not None:
            self._on_incident("drop-spike", spike)

    # -- reading -------------------------------------------------------
    @staticmethod
    def _render_talker(row: dict) -> dict:
        fam, s0, s1, s2, s3, d0, d1, d2, d3, sport, dport, proto = \
            row["key"]
        return {
            "src": words_to_ip(np.array([s0, s1, s2, s3],
                                        dtype=np.uint32), fam),
            "dst": words_to_ip(np.array([d0, d1, d2, d3],
                                        dtype=np.uint32), fam),
            "sport": sport, "dport": dport, "proto": proto,
            "packets": row["packets"], "bytes": row["bytes"],
            "error": row["error"],
        }

    def snapshot(self, top: int = 16) -> dict:
        # thread-affinity: capture, api, cli, offline
        """``GET /flows/aggregate``: windows, matrix, top talkers,
        spike state, ledger.  Drains pending first so queries read
        fresh aggregates (query threads are off the dispatch path by
        definition)."""
        self.drain()
        # the ledger reads OUTSIDE the aggregation lock: stats() now
        # takes both locks itself, and calling it from inside the
        # `with self._lock:` below would deadlock on the
        # non-reentrant lock
        ledger = self.stats()
        with self._lock:
            cur = self.windows.current
            out = {
                "enabled": self.enabled,
                "window-s": self.window_s,
                "windows-closed": self.windows.windows_closed,
                "retention": self.windows.retention,
                "current-window": (cur.to_dict(top)
                                   if cur is not None else None),
                "windows": [w.to_dict(top)
                            for w in self.windows.closed],
                "matrix": self.windows.matrix(top),
                "top-talkers": [self._render_talker(r)
                                for r in self.talkers.top(top)],
                "top-identity-pairs": [
                    {"src-identity": r["key"][0],
                     "dst-identity": r["key"][1],
                     "packets": r["packets"], "bytes": r["bytes"],
                     "error": r["error"]}
                    for r in self.pairs.top(top)],
                "top-k": self.topk,
                "sketch-error-bound": self.talkers.error_bound(),
                "evictions": (self.talkers.evictions
                              + self.pairs.evictions),
                "spike": self.detector.to_dict(),
                "ledger": ledger,
            }
            return out

    def stats(self) -> dict:
        # thread-affinity: any
        """The serving-stats / registry block (cheap counters; no
        drain — safe from any thread).  Takes both locks (aggregation
        then ledger, the drain() nesting order) so a scrape never
        reads a half-updated window count against the matching
        ledger; the bare reads it replaces raced live aggregation."""
        with self._lock:
            windows_closed = self.windows.windows_closed
            evictions = self.talkers.evictions + self.pairs.evictions
            spikes = self.detector.spikes
            packets = self.packets_seen
            with self._qlock:
                return {
                    "enabled": self.enabled,
                    "batches-submitted": self.batches_submitted,
                    "batches-ingested": self.batches_ingested,
                    "batches-dropped": self.batches_dropped,
                    "packets-seen": packets,
                    "pending": len(self._pending),
                    "windows-closed": windows_closed,
                    "talker-evictions": evictions,
                    "spikes": spikes,
                }
