"""Observability plane: per-packet trace spans, the unified metrics
registry, and compile/profile introspection.

Reference: upstream cilium's killer observability feature is Hubble —
every datapath event is attributed (``pkg/monitor`` + the
``threefour`` parser) and queryable.  The serving plane here has five
pipeline stages (admission -> batch assembly -> h2d staging -> device
dispatch -> ring drain/verdict join) whose latency the pre-obs
telemetry could only see as one opaque end-to-end histogram.  This
package is the Dapper-style answer (Sigelman et al., 2010): thread a
trace through the hot path for 1-in-N sampled packets at near-zero
cost, attribute per-stage latency, and make the recompile / demotion
/ recovery machinery explainable after the fact instead of only
countable.

Pieces (PARITY.md row 57):

- :mod:`.trace` — sampled per-packet trace spans: a span allocated at
  ``IngressQueue`` admission for 1-in-N packets (``span_sample`` /
  the ``serving_trace_sample`` DaemonConfig knob; default 0 = off =
  zero overhead), carried through the batcher, arena staging, device
  dispatch, and the drain-time verdict join, recording six monotonic
  stage timestamps plus batch/bucket/mode annotations into a
  fixed-size lock-cheap span ring.  Surfaced via ``GET
  /debug/traces`` and ``cilium-tpu trace [-f]``.
- :mod:`.registry` — the unified prometheus registry: every counter /
  gauge / histogram the agent exports lives behind ONE self-
  describing registry backing ``GET /metrics`` (the ``pkg/metrics``
  analogue), with log2 histograms exported as cumulative buckets.
  ``scripts/check_metrics_registry.py`` lints that no exposition
  text is built anywhere else, so the pre-obs scatter (serving
  stats, flow metrics, loader metricsmap, fault counters each
  rendering their own lines) cannot regrow.
- :mod:`.compile_log` — compile-event introspection: every XLA
  retrace on the serving path is recorded with shape/mode/latency,
  and the one-executable-per-(rung, mode) invariant is asserted at
  RUNTIME (a duplicate compile for a seen key counts as a violation
  and logs), not just in tests.
- :mod:`.analytics` — the flow analytics plane (PARITY row 59):
  windowed per-identity aggregation, space-saving top-K talkers,
  and drop-spike detection over the decoded event stream; all
  aggregation runs OFF the dispatch path (event-join worker / query
  threads).  ``GET /flows/aggregate``, ``cilium-tpu top [-f]``.
- :mod:`.history` / :mod:`.slo` — the SLO plane (ISSUE 19): fixed-
  memory two-tier rings retaining a declared subset of registry
  series (counter-reset splicing included), and declarative SLOs
  evaluated with fast+slow multi-window burn rates over those rings
  on one off-hot-path sampler thread — a page-severity burn opens a
  ``slo-burn`` incident episode (sysdump auto-capture, hysteresis,
  recovery recorded).  ``GET /metrics/history``, ``GET /slo``,
  ``cilium-tpu history/slo``, ``cilium_slo_*`` series.
- :mod:`.flightrec` — the incident flight recorder: named incidents
  (spike, watchdog restart, ladder demotion, terminal event worker,
  manual) capture bounded, retention-capped sysdump bundles to
  ``--sysdump-dir``.  ``GET /debug/sysdump``, ``cilium-tpu
  sysdump``, ``scripts/check_sysdump_schema.py``.
"""

from __future__ import annotations

from .analytics import (FlowAnalytics, SpaceSavingSketch,  # noqa: F401
                        SpikeDetector, WindowAggregator,
                        validate_analytics_config)
from .compile_log import CompileLog  # noqa: F401
from .flightrec import (SYSDUMP_REQUIRED_KEYS,  # noqa: F401
                        FlightRecorder, validate_flightrec_config)
from .history import (SeriesHistory, counters_reset,  # noqa: F401
                      validate_history_config)
from .registry import MetricsRegistry, build_daemon_registry  # noqa: F401
from .slo import (HISTORY_SERIES, SLODef, SLOEngine,  # noqa: F401
                  default_slos, validate_slo_config)
from .trace import (SPAN_STAGES, SpanTracer, TraceSpan,  # noqa: F401
                    validate_obs_config)

__all__ = [
    "CompileLog",
    "FlightRecorder",
    "FlowAnalytics",
    "HISTORY_SERIES",
    "MetricsRegistry",
    "SLODef",
    "SLOEngine",
    "SPAN_STAGES",
    "SYSDUMP_REQUIRED_KEYS",
    "SeriesHistory",
    "SpaceSavingSketch",
    "SpanTracer",
    "SpikeDetector",
    "TraceSpan",
    "WindowAggregator",
    "build_daemon_registry",
    "counters_reset",
    "default_slos",
    "validate_analytics_config",
    "validate_flightrec_config",
    "validate_history_config",
    "validate_obs_config",
    "validate_slo_config",
]
