"""Cluster observability relay: one operator surface over N nodes.

Reference: upstream cilium runs one agent per node, and the pieces
that make a CLUSTER operable are dedicated aggregators — Hubble Relay
(``pkg/hubble/relay``) fans GetFlows out to every node and merges the
streams time-ordered with a node label, Prometheus scrapes every
agent's ``/metrics`` and the ``instance`` label keys the dashboards,
and ``cilium-sysdump`` collects every node's bugtool bundle into one
archive.  PR 13 made this repo's nodes real processes and thereby
made its richest subsystem invisible: each worker's registry, flow
ring, span tracer, analytics top-K, and flight recorder live behind a
control channel.  This module is the aggregator tier (ISSUE 14):

- :class:`ClusterObsRelay` — a periodic LOW-DUTY scrape loop (its own
  thread, bounded control-RPC timeouts, never on the router's
  forward path) pulling each node's full observability snapshot: the
  registry exposition text, the flow-ring tail (since-cursor), the
  tracer + analytics snapshots, and the incident list.  Merged views:

  * :meth:`cluster_metrics` — ONE prometheus exposition where every
    per-node series carries a ``node`` label (grouped per family, no
    duplicate series), plus the relay's own meta-series:
    ``cilium_cluster_node_scrape_ok{node=}`` (0 marks a node whose
    scrape failed — the worker-death-during-scrape contract),
    ``cilium_cluster_node_scrape_age_seconds{node=}``,
    ``cilium_cluster_scrapes_total`` and the scrape round-trip
    histogram ``cilium_cluster_scrape_rtt_us``.  A failed node's
    last-known-good series keep serving until ``stale_after_s``,
    then drop (bounded staleness beats silently-frozen gauges);
  * :meth:`cluster_flows` — time-ordered merged flows from every
    node's ring tail, each stamped ``node_name`` (hubble-relay
    parity for the serving tier);
  * :meth:`cluster_top` — analytics top-K merged across nodes
    (space-saving sketches are mergeable summaries: per-key sums
    with summed error bounds — the PR 6 batch-merge idiom one level
    up);
  * :meth:`cluster_sysdump` — every worker's flight-recorder bundle
    plus the parent's cluster-level bundle in one tar archive with a
    manifest (the ``cilium-sysdump`` shape).

- :class:`ClusterSpanStore` — the landing zone for CROSS-PROCESS
  stitched spans: a 1-in-N sampled forward chunk carries
  ``(trace_id, t_enqueue, t_forward)`` through the socket transport,
  the worker stamps ``(t_recv, t_admit)`` and echoes them on the
  ack, and the router commits the completed span here with per-hop
  log2 histograms — BENCH_cluster's forward-latency percentiles
  become inspectable per-flow.  Same-host ``time.monotonic()``
  stamps compare across processes (Linux CLOCK_MONOTONIC is
  machine-wide), so consecutive stages are monotonic by
  construction.

Exposition text is deliberately built HERE and in ``obs/registry.py``
only — the CTA006 checker allowlists exactly these two modules.

THREAD AFFINITY: the scrape loop is control-plane work (``api``
domain — it shares the per-node control channel lock with membership
probes); :class:`ClusterSpanStore` commits arrive from router
forwarder threads.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..serving.stats import LatencyHistogram
from .registry import MetricsRegistry, escape_label_value
from .slo import STATE_CODES, STATE_NO_DATA, STATE_OK

__all__ = [
    "ClusterObsRelay", "ClusterSpanStore", "TraceCtx",
    "merge_expositions", "SPAN_HOPS", "CLUSTER_SYSDUMP_SCHEMA",
]

# per-node flow-ring tail retention inside the relay's merged buffer
FLOW_BUFFER = 4096
# flows pulled per node per scrape (since-cursor: the tail only)
FLOWS_PER_SCRAPE = 512
# with the periodic loop disabled, a query re-sweeps when the
# freshest cached snapshot is older than this (bursts of queries
# share one sweep; a lone query always answers fresh)
ON_DEMAND_MAX_AGE_S = 1.0

# default scrape duty bound: sweeps may consume at most this fraction
# of wall clock (the loop stretches its cadence to honor it).  Sized
# against the ISSUE 14 acceptance floor (scrape-overhead throughput
# ratio >= 0.95): on a fully-contended host the steady-state tax
# approaches the duty, so 2% leaves real margin; a sweep on this
# class of box costs ~0.2-0.4 s (registry render includes a device
# metricsmap fetch that waits out queued dispatches), putting the
# governed cadence at ~10-20 s under load and at the interval_s
# ceiling when idle
SCRAPE_DUTY = 0.02

CLUSTER_SYSDUMP_SCHEMA = 1

# the stitched span's hop vocabulary (consecutive stage pairs):
# router enqueue -> forwarder pop/send -> worker recv -> worker
# admit (runtime.submit returned) -> ack landed back on the router
SPAN_STAGES = ("enqueue", "forward", "worker-recv", "worker-admit",
               "ack")
SPAN_HOPS = tuple(f"{SPAN_STAGES[i]}->{SPAN_STAGES[i + 1]}"
                  for i in range(len(SPAN_STAGES) - 1))


class TraceCtx:
    """One sampled forward chunk's cross-process trace context.
    Mutated only by the thread currently holding the chunk (router
    submit -> forwarder -> the ack parse), committed once."""

    __slots__ = ("trace_id", "node", "rows", "t_enq", "t_fwd",
                 "t_recv", "t_admit", "t_ack")

    def __init__(self, trace_id: int, rows: int, t_enq: float):
        self.trace_id = trace_id
        self.node = ""
        self.rows = rows
        self.t_enq = t_enq
        self.t_fwd = 0.0
        self.t_recv = 0.0
        self.t_admit = 0.0
        self.t_ack = 0.0

    def stages(self) -> List[float]:
        return [self.t_enq, self.t_fwd, self.t_recv, self.t_admit,
                self.t_ack]

    def complete(self) -> bool:
        ts = self.stages()
        return all(t > 0.0 for t in ts)

    def monotonic(self) -> bool:
        ts = self.stages()
        return all(ts[i + 1] >= ts[i] for i in range(len(ts) - 1))

    def to_dict(self) -> dict:
        ts = self.stages()
        return {
            "trace-id": self.trace_id,
            "node": self.node,
            "rows": self.rows,
            "timestamps": list(ts),
            "hops-us": {SPAN_HOPS[i]:
                        round((ts[i + 1] - ts[i]) * 1e6, 3)
                        for i in range(len(SPAN_HOPS))},
            "e2e-us": round((self.t_ack - self.t_enq) * 1e6, 3),
            "monotonic": self.monotonic(),
        }


class ClusterSpanStore:
    """Completed cross-process spans: fixed ring (newest wins) +
    per-hop aggregate log2 histograms, loss-exact (sampled ==
    committed + dropped — a chunk whose worker died mid-flight is a
    counted drop, never a vanished span)."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # guarded-by: _lock: _ring, _w, sampled, committed, dropped
        self._ring: List[Optional[TraceCtx]] = [None] * self.capacity
        self._w = 0
        self.sampled = 0
        self.committed = 0
        self.dropped = 0
        self.hop_hist = [LatencyHistogram() for _ in SPAN_HOPS]
        self.e2e_hist = LatencyHistogram()

    def allocate_span(self, rows: int, t_enq: float) -> TraceCtx:
        # thread-affinity: router
        with self._lock:
            ctx = TraceCtx(self.sampled, rows, t_enq)
            self.sampled += 1
        return ctx

    def commit_span(self, ctx: TraceCtx) -> None:
        # thread-affinity: router, transport -- sync path commits on
        # the forwarder; pipelined frames commit on the parent's ack
        # reader (ISSUE 17) when the cumulative ack lands
        with self._lock:
            if not ctx.complete():
                self.dropped += 1
                return
            self._ring[self._w % self.capacity] = ctx
            self._w += 1
            self.committed += 1
            ts = ctx.stages()
            for i in range(len(SPAN_HOPS)):
                self.hop_hist[i].record(max(ts[i + 1] - ts[i], 0.0)
                                        * 1e6)
            self.e2e_hist.record(max(ctx.t_ack - ctx.t_enq, 0.0)
                                 * 1e6)

    def drop_span(self, ctx: TraceCtx) -> None:
        # thread-affinity: router, api, transport -- the parent's
        # ack reader counts a swept window's late hand-back as span
        # loss (ISSUE 17)
        """The chunk died before its ack (crashed worker, failover
        migration, stop sweep): the span is counted lost."""
        with self._lock:
            self.dropped += 1

    def span_stats(self) -> dict:
        # thread-affinity: any
        with self._lock:
            return {"sampled": self.sampled,
                    "committed": self.committed,
                    "dropped": self.dropped,
                    "in-flight": (self.sampled - self.committed
                                  - self.dropped)}

    def snapshot_spans(self, limit: int = 32) -> dict:
        # thread-affinity: api, cli -- the cluster_trace query
        # surface (the histogram-snapshot leaf has query-thread
        # affinity; counters-only reads ride span_stats instead)
        with self._lock:
            held = min(self._w, self.capacity)
            spans = [self._ring[(self._w - 1 - i) % self.capacity]
                     for i in range(held)]
            out = {
                "sampled": self.sampled,
                "committed": self.committed,
                "dropped": self.dropped,
                "hops-us": {SPAN_HOPS[i]: self.hop_hist[i].snapshot()
                            for i in range(len(SPAN_HOPS))},
                "e2e-us": self.e2e_hist.snapshot(),
            }
        out["spans"] = [sp.to_dict() for sp in spans[:limit]
                        if sp is not None]
        return out


# -- exposition merging ------------------------------------------------
def _inject_node(line: str, node_esc: str) -> str:
    """One sample line -> the same sample with a leading ``node``
    label.  ``name{a="b"} v`` and ``name v`` forms both handled; the
    value (and any exemplar/timestamp tail) is preserved verbatim."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        # labelled: name{...} value
        return (line[:brace + 1] + 'node="' + node_esc + '",'
                + line[brace + 1:])
    if space == -1:
        return line  # malformed; pass through untouched
    return (line[:space] + '{node="' + node_esc + '"}'
            + line[space:])


def merge_expositions(node_texts: "Dict[str, str]") -> List[str]:
    """Per-node exposition texts -> one cluster exposition, grouped
    per metric family (prometheus requires a family's samples
    contiguous), every sample stamped with its ``node`` label.  HELP
    and TYPE lines are emitted once per family (nodes render the
    same registry, so the first node's metadata stands for all)."""
    order: List[str] = []  # family names, first-seen order
    meta: Dict[str, List[str]] = {}  # family -> [# HELP, # TYPE]
    samples: Dict[str, List[str]] = {}  # family -> injected samples
    for node, text in node_texts.items():
        esc = escape_label_value(node)
        family = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) < 3:
                    continue
                family = parts[2]
                if family not in meta:
                    meta[family] = []
                    samples[family] = []
                    order.append(family)
                if line not in meta[family]:
                    meta[family].append(line)
            else:
                if family is None:
                    # headerless sample (never produced by the
                    # registry, but a peer must not tear the merge)
                    family = line.split("{")[0].split(" ")[0]
                    if family not in meta:
                        meta[family] = []
                        samples[family] = []
                        order.append(family)
                samples[family].append(_inject_node(line, esc))
    out: List[str] = []
    for family in order:
        out.extend(meta[family])
        out.extend(samples[family])
    return out


def _render_hist_lines(name: str, hist: LatencyHistogram,
                       lines: List[str]) -> None:
    """Cumulative log2 exposition for a relay-level histogram — the
    registry's ONE renderer (torn-read discipline and all), plus the
    HELP line it leaves to its caller."""
    lines.append(f"# HELP {name} relay scrape round trip (µs)")
    MetricsRegistry._render_histogram(lines, name, hist)


class ClusterObsRelay:
    """The parent-side scraper/merger.  ``peers_fn`` returns the
    CURRENT node handles (so scale-out replicas join the scrape set
    without registration); each handle implements the node obs
    interface — ``name`` / ``alive`` / ``obs_scrape(cursor, flows,
    top)`` / ``sysdump_bundle()`` (``cluster.ClusterNode`` in-process,
    ``cluster.process.ProcessNode`` over the control channel).

    The scrape loop NEVER runs on a router/forwarder thread and never
    takes router locks: a wedged worker costs one bounded control RPC
    timeout, after which the node is marked un-scrapeable
    (``scrape_ok 0``) and its last-known-good snapshot keeps serving
    until ``stale_after_s``."""

    # guarded-by: _lock: _cache, _cursors, scrapes_total,
    # guarded-by: _lock: scrape_errors

    def __init__(self, peers_fn: Callable[[], Sequence],
                 interval_s: float = 1.0,
                 stale_after_s: float = 30.0,
                 span_store: Optional[ClusterSpanStore] = None,
                 parent_collect: Optional[Callable[[], dict]] = None,
                 flows_per_scrape: int = FLOWS_PER_SCRAPE,
                 flow_buffer: int = FLOW_BUFFER,
                 duty: float = SCRAPE_DUTY):
        self._peers_fn = peers_fn
        self.interval_s = float(interval_s)
        self.stale_after_s = float(stale_after_s)
        self.span_store = span_store
        self._parent_collect = parent_collect
        self.flows_per_scrape = int(flows_per_scrape)
        self.flow_buffer = int(flow_buffer)
        # the scrape DUTY GOVERNOR (the flow-analytics max_duty idiom
        # one level up): interval_s is a cadence CEILING — after each
        # sweep the loop stretches its next delay so sweep time stays
        # under `duty` of wall clock.  A worker answering scrape ops
        # spends ITS core doing so (obs_scrape renders the registry,
        # drains analytics, materializes the flow tail); on saturated
        # hosts an eager cadence would tax serving throughput, which
        # is exactly what "off the hot path" must not do.  0 disables
        # the governor (fixed cadence).
        self.duty = float(duty)
        self._delay = self.interval_s
        self._lock = threading.Lock()
        # ONE sweep at a time (review hardening): two concurrent
        # scrape_now calls — API threads racing each other or the
        # periodic tick — would read the same per-node flow cursor
        # and commit the same ring tail twice, duplicating every
        # flow in the merged buffer
        self._sweep_lock = threading.Lock()
        # node name -> {"ok", "at" (monotonic), "metrics-text",
        #               "flows" (bounded list), "top", "trace",
        #               "incidents", "error"}
        self._cache: Dict[str, dict] = {}
        self._cursors: Dict[str, int] = {}
        # node name -> {"snap" (last-good slo_snapshot), "at"
        #               (monotonic), "ok", "error"} — same
        # last-known-good + staleness discipline as _cache, but for
        # the SLO verdict pull (cluster_slo sweeps on demand; the
        # verdict is too small to ride the scrape snapshot)
        self._slo_cache: Dict[str, dict] = {}
        self.scrapes_total = 0
        self.scrape_errors = 0
        self.rtt = LatencyHistogram()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="cluster-obs-relay")
        self._thread.start()

    def stop(self) -> None:
        # thread-affinity: api
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def _loop(self) -> None:
        # thread-affinity: api -- the relay's own scrape thread
        while not self._stop.wait(self._delay):
            t0 = time.monotonic()
            try:
                self.scrape_now()
            except Exception:  # noqa: BLE001 — one broken sweep must
                # not kill the loop; per-node failures are already
                # contained + counted inside scrape_now
                with self._lock:
                    self.scrape_errors += 1
            if self.duty > 0:
                # duty governor: cost/(cost+delay) <= duty
                cost = time.monotonic() - t0
                self._delay = max(
                    self.interval_s,
                    cost * (1.0 - self.duty) / self.duty)

    # -- scraping ------------------------------------------------------
    def scrape_now(self) -> Dict[str, bool]:
        # thread-affinity: api, cli
        """One synchronous sweep over the current peers; returns
        ``{node: ok}``.  Per-node failures are contained: the node is
        marked un-scrapeable, its cached snapshot stands (until the
        staleness bound), the sweep continues.  Sweeps are
        SERIALIZED (``_sweep_lock``): a second caller waits, then
        runs against the advanced cursors — never the same window
        twice."""
        with self._sweep_lock:
            return self._sweep()

    def _sweep(self) -> Dict[str, bool]:
        # thread-affinity: api, cli
        # holds: _sweep_lock
        results: Dict[str, bool] = {}
        for node in list(self._peers_fn()):
            name = node.name
            if not getattr(node, "alive", True):
                self._mark_failed(name, "node dead")
                results[name] = False
                continue
            with self._lock:
                cursor = self._cursors.get(name, 0)
            t0 = time.monotonic()
            try:
                snap = node.obs_scrape(cursor=cursor,
                                       flows=self.flows_per_scrape,
                                       top=16)
            except Exception as e:  # noqa: BLE001 — a worker dying
                # MID-SCRAPE (SIGKILL chaos leg) or a wedged control
                # channel: contained, counted, last-known-good stands
                self._mark_failed(name, f"{type(e).__name__}: {e}")
                results[name] = False
                continue
            rtt_us = (time.monotonic() - t0) * 1e6
            self._commit(name, snap, rtt_us)
            results[name] = True
        return results

    def _mark_failed(self, name: str, error: str) -> None:
        # thread-affinity: api, cli
        with self._lock:
            self.scrape_errors += 1
            ent = self._cache.get(name)
            if ent is None:
                self._cache[name] = {
                    "ok": False, "at": None, "metrics-text": None,
                    "flows": [], "top": None, "trace": None,
                    "incidents": [], "error": error}
            else:
                ent["ok"] = False
                ent["error"] = error

    def _commit(self, name: str, snap: dict, rtt_us: float) -> None:
        # thread-affinity: api, cli
        with self._lock:
            self.scrapes_total += 1
            self.rtt.record(rtt_us)
            ent = self._cache.setdefault(name, {"flows": []})
            ent["ok"] = True
            ent["error"] = None
            ent["at"] = time.monotonic()
            ent["metrics-text"] = snap.get("metrics-text")
            ent["top"] = snap.get("top")
            ent["trace"] = snap.get("trace")
            ent["incidents"] = snap.get("incidents") or []
            ent["l7-by-plugin"] = snap.get("l7-by-plugin") or {}
            fresh = snap.get("flows") or []
            for f in fresh:
                f["node_name"] = name
            flows = ent.get("flows") or []
            flows.extend(fresh)
            ent["flows"] = flows[-self.flow_buffer:]
            self._cursors[name] = int(snap.get("cursor", 0))

    def _fresh_cache(self) -> Dict[str, dict]:
        """Locked copy of the cache with staleness applied: a failed
        node's last-known-good snapshot serves inside the bound,
        after which its per-node series drop (only the relay's own
        scrape_ok/age meta-series remain to say why).  The age bound
        applies only to FAILED nodes (ok 0): on a saturated host the
        duty governor can legally stretch the sweep delay past
        ``stale_after_s``, and an unconditional bound would then mark
        every HEALTHY node stale between sweeps — blanking the merged
        views while scrape_ok still read 1.  A node whose LAST scrape
        succeeded serves that snapshot however old it is (it is as
        fresh as the scrape plane can make it, and the age
        meta-series says exactly how old that is)."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for name, ent in self._cache.items():
                e = dict(ent)
                # the flow buffer is mutated in place by _commit;
                # hand readers their own copy, taken under the lock
                e["flows"] = list(ent.get("flows") or [])
                at = e.get("at")
                e["age-s"] = (now - at) if at is not None else None
                e["stale"] = (e["age-s"] is None
                              or (not e.get("ok")
                                  and e["age-s"] > self.stale_after_s))
                out[name] = e
            return out

    def _ensure_scraped(self) -> None:
        """Queries keep the surface answering without the periodic
        loop: a never-scraped relay (or a first query racing the
        first tick) runs one synchronous sweep, and with the loop
        DISABLED (interval 0) a query re-sweeps whenever the
        freshest snapshot is older than ``ON_DEMAND_MAX_AGE_S`` —
        otherwise merged views would freeze at the first query's
        snapshot and go permanently empty past the staleness
        bound while scrape_ok still read 1."""
        with self._lock:
            if self._cache:
                if self._thread is not None:
                    return  # the periodic loop owns freshness
                now = time.monotonic()
                ages = [now - e["at"] for e in self._cache.values()
                        if e.get("at") is not None]
                if ages and min(ages) <= ON_DEMAND_MAX_AGE_S:
                    return
        self.scrape_now()

    # -- merged views --------------------------------------------------
    def cluster_metrics(self) -> str:
        # thread-affinity: api, cli
        """``GET /cluster/metrics``: one exposition, every series
        node-labelled, relay meta-series appended."""
        self._ensure_scraped()
        cache = self._fresh_cache()
        texts = {name: e["metrics-text"] for name, e in cache.items()
                 if not e["stale"] and e.get("metrics-text")}
        lines = merge_expositions(texts)
        # node+plugin-labeled L7 parse latency (PR 16 residue c):
        # the per-node registries already render an L7 family, but
        # summed across plugins — operators comparing one plugin's
        # tail across nodes need the plugin label preserved
        l7_lines: List[str] = []
        for name, e in sorted(cache.items()):
            if e["stale"]:
                continue
            esc = escape_label_value(name)
            for plugin, snap in sorted(
                    (e.get("l7-by-plugin") or {}).items()):
                pesc = escape_label_value(str(plugin))
                for stat in ("p50", "p95", "p99", "max", "count"):
                    v = snap.get(stat)
                    if v is None:
                        continue
                    l7_lines.append(
                        f'cilium_cluster_l7_parse_latency_us{{'
                        f'node="{esc}",plugin="{pesc}",'
                        f'stat="{stat}"}} {v}')
        if l7_lines:
            lines.append("# HELP cilium_cluster_l7_parse_latency_us "
                         "per-plugin L7 parse+verdict latency by "
                         "node (µs percentiles)")
            lines.append("# TYPE cilium_cluster_l7_parse_latency_us "
                         "gauge")
            lines.extend(l7_lines)
        # relay meta-series: the scrape plane's own observability
        lines.append("# HELP cilium_cluster_node_scrape_ok last "
                     "relay scrape of this node succeeded")
        lines.append("# TYPE cilium_cluster_node_scrape_ok gauge")
        for name, e in sorted(cache.items()):
            esc = escape_label_value(name)
            lines.append(f'cilium_cluster_node_scrape_ok{{'
                         f'node="{esc}"}} '
                         f'{1 if e.get("ok") else 0}')
        lines.append("# HELP cilium_cluster_node_scrape_age_seconds "
                     "age of the node's last successful scrape")
        lines.append("# TYPE cilium_cluster_node_scrape_age_seconds "
                     "gauge")
        for name, e in sorted(cache.items()):
            if e.get("age-s") is None:
                continue
            esc = escape_label_value(name)
            lines.append(f'cilium_cluster_node_scrape_age_seconds{{'
                         f'node="{esc}"}} {round(e["age-s"], 3)}')
        with self._lock:
            total = self.scrapes_total
            errors = self.scrape_errors
            rtt = self.rtt
        lines.append("# HELP cilium_cluster_scrapes_total successful "
                     "per-node relay scrapes")
        lines.append("# TYPE cilium_cluster_scrapes_total counter")
        lines.append(f"cilium_cluster_scrapes_total {total}")
        lines.append("# HELP cilium_cluster_scrape_errors_total "
                     "failed per-node relay scrapes")
        lines.append("# TYPE cilium_cluster_scrape_errors_total "
                     "counter")
        lines.append(f"cilium_cluster_scrape_errors_total {errors}")
        _render_hist_lines("cilium_cluster_scrape_rtt_us", rtt,
                           lines)
        return "\n".join(lines) + "\n"

    def cluster_flows(self, number: int = 100,
                      oldest_first: bool = False) -> List[dict]:
        # thread-affinity: api, cli
        """Merged time-ordered flows (each dict stamped
        ``node_name``) — the hubble-relay GetFlows shape over the
        relay's since-cursor buffers."""
        self._ensure_scraped()
        cache = self._fresh_cache()
        merged: List[dict] = []
        for name, e in cache.items():
            if not e["stale"]:
                merged.extend(e.get("flows") or [])
        merged.sort(key=lambda d: d.get("time", 0.0))
        merged = merged[-number:] if number else merged
        if not oldest_first:
            merged = merged[::-1]
        return merged

    def cluster_top(self, top: int = 16) -> dict:
        # thread-affinity: api, cli
        """Analytics top-K merged across nodes.  Space-saving
        sketches are mergeable: per-key counts SUM and per-key error
        bounds sum too (the union's overcount is at most the sum of
        the parts' — the PR 6 merge bound, applied across nodes)."""
        self._ensure_scraped()
        cache = self._fresh_cache()
        talkers: Dict[tuple, dict] = {}
        pairs: Dict[tuple, dict] = {}
        per_node: Dict[str, dict] = {}
        error_bound = 0
        enabled = False
        for name, e in sorted(cache.items()):
            t = e.get("top")
            per_node[name] = {
                "ok": bool(e.get("ok")), "stale": e["stale"],
                "age-s": (round(e["age-s"], 3)
                          if e.get("age-s") is not None else None),
                "windows-closed": (t or {}).get("windows-closed"),
                "spike": ((t or {}).get("spike") or {}).get(
                    "in-spike"),
            }
            if e["stale"] or not t:
                continue
            enabled = enabled or bool(t.get("enabled"))
            error_bound += int(t.get("sketch-error-bound") or 0)
            for row in t.get("top-talkers") or []:
                key = (row["src"], row["sport"], row["dst"],
                       row["dport"], row["proto"])
                ent = talkers.setdefault(key, dict(
                    row, packets=0, bytes=0, error=0, nodes=[]))
                ent["packets"] += int(row["packets"])
                ent["bytes"] += int(row["bytes"])
                ent["error"] += int(row["error"])
                ent["nodes"].append(name)
            for row in t.get("top-identity-pairs") or []:
                key = (row["src-identity"], row["dst-identity"])
                ent = pairs.setdefault(key, dict(
                    row, packets=0, bytes=0, error=0, nodes=[]))
                ent["packets"] += int(row["packets"])
                ent["bytes"] += int(row["bytes"])
                ent["error"] += int(row["error"])
                ent["nodes"].append(name)
        rank = sorted(talkers.values(), key=lambda r: -r["packets"])
        prank = sorted(pairs.values(), key=lambda r: -r["packets"])
        return {
            "enabled": enabled,
            "nodes": per_node,
            "top-talkers": rank[:top],
            "top-identity-pairs": prank[:top],
            "sketch-error-bound": error_bound,
        }

    def cluster_trace(self, limit: int = 32) -> dict:
        # thread-affinity: api, cli
        """Stitched cross-process spans (when the router samples
        them) + each node's own tracer summary from the scrape."""
        self._ensure_scraped()
        cache = self._fresh_cache()
        out: dict = {
            "stitched": (self.span_store.snapshot_spans(limit)
                         if self.span_store is not None else None),
            "nodes": {},
        }
        for name, e in sorted(cache.items()):
            tr = e.get("trace")
            if tr is not None and not e["stale"]:
                out["nodes"][name] = {
                    k: tr.get(k)
                    for k in ("sample", "started", "completed",
                              "dropped")}
        return out

    def cluster_slo(self) -> dict:
        # thread-affinity: api, cli
        """``GET /cluster/slo``: ONE cluster health verdict, merged
        worst-of over every node's SLO verdict with each node's
        contribution labeled.  Per-node pulls are contained exactly
        like ``_sweep``: a dead/wedged worker is COUNTED (its node
        entry degrades to no-data with the error string), never
        skipped — a SIGKILLed worker must move the cluster verdict,
        not silently shrink the denominator.  Last-known-good
        verdicts serve under the PR 14 staleness rules (the age
        bound applies only to FAILED nodes; a node whose last pull
        succeeded serves however old, with age-s saying how old)."""
        now = time.monotonic()
        for node in list(self._peers_fn()):
            name = node.name
            snap, err = None, None
            if not getattr(node, "alive", True):
                err = "node dead"
            else:
                try:
                    snap = node.slo()
                except Exception as e:  # noqa: BLE001 — contained,
                    # like _sweep: the verdict merge below turns the
                    # failure into a node-labeled degradation
                    err = f"{type(e).__name__}: {e}"
            with self._lock:
                if snap is not None:
                    self._slo_cache[name] = {
                        "snap": snap, "at": time.monotonic(),
                        "ok": True, "error": None}
                else:
                    ent = self._slo_cache.setdefault(
                        name, {"snap": None, "at": None})
                    ent["ok"] = False
                    ent["error"] = err
        with self._lock:
            cache = {name: dict(e)
                     for name, e in self._slo_cache.items()}
        worst = STATE_OK
        nodes: Dict[str, dict] = {}
        unreachable: List[str] = []
        for name, ent in sorted(cache.items()):
            at = ent.get("at")
            age = (now - at) if at is not None else None
            stale = (age is None
                     or (not ent.get("ok")
                         and age > self.stale_after_s))
            snap = ent.get("snap")
            out = {"ok": bool(ent.get("ok")), "stale": stale,
                   "age-s": (round(age, 3) if age is not None
                             else None)}
            if ent.get("error"):
                out["error"] = ent["error"]
            if stale or snap is None:
                out["verdict"] = STATE_NO_DATA
            else:
                out["verdict"] = str(snap.get("verdict",
                                              STATE_NO_DATA))
                out["slos"] = {
                    sname: ev.get("state")
                    for sname, ev in (snap.get("slos") or {}).items()}
                out["active"] = sorted(snap.get("active") or {})
            if not ent.get("ok"):
                unreachable.append(name)
            if (STATE_CODES.get(out["verdict"], 0)
                    > STATE_CODES.get(worst, 0)):
                worst = out["verdict"]
            nodes[name] = out
        return {"verdict": worst,
                "nodes": nodes,
                "node-count": len(nodes),
                "unreachable": unreachable}

    def scrape_counts(self) -> "Tuple[int, int]":
        # thread-affinity: any
        """(scrapes_total, scrape_errors) under the lock — the cheap
        read the parent registry's cluster scrape-health SLO
        denominators use (``stats()`` copies every node's flow
        buffer; a 10 s sampler should not)."""
        with self._lock:
            return self.scrapes_total, self.scrape_errors

    def stats(self) -> dict:
        # thread-affinity: any
        cache = self._fresh_cache()
        with self._lock:
            out = {
                "interval-s": self.interval_s,
                "effective-interval-s": round(self._delay, 3),
                "duty": self.duty,
                "stale-after-s": self.stale_after_s,
                "scrapes": self.scrapes_total,
                "scrape-errors": self.scrape_errors,
                "rtt-us": {"p50": self.rtt.percentile(0.50),
                           "p95": self.rtt.percentile(0.95),
                           "p99": self.rtt.percentile(0.99),
                           "count": self.rtt.count},
            }
        out["nodes"] = {
            name: {"ok": bool(e.get("ok")), "stale": e["stale"],
                   "age-s": (round(e["age-s"], 3)
                             if e.get("age-s") is not None
                             else None),
                   "flows-buffered": len(e.get("flows") or []),
                   **({"error": e["error"]} if e.get("error")
                      else {})}
            for name, e in sorted(cache.items())}
        if self.span_store is not None:
            out["spans"] = self.span_store.span_stats()
        return out

    # -- cluster sysdump -----------------------------------------------
    def cluster_sysdump(self, out_dir: str) -> dict:
        # thread-affinity: api, cli, capture
        """Pull every node's flight-recorder bundle + the parent's
        cluster-level bundle into ONE tar archive with a manifest
        (the ``cilium-sysdump`` shape).  Per-node collection is
        contained: a dead/wedged worker becomes a manifest entry
        with its error, never a failed archive."""
        nodes: Dict[str, dict] = {}
        bundles: Dict[str, dict] = {}
        for node in list(self._peers_fn()):
            name = node.name
            if not getattr(node, "alive", True):
                nodes[name] = {"ok": False, "error": "node dead"}
                continue
            try:
                bundle = node.sysdump_bundle()
                bundles[name] = bundle
                nodes[name] = {"ok": True,
                               "trigger": bundle.get("trigger"),
                               "taken-at": bundle.get("taken-at")}
            except Exception as e:  # noqa: BLE001 — contained per
                # node; the manifest records why
                nodes[name] = {"ok": False,
                               "error": f"{type(e).__name__}: {e}"}
        parent: dict = {"taken-at": time.time()}
        if self._parent_collect is not None:
            try:
                parent.update(self._parent_collect() or {})
            except Exception as e:  # noqa: BLE001
                parent["error"] = f"{type(e).__name__}: {e}"
        manifest = {
            "schema": CLUSTER_SYSDUMP_SCHEMA,
            "taken-at": time.time(),
            "nodes": nodes,
            "relay": self.stats(),
        }
        name = (f"cluster-sysdump-"
                f"{time.strftime('%Y%m%d-%H%M%S')}.tar")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, name)
        tmp = path + ".tmp"

        def add(tar: tarfile.TarFile, arcname: str, obj) -> int:
            body = json.dumps(obj, indent=1, default=str).encode()
            info = tarfile.TarInfo(arcname)
            info.size = len(body)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(body))
            return len(body)

        with tarfile.open(tmp, "w") as tar:
            for node_name, bundle in bundles.items():
                nodes[node_name]["bytes"] = add(
                    tar, f"nodes/{node_name}.json", bundle)
            add(tar, "parent.json", parent)
            add(tar, "manifest.json", manifest)
        os.replace(tmp, path)
        return {"path": path, "manifest": manifest}
