"""Compile-event introspection for the serving hot path.

Every distinct batch shape on the serving path is one XLA compile,
and the whole serving design (power-of-two bucket ladder, packed vs
wide formats, per-(rung, mode) sharded steps) exists to BOUND that
set.  PR 2 proved the invariant in tests by jit-cache inspection —
and promptly caught the ``P(axis)`` vs ``P(axis, None)``
sharding-spelling retrace.  This module makes the same check a
RUNTIME surface: the loader reports its jit-cache size around every
serving dispatch, a growth is recorded as a compile event (shape,
mode, wall time — the wall time of the dispatch that paid the
trace), and a SECOND compile for an already-seen ``(mode, shape)``
key is an invariant VIOLATION: counted, logged, and surfaced through
``serving stats`` / ``GET /metrics`` so a recompile storm shows up
where operators look instead of only as mysteriously lost
throughput.

Cost when nothing compiles: two ``_cache_size()`` reads (dict-len
lookups on the jitted callables) per dispatch — noise against a
device dispatch.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

MAX_EVENTS = 256


class CompileLog:
    """Bounded log of serving-path compile events + the
    one-executable-per-(mode, shape) invariant.

    ``mode`` is the dispatch flavor ("wide" | "packed" | "sharded" |
    "sharded-packed"); the daemon maps it onto the degraded-mode
    ladder rung (wide -> wide, packed -> single, sharded-* ->
    sharded) when surfacing."""

    def __init__(self, capacity: int = MAX_EVENTS):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # guarded-by: _lock: _events, executables, compiles, violations
        self._events: List[dict] = []
        # (mode, shape) -> compile count; >1 is a violation
        self.executables: Dict[Tuple[str, tuple], int] = {}
        self.compiles = 0
        self.violations = 0

    def record_dispatch(self, mode: str, shape: tuple,
                        cache_before: int, cache_after: int,
                        elapsed_s: float,
                        key_extra: tuple = ()) -> None:
        # thread-affinity: any
        """Called by the loader after a serving dispatch with the
        jit-cache sizes sampled around it.  No growth = no event.
        ``key_extra`` extends the dedup key with everything that
        LEGITIMATELY selects a distinct executable beyond (mode,
        shape) — ring capacity, static args, the attach generation —
        so only a same-key regrowth counts as a violation."""
        if cache_after <= cache_before:
            return
        key = (str(mode), tuple(int(d) for d in shape)
               + tuple(key_extra))
        with self._lock:
            seen = self.executables.get(key, 0)
            self.executables[key] = seen + 1
            self.compiles += cache_after - cache_before
            duplicate = seen > 0
            if duplicate:
                self.violations += 1
            ev = {
                "t": time.time(),
                "mode": key[0],
                "shape": [int(d) for d in shape],
                "key": list(key[1]),
                "compile-ms": round(elapsed_s * 1e3, 3),
                "cache-size": cache_after,
                "duplicate": duplicate,
            }
            self._events.append(ev)
            if len(self._events) > self.capacity:
                del self._events[:len(self._events) - self.capacity]
        if duplicate:
            # hot-path-ok: fires only on a one-executable-per-(rung,
            # mode) invariant VIOLATION — the warning is the surface
            # the recompile storm is reported on, never steady state
            logging.getLogger(__name__).warning(
                "serving recompile VIOLATION: a second executable "
                "compiled for mode=%s shape=%s (one-executable-per-"
                "(rung, mode) invariant; sharding-spec spelling or a "
                "leaked non-ladder shape are the usual causes)",
                key[0], key[1])

    def snapshot(self, limit: int = 32) -> dict:
        # thread-affinity: any
        with self._lock:
            return {
                "compiles": self.compiles,
                "executables": len(self.executables),
                "violations": self.violations,
                "by-key": [
                    {"mode": m, "shape": list(s), "compiles": c}
                    for (m, s), c in sorted(self.executables.items())],
                "events": list(self._events[-limit:]),
            }

    def summary(self) -> dict:
        # thread-affinity: any
        """The compact form riding ``serving_stats()``."""
        with self._lock:
            return {
                "compiles": self.compiles,
                "executables": len(self.executables),
                "violations": self.violations,
            }

    def dispatch_summary(self) -> dict:
        # thread-affinity: any
        """Dispatch-executable compiles only (the event plane's
        "gather" rung ladder excluded) + violations — the cluster
        tier's zero-survivor-recompile oracle, shared by the
        in-process node handle and the worker-side RPC op so the
        two modes can never skew against each other."""
        with self._lock:
            dispatch = sum(c for (m, _s), c
                           in self.executables.items()
                           if m != "gather")
            return {"dispatch_compiles": int(dispatch),
                    "violations": int(self.violations)}
