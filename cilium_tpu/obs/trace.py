"""Sampled per-packet trace spans through the serving pipeline.

Dapper-shaped (Sigelman et al., 2010): a trace context is allocated
at admission for 1-in-N packets and carried THROUGH the hot path —
never looked up — so the cost when sampling is off is a single
``is not None`` branch per chunk, and when on it is O(sampled
packets), not O(packets).

The seven stage timestamps (``SPAN_STAGES``):

================  ===================================================
``admit``         the packet's chunk was admitted by ``IngressQueue``
``dequeue``       ``take_into`` memcpy'd its row out of the queue
``staged``        the batcher finished arena staging/packing+masking
``dispatch``      the drain loop handed the batch to the device leg
``dispatch-ret``  the (async) dispatch call returned
``device``        the batch's drain window was fetched — device work
                  provably complete (stamped by the event-join
                  worker; under-reported as the dispatch return
                  before the async event plane existed)
``join``          the batch's events were emitted to the monitor
                  plane
================  ===================================================

Timestamps are ``time.monotonic`` so consecutive stamps are
monotonic by construction and the six stage intervals telescope to
exactly the end-to-end latency — the property the determinism tests
assert.  Without an event-join worker (a bare ServingRuntime), the
``device``/``join`` stamps fall back to the completion boundary the
latency histogram uses, so the telescoping property holds on every
path.

Sampling is DETERMINISTIC over the admitted-packet sequence: packet
``seq`` is sampled iff ``(seq + seed) % sample == 0``, so the same
seed + the same packet stream yields the identical sampled-trace
set (the replayable-chaos property the fault-injection plane already
has, applied to tracing).

Completed spans land in a fixed-size ring (newest wins — the
wrap-overwrite discipline every other ring in this codebase uses);
per-stage log2 histograms aggregate across ALL completed spans so
the breakdown survives ring wrap.  Spans that die mid-pipeline
(shed by drop-oldest, swept by recovery, lost to a dead dispatch)
are counted, never silently vanished — the no-silent-loss contract
the serving ledger has, applied to its own instrumentation.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..serving.stats import LatencyHistogram

SPAN_STAGES = ("admit", "dequeue", "staged", "dispatch",
               "dispatch-ret", "device", "join")
N_STAGES = len(SPAN_STAGES)
# indices into TraceSpan.ts
STAGE_ADMIT, STAGE_DEQUEUE, STAGE_STAGED, STAGE_DISPATCH, \
    STAGE_DISPATCH_RET, STAGE_DEVICE, STAGE_JOIN = range(N_STAGES)

DEFAULT_SPAN_RING = 512


def validate_obs_config(trace_sample, profile_dir,
                        profile_batches) -> tuple:
    """Validate the observability DaemonConfig knobs; returns the
    normalized ``(trace_sample, profile_dir, profile_batches)``.
    Same contract as ``validate_serving_config``: a bad knob fails at
    daemon construction, not as tracing that silently never fires."""
    sample = int(trace_sample)
    if sample < 0:
        raise ValueError("serving_trace_sample must be >= 0 "
                         "(0 disables span tracing)")
    batches = int(profile_batches)
    if batches < 1:
        raise ValueError("profile_batches must be >= 1 "
                         "(the capture window traces N batches)")
    if profile_dir is not None and not str(profile_dir):
        profile_dir = None
    return sample, profile_dir, batches


class TraceSpan:
    """One sampled packet's trip through the pipeline.  Mutated only
    by the thread currently holding the packet (producer at admit,
    drain thread thereafter) — no lock needed until the final commit
    into the tracer ring."""

    __slots__ = ("trace_id", "seq", "ts", "bucket", "n_valid",
                 "batch_pos", "batch_id", "mode", "shard", "demoted",
                 "done")

    def __init__(self, trace_id: int, seq: int):
        self.trace_id = trace_id
        self.seq = seq  # admitted-packet sequence number
        self.ts: List[float] = [0.0] * N_STAGES
        self.bucket = 0  # padded batch size
        self.n_valid = 0
        self.batch_pos = -1  # row index within the bucket
        self.batch_id = -1  # serving seq (ring batch field)
        self.mode = ""  # dispatch mode ("wide"|"packed"|"sharded-*")
        self.shard = -1  # owning shard (sharded dispatch only)
        self.demoted = False  # dispatch crossed a ladder demotion
        self.done = False

    # -- derived reads -------------------------------------------------
    def stage_us(self) -> Dict[str, float]:
        """The five stage intervals in microseconds (telescoping:
        their sum IS the end-to-end latency)."""
        return {
            f"{SPAN_STAGES[i]}->{SPAN_STAGES[i + 1]}":
                (self.ts[i + 1] - self.ts[i]) * 1e6
            for i in range(N_STAGES - 1)
        }

    def e2e_us(self) -> float:
        return (self.ts[STAGE_JOIN] - self.ts[STAGE_ADMIT]) * 1e6

    def monotonic(self) -> bool:
        return all(self.ts[i + 1] >= self.ts[i]
                   for i in range(N_STAGES - 1))

    def to_dict(self) -> dict:
        return {
            "trace-id": self.trace_id,
            "seq": self.seq,
            "timestamps": list(self.ts),
            "stages-us": {k: round(v, 3)
                          for k, v in self.stage_us().items()},
            "e2e-us": round(self.e2e_us(), 3),
            "monotonic": self.monotonic(),
            "bucket": self.bucket,
            "n-valid": self.n_valid,
            "batch-pos": self.batch_pos,
            "batch-id": self.batch_id,
            "mode": self.mode,
            "shard": self.shard,
            "demoted": self.demoted,
        }


class SpanTracer:
    """The per-session span plane: deterministic 1-in-N admission
    sampling, a fixed-size completed-span ring, per-stage aggregate
    histograms, and exact loss accounting for spans that die
    mid-pipeline.

    Thread model: :meth:`sample_chunk` runs under the IngressQueue
    lock (the admitted-seq counter needs no lock of its own); stage
    stamping happens on whichever single thread owns the packet at
    that stage; :meth:`commit` / :meth:`evict` / :meth:`snapshot`
    take the tracer lock (commit is O(1): one ring write + six
    histogram records, far off the per-packet path)."""

    def __init__(self, sample: int, seed: int = 0,
                 capacity: int = DEFAULT_SPAN_RING):
        sample = int(sample)  # coerce FIRST: int(0.5) == 0 must be
        if sample <= 0:  # rejected here, not as a ZeroDivisionError
            raise ValueError("SpanTracer wants sample >= 1; use "
                             "tracer=None for disabled tracing")
        self.sample = sample
        self.seed = int(seed)
        self.capacity = int(capacity)
        self._ring: List[Optional[TraceSpan]] = [None] * self.capacity
        self._w = 0  # total committed (ring cursor)
        self._lock = threading.Lock()
        # guarded-by: _lock: _ring, _w, completed, dropped
        # (started/_seq/_next_id are guarded EXTERNALLY by the
        # IngressQueue lock — sample_chunk's documented contract —
        # which a per-class lexical checker cannot see)
        self._seq = 0  # admitted packets seen (queue-lock guarded)
        self._next_id = 0
        self.started = 0
        self.completed = 0
        self.dropped = 0  # spans evicted mid-pipeline (shed/lost)
        self.stage_hist = [LatencyHistogram() for _ in
                           range(N_STAGES - 1)]
        self.e2e_hist = LatencyHistogram()

    # -- admission side (under the IngressQueue lock) ------------------
    def sample_chunk(self, n: int,
                     t: float) -> List[Tuple[int, TraceSpan]]:
        # thread-affinity: any
        """Advance the admitted-seq counter by ``n`` and allocate
        spans for the sampled offsets; returns ``[(offset_in_chunk,
        span)]`` (usually empty).  ``t`` is the chunk's arrival
        stamp — the same clock the queue-wait histogram uses."""
        base = self._seq
        self._seq += n
        # first offset with (base + off + seed) % sample == 0
        first = (-(base + self.seed)) % self.sample
        if first >= n:
            return []
        out = []
        for off in range(first, n, self.sample):
            sp = TraceSpan(self._next_id, base + off)
            self._next_id += 1
            sp.ts[STAGE_ADMIT] = t
            out.append((off, sp))
        self.started += len(out)
        return out

    # -- pipeline side -------------------------------------------------
    def commit(self, span: TraceSpan) -> None:
        # thread-affinity: any
        """A span reached the join boundary with all six stamps."""
        if span.done:
            return
        span.done = True
        with self._lock:
            self._ring[self._w % self.capacity] = span
            self._w += 1
            self.completed += 1
            for i in range(N_STAGES - 1):
                self.stage_hist[i].record(
                    (span.ts[i + 1] - span.ts[i]) * 1e6)
            self.e2e_hist.record(span.e2e_us())

    def evict(self, spans) -> None:
        # thread-affinity: any
        """Spans whose packet died mid-pipeline (admission shed,
        recovery drop, lost batch): counted, never completed."""
        n = 0
        for sp in spans:
            if not sp.done:
                sp.done = True
                n += 1
        if n:
            with self._lock:
                self.dropped += n

    # -- reading (API threads) -----------------------------------------
    def stats(self) -> dict:
        # thread-affinity: any
        """The compact summary riding ``serving_stats()``."""
        with self._lock:
            return {
                "sample": self.sample,
                "started": self.started,
                "completed": self.completed,
                "dropped": self.dropped,
                "ring-capacity": self.capacity,
                "ring-held": min(self._w, self.capacity),
            }

    def snapshot(self, limit: int = 64) -> dict:
        """``GET /debug/traces``: summary + per-stage aggregate
        histograms + the most recent completed spans + the
        slowest-trace table (over the spans the ring still holds)."""
        with self._lock:
            held = min(self._w, self.capacity)
            # newest first
            spans = [self._ring[(self._w - 1 - i) % self.capacity]
                     for i in range(held)]
            out = {
                "sample": self.sample,
                "seed": self.seed,
                "started": self.started,
                "completed": self.completed,
                "dropped": self.dropped,
                "stages-us": {
                    f"{SPAN_STAGES[i]}->{SPAN_STAGES[i + 1]}":
                        self.stage_hist[i].snapshot()
                    for i in range(N_STAGES - 1)},
                "e2e-us": self.e2e_hist.snapshot(),
            }
        out["traces"] = [sp.to_dict() for sp in spans[:limit]
                         if sp is not None]
        slowest = sorted((sp for sp in spans if sp is not None),
                         key=lambda s: s.e2e_us(), reverse=True)
        out["slowest"] = [sp.to_dict() for sp in slowest[:limit]]
        return out
