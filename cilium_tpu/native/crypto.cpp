// Transparent-encryption primitives (the WireGuard-analogue crypto).
//
// Reference: upstream cilium's --enable-wireguard encrypts node-to-node
// pod traffic through the kernel's wireguard device (Curve25519 key
// exchange + ChaCha20-Poly1305 AEAD, per packet).  Here the same
// primitives run in the framework's own native layer — RFC 7748 X25519
// and RFC 8439 ChaCha20-Poly1305 — and seal whole BATCH buffers at the
// node boundary (one AEAD per batch, not per packet; see
// cilium_tpu/encryption).  No third-party code: both primitives are
// implemented from their RFCs and validated against the RFC test
// vectors (tests/test_encryption.py).
//
// Build: g++ -O3 -shared -fPIC (driven by cilium_tpu/native/crypto.py,
// content-addressed like ingest.cpp).

#include <cstdint>
#include <cstring>

typedef uint8_t u8;
typedef uint32_t u32;
typedef uint64_t u64;
typedef unsigned __int128 u128;

// ---------------------------------------------------------------------------
// X25519 — RFC 7748.  GF(2^255-19) as 5 x 51-bit limbs.

struct fe { u64 v[5]; };

static const u64 MASK51 = 0x7FFFFFFFFFFFFULL;

static void fe_copy(fe &o, const fe &a) { o = a; }

static void fe_add(fe &o, const fe &a, const fe &b) {
    for (int i = 0; i < 5; i++) o.v[i] = a.v[i] + b.v[i];
}

// o = a - b + 8p (bias keeps limbs positive; inputs < 2^52)
static void fe_sub(fe &o, const fe &a, const fe &b) {
    static const u64 B0 = 0x3FFFFFFFFFFF68ULL;  // 8 * (2^51 - 19)
    static const u64 BI = 0x3FFFFFFFFFFFF8ULL;  // 8 * (2^51 - 1)
    o.v[0] = a.v[0] + B0 - b.v[0];
    for (int i = 1; i < 5; i++) o.v[i] = a.v[i] + BI - b.v[i];
}

static void fe_carry(fe &o) {
    u64 c;
    c = o.v[0] >> 51; o.v[0] &= MASK51; o.v[1] += c;
    c = o.v[1] >> 51; o.v[1] &= MASK51; o.v[2] += c;
    c = o.v[2] >> 51; o.v[2] &= MASK51; o.v[3] += c;
    c = o.v[3] >> 51; o.v[3] &= MASK51; o.v[4] += c;
    c = o.v[4] >> 51; o.v[4] &= MASK51; o.v[0] += 19 * c;
    c = o.v[0] >> 51; o.v[0] &= MASK51; o.v[1] += c;
}

static void fe_mul(fe &o, const fe &a, const fe &b) {
    u128 t0 = (u128)a.v[0] * b.v[0]
            + (u128)(19 * a.v[1]) * b.v[4] + (u128)(19 * a.v[2]) * b.v[3]
            + (u128)(19 * a.v[3]) * b.v[2] + (u128)(19 * a.v[4]) * b.v[1];
    u128 t1 = (u128)a.v[0] * b.v[1] + (u128)a.v[1] * b.v[0]
            + (u128)(19 * a.v[2]) * b.v[4] + (u128)(19 * a.v[3]) * b.v[3]
            + (u128)(19 * a.v[4]) * b.v[2];
    u128 t2 = (u128)a.v[0] * b.v[2] + (u128)a.v[1] * b.v[1]
            + (u128)a.v[2] * b.v[0]
            + (u128)(19 * a.v[3]) * b.v[4] + (u128)(19 * a.v[4]) * b.v[3];
    u128 t3 = (u128)a.v[0] * b.v[3] + (u128)a.v[1] * b.v[2]
            + (u128)a.v[2] * b.v[1] + (u128)a.v[3] * b.v[0]
            + (u128)(19 * a.v[4]) * b.v[4];
    u128 t4 = (u128)a.v[0] * b.v[4] + (u128)a.v[1] * b.v[3]
            + (u128)a.v[2] * b.v[2] + (u128)a.v[3] * b.v[1]
            + (u128)a.v[4] * b.v[0];
    u64 c;
    c = (u64)(t0 >> 51); o.v[0] = (u64)t0 & MASK51; t1 += c;
    c = (u64)(t1 >> 51); o.v[1] = (u64)t1 & MASK51; t2 += c;
    c = (u64)(t2 >> 51); o.v[2] = (u64)t2 & MASK51; t3 += c;
    c = (u64)(t3 >> 51); o.v[3] = (u64)t3 & MASK51; t4 += c;
    c = (u64)(t4 >> 51); o.v[4] = (u64)t4 & MASK51;
    o.v[0] += 19 * c;
    c = o.v[0] >> 51; o.v[0] &= MASK51; o.v[1] += c;
}

static void fe_sq(fe &o, const fe &a) { fe_mul(o, a, a); }

static void fe_mul121665(fe &o, const fe &a) {
    u128 t;
    u64 c = 0;
    for (int i = 0; i < 5; i++) {
        t = (u128)a.v[i] * 121665 + c;
        o.v[i] = (u64)t & MASK51;
        c = (u64)(t >> 51);
    }
    o.v[0] += 19 * c;
    c = o.v[0] >> 51; o.v[0] &= MASK51; o.v[1] += c;
}

// o = z^(p-2) (inversion): p-2 = 2^255 - 21 = 250 ones then 01011
static void fe_invert(fe &o, const fe &z) {
    fe r;
    fe_copy(r, z);
    for (int i = 1; i < 250; i++) { fe_sq(r, r); fe_mul(r, r, z); }
    fe_sq(r, r);                    // bit 0
    fe_sq(r, r); fe_mul(r, r, z);   // bit 1
    fe_sq(r, r);                    // bit 0
    fe_sq(r, r); fe_mul(r, r, z);   // bit 1
    fe_sq(r, r); fe_mul(r, r, z);   // bit 1
    fe_copy(o, r);
}

static void fe_frombytes(fe &o, const u8 s[32]) {
    u64 w[4];
    memcpy(w, s, 32);
    o.v[0] = w[0] & MASK51;
    o.v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
    o.v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
    o.v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
    o.v[4] = (w[3] >> 12) & MASK51;  // masks the top bit (RFC 7748)
}

static void fe_tobytes(u8 s[32], const fe &a) {
    fe t = a;
    fe_carry(t);
    fe_carry(t);
    // q = 1 iff t >= p  (computed as whether t + 19 overflows 2^255)
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;  // drop the 2^255 carry (== subtracting p+19q)
    u64 w[4];
    w[0] = t.v[0] | (t.v[1] << 51);
    w[1] = (t.v[1] >> 13) | (t.v[2] << 38);
    w[2] = (t.v[2] >> 26) | (t.v[3] << 25);
    w[3] = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, w, 32);
}

static void fe_cswap(fe &a, fe &b, u64 swap) {
    u64 m = (u64)0 - swap;
    for (int i = 0; i < 5; i++) {
        u64 x = m & (a.v[i] ^ b.v[i]);
        a.v[i] ^= x;
        b.v[i] ^= x;
    }
}

extern "C" int x25519(u8 out[32], const u8 scalar[32],
                      const u8 point[32]) {
    u8 k[32];
    memcpy(k, scalar, 32);
    k[0] &= 248; k[31] &= 127; k[31] |= 64;  // clamp
    fe x1, x2, z2, x3, z3, a, aa, b, bb, e, c, d, da, cb, t;
    fe_frombytes(x1, point);
    memset(&x2, 0, sizeof x2); x2.v[0] = 1;
    memset(&z2, 0, sizeof z2);
    fe_copy(x3, x1);
    memset(&z3, 0, sizeof z3); z3.v[0] = 1;
    u64 swap = 0;
    for (int t_ = 254; t_ >= 0; t_--) {
        u64 kt = (k[t_ >> 3] >> (t_ & 7)) & 1;
        swap ^= kt;
        fe_cswap(x2, x3, swap);
        fe_cswap(z2, z3, swap);
        swap = kt;
        fe_add(a, x2, z2);  fe_carry(a);
        fe_sq(aa, a);
        fe_sub(b, x2, z2);  fe_carry(b);
        fe_sq(bb, b);
        fe_sub(e, aa, bb);  fe_carry(e);
        fe_add(c, x3, z3);  fe_carry(c);
        fe_sub(d, x3, z3);  fe_carry(d);
        fe_mul(da, d, a);
        fe_mul(cb, c, b);
        fe_add(t, da, cb);  fe_carry(t);
        fe_sq(x3, t);
        fe_sub(t, da, cb);  fe_carry(t);
        fe_sq(t, t);
        fe_mul(z3, x1, t);
        fe_mul(x2, aa, bb);
        // z2 = E * (AA + a24*E), a24 = 121665 (RFC 7748; the ref10
        // 121666 variant pairs with BB, not AA)
        fe_mul121665(t, e);
        fe_add(t, aa, t);   fe_carry(t);
        fe_mul(z2, e, t);
    }
    fe_cswap(x2, x3, swap);
    fe_cswap(z2, z3, swap);
    fe_invert(z2, z2);
    fe_mul(x2, x2, z2);
    fe_tobytes(out, x2);
    // RFC 7748: an all-zero output means a low-order point
    u8 zero = 0;
    for (int i = 0; i < 32; i++) zero |= out[i];
    return zero ? 0 : -1;
}

extern "C" int x25519_base(u8 out[32], const u8 scalar[32]) {
    u8 base[32] = {9};
    return x25519(out, scalar, base);
}

// ---------------------------------------------------------------------------
// ChaCha20 — RFC 8439 §2.3.

static inline u32 rotl(u32 x, int n) { return (x << n) | (x >> (32 - n)); }

#define QR(a, b, c, d) \
    a += b; d ^= a; d = rotl(d, 16); \
    c += d; b ^= c; b = rotl(b, 12); \
    a += b; d ^= a; d = rotl(d, 8);  \
    c += d; b ^= c; b = rotl(b, 7);

static void chacha_block(u8 out[64], const u32 key[8], u32 counter,
                         const u32 nonce[3]) {
    u32 s[16] = {0x61707865, 0x3320646e, 0x79622d32, 0x6b206574,
                 key[0], key[1], key[2], key[3],
                 key[4], key[5], key[6], key[7],
                 counter, nonce[0], nonce[1], nonce[2]};
    u32 w[16];
    memcpy(w, s, sizeof w);
    for (int i = 0; i < 10; i++) {
        QR(w[0], w[4], w[8],  w[12])
        QR(w[1], w[5], w[9],  w[13])
        QR(w[2], w[6], w[10], w[14])
        QR(w[3], w[7], w[11], w[15])
        QR(w[0], w[5], w[10], w[15])
        QR(w[1], w[6], w[11], w[12])
        QR(w[2], w[7], w[8],  w[13])
        QR(w[3], w[4], w[9],  w[14])
    }
    for (int i = 0; i < 16; i++) {
        u32 v = w[i] + s[i];
        memcpy(out + 4 * i, &v, 4);
    }
}

static void chacha_xor(u8 *data, long len, const u32 key[8],
                       u32 counter, const u32 nonce[3]) {
    u8 block[64];
    long off = 0;
    while (off + 64 <= len) {  // full blocks: 8-byte-wide XOR
        chacha_block(block, key, counter++, nonce);
        u64 d[8], b[8];
        memcpy(d, data + off, 64);
        memcpy(b, block, 64);
        for (int i = 0; i < 8; i++) d[i] ^= b[i];
        memcpy(data + off, d, 64);
        off += 64;
    }
    if (off < len) {
        chacha_block(block, key, counter, nonce);
        for (long i = 0; off + i < len; i++) data[off + i] ^= block[i];
    }
}

// ---------------------------------------------------------------------------
// Poly1305 — RFC 8439 §2.5 (26-bit limbs).

struct poly1305 {
    u32 r[5], h[5], pad[4];
};

static void poly_init(poly1305 &st, const u8 key[32]) {
    u32 t[4];
    memcpy(t, key, 16);
    st.r[0] = t[0] & 0x3ffffff;
    st.r[1] = ((t[0] >> 26) | (t[1] << 6)) & 0x3ffff03;
    st.r[2] = ((t[1] >> 20) | (t[2] << 12)) & 0x3ffc0ff;
    st.r[3] = ((t[2] >> 14) | (t[3] << 18)) & 0x3f03fff;
    st.r[4] = (t[3] >> 8) & 0x00fffff;
    memset(st.h, 0, sizeof st.h);
    memcpy(st.pad, key + 16, 16);
}

static void poly_blocks(poly1305 &st, const u8 *m, long len, u32 hibit) {
    u32 r0 = st.r[0], r1 = st.r[1], r2 = st.r[2], r3 = st.r[3],
        r4 = st.r[4];
    u32 s1 = r1 * 5, s2 = r2 * 5, s3 = r3 * 5, s4 = r4 * 5;
    u32 h0 = st.h[0], h1 = st.h[1], h2 = st.h[2], h3 = st.h[3],
        h4 = st.h[4];
    while (len >= 16) {
        u32 t[4];
        memcpy(t, m, 16);
        h0 += t[0] & 0x3ffffff;
        h1 += ((t[0] >> 26) | ((u64)t[1] << 6)) & 0x3ffffff;
        h2 += ((t[1] >> 20) | ((u64)t[2] << 12)) & 0x3ffffff;
        h3 += ((t[2] >> 14) | ((u64)t[3] << 18)) & 0x3ffffff;
        h4 += (t[3] >> 8) | hibit;
        u64 d0 = (u64)h0 * r0 + (u64)h1 * s4 + (u64)h2 * s3
               + (u64)h3 * s2 + (u64)h4 * s1;
        u64 d1 = (u64)h0 * r1 + (u64)h1 * r0 + (u64)h2 * s4
               + (u64)h3 * s3 + (u64)h4 * s2;
        u64 d2 = (u64)h0 * r2 + (u64)h1 * r1 + (u64)h2 * r0
               + (u64)h3 * s4 + (u64)h4 * s3;
        u64 d3 = (u64)h0 * r3 + (u64)h1 * r2 + (u64)h2 * r1
               + (u64)h3 * r0 + (u64)h4 * s4;
        u64 d4 = (u64)h0 * r4 + (u64)h1 * r3 + (u64)h2 * r2
               + (u64)h3 * r1 + (u64)h4 * r0;
        u64 c;
        c = d0 >> 26; h0 = (u32)d0 & 0x3ffffff; d1 += c;
        c = d1 >> 26; h1 = (u32)d1 & 0x3ffffff; d2 += c;
        c = d2 >> 26; h2 = (u32)d2 & 0x3ffffff; d3 += c;
        c = d3 >> 26; h3 = (u32)d3 & 0x3ffffff; d4 += c;
        c = d4 >> 26; h4 = (u32)d4 & 0x3ffffff;
        h0 += (u32)c * 5;
        c = h0 >> 26; h0 &= 0x3ffffff; h1 += (u32)c;
        m += 16;
        len -= 16;
    }
    st.h[0] = h0; st.h[1] = h1; st.h[2] = h2; st.h[3] = h3; st.h[4] = h4;
}

static void poly_finish(poly1305 &st, u8 mac[16]) {
    u32 h0 = st.h[0], h1 = st.h[1], h2 = st.h[2], h3 = st.h[3],
        h4 = st.h[4];
    u32 c;
    c = h1 >> 26; h1 &= 0x3ffffff; h2 += c;
    c = h2 >> 26; h2 &= 0x3ffffff; h3 += c;
    c = h3 >> 26; h3 &= 0x3ffffff; h4 += c;
    c = h4 >> 26; h4 &= 0x3ffffff; h0 += c * 5;
    c = h0 >> 26; h0 &= 0x3ffffff; h1 += c;
    // compute h + -p
    u32 g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
    u32 g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
    u32 g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
    u32 g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
    u32 g4 = h4 + c - (1u << 26);
    u32 mask = (g4 >> 31) - 1;  // all-ones when h >= p
    h0 = (h0 & ~mask) | (g0 & mask);
    h1 = (h1 & ~mask) | (g1 & mask);
    h2 = (h2 & ~mask) | (g2 & mask);
    h3 = (h3 & ~mask) | (g3 & mask);
    h4 = (h4 & ~mask) | (g4 & mask);
    u64 f;
    u32 out[4];
    f = (u64)(h0 | (h1 << 26)) + st.pad[0];
    out[0] = (u32)f;
    f = (u64)((h1 >> 6) | (h2 << 20)) + st.pad[1] + (f >> 32);
    out[1] = (u32)f;
    f = (u64)((h2 >> 12) | (h3 << 14)) + st.pad[2] + (f >> 32);
    out[2] = (u32)f;
    f = (u64)((h3 >> 18) | (h4 << 8)) + st.pad[3] + (f >> 32);
    out[3] = (u32)f;
    memcpy(mac, out, 16);
}

// ---------------------------------------------------------------------------
// AEAD_CHACHA20_POLY1305 — RFC 8439 §2.8.

// AEAD pads each section (AAD, ciphertext) to 16 with ZEROS — not the
// raw-poly1305 1-marker tail:
static void poly_update_padded(poly1305 &st, const u8 *m, long len) {
    long full = len & ~15L;
    if (full) poly_blocks(st, m, full, 1u << 24);
    if (len & 15) {
        u8 block[16] = {0};
        memcpy(block, m + full, len & 15);
        poly_blocks(st, block, 16, 1u << 24);
    }
}

static void aead_tag(u8 mac[16], const u32 key_words[8],
                     const u32 nonce[3], const u8 *aad, long aad_len,
                     const u8 *ct, long ct_len) {
    u8 polykey[64];
    chacha_block(polykey, key_words, 0, nonce);
    poly1305 st;
    poly_init(st, polykey);
    poly_update_padded(st, aad, aad_len);
    poly_update_padded(st, ct, ct_len);
    u8 lens[16];
    u64 al = (u64)aad_len, cl = (u64)ct_len;
    memcpy(lens, &al, 8);
    memcpy(lens + 8, &cl, 8);
    poly_blocks(st, lens, 16, 1u << 24);
    poly_finish(st, mac);
}

static void load_key(u32 kw[8], const u8 key[32]) { memcpy(kw, key, 32); }

static void load_nonce(u32 nw[3], const u8 nonce[12]) {
    memcpy(nw, nonce, 12);
}

extern "C" long aead_seal(const u8 key[32], const u8 nonce[12],
                          const u8 *aad, long aad_len,
                          const u8 *pt, long pt_len, u8 *out) {
    u32 kw[8], nw[3];
    load_key(kw, key);
    load_nonce(nw, nonce);
    memcpy(out, pt, pt_len);
    chacha_xor(out, pt_len, kw, 1, nw);
    aead_tag(out + pt_len, kw, nw, aad, aad_len, out, pt_len);
    return pt_len + 16;
}

extern "C" long aead_open(const u8 key[32], const u8 nonce[12],
                          const u8 *aad, long aad_len,
                          const u8 *ct, long ct_len, u8 *out) {
    if (ct_len < 16) return -1;
    long pt_len = ct_len - 16;
    u32 kw[8], nw[3];
    load_key(kw, key);
    load_nonce(nw, nonce);
    u8 tag[16];
    aead_tag(tag, kw, nw, aad, aad_len, ct, pt_len);
    u8 diff = 0;
    for (int i = 0; i < 16; i++) diff |= tag[i] ^ ct[pt_len + i];
    if (diff) return -1;
    memcpy(out, ct, pt_len);
    chacha_xor(out, pt_len, kw, 1, nw);
    return pt_len;
}
