"""Native crypto bindings (X25519 + ChaCha20-Poly1305) with a pure
Python fallback.

The native library (``crypto.cpp``) carries the hot path — sealing
node-to-node batch buffers (see ``cilium_tpu/encryption``).  The
Python implementations below exist for compiler-less environments AND
as an independent cross-check: tests assert native == python on random
inputs and both == the RFC 7748 / RFC 8439 vectors.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "crypto.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _so_path() -> str:
    # hot-path-ok: one-time lazy .so fingerprint under _lock — the
    # library handle is cached in _lib after the first load, so the
    # transport's per-frame seal/open never re-enters this
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"_crypto_{digest}.so")


def _compile(so: str) -> bool:
    tmp = so + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _build_failed = True
            return None
        for fn in (lib.x25519, lib.x25519_base):
            fn.restype = ctypes.c_int
        lib.x25519.argtypes = [ctypes.c_char_p] * 3
        lib.x25519_base.argtypes = [ctypes.c_char_p] * 2
        for fn in (lib.aead_seal, lib.aead_open):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_char_p, ctypes.c_long,
                           ctypes.c_char_p, ctypes.c_long,
                           ctypes.c_char_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Pure-Python reference (fallback + cross-check)

_P = 2 ** 255 - 19
_A24 = 121665


def _clamp(k: bytes) -> int:
    a = bytearray(k)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


class LowOrderPointError(ValueError):
    """The peer's point is low-order: the shared secret would be the
    all-zero string, i.e. derivable from PUBLIC data (RFC 7748 §6.1
    mandates rejecting a zero output)."""


def _x25519_py(scalar: bytes, point: bytes) -> bytes:
    k = _clamp(scalar)
    u = int.from_bytes(point, "little") & ((1 << 255) - 1)
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a, b = (x2 + z2) % _P, (x2 - z2) % _P
        aa, bb = a * a % _P, b * b % _P
        e = (aa - bb) % _P
        c, d = (x3 + z3) % _P, (x3 - z3) % _P
        da, cb = d * a % _P, c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * (z3 * z3) % _P
        x2 = aa * bb % _P
        z2 = e * ((aa + _A24 * e) % _P) % _P
    if swap:
        x2, z2 = x3, z3
    out = x2 * pow(z2, _P - 2, _P) % _P
    if out == 0:
        raise LowOrderPointError("x25519: low-order point")
    return out.to_bytes(32, "little")


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _chacha_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    import struct
    s = list(struct.unpack("<4I", b"expa" + b"nd 3" + b"2-by" + b"te k")) \
        + list(struct.unpack("<8I", key)) \
        + [counter] + list(struct.unpack("<3I", nonce))
    w = s[:]

    def qr(a, b, c, d):
        w[a] = (w[a] + w[b]) & 0xFFFFFFFF; w[d] = _rotl(w[d] ^ w[a], 16)
        w[c] = (w[c] + w[d]) & 0xFFFFFFFF; w[b] = _rotl(w[b] ^ w[c], 12)
        w[a] = (w[a] + w[b]) & 0xFFFFFFFF; w[d] = _rotl(w[d] ^ w[a], 8)
        w[c] = (w[c] + w[d]) & 0xFFFFFFFF; w[b] = _rotl(w[b] ^ w[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12); qr(1, 5, 9, 13)
        qr(2, 6, 10, 14); qr(3, 7, 11, 15)
        qr(0, 5, 10, 15); qr(1, 6, 11, 12)
        qr(2, 7, 8, 13); qr(3, 4, 9, 14)
    return struct.pack("<16I", *((w[i] + s[i]) & 0xFFFFFFFF
                                 for i in range(16)))


def _chacha_xor(data: bytes, key: bytes, counter: int,
                nonce: bytes) -> bytes:
    out = bytearray(data)
    for off in range(0, len(data), 64):
        block = _chacha_block(key, counter + off // 64, nonce)
        for i in range(min(64, len(data) - off)):
            out[off + i] ^= block[i]
    return bytes(out)


def _poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i:i + 16]
        n = int.from_bytes(block, "little") + (1 << (8 * len(block)))
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b + b"\x00" * (-len(b) % 16)


def _aead_tag_py(key: bytes, nonce: bytes, aad: bytes,
                 ct: bytes) -> bytes:
    polykey = _chacha_block(key, 0, nonce)[:32]
    mac_data = (_pad16(aad) + _pad16(ct)
                + len(aad).to_bytes(8, "little")
                + len(ct).to_bytes(8, "little"))
    return _poly1305(polykey, mac_data)


def _aead_seal_py(key: bytes, nonce: bytes, aad: bytes,
                  pt: bytes) -> bytes:
    ct = _chacha_xor(pt, key, 1, nonce)
    return ct + _aead_tag_py(key, nonce, aad, ct)


def _aead_open_py(key: bytes, nonce: bytes, aad: bytes,
                  ct: bytes) -> Optional[bytes]:
    if len(ct) < 16:
        return None
    body, tag = ct[:-16], ct[-16:]
    import hmac
    if not hmac.compare_digest(tag, _aead_tag_py(key, nonce, aad,
                                                 body)):
        return None
    return _chacha_xor(body, key, 1, nonce)


# ---------------------------------------------------------------------------
# Public API (native when available, python otherwise)


def x25519(scalar: bytes, point: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _x25519_py(scalar, point)
    out = ctypes.create_string_buffer(32)
    if lib.x25519(out, bytes(scalar), bytes(point)) != 0:
        raise LowOrderPointError("x25519: low-order point")
    return out.raw


def x25519_base(scalar: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _x25519_py(scalar, (9).to_bytes(32, "little"))
    out = ctypes.create_string_buffer(32)
    if lib.x25519_base(out, bytes(scalar)) != 0:
        raise LowOrderPointError("x25519: low-order scalar/point")
    return out.raw


def aead_seal(key: bytes, nonce: bytes, aad: bytes,
              pt: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _aead_seal_py(key, nonce, aad, pt)
    out = ctypes.create_string_buffer(len(pt) + 16)
    n = lib.aead_seal(bytes(key), bytes(nonce), bytes(aad), len(aad),
                      bytes(pt), len(pt), out)
    return out.raw[:n]


def aead_open(key: bytes, nonce: bytes, aad: bytes,
              ct: bytes) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return _aead_open_py(key, nonce, aad, ct)
    if len(ct) < 16:
        return None
    out = ctypes.create_string_buffer(max(len(ct) - 16, 1))
    n = lib.aead_open(bytes(key), bytes(nonce), bytes(aad), len(aad),
                      bytes(ct), len(ct), out)
    if n < 0:
        return None
    return out.raw[:n]
