"""Native (C++) runtime components, loaded via ctypes.

Reference: upstream cilium's datapath hot path is native C compiled at
runtime by the agent (pkg/datapath/loader runs clang on bpf/*.c).  The
analogue here: the host-side ingest parser is C++ compiled on first
use by the resident toolchain (g++), cached next to the source, and
loaded with ctypes — no pybind11/pip needed.  Every entry point has a
pure-Python fallback so the framework degrades gracefully on hosts
without a compiler.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ingest.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

N_COLS = 16


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"_ingest_{digest}.so")


def _compile(so: str) -> bool:
    tmp = so + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Compile (once, content-addressed) and dlopen the ingest library."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so = _so_path()
        preexisting = os.path.exists(so)
        if not preexisting and not _compile(so):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # A stale .so built on another arch/glibc must not disable
            # the native path while g++ can rebuild from source: drop
            # it and try one rebuild before falling back.
            lib = None
            if preexisting:
                try:
                    os.unlink(so)
                except OSError:
                    pass
                if _compile(so):
                    try:
                        lib = ctypes.CDLL(so)
                    except OSError:
                        lib = None
            if lib is None:
                _build_failed = True
                return None
        for fn in (lib.parse_frames, lib.parse_pcap):
            fn.restype = ctypes.c_long
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_long,
                ctypes.c_uint32, ctypes.c_uint32,
            ]
        lib.parse_frames_packed.restype = ctypes.c_long
        lib.parse_frames_packed.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _call(fn_name: str, buf: bytes, max_rows: int, ep: int,
          direction: int,
          out: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    copy = out is None
    if copy:
        out = np.empty((max_rows, N_COLS), dtype=np.uint32)
    n = getattr(lib, fn_name)(
        buf, len(buf),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        max_rows, ep, direction)
    if n < 0:
        raise ValueError("not a pcap buffer")
    return out[:n].copy() if copy else out[:n]


def parse_frames(buf: bytes, ep: int = 0, direction: int = 0,
                 max_rows: Optional[int] = None,
                 out: Optional[np.ndarray] = None
                 ) -> Optional[np.ndarray]:
    """Length-prefixed ethernet frame stream -> [N, N_COLS] rows.

    Pass a reused ``out`` buffer ([max_rows, N_COLS] u32,
    C-contiguous) on transfer-bound paths so h2d hits the host
    page-registration cache (same contract as parse_frames_packed;
    the result is then ``out[:n]``, a VIEW).  Returns None when the
    native library is unavailable (callers fall back to the Python
    parser)."""
    if out is not None:
        if out.dtype != np.uint32 or not out.flags["C_CONTIGUOUS"] \
                or out.ndim != 2 or out.shape[1] != N_COLS:
            raise ValueError("out must be C-contiguous [n, N_COLS] u32")
        max_rows = out.shape[0]
    elif max_rows is None:
        max_rows = max(len(buf) // 24, 1)  # 4B prefix + >=20B IP
    return _call("parse_frames", buf, max_rows, ep, direction, out)


def parse_frames_packed(buf: bytes, out: Optional[np.ndarray] = None
                        ) -> Optional[tuple]:
    """Length-prefixed frame stream -> packed IPv4 rows [n, 4] u32.

    The packed format is the h2d wire layout (core/packets.py
    PACKED_*); non-IPv4 frames are skipped and counted.  Pass a reused
    ``out`` buffer ([max_rows, 4] u32, C-contiguous) so transfers hit
    the host page-registration cache — the packed path exists for
    ingest bandwidth (SURVEY.md §7 hard part #4).

    Returns (rows_view, n_rows, n_skipped); rows_view is ``out[:n]``
    (a view, NOT a copy).  None when the native library is missing.
    """
    lib = _load()
    if lib is None:
        return None
    if out is None:
        out = np.empty((max(len(buf) // 24, 1), 4), dtype=np.uint32)
    if out.dtype != np.uint32 or not out.flags["C_CONTIGUOUS"]:
        # a bare assert would vanish under python -O and hand the raw
        # pointer of a wrong-dtype/strided buffer to C
        raise ValueError("out must be a C-contiguous uint32 array")
    skipped = ctypes.c_long(0)
    overflow = ctypes.c_long(0)
    n = lib.parse_frames_packed(
        buf, len(buf),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.shape[0], ctypes.byref(skipped), ctypes.byref(overflow))
    if overflow.value:
        raise ValueError(
            f"out buffer too small: {overflow.value} frames beyond "
            f"{out.shape[0]} rows (silent truncation would be "
            "undetectable packet loss)")
    return out[:n], int(n), int(skipped.value)


def parse_frames_packed_py(buf: bytes,
                           out: Optional[np.ndarray] = None) -> tuple:
    """Pure-Python fallback for :func:`parse_frames_packed` — parses
    wide rows then packs; same return contract.

    ICMP-error frames carry the EMBEDDED tuple + the META_RELATED_BIT
    (r04: the packed format gained a flag bit — bit 15 of the length
    half-word — so RELATED semantics ride the fast path exactly like
    the wide one; pack_rows preserves the bit)."""
    import struct

    from ..core.packets import COL_FAMILY, pack_rows

    # skipped counts every frame that produced no packed row — non-v4
    # rows AND frames the wide parse dropped entirely (malformed,
    # orphan mid-fragments) — matching the native counter exactly
    n_frames, off = 0, 0
    while off + 4 <= len(buf):
        (flen,) = struct.unpack_from("<I", buf, off)
        if off + 4 + flen > len(buf):
            break
        off += 4 + flen
        n_frames += 1
    wide = parse_frames_py(buf, related=True)
    v4 = wide[wide[:, COL_FAMILY] == 4]
    skipped = n_frames - len(v4)
    packed = pack_rows(v4)
    if out is None:
        return packed, len(v4), skipped
    if len(v4) > out.shape[0]:  # same contract as the native path
        raise ValueError(
            f"out buffer too small: {len(v4) - out.shape[0]} frames "
            f"beyond {out.shape[0]} rows")
    out[:len(v4)] = packed
    return out[:len(v4)], len(v4), skipped


def parse_pcap_bytes(buf: bytes, ep: int = 0, direction: int = 0,
                     max_rows: Optional[int] = None
                     ) -> Optional[np.ndarray]:
    """Classic pcap file bytes -> [N, N_COLS] rows (None = no native)."""
    if max_rows is None:
        max_rows = max((len(buf) - 24) // 36, 1)  # 16B rec hdr + 20B IP
    return _call("parse_pcap", buf, max_rows, ep, direction)


def parse_frames_py(buf: bytes, ep: int = 0,
                    direction: int = 0,
                    related: bool = True) -> np.ndarray:
    """Pure-Python reference/fallback for :func:`parse_frames` —
    identical semantics, used when g++ is unavailable and by the
    native-vs-python equivalence tests.  ``related=False`` skips the
    ICMP-error RELATED transform (packed-path semantics)."""
    import struct

    from ..core.pcap import _parse_ip, build_row

    rows = []
    off = 0
    while off + 4 <= len(buf):
        (flen,) = struct.unpack_from("<I", buf, off)
        off += 4
        if off + flen > len(buf):
            break
        frame = buf[off:off + flen]
        off += flen
        if len(frame) < 14:
            continue
        ethertype = struct.unpack_from("!H", frame, 12)[0]
        l3 = 14
        while ethertype in (0x8100, 0x88A8) and len(frame) >= l3 + 4:
            ethertype = struct.unpack_from("!H", frame, l3 + 2)[0]
            l3 += 4
        if ethertype not in (0x0800, 0x86DD):
            continue
        parsed = _parse_ip(frame[l3:])
        if parsed is None:
            continue
        rows.append(build_row(parsed, ep, direction, related=related))
    if not rows:
        return np.zeros((0, N_COLS), dtype=np.uint32)
    return np.stack(rows)
