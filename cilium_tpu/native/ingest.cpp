// Native packet ingest: raw frames -> header-tensor rows.
//
// Reference: upstream cilium parses packets in native code on the hot
// path (bpf/lib/eth.h, ipv4.h, ipv6.h, l4.h compiled to eBPF).  The
// TPU framework's hot path is the device pipeline; THIS is the
// host-side ingest stage that feeds it — the one part of the ingest
// path where Python-per-packet cost would dominate the end-to-end
// verdict rate (SURVEY.md §7 hard part #4: ingest bandwidth).
//
// Row layout mirrors cilium_tpu/core/packets.py exactly:
//   0-3 SRC_IP0-3 | 4-7 DST_IP0-3 | 8 SPORT | 9 DPORT/ICMP-type
//   10 PROTO | 11 TCP FLAGS | 12 IP LEN | 13 FAMILY | 14 EP | 15 DIR
//
// Build: g++ -O3 -shared -fPIC (driven by cilium_tpu/native/__init__.py,
// loaded via ctypes; no pybind11 dependency).

#include <cstdint>
#include <cstring>
#include <mutex>

namespace {

constexpr int N_COLS = 16;

inline uint32_t be32(const uint8_t* p) {
    return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
           (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline uint16_t be16(const uint8_t* p) {
    return uint16_t((p[0] << 8) | p[1]);
}

constexpr uint32_t FLAG_RELATED = 0x100;  // core/packets.py
constexpr uint16_t VXLAN_PORT = 8472;
constexpr uint16_t GENEVE_PORT = 6081;

// VXLAN/Geneve UDP payload -> inner IP packet, or nullptr.
const uint8_t* decap_overlay(uint32_t proto, const uint8_t* l4,
                             long l4_len, long* inner_len) {
    if (proto != 17 || l4_len < 8) return nullptr;
    const uint16_t dport = be16(l4 + 2);
    const uint8_t* p = l4 + 8;
    long n = l4_len - 8;
    long hdr;
    if (dport == VXLAN_PORT) {
        hdr = 8;  // flags + VNI
    } else if (dport == GENEVE_PORT) {
        if (n < 8) return nullptr;
        hdr = 8 + (p[0] & 0x3F) * 4;
    } else {
        return nullptr;
    }
    if (n < hdr + 14) return nullptr;
    const uint8_t* eth = p + hdr;
    const uint16_t ethertype = be16(eth + 12);
    if (ethertype != 0x0800 && ethertype != 0x86DD) return nullptr;
    *inner_len = n - hdr - 14;
    return eth + 14;
}

// --- IPv4 fragment tracking (reference: bpf/lib/ipv4.h
// ipv4_handle_fragmentation + pkg/maps/fragmap).  The first fragment
// records (src, dst, proto, ipid) -> its L4 prefix; later fragments
// (which carry no L4 header) resolve ports through it; a miss is a
// parse-stage drop (upstream: DROP_FRAG_NOT_FOUND).  Mirrors
// core/pcap.py FragTracker.
uint64_t fnv64_bytes(const uint8_t* p, int n) {
    uint64_t h = 0xCBF29CE484222325ull;
    for (int i = 0; i < n; ++i) h = (h ^ p[i]) * 0x100000001B3ull;
    return h;
}

constexpr int FRAG_KEY_LEN = 11;  // src4 + dst4 + proto + ipid2

struct FragSlot {
    uint8_t kb[FRAG_KEY_LEN];  // the EXACT key: hash collisions must
    uint8_t pre[8];            // not alias distinct datagrams
    bool used;
};
constexpr int FRAG_CAP = 4096;
FragSlot g_frags[FRAG_CAP];
std::mutex g_frags_mu;

inline void frag_key(const uint8_t* ip4, uint8_t* kb) {
    std::memcpy(kb, ip4 + 12, 8);  // src + dst
    kb[8] = ip4[9];                // proto
    std::memcpy(kb + 9, ip4 + 4, 2);  // identification
}

void frag_record(const uint8_t* kb, const uint8_t* l4, long l4_len) {
    std::lock_guard<std::mutex> lk(g_frags_mu);
    const size_t h =
        size_t(fnv64_bytes(kb, FRAG_KEY_LEN)) % FRAG_CAP;
    size_t slot = h;
    for (int i = 0; i < 8; ++i) {
        const size_t s = (h + i) % FRAG_CAP;
        if (!g_frags[s].used ||
            !std::memcmp(g_frags[s].kb, kb, FRAG_KEY_LEN)) {
            slot = s;
            break;
        }
    }
    std::memcpy(g_frags[slot].kb, kb, FRAG_KEY_LEN);
    g_frags[slot].used = true;
    std::memset(g_frags[slot].pre, 0, 8);
    std::memcpy(g_frags[slot].pre, l4, l4_len < 8 ? l4_len : 8);
}

bool frag_lookup(const uint8_t* kb, uint8_t* out8) {
    std::lock_guard<std::mutex> lk(g_frags_mu);
    const size_t h =
        size_t(fnv64_bytes(kb, FRAG_KEY_LEN)) % FRAG_CAP;
    for (int i = 0; i < 8; ++i) {
        const size_t s = (h + i) % FRAG_CAP;
        if (g_frags[s].used &&
            !std::memcmp(g_frags[s].kb, kb, FRAG_KEY_LEN)) {
            std::memcpy(out8, g_frags[s].pre, 8);
            return true;
        }
    }
    return false;
}

// Resolve IPv4 fragmentation for one packet: returns false when the
// packet is an unresolvable mid-fragment (drop).  On a resolved
// mid-fragment, *l4 / *l4_len point at the recorded 8-byte prefix in
// scratch8.
bool resolve_fragment(const uint8_t* ip4, uint32_t proto,
                      const uint8_t** l4, long* l4_len,
                      uint8_t* scratch8) {
    const uint16_t fo = be16(ip4 + 6);
    const uint16_t frag_off = fo & 0x1FFF;
    const bool more = fo & 0x2000;
    if (!(frag_off || more)) return true;  // not fragmented
    if (!(proto == 6 || proto == 17 || proto == 132)) return true;
    uint8_t kb[FRAG_KEY_LEN];
    frag_key(ip4, kb);
    if (frag_off == 0) {  // first fragment carries the L4 header
        frag_record(kb, *l4, *l4_len);
        return true;
    }
    if (!frag_lookup(kb, scratch8)) return false;  // FRAG_NOT_FOUND
    *l4 = scratch8;
    *l4_len = 8;
    return true;
}

inline bool icmp_is_error(uint32_t proto, uint8_t type) {
    if (proto == 1)
        return type == 3 || type == 4 || type == 5 || type == 11 ||
               type == 12;
    if (proto == 58) return type >= 1 && type <= 4;
    return false;
}

// Parse one IP packet (no link header) into a header row.
// Returns true when the row was produced.  depth bounds overlay decap
// recursion to match the Python reference (core/pcap.py: 2 levels).
bool parse_ip(const uint8_t* pkt, long len, uint32_t* row, uint32_t ep,
              uint32_t dir, int depth = 0) {
    if (len < 20) return false;
    const int ver = pkt[0] >> 4;
    uint32_t proto, ip_len, fam;
    const uint8_t* l4;
    long l4_len;
    if (ver == 4) {
        const int ihl = (pkt[0] & 0xF) * 4;
        if (len < ihl || ihl < 20) return false;
        proto = pkt[9];
        ip_len = be16(pkt + 2);
        fam = 4;
        row[0] = row[1] = row[2] = 0;
        row[3] = be32(pkt + 12);
        row[4] = row[5] = row[6] = 0;
        row[7] = be32(pkt + 16);
        l4 = pkt + ihl;
        l4_len = len - ihl;
        uint8_t scratch[8];
        if (!resolve_fragment(pkt, proto, &l4, &l4_len, scratch))
            return false;  // mid-fragment with no tracked first frag
        if (l4 == scratch) {
            // the prefix must outlive this frame's scope: parse ports
            // now and short-circuit (a resolved mid-fragment is never
            // an overlay or an ICMP error)
            row[8] = be16(scratch);
            row[9] = be16(scratch + 2);
            row[10] = proto;
            row[11] = 0;  // no TCP flags on a headerless fragment
            row[12] = ip_len;
            row[13] = fam;
            row[14] = ep;
            row[15] = dir;
            return true;
        }
    } else if (ver == 6 && len >= 40) {
        proto = pkt[6];
        ip_len = 40 + be16(pkt + 4);
        fam = 6;
        for (int w = 0; w < 4; ++w) row[w] = be32(pkt + 8 + 4 * w);
        for (int w = 0; w < 4; ++w) row[4 + w] = be32(pkt + 24 + 4 * w);
        l4 = pkt + 40;
        l4_len = len - 40;
    } else {
        return false;
    }
    // overlay decap: the row carries the INNER packet (bounded depth)
    if (depth < 2) {
        long inner_len;
        const uint8_t* inner = decap_overlay(proto, l4, l4_len,
                                             &inner_len);
        if (inner) {
            if (parse_ip(inner, inner_len, row, ep, dir, depth + 1))
                return true;
            // unparseable inner: fall through to the outer row,
            // matching the Python reference
        }
    }
    uint32_t sport = 0, dport = 0, flags = 0;
    if ((proto == 6 || proto == 17 || proto == 132) && l4_len >= 4) {
        sport = be16(l4);
        dport = be16(l4 + 2);
        if (proto == 6 && l4_len >= 14) flags = l4[13];
    } else if ((proto == 1 || proto == 58) && l4_len >= 2) {
        dport = l4[0];  // ICMP type rides the dport column
        // ICMP ERROR: relate to the embedded original packet — the
        // row carries the INNER tuple + FLAG_RELATED (matches
        // core/pcap.py build_row)
        if (icmp_is_error(proto, l4[0]) && l4_len >= 8 + 20) {
            const uint8_t* in = l4 + 8;
            const long in_len = l4_len - 8;
            const int iver = in[0] >> 4;
            if (iver == 4 && fam == 4 && in_len >= 20) {
                const int iihl = (in[0] & 0xF) * 4;
                if (iihl >= 20 && in_len >= iihl) {
                    const uint32_t iproto = in[9];
                    uint32_t isp = 0, idp = 0;
                    const uint8_t* il4 = in + iihl;
                    const long il4_len = in_len - iihl;
                    if ((iproto == 6 || iproto == 17 || iproto == 132)
                        && il4_len >= 4) {
                        isp = be16(il4);
                        idp = be16(il4 + 2);
                    } else if ((iproto == 1 || iproto == 58)
                               && il4_len >= 2) {
                        idp = il4[0];
                    }
                    row[0] = row[1] = row[2] = 0;
                    row[3] = be32(in + 12);
                    row[4] = row[5] = row[6] = 0;
                    row[7] = be32(in + 16);
                    row[8] = isp;
                    row[9] = idp;
                    row[10] = iproto;
                    row[11] = FLAG_RELATED;
                    row[12] = ip_len;
                    row[13] = fam;
                    row[14] = ep;
                    row[15] = dir;
                    return true;
                }
            } else if (iver == 6 && fam == 6 && in_len >= 40) {
                const uint32_t iproto = in[6];
                uint32_t isp = 0, idp = 0;
                const uint8_t* il4 = in + 40;
                const long il4_len = in_len - 40;
                if ((iproto == 6 || iproto == 17 || iproto == 132)
                    && il4_len >= 4) {
                    isp = be16(il4);
                    idp = be16(il4 + 2);
                } else if ((iproto == 1 || iproto == 58)
                           && il4_len >= 2) {
                    idp = il4[0];
                }
                for (int w = 0; w < 4; ++w) row[w] = be32(in + 8 + 4 * w);
                for (int w = 0; w < 4; ++w)
                    row[4 + w] = be32(in + 24 + 4 * w);
                row[8] = isp;
                row[9] = idp;
                row[10] = iproto;
                row[11] = FLAG_RELATED;
                row[12] = ip_len;
                row[13] = fam;
                row[14] = ep;
                row[15] = dir;
                return true;
            }
        }
    }
    row[8] = sport;
    row[9] = dport;
    row[10] = proto;
    row[11] = flags;
    row[12] = ip_len;
    row[13] = fam;
    row[14] = ep;
    row[15] = dir;
    return true;
}

// Ethernet frame -> IP payload (skipping VLAN tags); nullptr if non-IP.
const uint8_t* eth_payload(const uint8_t* frame, long len, long* ip_len) {
    if (len < 14) return nullptr;
    uint16_t ethertype = be16(frame + 12);
    long off = 14;
    while ((ethertype == 0x8100 || ethertype == 0x88A8) &&
           len >= off + 4) {
        ethertype = be16(frame + off + 2);
        off += 4;
    }
    if (ethertype != 0x0800 && ethertype != 0x86DD) return nullptr;
    *ip_len = len - off;
    return frame + off;
}

}  // namespace

extern "C" {

// Length-prefixed frame stream: [u32le frame_len][frame bytes]...
// Writes up to max_rows rows into out ([max_rows * N_COLS] u32);
// returns the number of rows produced.
long parse_frames(const uint8_t* buf, long buf_len, uint32_t* out,
                  long max_rows, uint32_t ep, uint32_t dir) {
    long off = 0, rows = 0;
    while (off + 4 <= buf_len && rows < max_rows) {
        uint32_t flen;
        std::memcpy(&flen, buf + off, 4);  // little-endian host
        off += 4;
        if (off + flen > buf_len) break;
        long ip_len;
        const uint8_t* ip = eth_payload(buf + off, flen, &ip_len);
        if (ip && parse_ip(ip, ip_len, out + rows * N_COLS, ep, dir))
            ++rows;
        off += flen;
    }
    return rows;
}

// Classic libpcap file buffer -> rows.  Handles both byte orders and
// LINKTYPE_ETHERNET (1) / LINKTYPE_RAW (101).
long parse_pcap(const uint8_t* buf, long buf_len, uint32_t* out,
                long max_rows, uint32_t ep, uint32_t dir) {
    if (buf_len < 24) return 0;
    uint32_t magic;
    std::memcpy(&magic, buf, 4);
    bool swapped;
    if (magic == 0xA1B2C3D4u) swapped = false;
    else if (magic == 0xD4C3B2A1u) swapped = true;
    else return -1;  // not a pcap
    auto rd32 = [&](long off) {
        uint32_t v;
        std::memcpy(&v, buf + off, 4);
        if (swapped) v = __builtin_bswap32(v);
        return v;
    };
    const uint32_t linktype = rd32(20);
    long off = 24, rows = 0;
    while (off + 16 <= buf_len && rows < max_rows) {
        const uint32_t caplen = rd32(off + 8);
        off += 16;
        if (off + caplen > buf_len) break;
        const uint8_t* frame = buf + off;
        off += caplen;
        const uint8_t* ip = nullptr;
        long ip_len = 0;
        if (linktype == 1) {
            ip = eth_payload(frame, caplen, &ip_len);
        } else if (linktype == 101) {
            ip = frame;
            ip_len = caplen;
        } else {
            continue;
        }
        if (ip && parse_ip(ip, ip_len, out + rows * N_COLS, ep, dir))
            ++rows;
    }
    return rows;
}

// Packed IPv4 fast path: 4 u32 words per packet, the h2d wire format
// (cilium_tpu/core/packets.py PACKED_*):
//   w0 = src ip | w1 = dst ip | w2 = sport<<16|dport
//   w3 = proto<<24 | tcp_flags<<16 | ip_total_len
// One pass, no intermediate wide row: frames stream -> packed rows
// written straight into the (reused) transfer buffer.  Non-IPv4
// frames are skipped and counted in *n_skipped (callers route those
// through the wide parser).
// *n_overflow counts parseable IPv4 frames that did NOT fit in
// max_rows — the caller's buffer was undersized and it must know
// (silent truncation would be undetectable packet loss).
long parse_frames_packed(const uint8_t* buf, long buf_len, uint32_t* out,
                         long max_rows, long* n_skipped,
                         long* n_overflow) {
    long off = 0, rows = 0, skipped = 0, overflow = 0;
    while (off + 4 <= buf_len) {
        uint32_t flen;
        std::memcpy(&flen, buf + off, 4);
        off += 4;
        if (off + flen > buf_len) break;
        long ip_len;
        const uint8_t* p = eth_payload(buf + off, flen, &ip_len);
        off += flen;
        if (!p || ip_len < 20 || (p[0] >> 4) != 4) { ++skipped; continue; }
        int ihl = (p[0] & 0xF) * 4;
        if (ip_len < ihl || ihl < 20) { ++skipped; continue; }
        uint32_t proto = p[9];
        const uint8_t* l4 = p + ihl;
        long l4_len = ip_len - ihl;
        // fragment resolution BEFORE decap (matches the Python
        // ordering: a mid-fragment's synthesized 8-byte prefix can
        // never satisfy the decap length checks)
        uint8_t fscratch[8];
        if (!resolve_fragment(p, proto, &l4, &l4_len, fscratch)) {
            ++skipped;  // mid-fragment with no tracked first fragment
            continue;
        }
        // overlay decap (v4-in-v4 only on the fast path; depth 2 to
        // match the wide/Python parsers)
        bool drop = false;
        for (int d = 0; d < 2; ++d) {
            long inner_len;
            const uint8_t* inner = decap_overlay(proto, l4, l4_len,
                                                 &inner_len);
            if (!inner) break;
            if (inner_len < 20 || (inner[0] >> 4) != 4) {
                drop = true;  // v6-in-v4 overlay: wide path only
                break;
            }
            const int iihl = (inner[0] & 0xF) * 4;
            if (inner_len < iihl || iihl < 20) { drop = true; break; }
            const uint32_t iproto = inner[9];
            const uint8_t* il4 = inner + iihl;
            long il4_len = inner_len - iihl;
            // inner fragments resolve like outer ones (the Python
            // fallback runs the same logic on the decapped header);
            // an UNRESOLVABLE inner mid-fragment keeps the OUTER row,
            // matching _parse_ip's fallback-to-outer
            if (!resolve_fragment(inner, iproto, &il4, &il4_len,
                                  fscratch))
                break;
            p = inner;
            ip_len = inner_len;
            ihl = iihl;
            proto = iproto;
            l4 = il4;
            l4_len = il4_len;
        }
        if (drop) { ++skipped; continue; }
        // overflow is counted only AFTER full validation so it counts
        // exactly the frames that would have produced rows — an out
        // buffer sized for the valid rows never spuriously overflows
        if (rows >= max_rows) { ++overflow; continue; }
        // length caps at 0x7FFF: bit 15 of the META half-word is the
        // RELATED flag (core/packets.py META_RELATED_BIT)
        uint32_t len15 = be16(p + 2);
        if (len15 > 0x7FFF) len15 = 0x7FFF;
        uint32_t sport = 0, dport = 0, flags = 0;
        if ((proto == 6 || proto == 17 || proto == 132) && l4_len >= 4) {
            sport = be16(l4);
            dport = be16(l4 + 2);
            if (proto == 6 && l4_len >= 14) flags = l4[13];
        } else if ((proto == 1 || proto == 58) && l4_len >= 2) {
            dport = l4[0];  // ICMP/ICMPv6 type rides the dport column
            // ICMP ERROR: the row carries the EMBEDDED original
            // tuple + the RELATED bit (r04 — previously wide-path
            // only; matches parse_ip's wide transform)
            if (proto == 1 && icmp_is_error(proto, l4[0]) &&
                l4_len >= 8 + 20) {
                const uint8_t* in = l4 + 8;
                const long in_len = l4_len - 8;
                if ((in[0] >> 4) == 4 && in_len >= 20) {
                    const int iihl = (in[0] & 0xF) * 4;
                    if (iihl >= 20 && in_len >= iihl) {
                        const uint32_t iproto = in[9];
                        const uint8_t* il4 = in + iihl;
                        const long il4_len = in_len - iihl;
                        uint32_t isp = 0, idp = 0;
                        if ((iproto == 6 || iproto == 17 ||
                             iproto == 132) && il4_len >= 4) {
                            isp = be16(il4);
                            idp = be16(il4 + 2);
                        } else if ((iproto == 1 || iproto == 58)
                                   && il4_len >= 2) {
                            idp = il4[0];
                        }
                        uint32_t* w = out + rows * 4;
                        w[0] = be32(in + 12);
                        w[1] = be32(in + 16);
                        w[2] = (isp << 16) | idp;
                        w[3] = (iproto << 24) | 0x8000u | len15;
                        ++rows;
                        continue;
                    }
                }
            }
        }
        uint32_t* w = out + rows * 4;
        w[0] = be32(p + 12);
        w[1] = be32(p + 16);
        w[2] = (sport << 16) | dport;
        w[3] = (proto << 24) | (flags << 16) | len15;
        ++rows;
    }
    if (n_skipped) *n_skipped = skipped;
    if (n_overflow) *n_overflow = overflow;
    return rows;
}

}  // extern "C"
