"""cilium-tpu CLI (reference: cilium/cmd cobra CLI).

Verbs mirror the reference operator tooling: ``policy import|get|
delete``, ``endpoint list|add|delete``, ``identity list``, ``bpf ct
list``, ``bpf policy get``, ``map list``, ``monitor``, ``status``,
``metrics``, ``flows`` (hubble observe), plus ``daemon run`` to start
an agent serving the API socket.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..api.client import DEFAULT_SOCKET, APIClient, APIError


def _client(args) -> APIClient:
    return APIClient(args.socket)


def _print(obj) -> None:
    print(json.dumps(obj, indent=2))


def cmd_status(args) -> int:
    st = _client(args).healthz()
    if args.json:
        _print(st)
        return 0
    print(f"Agent:     {st['node']} v{st['version']} "
          f"(backend={st['backend']}, up {st['uptime-seconds']}s)")
    print(f"Policy:    revision {st['policy-revision']}, "
          f"{st['identities']} identities, "
          f"{st['ipcache-entries']} ipcache entries")
    eps = st["endpoints"]
    print(f"Endpoints: {eps['total']} ({eps['by-state']})")
    print(f"Datapath:  {st['forwarded']} forwarded, "
          f"{st['dropped']} dropped, {st['flows-seen']} flows seen")
    if "auth" in st:
        a = st["auth"]
        print(f"Auth:      provider={a['provider']} "
              f"granted={a['granted']} failed={a['failed']}")
    if "encryption" in st:
        e = st["encryption"]
        print(f"Encrypt:   wireguard-analogue epoch={e['epoch']} "
              f"peers={len(e['peers'])} "
              f"pubkey={e['public-key'][:16]}...")
    for name, c in st.get("controllers", {}).items():
        ok = "ok" if not c["last-error"] else f"FAILING: {c['last-error']}"
        print(f"Controller {name}: {c['success']} runs, {ok}")
    return 0


def cmd_connectivity(args) -> int:
    """`cilium-tpu connectivity test` (reference: cilium-cli
    connectivity test — BASELINE config 1): self-contained two-pod
    world + the L3/L4/L7/deny/entity/auth scenario matrix through
    the real datapath."""
    from ..testing.connectivity import (format_results,
                                        run_connectivity_tests)
    res = run_connectivity_tests(backend=args.backend)
    if args.json:
        _print([r.__dict__ for r in res])
    else:
        print(format_results(res))
    return 0 if all(r.ok for r in res) else 1


def cmd_encrypt(args) -> int:
    """`cilium-tpu encrypt status` (reference: cilium encrypt
    status)."""
    st = _client(args).healthz()
    enc = st.get("encryption")
    if args.json:
        _print(enc or {"enabled": False})
        return 0
    if not enc:
        print("Encryption: disabled")
        return 0
    print(f"Encryption: wireguard-analogue (X25519 + "
          f"ChaCha20-Poly1305, batch-sealed)")
    print(f"Public key: {enc['public-key']}")
    print(f"Key epoch:  {enc['epoch']}")
    for peer, c in enc["peers"].items():
        print(f"Peer {peer}: sealed={c['sealed']} "
              f"opened={c['opened']} rejected={c['rejected']}")
    return 0


def cmd_policy(args) -> int:
    c = _client(args)
    if args.action == "get":
        _print(c.policy_get())
    elif args.action == "import":
        if not args.file:
            print("usage: cilium-tpu policy import FILE", file=sys.stderr)
            return 1
        with open(args.file) as f:
            rules = json.load(f)
        out = c.policy_put(rules)
        print(f"Revision: {out['revision']}")
    elif args.action == "delete":
        out = c.policy_delete(args.labels.split(","))
        print(f"Revision: {out['revision']}")
    return 0


def cmd_endpoint(args) -> int:
    c = _client(args)
    if args.action == "list":
        eps = c.endpoint_list()
        if args.json:
            _print(eps)
            return 0
        print(f"{'ID':<6}{'STATE':<22}{'IDENTITY':<10}{'IPS':<34}NAME")
        for ep in eps:
            print(f"{ep['id']:<6}{ep['state']:<22}"
                  f"{str(ep['identity']):<10}"
                  f"{','.join(ep['ips']):<34}{ep['name']}")
    elif args.action == "get":
        _print(c.endpoint_get(args.id))
    elif args.action == "add":
        ep = c.endpoint_create(args.name, args.ip, args.label)
        _print(ep)
    elif args.action == "delete":
        _print(c.endpoint_delete(args.id))
    return 0


def cmd_service(args) -> int:
    c = _client(args)
    if args.action == "list":
        svcs = c.service_list()
        if args.json:
            _print(svcs)
            return 0
        print(f"{'NAME':<20}{'FRONTEND':<24}BACKENDS")
        for s in svcs:
            bes = ",".join(f"{b['ip']}:{b['port']}"
                           for b in s["backends"])
            print(f"{s['name']:<20}{s['frontend']:<24}{bes}")
    elif args.action == "upsert":
        if not args.name or not args.frontend:
            print("usage: cilium-tpu service upsert NAME --frontend "
                  "IP:PORT [--backend IP:PORT ...]", file=sys.stderr)
            return 1
        _print(c.service_upsert(args.name, args.frontend,
                                args.backend or []))
    elif args.action == "delete":
        _print(c.service_delete(args.name))
    return 0


def cmd_fqdn(args) -> int:
    entries = _client(args).fqdn_cache()
    if args.json:
        _print(entries)
        return 0
    print(f"{'IP':<40}{'IDENTITY':<12}NAMES")
    for e in entries:
        print(f"{e['ip']:<40}{e['identity']:<12}{','.join(e['names'])}")
    return 0


def cmd_health(args) -> int:
    h = _client(args).cluster_health()
    if args.json:
        _print(h)
        return 0
    print(f"Cluster health (from {h['local']}): "
          f"{h['reachable']} reachable, {h['unreachable']} unreachable")
    for n in h["nodes"]:
        state = (f"reachable {n['latency-ms']}ms" if n["reachable"]
                 else f"UNREACHABLE ({n.get('error', '')})")
        print(f"  {n['name']:<20}{state}")
    return 0


def cmd_cluster(args) -> int:
    """`cilium-tpu cluster status`: the clustermesh serving tier —
    membership, routing table, failover/scale-out history, and the
    cluster-wide no-silent-loss ledger (any member node answers).
    `cilium-tpu cluster scale` adds one replica live (ISSUE 13);
    `cluster scale --down [--node NAME]` retires one (ISSUE 17)."""
    if getattr(args, "action", "status") == "scale":
        down = getattr(args, "down", False)
        rec = _client(args).cluster_scale(
            down=down, node=getattr(args, "node", None))
        if args.json:
            _print(rec)
            return 0
        verb = ("Scaled in: {node} retired" if down
                else "Scaled out: {node} joined").format(
                    node=rec['node'])
        print(f"{verb} "
              f"({rec['nodes-after']} nodes, "
              f"{rec['moved-slots']} slots re-pinned, "
              f"{rec['ct-migrated-entries']} CT entries migrated, "
              f"pause {rec['pause-ms']}ms, survivor recompiles "
              f"{rec['survivor-recompiles']})")
        return 0
    if getattr(args, "action", "status") == "rotate":
        # cluster-wide key-epoch rotation (ISSUE 18): every live
        # encrypted channel re-keys under the grace window, serving
        # uninterrupted
        rec = _client(args).cluster_rotate(
            grace_s=getattr(args, "grace", None))
        if args.json:
            _print(rec)
            return 0
        failed = rec.get("failed") or []
        print(f"Rotated to epoch {rec['epoch']}: "
              f"{len(rec['acked'])} nodes acked in {rec['ms']}ms "
              f"(grace {rec['grace-s']}s)"
              + (f", {len(failed)} FAILED" if failed else ""))
        for f in failed:
            print(f"  {f['node']:<16}{f['error']}")
        return 0
    if getattr(args, "action", "status") == "sysdump":
        # the cluster sysdump archive (ISSUE 14): every worker's
        # flight-recorder bundle + the parent bundle + a manifest
        out = _client(args).cluster_sysdump()
        if args.json:
            _print(out)
            return 0
        man = out.get("manifest") or {}
        print(f"wrote {out.get('path')}")
        for name, ent in sorted((man.get("nodes") or {}).items()):
            state = ("ok" if ent.get("ok")
                     else f"FAILED ({ent.get('error', '?')})")
            print(f"  {name:<16}{state}")
        return 0
    if getattr(args, "action", "status") == "slo":
        # the relay's merged cluster health verdict (ISSUE 19):
        # worst-of over node verdicts, every contribution labeled
        out = _client(args).cluster_slo()
        if args.json:
            _print(out)
            return 0
        un = out.get("unreachable") or []
        print(f"Cluster SLO: {str(out.get('verdict', '?')).upper()} "
              f"({out.get('node-count', 0)} nodes"
              + (f", {len(un)} unreachable" if un else "") + ")")
        for name, e in sorted((out.get("nodes") or {}).items()):
            bad = ", ".join(
                f"{k}={v}"
                for k, v in sorted((e.get("slos") or {}).items())
                if v != "ok")
            extra = e.get("error") or bad
            age = e.get("age-s")
            age_s = "-" if age is None else f"{age:.1f}s"
            print(f"  {name:<16}{e.get('verdict', '?'):<9}"
                  f"age {age_s}"
                  + (f"  {extra}" if extra else ""))
        return 0
    if getattr(args, "action", "status") == "trace":
        # stitched cross-process spans (router-queue -> forward ->
        # worker-admit -> ack) + per-node tracer summaries
        tr = _client(args).cluster_trace()
        if args.json:
            _print(tr)
            return 0
        st = tr.get("stitched")
        if not st:
            print("No stitched spans (set cluster_trace_sample > 0)")
            return 0
        print(f"Stitched spans: {st['committed']} committed, "
              f"{st['dropped']} dropped of {st['sampled']} sampled")
        for hop, h in (st.get("hops-us") or {}).items():
            if h and h.get("count"):
                print(f"  {hop:<28}p50 {_us(h['p50'])} "
                      f"p99 {_us(h['p99'])}")
        for sp in (st.get("spans") or [])[:8]:
            hops = " ".join(f"{k.split('->')[1]}+{_us(v)}"
                            for k, v in sp["hops-us"].items())
            print(f"  #{sp['trace-id']} {sp['node']} "
                  f"rows={sp['rows']} e2e {_us(sp['e2e-us'])}: "
                  f"{hops}")
        return 0
    st = _client(args).cluster_status()
    if args.json:
        _print(st)
        return 0
    c = st["cluster"]
    print(f"Cluster: {c['live']}/{c['nodes']} nodes live "
          f"(mode {c.get('mode', 'thread')}, kvstore {c['kvstore']}, "
          f"failovers {c['failovers']}, "
          f"scale-outs {c.get('scale-outs', 0)})")
    for m in st["membership"]:
        node = st["per-node"].get(m["name"], {})
        mode = node.get("mode") or "-"
        lat = m.get("probe-latency-ms")
        extra = (f"probe {lat}ms" if m["state"] == "live"
                 and lat is not None else
                 m.get("death", {}).get("cause", ""))
        print(f"  {m['name']:<16}{m['state']:<6}mode={mode:<9}{extra}")
    r = c.get("router")
    if r is not None:
        print(f"Router: submitted {r['submitted']}, pending "
              f"{sum(r['pending'])}, overflow {r['router-overflow']}, "
              f"failover-dropped {r['failover-dropped']}, "
              f"crash-dropped {r.get('crash-dropped', 0)}, "
              f"crypto-dropped {r.get('crypto-dropped', 0)}")
        owners = r["slot-owner"]
        counts = {}
        for o in owners:
            counts[o] = counts.get(o, 0) + 1
        share = ", ".join(f"node{o}:{n}"
                          for o, n in sorted(counts.items()))
        print(f"  slots: {len(owners)} ({share})")
        lat = r.get("forward-latency-us") or {}
        if lat.get("count"):
            print(f"  forward latency: p50 {_us(lat['p50'])} "
                  f"p95 {_us(lat['p95'])} p99 {_us(lat['p99'])}")
    led = st["ledger"]
    print(f"Ledger: submitted {led['submitted']} == accounted "
          f"{led['accounted']} -> "
          f"{'EXACT' if led['exact'] else 'OPEN (in flight)'}")
    lf = c.get("last-failover")
    if lf:
        print(f"Last failover: {lf['dead']} -> {lf['peer']} "
              f"(blackout {lf['blackout-ms']}ms, detect "
              f"{lf.get('detect-ms')}ms, CT entries "
              f"{lf['ct-replayed-entries']}, dropped "
              f"{lf['dropped-rows']})")
    ls = c.get("last-scale-out")
    if ls:
        print(f"Last scale-out: {ls['node']} joined "
              f"(pause {ls['pause-ms']}ms, "
              f"{ls['ct-migrated-entries']} CT entries migrated)")
    asc = c.get("autoscale")
    if asc:
        print(f"Autoscale: watermark {asc['high-frac']}, streak "
              f"{asc['streak']}/{asc['ticks']}, triggered "
              f"{asc['triggered']}, max {asc['max-nodes']}")
    cr = c.get("crypto")
    if cr:
        ch = (r or {}).get("crypto") or {}
        print(f"Crypto: epoch {cr['epoch']}, rotations "
              f"{cr['rotations']} (grace {cr['grace-s']}s), sealed "
              f"{ch.get('sealed', 0)}, rejected "
              f"{ch.get('rejected', 0)}, replays "
              f"{ch.get('replays', 0)}")
        lr = c.get("last-rotation")
        if lr:
            print(f"  last rotation: -> epoch {lr['epoch']} "
                  f"({len(lr['acked'])} acked, {lr['ms']}ms)")
    return 0


def _us(v):
    if v is None:
        return "-"
    return f"{v / 1e3:.1f}ms" if v >= 1e3 else f"{v:.0f}µs"


def cmd_config(args) -> int:
    c = _client(args)
    if args.action == "get":
        _print(c.config())
    else:  # set KEY VALUE
        _print(c.config_patch({args.key: args.value}))
    return 0


def cmd_proxy(args) -> int:
    """L7 plane: redirect listeners or the xDS push-surface status."""
    c = _client(args)
    if args.obj == "xds":
        st = c.xds_status()
        if args.json:
            _print(st)
            return 0
        print(f"xDS version {st['version']}, "
              f"{len(st['resources'])} resources")
        for name in st["resources"]:
            print(f"  {name}")
        for nonce, detail in st.get("nacks", ()):
            print(f"  NACK @{nonce}: {detail}")
        return 0
    if args.obj == "stats":
        st = c.proxy_stats()
        if args.json:
            _print(st)
            return 0
        plane = st.get("plane") or {}
        print(f"plane {'ACTIVE' if st.get('plane-active') else 'stopped'}"
              f", {len(st.get('listeners') or ())} listener(s), "
              f"requests {st.get('requests-total', 0)} "
              f"(denied {st.get('requests-denied', 0)})")
        if plane:
            print(f"redirected {plane.get('redirected', 0)}: "
                  f"allowed {plane.get('l7-allowed', 0)} "
                  f"denied {plane.get('l7-denied', 0)} "
                  f"shed {plane.get('l7-shed', 0)} "
                  f"failed {plane.get('l7-failed', 0)} "
                  f"(ledger "
                  f"{'exact' if plane.get('ledger-exact') else 'OPEN'})")
            print(f"workers {plane.get('workers', 0)} "
                  f"restarts {plane.get('worker-restarts', 0)} "
                  f"queue {plane.get('queue-depth', 0)} "
                  f"dns-answers {plane.get('dns-answers', 0)}")
        for name, h in sorted(
                (st.get("parse-latency-by-plugin") or {}).items()):
            print(f"  {name}: p50={h.get('p50')}us "
                  f"p95={h.get('p95')}us p99={h.get('p99')}us "
                  f"n={h.get('count')}")
        return 0
    listeners = c.proxy_listeners()
    if args.json:
        _print(listeners)
        return 0
    for l in listeners:
        rules = {k: v for k, v in l.items()
                 if k.endswith("-rules") and v}
        print(f"port {l['proxy-port']}: {rules or 'no rules'}")
    return 0


def cmd_identity(args) -> int:
    ids = _client(args).identity_list()
    if args.json:
        _print(ids)
        return 0
    print(f"{'ID':<12}LABELS")
    for i in ids:
        print(f"{i['id']:<12}{' '.join(i['labels'])}")
    return 0


def cmd_bpf(args) -> int:
    c = _client(args)
    if args.obj == "ct":
        entries = c.map_get("ct")
        if args.json:
            _print(entries)
            return 0
        for e in entries:
            print(f"{e['proto']} {args_dir(e)} {e['src']}:{e['sport']} "
                  f"-> {e['dst']}:{e['dport']} {e['state']} "
                  f"expires={e['expires']} tx={e['tx_packets']} "
                  f"rx={e['rx_packets']}"
                  + (f" proxy={e['proxy_port']}" if e['proxy_port']
                     else ""))
    elif args.obj == "policy":
        entries = c.map_get(f"policy/{args.id}")
        if args.json:
            _print(entries)
            return 0
        print(f"{'DIR':<9}{'IDENTITY':<10}{'PROTO':<7}{'PORT':<12}"
              f"{'VERDICT':<10}DERIVED-FROM")
        for e in entries:
            print(f"{e['direction']:<9}{e['identity']:<10}"
                  f"{e['proto']:<7}{e['dport']:<12}{e['verdict']:<10}"
                  f"{';'.join(e['derived-from'])}")
    elif args.obj == "ipcache":
        entries = c.map_get("ipcache")
        if args.json:
            _print(entries)
            return 0
        for e in entries:
            print(f"{e['cidr']:<24}identity={e['identity']} "
                  f"source={e['source']}")
    elif args.obj == "lb":
        entries = c.map_get("lb")
        if args.json:
            _print(entries)
            return 0
        for e in entries:
            be = e["backend"] or "(no service)"
            print(f"{e['proto']} {e['src']}:{e['sport']} -> "
                  f"{e['vip']}:{e['dport']} backend={be} "
                  f"expires={e['expires']}")
    elif args.obj == "auth":
        entries = c.map_get("auth")
        if args.json:
            _print(entries)
            return 0
        for e in entries:
            print(f"ep={e['endpoint']} remote-identity="
                  f"{e['remote_identity']} expires={e['expires']}")
    elif args.obj == "nat":
        entries = c.map_get("nat")
        if args.json:
            _print(entries)
            return 0
        for e in entries:
            print(f"{e['proto']} {e['src']}:{e['sport']} -> "
                  f"{e['dst']}:{e['dport']} node-port={e['node_port']} "
                  f"expires={e['expires']}")
    return 0


def args_dir(e) -> str:
    return {"ingress": "in ", "egress": "out"}.get(e.get("dir", ""), "?")


def cmd_egress(args) -> int:
    entries = _client(args).egress_list()
    if args.json:
        _print(entries)
        return 0
    print(f"{'SOURCE':<18}{'DESTINATION':<20}EGRESS-IP")
    for e in entries:
        print(f"{e['source']:<18}{e['destination']:<20}"
              f"{e['egress-ip']}")
    return 0


def cmd_map(args) -> int:
    _print(_client(args).map_list())
    return 0


def cmd_metrics(args) -> int:
    c = _client(args)
    if getattr(args, "cluster", False):
        # the relay's merged exposition: every series node-labelled
        print(c.cluster_metrics(), end="")
        return 0
    print(c.metrics(), end="")
    return 0


def cmd_flows(args) -> int:
    """`cilium-tpu flows [-f]` (hubble observe): recent flows with
    the SHARED filter vocabulary — `--verdict/--identity/--port/
    --protocol/--since` map onto the Observer's vectorized
    FlowFilter, and `top` renders aggregates over the same fields.
    Follow mode tails new flows by uuid."""
    c = _client(args)
    # --since S = "the last S seconds": resolve to the epoch once so
    # a follow session keeps its original left edge
    since = (time.time() - args.since) if args.since else None
    seen = 0
    if getattr(args, "cluster", False):
        # `flows --cluster` (hubble-relay parity): merged
        # time-ordered flows from every node, node_name stamped.
        # The shared filter vocabulary applies CLIENT-side over the
        # merged dicts (the relay buffer is node-merged, not an
        # Observer ring), and -f tails by (node, uuid).
        from ..flow.flow import PROTO_NAMES, VERDICT_NAMES

        want_verdict = (VERDICT_NAMES.get(args.verdict)
                        if args.verdict is not None else None)
        # the merged dicts carry protocol as the l4 key name
        # ("TCP"/"UDP"/...) or {"proto": n} for codes without a name
        want_proto = (PROTO_NAMES.get(args.protocol, args.protocol)
                      if args.protocol is not None else None)

        def keep(fl) -> bool:
            if want_verdict is not None \
                    and fl.get("verdict") != want_verdict:
                return False
            if want_proto is not None:
                l4 = fl.get("l4") or {}
                if want_proto not in l4 \
                        and l4.get("proto") != want_proto:
                    return False
            if args.port is not None:
                l4 = next(iter((fl.get("l4") or {}).values()), {})
                if args.port not in (l4.get("source_port"),
                                     l4.get("destination_port")):
                    return False
            if args.identity is not None:
                idents = {(fl.get("source") or {}).get("identity"),
                          (fl.get("destination") or {})
                          .get("identity")}
                if args.identity not in idents:
                    return False
            if since is not None and fl.get("time", 0) < since:
                return False
            return True

        seen_keys = set()
        try:
            while True:
                flows = [fl for fl in c.cluster_flows(
                    number=args.number, oldest_first=1)
                    if keep(fl)]
                if args.json:
                    _print(flows)
                else:
                    for fl in flows:
                        key = (fl.get("node_name"), fl.get("uuid"))
                        if key in seen_keys:
                            continue
                        seen_keys.add(key)
                        print(f"{fl.get('time', 0):.3f} "
                              f"[{fl.get('node_name', '?')}] "
                              f"{fl.get('Summary', '')}")
                if not args.follow:
                    return 0
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    try:
        while True:
            flows = c.flows(number=args.number, verdict=args.verdict,
                            port=args.port, protocol=args.protocol,
                            identity=args.identity, since=since)
            if args.json:
                # json mode follows too (one snapshot per tick, like
                # `top --json -f`) instead of silently ignoring -f
                _print(flows)
            else:
                fresh = [f for f in flows if int(f["uuid"]) >= seen]
                for fl in sorted(fresh, key=lambda f: int(f["uuid"])):
                    print(f"{fl['time']:.3f} {fl['Summary']}")
                    seen = max(seen, int(fl["uuid"]) + 1)
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_top(args) -> int:
    """`cilium-tpu top [-f]`: the flow analytics plane live — top
    talkers (space-saving sketch with its error bound), the
    per-identity verdict matrix over the retained windows, and the
    drop-spike state (GET /flows/aggregate)."""
    c = _client(args)
    if getattr(args, "cluster", False):
        # `top --cluster`: the relay's merged top-K (sketch sums,
        # summed error bounds, per-node scrape health)
        agg = c.cluster_top(top=args.number)
        if args.json:
            _print(agg)
            return 0
        print("Cluster top (merged across nodes; overcount <= "
              f"{agg.get('sketch-error-bound', 0)}):")
        for name, st in (agg.get("nodes") or {}).items():
            mark = "ok" if st.get("ok") else "STALE"
            print(f"  {name:<16}{mark:<7}"
                  f"windows={st.get('windows-closed')} "
                  + ("[IN SPIKE]" if st.get("spike") else ""))
        talkers = agg.get("top-talkers") or []
        if talkers:
            print(f"{'SRC':<24}{'DST':<24}{'PROTO':<7}"
                  f"{'PACKETS':>10}{'BYTES':>13}  NODES")
            for t in talkers[:args.number]:
                print(f"{t['src'] + ':' + str(t['sport']):<24}"
                      f"{t['dst'] + ':' + str(t['dport']):<24}"
                      f"{t['proto']:<7}{t['packets']:>10}"
                      f"{t['bytes']:>13}  "
                      f"{','.join(t.get('nodes', []))}")
        return 0
    try:
        while True:
            agg = c.flows_aggregate(top=args.number)
            if args.json:
                _print(agg)
            elif not agg.get("enabled"):
                print("Flow analytics: disabled "
                      "(flow-agg-enabled=false)")
            else:
                cur = agg.get("current-window") or {}
                spike = agg.get("spike") or {}
                led = agg.get("ledger") or {}
                print(f"Analytics: window {agg.get('window-s')}s x "
                      f"{agg.get('retention')} retained, "
                      f"{agg.get('windows-closed', 0)} closed, "
                      f"{led.get('packets-seen', 0)} packets seen, "
                      f"spikes {spike.get('spikes', 0)}"
                      + (" [IN SPIKE]" if spike.get("in-spike")
                         else ""))
                print(f"Window:    {cur.get('packets', 0)} packets, "
                      f"{cur.get('bytes', 0)} B, "
                      f"{cur.get('drops', 0)} drops "
                      f"(baseline {spike.get('baseline-drops')}, "
                      f"threshold >= {spike.get('min-drops')} or "
                      f"{spike.get('factor')}x)")
                talkers = agg.get("top-talkers") or []
                if talkers:
                    print(f"\nTop talkers (overcount <= "
                          f"{agg.get('sketch-error-bound', 0)}):")
                    print(f"{'SRC':<24}{'DST':<24}{'PROTO':<7}"
                          f"{'PACKETS':>10}{'BYTES':>13}{'ERR':>7}")
                    for t in talkers[:args.number]:
                        print(f"{t['src'] + ':' + str(t['sport']):<24}"
                              f"{t['dst'] + ':' + str(t['dport']):<24}"
                              f"{t['proto']:<7}{t['packets']:>10}"
                              f"{t['bytes']:>13}{t['error']:>7}")
                matrix = agg.get("matrix") or []
                if matrix:
                    print(f"\nVerdict matrix (retained windows):")
                    print(f"{'SRC-ID':<10}{'DST-ID':<10}"
                          f"{'VERDICT':<9}{'REASON':<8}"
                          f"{'PACKETS':>10}{'BYTES':>13}")
                    for m in matrix[:args.number]:
                        print(f"{m['src-identity']:<10}"
                              f"{m['dst-identity']:<10}"
                              f"{m['verdict']:<9}{m['reason']:<8}"
                              f"{m['packets']:>10}{m['bytes']:>13}")
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_sysdump(args) -> int:
    """`cilium-tpu sysdump [list]`: trigger a manual flight-recorder
    bundle (bypasses the auto rate limit) or list what the incident
    machinery has already captured."""
    c = _client(args)
    if args.action == "list":
        out = c.sysdump(trigger=False)
        if args.json:
            _print(out)
            return 0
        if not out.get("enabled"):
            print("Sysdump: disabled (run the agent with "
                  "--sysdump-dir)")
        for b in out.get("bundles", []):
            print(f"{b['name']}  {b['bytes']} B")
        for i in (out.get("incidents") or [])[-10:]:
            print(f"incident #{i['seq']} {i['kind']} "
                  f"@{i['time']:.3f}")
        return 0
    out = c.sysdump(trigger=True)
    if args.json:
        _print(out)
        return 0 if out.get("written") else 1
    written = out.get("written")
    if not written:
        # enabled but nothing written: another capture held the
        # re-entrancy guard — tell the operator instead of lying
        # "wrote None" with a zero exit
        print("no bundle written (another capture in progress; "
              "retry, or see `sysdump list`)", file=sys.stderr)
        return 1
    print(f"wrote {written}")
    st = out.get("stats") or {}
    print(f"bundles: {len(out.get('bundles', []))} on disk, "
          f"writes {st.get('writes')}, "
          f"incidents {st.get('incidents')}")
    return 0


def cmd_anomaly(args) -> int:
    action = getattr(args, "action", "stats")
    if action == "stats":
        _print(_client(args)._request("GET", "/anomaly"))
        return 0
    # offline verbs: no agent needed (BASELINE eval config #5)
    from ..ml.evaluate import (evaluate_capture, synth_labeled_capture,
                               train_and_evaluate)

    if action == "train":
        result = train_and_evaluate(n_identities=args.identities,
                                    model_out=args.model)
        _print(result)
        return 0
    if not (args.pcap and args.labels):
        print(f"usage: cilium-tpu anomaly {action} --pcap FILE "
              "--labels FILE", file=sys.stderr)
        return 1

    # synth and score MUST agree with train on the world shape —
    # identity rows index the model's embedding table, so a mismatched
    # world silently remaps identities and poisons the AUC
    from ..testing.fixtures import build_world

    world = build_world(n_identities=args.identities, n_rules=16,
                        ct_capacity=1 << 16)
    if action == "synth":
        synth_labeled_capture(args.pcap, args.labels, world,
                              n=args.number)
        print(f"wrote {args.pcap} + {args.labels}")
        return 0
    if action == "score":
        import jax

        from ..ml.model import init_params, load_model

        if args.model:
            model = load_model(args.model)
            if model.embed.shape[0] != world.row_map.capacity:
                print(f"error: model embedding rows "
                      f"({model.embed.shape[0]}) != world identity "
                      f"rows ({world.row_map.capacity}); pass the "
                      "--identities the model was trained with",
                      file=sys.stderr)
                return 1
        else:
            print("note: no --model given; scoring with an untrained "
                  "model", file=sys.stderr)
            model = init_params(jax.random.PRNGKey(0),
                                world.row_map.capacity)
        result = evaluate_capture(model, world, args.pcap, args.labels)
        _print(result)
        return 0
    return 1


# cumulative serving counters the follow mode diffs per interval
# (path into the snapshot dict -> display label)
_SERVING_RATE_KEYS = (
    (("submitted",), "submitted"),
    (("admitted",), "admitted"),
    (("shed",), "shed"),
    (("batches",), "batches"),
    (("dispatch", "dispatches"), "dispatches"),
    (("verdicts",), "verdicts"),
    (("h2d", "bytes"), "h2d-bytes"),
    (("ring", "events"), "ring-events"),
    (("event-plane", "ring-lost"), "ring-lost"),
    (("event-plane", "d2h-bytes"), "d2h-bytes"),
    (("event-plane", "windows-joined"), "windows-joined"),
    (("event-plane", "windows-dropped"), "windows-dropped"),
    (("fault-tolerance", "restarts"), "restarts"),
    (("fault-tolerance", "recovery-dropped"), "recovery-dropped"),
    (("fault-tolerance", "dispatch-timeouts"), "timeouts"),
    # map-pressure counters (datapath/pressure.py): cumulative, so
    # the follow mode renders them as per-interval rates like every
    # other counter here
    (("pressure", "ct", "insert-drops"), "ct-insert-drops"),
    (("pressure", "nat", "failures"), "nat-failures"),
)


def _pluck(st: dict, keys) -> object:
    v = st
    for k in keys:
        if not isinstance(v, dict):
            return None
        v = v.get(k)
    return v


def _counters_reset(cur: dict, prev: dict) -> bool:
    """Any cumulative counter going BACKWARD means the serving
    session restarted between ticks (stop_serving + start_serving
    zeroes them): the diff would render nonsense negative rates, so
    the follow loop resyncs with a full block instead.  The reset
    DEFINITION lives in ``obs.history`` — the one convention shared
    with the SeriesHistory ring's splice — this wrapper only plucks
    the serving rate keys."""
    from ..obs.history import counters_reset

    return counters_reset(
        (_pluck(cur, keys), _pluck(prev, keys))
        for keys, _label in _SERVING_RATE_KEYS)


def _print_serving_interval(cur: dict, prev: dict,
                            dt: float) -> None:
    """Follow-mode rendering: DIFF the cumulative counters against
    the previous sample so each tick reads as a rate, not a growing
    total (totals made chaos runs unreadable — a restart burst looks
    identical to steady state when you only see lifetime sums)."""
    parts = []
    for keys, label in _SERVING_RATE_KEYS:
        a, b = _pluck(cur, keys), _pluck(prev, keys)
        if a is None or b is None or not isinstance(a, (int, float)):
            continue
        delta = a - b
        if delta == 0 and label not in ("submitted", "verdicts"):
            continue  # quiet counters stay off the line
        parts.append(f"{label} +{delta:g} ({delta / dt:,.0f}/s)")
    print(f"[{dt:.1f}s] " + ", ".join(parts))
    q = cur.get("queue-pending", 0)
    lat = cur.get("latency-us") or {}
    mode = cur.get("mode")
    tail = (f"     queue {q}/{cur.get('queue-depth', 0)}, "
            f"p50={lat.get('p50')}us p99={lat.get('p99')}us")
    if mode:
        tail += f", mode={mode}"
    print(tail)


def cmd_serving(args) -> int:
    """`cilium-tpu serving stats [--follow]`: the serving front-end's
    live telemetry (queue depth/wait, pad efficiency, batches/sec,
    verdicts/sec, shed counters, p50/p95/p99 latency).  Follow mode
    diffs the cumulative counters per interval."""
    c = _client(args)
    prev = None
    prev_t = None
    try:
        while True:
            st = c.serving_stats()
            now = time.monotonic()
            if args.json:
                _print(st)
            elif (prev is not None and st.get("active")
                    and prev.get("active")
                    and not _counters_reset(st, prev)):
                _print_serving_interval(st, prev, max(now - prev_t,
                                                      1e-9))
            elif not st.get("active"):
                print("Serving: inactive (start_serving has not run)")
            else:
                ring = st.get("ring", {})
                print(f"Serving:   up {st.get('uptime-seconds', 0)}s, "
                      f"{st.get('batches', 0)} batches, "
                      f"{st.get('batches-per-sec', 0)}/s")
                print(f"Verdicts:  {st.get('verdicts', 0)} "
                      f"({st.get('verdicts-per-sec', 0)}/s), "
                      f"pad-efficiency {st.get('pad-efficiency')}")
                print(f"Queue:     {st.get('queue-pending', 0)}/"
                      f"{st.get('queue-depth', 0)} pending, "
                      f"admitted {st.get('admitted', 0)}, "
                      f"shed {st.get('shed', 0)} "
                      f"({st.get('shed-events', 0)} as drop events)")
                print(f"Shapes:    {st.get('batch-shapes', {})}")
                dp = st.get("dispatch") or {}
                if dp.get("superbatches"):
                    fill = dp.get("superbatch-fill")
                    print(f"Dispatch:  {dp.get('dispatches', 0)} "
                          f"dispatches, "
                          f"{dp.get('batches-per-dispatch')} "
                          f"batches/dispatch "
                          f"({dp.get('superbatches', 0)} superbatches"
                          f" {dp.get('superbatch-shapes', {})}, "
                          f"fill {'-' if fill is None else fill})")
                h2d = st.get("h2d") or {}
                if h2d.get("packed-batches") or h2d.get("wide-batches"):
                    print(f"H2D:       {h2d.get('bytes-per-packet')} "
                          f"B/packet "
                          f"({h2d.get('packed-batches', 0)} packed / "
                          f"{h2d.get('wide-batches', 0)} wide batches)")
                if st.get("shards"):
                    print(f"Shards:    {st['shards']} chips, "
                          f"route-overflow "
                          f"{st.get('route-overflow', 0)}")
                ft = st.get("fault-tolerance") or {}
                if ft.get("supervised"):
                    lad = st.get("ladder") or {}
                    mode = st.get("mode", "?")
                    flag = (" DEGRADED" if lad.get("degraded")
                            else "")
                    print(f"Fault-tol: mode={mode}{flag}, restarts "
                          f"{ft.get('restarts', 0)}/"
                          f"{ft.get('restart-budget', 0)}, "
                          f"recovery-dropped "
                          f"{ft.get('recovery-dropped', 0)} "
                          f"({ft.get('dispatch-timeouts', 0)} "
                          f"deadline hits), demotions "
                          f"{lad.get('demotions', 0)}")
                snap = st.get("ct-snapshot")
                if snap:
                    print(f"CT-snap:   {snap.get('entries', 0)} "
                          f"entries, age "
                          f"{snap.get('age-seconds', 0)}s "
                          f"({snap.get('trigger')}, "
                          f"mode {snap.get('mode')})")
                pr = st.get("pressure")
                if pr and pr.get("ct"):
                    ct = pr["ct"]
                    nat = pr.get("nat") or {}
                    occ = ct.get("occupancy")
                    flag = (" ACCELERATED (gc "
                            f"{pr.get('gc-pressure-interval-s')}s)"
                            if pr.get("accelerated") else "")
                    print(f"Pressure:  {pr.get('state', '?')}{flag}, "
                          f"ct {ct.get('occupied', 0)}/"
                          f"{ct.get('capacity', 0)} "
                          f"({'-' if occ is None else occ}), "
                          f"insert-drops {ct.get('insert-drops', 0)}"
                          f", nat-failures {nat.get('failures', 0)}, "
                          f"episodes {pr.get('episodes', 0)}")
                tb = st.get("tables")
                if tb:
                    stall = tb.get("swap-stall-us") or {}
                    vis = tb.get("update-visible-us") or {}
                    print(f"Tables:    gen {tb.get('generation', 0)}, "
                          f"{tb.get('swaps', 0)} swaps "
                          f"({tb.get('delta-attaches', 0)} delta / "
                          f"{tb.get('full-attaches', 0)} full / "
                          f"{tb.get('patches', 0)} patches), "
                          f"stall p99={_us(stall.get('p99'))} "
                          f"visible p99={_us(vis.get('p99'))} "
                          f"last {_us(tb.get('last-swap-us'))}")
                for name, key in (("Queue-wait", "queue-wait-us"),
                                  ("Latency", "latency-us")):
                    h = st.get(key) or {}
                    print(f"{name}: p50={h.get('p50')}us "
                          f"p95={h.get('p95')}us p99={h.get('p99')}us "
                          f"max={h.get('max')}us n={h.get('count')}")
                print(f"Ring:      {ring.get('windows', 0)} windows, "
                      f"{ring.get('events', 0)} events, "
                      f"{ring.get('lost', 0)} lost")
                ev = st.get("event-plane") or {}
                if ev:
                    lag = ev.get("join-lag-us") or {}
                    print(f"Event:     {ev.get('windows-joined', 0)} "
                          f"windows joined / "
                          f"{ev.get('windows-dropped', 0)} dropped "
                          f"({ev.get('queue-overflows', 0)} queue "
                          f"overflows), {ev.get('windows-pending', 0)}"
                          f"/{ev.get('queue-depth', 0)} pending, "
                          f"ring-lost {ev.get('ring-lost', 0)}")
                    bpe = ev.get("d2h-bytes-per-event")
                    print(f"           d2h "
                          f"{ev.get('d2h-bytes', 0)} B "
                          f"({'-' if bpe is None else bpe} B/event)"
                          f", join-lag p50={_us(lag.get('p50'))} "
                          f"p99={_us(lag.get('p99'))}, restarts "
                          f"{ev.get('worker-restarts', 0)}"
                          + (f" TERMINAL: {ev['error']}"
                             if ev.get("error") else ""))
            prev, prev_t = st, now
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _print_profile_state(tr: dict) -> None:
    """The jax.profiler capture-window status line — a capture can
    be armed with tracing off, so both cmd_trace branches print it."""
    prof = tr.get("profile")
    if prof:
        print(f"Profile:   {prof['state']} "
              f"({prof['batches']}/{prof['window']} "
              f"batches) -> {prof['dir']}")


def cmd_trace(args) -> int:
    """`cilium-tpu trace [-f]`: the sampled span plane — per-stage
    latency breakdown across the serving pipeline (admission ->
    dequeue -> staging -> dispatch -> device -> verdict join) plus
    the slowest-trace table and the compile-event log."""
    c = _client(args)
    try:
        while True:
            tr = c.debug_traces(limit=args.number)
            if args.json:
                _print(tr)
            elif not tr.get("enabled"):
                print("Tracing: off (start_serving(ingress=True) "
                      "with serving_trace_sample=N, or "
                      "span_sample=N)")
                comp = tr.get("compile")
                if comp:
                    print(f"Compiles:  {comp['compiles']} "
                          f"({comp['executables']} executables, "
                          f"{comp['violations']} violations)")
                _print_profile_state(tr)
            else:
                print(f"Tracing:   1-in-{tr['sample']} sampled; "
                      f"{tr['completed']} complete, "
                      f"{tr['started']} started, "
                      f"{tr['dropped']} dropped"
                      + (f", mode={tr['mode']}" if tr.get("mode")
                         else ""))
                print(f"{'STAGE':<20}{'P50us':>10}{'P95us':>10}"
                      f"{'P99us':>10}{'MAXus':>10}{'N':>8}")
                stages = tr.get("stages-us") or {}
                for name, h in stages.items():
                    print(f"{name:<20}"
                          f"{_us(h.get('p50')):>10}"
                          f"{_us(h.get('p95')):>10}"
                          f"{_us(h.get('p99')):>10}"
                          f"{_us(h.get('max')):>10}"
                          f"{h.get('count', 0):>8}")
                e2e = tr.get("e2e-us") or {}
                print(f"{'end-to-end':<20}"
                      f"{_us(e2e.get('p50')):>10}"
                      f"{_us(e2e.get('p95')):>10}"
                      f"{_us(e2e.get('p99')):>10}"
                      f"{_us(e2e.get('max')):>10}"
                      f"{e2e.get('count', 0):>8}")
                slow = tr.get("slowest") or []
                if slow:
                    print(f"\nSlowest traces:")
                    print(f"{'SEQ':<10}{'E2Eus':>10}{'BUCKET':>8}"
                          f"{'MODE':>16}{'SHARD':>7}{'DEMOTED':>9}"
                          f"  SLOWEST-STAGE")
                    for t in slow[:args.number]:
                        st = t.get("stages-us") or {}
                        worst = max(st, key=st.get) if st else ""
                        tail = (f"  {worst} ({_us(st.get(worst))}us)"
                                if worst else "")
                        shard = t.get("shard", -1)
                        print(f"{t['seq']:<10}"
                              f"{_us(t.get('e2e-us')):>10}"
                              f"{t.get('bucket', 0):>8}"
                              f"{t.get('mode', ''):>16}"
                              f"{shard if shard >= 0 else '':>7}"
                              f"{'yes' if t.get('demoted') else '':>9}"
                              + tail)
                comp = tr.get("compile") or {}
                if comp:
                    print(f"\nCompiles:  {comp['compiles']} "
                          f"({comp['executables']} executables, "
                          f"{comp['violations']} violations)")
                    for ev in (comp.get("events") or [])[-5:]:
                        print(f"  {ev['mode']:<16}"
                              f"shape={tuple(ev['shape'])} "
                              f"{ev['compile-ms']}ms"
                              + (" DUPLICATE" if ev["duplicate"]
                                 else ""))
                _print_profile_state(tr)
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _us(v) -> str:
    return "-" if v is None else f"{v:,.0f}"


def _print_slo(st: dict) -> None:
    en = "" if st.get("enabled") else " (sampler disabled)"
    print(f"Verdict:   {str(st.get('verdict', '?')).upper()}{en} — "
          f"{st.get('ticks', 0)} ticks, windows "
          f"{st.get('fast-window-s')}s/{st.get('slow-window-s')}s, "
          f"page>={st.get('page-burn')}x warn>={st.get('warn-burn')}x"
          f", resyncs {st.get('resyncs', 0)}")
    slos = st.get("slos") or {}
    if not slos:
        print("  (no evaluations yet — first tick pending)")
    else:
        print(f"  {'SLO':<26}{'STATE':<9}{'BUDGET':>8}"
              f"{'FAST-BURN':>11}{'SLOW-BURN':>11}")
        for name, ev in sorted(slos.items()):
            bud = ev.get("budget-remaining")
            fb = ev.get("fast-burn")
            sb = ev.get("slow-burn")
            bud_s = "-" if bud is None else f"{bud:.1%}"
            fb_s = "-" if fb is None else f"{fb:.2f}x"
            sb_s = "-" if sb is None else f"{sb:.2f}x"
            print(f"  {name:<26}{ev.get('state', '?'):<9}"
                  f"{bud_s:>8}{fb_s:>11}{sb_s:>11}")
    for name, ep in sorted((st.get("active") or {}).items()):
        print(f"  BURNING {name}: peak {ep.get('peak-burn')}x, "
              f"calm {ep.get('calm', 0)}/{st.get('clear-ticks')} "
              f"(since {ep.get('started-at')})")
    for e in (st.get("episodes") or [])[-3:]:
        print(f"  recovered {e.get('slo')}: "
              f"{e.get('duration-s')}s burn episode, "
              f"peak {e.get('peak-burn')}x")


def cmd_slo(args) -> int:
    """`cilium-tpu slo [-f]`: the SLO plane (ISSUE 19) — per-SLO
    multi-window burn rates, budget remaining, burn-episode state,
    and the node verdict.  Follow mode re-renders per interval."""
    c = _client(args)
    try:
        while True:
            st = c.slo()
            if args.json:
                _print(st)
            else:
                _print_slo(st)
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_history(args) -> int:
    """`cilium-tpu history [SERIES...]`: the in-process metrics
    history ring (ISSUE 19) — recent fast-tier samples per series,
    newest last; histograms render their cumulative event count.
    No Prometheus required."""
    c = _client(args)
    h = c.metrics_history(series=args.series or None,
                          since=args.since or 0.0)
    if args.json:
        _print(h)
        return 0
    fast = h.get("fast") or []
    print(f"History:   {h.get('samples', 0)} samples, "
          f"{h.get('resyncs', 0)} resyncs; fast {len(fast)}"
          f"x{h.get('interval-s')}s, slow {len(h.get('slow') or [])}"
          f" (1-in-{h.get('slow-every')})")
    recs = fast[-args.number:]
    for name in h.get("series") or []:
        vals = []
        for r in recs:
            v = (r.get("v") or {}).get(name)
            if isinstance(v, dict):
                v = v.get("count")
            vals.append("-" if v is None else f"{v:g}")
        print(f"  {name:<44}{' '.join(vals)}")
    return 0


def cmd_monitor(args) -> int:
    """Tail the flow stream (reference: `cilium monitor`)."""
    c = _client(args)
    seen = 0
    try:
        while True:
            flows = c.flows(number=500)
            fresh = [f for f in flows if int(f["uuid"]) >= seen]
            for fl in sorted(fresh, key=lambda f: int(f["uuid"])):
                print(f"{fl['time']:.3f} [{fl['event_type']['type']}] "
                      f"{fl['Summary']}")
                seen = max(seen, int(fl["uuid"]) + 1)
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_daemon(args) -> int:
    import os

    from ..agent.config import load_config
    from ..agent.daemon import Daemon
    from ..api.server import APIServer

    # resolution order (agent/config.py): defaults < --config-dir
    # files < CILIUM_TPU_* env < explicit CLI flags
    overrides = {k: v for k, v in {
        "node_name": args.node_name,
        "backend": args.backend,
        "state_dir": args.state_dir,
        "export_path": args.export,
        "anomaly_model_path": args.anomaly_model,
        "serving_queue_depth": args.serving_queue_depth,
        "serving_bucket_ladder": args.serving_bucket_ladder,
        "serving_max_wait_us": args.serving_max_wait_us,
        "serving_overflow_policy": args.serving_overflow_policy,
        "serving_packed_ingest": args.serving_packed_ingest,
        "serving_dispatch_deadline_ms":
            args.serving_dispatch_deadline_ms,
        "serving_restart_budget": args.serving_restart_budget,
        "ct_snapshot_interval": args.ct_snapshot_interval,
        "fault_injection": args.fault_injection,
        "serving_trace_sample": args.serving_trace_sample,
        "profile_dir": args.profile_dir,
        "profile_batches": args.profile_batches,
        "sysdump_dir": args.sysdump_dir,
        "flow_agg_enabled": args.flow_agg,
    }.items() if v is not None}
    cfg = load_config(config_dir=args.config_dir, **overrides)
    d = Daemon(cfg)
    if args.state_dir and d.restore(args.state_dir):
        print(f"restored state from {args.state_dir}")
    d.start()
    sock_dir = os.path.dirname(args.socket)
    if sock_dir:
        os.makedirs(sock_dir, exist_ok=True)
    server = APIServer(d, args.socket)
    server.start()
    print(f"cilium-tpu agent up — API on {args.socket}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down")
        server.stop()
        d.shutdown()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cilium-tpu",
        description="TPU-native network policy + flow analytics CLI",
    )
    parser.add_argument("--socket", default=DEFAULT_SOCKET,
                        help="agent API socket path")
    parser.add_argument("--json", action="store_true",
                        help="raw JSON output")
    sub = parser.add_subparsers(dest="cmd")

    sub.add_parser("version", help="print version")
    sub.add_parser("status", help="agent status")

    p = sub.add_parser("policy", help="policy get|import|delete")
    p.add_argument("action", choices=["get", "import", "delete"])
    p.add_argument("file", nargs="?", help="rules JSON (import)")
    p.add_argument("--labels", default="", help="labels (delete)")

    p = sub.add_parser("endpoint", help="endpoint list|get|add|delete")
    p.add_argument("action", choices=["list", "get", "add", "delete"])
    p.add_argument("id", nargs="?", type=int)
    p.add_argument("--name", default="ep")
    p.add_argument("--ip", action="append", default=[])
    p.add_argument("--label", action="append", default=[])

    sub.add_parser("identity", help="identity list")

    p = sub.add_parser("service", help="service list|upsert|delete")
    p.add_argument("action", choices=["list", "upsert", "delete"])
    p.add_argument("name", nargs="?", default="")
    p.add_argument("--frontend", help="VIP ip:port")
    p.add_argument("--backend", action="append", help="backend ip:port")

    p = sub.add_parser("fqdn", help="fqdn cache list")
    p.add_argument("action", nargs="?", default="cache",
                   choices=["cache"])

    sub.add_parser("health", help="cluster health (probe mesh)")

    p = sub.add_parser("cluster",
                       help="clustermesh serving tier: status "
                            "(membership, router, failovers, ledger)"
                            " | scale (live add_node; --down retires"
                            " one) | sysdump (all-node archive) | "
                            "trace (stitched cross-process spans) | "
                            "rotate (key-epoch rotation, live) | "
                            "slo (merged node-labeled health "
                            "verdict)")
    p.add_argument("action", nargs="?", default="status",
                   choices=["status", "scale", "sysdump", "trace",
                            "rotate", "slo"])
    p.add_argument("--down", action="store_true",
                   help="scale IN: retire one replica (drain its "
                        "send window, re-pin slots, migrate CT)")
    p.add_argument("--node",
                   help="scale --down victim (default: the "
                        "highest-index live node)")
    p.add_argument("--grace", type=float,
                   help="rotate: seconds old-epoch frames stay "
                        "openable (default cluster_epoch_grace_s)")

    p = sub.add_parser("config", help="config get | set KEY VALUE")
    p.add_argument("action", nargs="?", default="get",
                   choices=["get", "set"])
    p.add_argument("key", nargs="?")
    p.add_argument("value", nargs="?")

    p = sub.add_parser("proxy",
                       help="proxy listeners | proxy stats (L7 plane "
                            "ledger) | proxy xds (push status)")
    p.add_argument("obj", nargs="?", default="listeners",
                   choices=["listeners", "stats", "xds"])

    p = sub.add_parser("bpf", help="bpf ct list | bpf policy get ID | "
                                   "bpf ipcache list | bpf nat list | "
                                   "bpf lb list | bpf auth list")
    p.add_argument("obj", choices=["ct", "policy", "ipcache", "nat",
                                   "lb", "auth"])
    p.add_argument("action", nargs="?", default="list")
    p.add_argument("id", nargs="?", type=int, default=0)

    p = sub.add_parser("connectivity",
                       help="connectivity test (self-contained)")
    p.add_argument("action", nargs="?", default="test",
                   choices=["test"])
    p.add_argument("--backend", default="interpreter",
                   choices=["interpreter", "tpu"])

    p = sub.add_parser("encrypt", help="encrypt status")
    p.add_argument("action", nargs="?", default="status",
                   choices=["status"])

    sub.add_parser("egress", help="egress-gateway rules (expanded)")
    sub.add_parser("map", help="list datapath maps")
    p = sub.add_parser("metrics", help="prometheus metrics")
    p.add_argument("--cluster", action="store_true",
                   help="the relay's merged cluster exposition "
                        "(every series node-labelled)")

    p = sub.add_parser("flows", help="recent flows (hubble observe); "
                                     "-f tails, filters share the "
                                     "`top` vocabulary")
    p.add_argument("--cluster", action="store_true",
                   help="merged time-ordered flows from every "
                        "cluster node (node_name stamped)")
    p.add_argument("--number", type=int, default=20)
    p.add_argument("--verdict", type=int)
    p.add_argument("--port", type=int)
    p.add_argument("--protocol", type=int)
    p.add_argument("--identity", type=int,
                   help="the flow's remote security identity "
                        "(numeric)")
    p.add_argument("--since", type=float,
                   help="only flows from the last SECONDS")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)

    p = sub.add_parser("top",
                       help="live top talkers + per-identity verdict "
                            "matrix + drop-spike state (the flow "
                            "analytics plane)")
    p.add_argument("--cluster", action="store_true",
                   help="top-K merged across every cluster node "
                        "(sketch sums + summed error bounds)")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--number", type=int, default=10,
                   help="rows per table")

    p = sub.add_parser("sysdump",
                       help="trigger a flight-recorder bundle | "
                            "sysdump list")
    p.add_argument("action", nargs="?", default="capture",
                   choices=["capture", "list"])

    p = sub.add_parser("monitor", help="tail the event stream")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)

    p = sub.add_parser("serving",
                       help="serving front-end stats (queue, batches, "
                            "sheds, latency percentiles); follow "
                            "mode diffs counters per interval")
    p.add_argument("action", nargs="?", default="stats",
                   choices=["stats"])
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)

    p = sub.add_parser("trace",
                       help="sampled per-packet traces: per-stage "
                            "latency breakdown, slowest-trace table, "
                            "compile-event log")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--number", type=int, default=10,
                   help="traces to show in the slowest table")

    p = sub.add_parser("slo",
                       help="the SLO plane: per-SLO multi-window "
                            "burn rates, budget remaining, burn "
                            "episodes, node verdict")
    p.add_argument("--follow", "-f", action="store_true")
    p.add_argument("--interval", type=float, default=1.0)

    p = sub.add_parser("history",
                       help="in-process metrics history ring: "
                            "recent samples per declared series "
                            "(10s fast tier + 5min slow tier)")
    p.add_argument("series", nargs="*",
                   help="series names (default: every declared "
                        "history series)")
    p.add_argument("--since", type=float, default=0.0,
                   help="only samples from the last SECONDS")
    p.add_argument("--number", type=int, default=12,
                   help="fast-tier samples to render per series")

    p = sub.add_parser("anomaly", help="anomaly stats | train | synth "
                                       "| score (pcap evaluation)")
    p.add_argument("action", nargs="?", default="stats",
                   choices=["stats", "train", "synth", "score"])
    p.add_argument("--pcap", help="capture file")
    p.add_argument("--labels", help="label sidecar (.npz or CIC .csv)")
    p.add_argument("--model", help="AnomalyModel .npz path")
    p.add_argument("--number", type=int, default=65536,
                   help="packets for synth")
    p.add_argument("--identities", type=int, default=1024,
                   help="world size; must match across train/synth/"
                        "score (identity rows index the embedding)")

    p = sub.add_parser("daemon", help="run the agent")
    p.add_argument("action", choices=["run"])
    p.add_argument("--config-dir",
                   help="one-file-per-key config dir (the mounted "
                        "cilium-config ConfigMap layout); CLI flags "
                        "override it, CILIUM_TPU_* env between")
    p.add_argument("--backend", default=None,
                   choices=["tpu", "interpreter"])
    p.add_argument("--node-name", default=None)
    p.add_argument("--state-dir")
    p.add_argument("--export", help="flow export JSONL path")
    p.add_argument("--anomaly-model", help="trained AnomalyModel .npz")
    p.add_argument("--serving-queue-depth", type=int, default=None,
                   help="serving admission queue capacity in packets "
                        "(default 65536); overflow sheds by "
                        "--serving-overflow-policy and is counted as "
                        "monitor drop events")
    p.add_argument("--serving-bucket-ladder", default=None,
                   help="comma-separated power-of-two batch buckets, "
                        "ascending (default 1024,4096,16384,65536); "
                        "each distinct bucket is one JIT-compiled "
                        "shape, so the ladder bounds recompiles")
    p.add_argument("--serving-max-wait-us", type=float, default=None,
                   help="max microseconds a queued packet waits before "
                        "a partial bucket flushes (default 2000); "
                        "bounds tail latency at low load")
    p.add_argument("--serving-overflow-policy", default=None,
                   choices=["drop-tail", "drop-oldest"],
                   help="admission shed policy when the queue is full "
                        "(default drop-tail: arriving overflow sheds; "
                        "drop-oldest evicts stale queued rows)")
    p.add_argument("--serving-packed-ingest", default=None,
                   choices=["true", "false"],
                   help="ship eligible IPv4 single-stream batches as "
                        "the packed 16 B/packet h2d wire format (4x "
                        "fewer bytes than wide rows; IPv6/mixed "
                        "streams fall back to wide per batch); "
                        "'false' overrides a config-dir/env true")
    p.add_argument("--serving-dispatch-deadline-ms", type=float,
                   default=None,
                   help="per-batch dispatch deadline in ms (default "
                        "1000): a dispatch exceeding it is declared "
                        "hung, its rows counted as DISPATCH_TIMEOUT "
                        "drops, and the drain loop restarted; 0 "
                        "disables hang detection")
    p.add_argument("--serving-restart-budget", type=int, default=None,
                   help="drain-loop restarts the serving watchdog "
                        "may spend before going terminal (default "
                        "8; 0 disables supervision)")
    p.add_argument("--ct-snapshot-interval", type=float, default=None,
                   help="periodic CT snapshot cadence in seconds "
                        "(default 0 = only on demotion/checkpoint); "
                        "recovery restores established flows from "
                        "the last snapshot when the live CT is "
                        "unreadable")
    p.add_argument("--fault-injection", default=None,
                   help="deterministic fault-injection spec "
                        "(infra/faults.py), e.g. "
                        "'serving.dispatch=1x1~0.3'; chaos testing "
                        "only")
    p.add_argument("--serving-trace-sample", type=int, default=None,
                   help="sample 1-in-N admitted packets with a "
                        "per-packet trace span (six-stage latency "
                        "breakdown via GET /debug/traces and "
                        "`cilium-tpu trace`); default 0 = off = "
                        "zero overhead")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the first "
                        "--profile-batches serving dispatches into "
                        "this directory (TensorBoard/Perfetto "
                        "viewable), then stop")
    p.add_argument("--profile-batches", type=int, default=None,
                   help="profile capture window length in batches "
                        "(default 16)")
    p.add_argument("--sysdump-dir", default=None,
                   help="incident flight-recorder bundle directory: "
                        "drop-spike / watchdog-restart / "
                        "ladder-demotion / terminal-event-worker / "
                        "manual incidents each capture a bounded "
                        "JSON sysdump here (retention-capped); "
                        "unset = incidents recorded, no bundles")
    p.add_argument("--flow-agg", default=None,
                   choices=["true", "false"],
                   help="flow analytics plane (windowed per-identity "
                        "aggregation, top-K talkers, drop-spike "
                        "detection; runs off the dispatch path on "
                        "the event-join worker; default true)")

    args = parser.parse_args(argv)
    if args.cmd == "version":
        from .. import __version__

        print(f"cilium-tpu {__version__}")
        return 0
    try:
        handler = {
            "status": cmd_status, "policy": cmd_policy,
            "endpoint": cmd_endpoint, "identity": cmd_identity,
            "bpf": cmd_bpf, "map": cmd_map, "metrics": cmd_metrics,
            "flows": cmd_flows, "monitor": cmd_monitor,
            "top": cmd_top, "sysdump": cmd_sysdump,
            "serving": cmd_serving, "trace": cmd_trace,
            "slo": cmd_slo, "history": cmd_history,
            "anomaly": cmd_anomaly, "daemon": cmd_daemon,
            "service": cmd_service, "fqdn": cmd_fqdn,
            "health": cmd_health, "cluster": cmd_cluster,
            "config": cmd_config,
            "proxy": cmd_proxy,
            "egress": cmd_egress,
            "encrypt": cmd_encrypt,
            "connectivity": cmd_connectivity,
        }.get(args.cmd)
        if handler is None:
            parser.print_help()
            return 1
        return handler(args)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except ConnectionRefusedError:
        print(f"error: agent not reachable on {args.socket} "
              "(start one: cilium-tpu daemon run)", file=sys.stderr)
        return 1
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
