"""cilium-tpu CLI (reference: cilium/cmd cobra CLI).

Verbs mirror the reference operator tooling: ``policy import|get``,
``endpoint list``, ``bpf policy get``, ``bpf ct list``, ``monitor``,
``status``.  Grows alongside the agent; verbs not yet wired report so
explicitly instead of failing cryptically.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cilium-tpu",
        description="TPU-native network policy + flow analytics CLI",
    )
    sub = parser.add_subparsers(dest="cmd")
    sub.add_parser("status", help="agent status")
    sub.add_parser("version", help="print version")
    args = parser.parse_args(argv)
    if args.cmd == "version":
        from .. import __version__
        print(f"cilium-tpu {__version__}")
        return 0
    if args.cmd == "status":
        print("agent: not running (standalone CLI) — see cilium_tpu.api")
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
