"""Distributed identity allocation over the kvstore.

Reference: upstream cilium ``pkg/allocator`` + ``pkg/kvstore/allocator``
— cluster-wide collision-free numeric IDs via an etcd protocol:

- master key   ``id/<numeric>`` -> label key (created create-only; the
  atomic claim that makes allocation collision-free)
- node ref     ``value/<labels>/<node>`` -> numeric (leased; a node's
  liveness reference — when every node's lease expires the identity is
  garbage, swept by the operator)

TPU-first framing: the kvstore is the control-plane consistency axis
(SURVEY.md §2c "cluster-wide consistency"); every agent replays the
``id/`` prefix into its local allocator, whose observers patch device
tensors incrementally — the identity tensor IS the replicated state.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..identity.identity import MAX_ALLOCATED, MIN_ALLOCATED
from ..labels import LabelSet
from .store import InMemoryKVStore, KVEvent

DEFAULT_PREFIX = "cilium/state/identities/v1"


class KVStoreAllocatorBackend:
    """The ``backend.allocate(key)`` hook for CachingIdentityAllocator,
    speaking the id/ + value/ kvstore protocol."""

    def __init__(self, kv: InMemoryKVStore, node: str = "node0",
                 prefix: str = DEFAULT_PREFIX,
                 min_id: int = MIN_ALLOCATED,
                 max_id: int = MAX_ALLOCATED,
                 lease_ttl: Optional[float] = None):
        self.kv = kv
        self.node = node
        self.prefix = prefix.rstrip("/")
        self.min_id = min_id
        self.max_id = max_id
        self.lease_ttl = lease_ttl
        self._lock = threading.Lock()

    def _id_key(self, num: int) -> str:
        return f"{self.prefix}/id/{num}"

    def _value_prefix(self, key: str) -> str:
        return f"{self.prefix}/value/{key}/"

    def allocate(self, key: str) -> int:
        """Return the cluster-wide numeric id for a label key —
        reusing the existing id when one exists, claiming a fresh one
        (create-only on the master key) otherwise."""
        # reuse path 1: a node currently references this key
        existing = self.kv.list_prefix(self._value_prefix(key))
        for _, raw in existing.items():
            num = int(raw)
            self.kv.update(self._value_prefix(key) + self.node,
                           raw, lease_ttl=self.lease_ttl)
            return num
        # reuse path 2: an unreferenced MASTER key still maps this
        # label set (all node refs released but identity GC has not
        # swept it) — minting a fresh id here would make nodes that
        # replayed the master disagree on the numeric
        for id_key, raw in self.kv.list_prefix(
                f"{self.prefix}/id/").items():
            if raw.decode() == key:
                num = int(id_key.rsplit("/", 1)[1])
                self.kv.update(self._value_prefix(key) + self.node,
                               str(num).encode(),
                               lease_ttl=self.lease_ttl)
                return num
        # claim path: race create-only on successive candidate ids
        # (reference: pkg/allocator selects a random free id and
        # retries on conflict; sequential probing is equivalent under
        # the same atomicity and deterministic for tests)
        num = self._first_free()
        while num < self.max_id:
            if self.kv.create_only(self._id_key(num), key.encode()):
                self.kv.update(self._value_prefix(key) + self.node,
                               str(num).encode(),
                               lease_ttl=self.lease_ttl)
                return num
            num += 1
        raise RuntimeError("identity space exhausted")

    def _first_free(self) -> int:
        used = self.kv.list_prefix(f"{self.prefix}/id/")
        nums = [int(k.rsplit("/", 1)[1]) for k in used]
        return max(nums) + 1 if nums else self.min_id

    def ref(self, key: str, num: int) -> None:
        """Write this node's reference for an id learned by watch
        replay (a replayed master key conveys no liveness; the first
        local use must take a ref or identity GC could sweep an id
        this node actively enforces with)."""
        self.kv.update(self._value_prefix(key) + self.node,
                       str(num).encode(), lease_ttl=self.lease_ttl)

    def release(self, key: str) -> None:
        """Drop this node's reference (master key stays; identity GC —
        the operator's job in the reference — sweeps orphans)."""
        self.kv.delete(self._value_prefix(key) + self.node)

    def gc(self) -> int:
        """Operator-style sweep: delete master keys with no node refs.
        Returns the number of identities collected."""
        n = 0
        for id_key, raw in self.kv.list_prefix(
                f"{self.prefix}/id/").items():
            key = raw.decode()
            if not self.kv.list_prefix(self._value_prefix(key)):
                if self.kv.delete(id_key):
                    n += 1
        return n


class ClusterIdentitySync:
    """Watch the id/ prefix and replay remote allocations into the
    local allocator (the ClusterMesh identity-replication analogue).

    A remote agent's allocation appears as an ``id/<n>`` create; the
    local allocator registers it under the SAME numeric id
    (restore_identity), its observers fire, and the incremental patch
    path updates the device tensors — remote identity churn costs this
    node one row patch, no recompile."""

    def __init__(self, kv: InMemoryKVStore, allocator,
                 prefix: str = DEFAULT_PREFIX):
        self.prefix = prefix.rstrip("/")
        self._allocator = allocator
        self._cancel = kv.watch_prefix(f"{self.prefix}/id/",
                                       self._on_event, replay=True)

    def _on_event(self, ev: KVEvent) -> None:
        if ev.kind == "delete":
            return  # master-key GC; local release is refcount-driven
        num = int(ev.key.rsplit("/", 1)[1])
        labels = LabelSet.parse(
            *[s for s in ev.value.decode().split(";") if s])
        if self._allocator.lookup_by_id(num) is None:
            self._allocator.restore_identity(num, labels)

    def close(self) -> None:
        self._cancel()
