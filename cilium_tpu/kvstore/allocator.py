"""Distributed identity allocation over the kvstore.

Reference: upstream cilium ``pkg/allocator`` + ``pkg/kvstore/allocator``
— cluster-wide collision-free numeric IDs via an etcd protocol:

- master key   ``id/<numeric>`` -> label key (created create-only; the
  atomic claim that makes allocation collision-free)
- node ref     ``value/<labels>/<node>`` -> numeric (leased; a node's
  liveness reference — when every node's lease expires the identity is
  garbage, swept by the operator)

TPU-first framing: the kvstore is the control-plane consistency axis
(SURVEY.md §2c "cluster-wide consistency"); every agent replays the
``id/`` prefix into its local allocator, whose observers patch device
tensors incrementally — the identity tensor IS the replicated state.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

from ..identity.identity import MAX_ALLOCATED, MIN_ALLOCATED
from ..labels import LabelSet
from .store import InMemoryKVStore, KVEvent

DEFAULT_PREFIX = "cilium/state/identities/v1"


class KVStoreAllocatorBackend:
    """The ``backend.allocate(key)`` hook for CachingIdentityAllocator,
    speaking the id/ + value/ kvstore protocol."""

    def __init__(self, kv: InMemoryKVStore, node: str = "node0",
                 prefix: str = DEFAULT_PREFIX,
                 min_id: int = MIN_ALLOCATED,
                 max_id: int = MAX_ALLOCATED,
                 lease_ttl: Optional[float] = None):
        self.kv = kv
        self.node = node
        self.prefix = prefix.rstrip("/")
        self.min_id = min_id
        self.max_id = max_id
        self.lease_ttl = lease_ttl
        self._lock = threading.Lock()
        # Local mirror of the id/ prefix, maintained by watch: one
        # subscription replaces the per-allocation prefix scans the
        # reference avoids the same way (pkg/allocator caches the id
        # space in its idpool).  Over a networked store this turns
        # allocation from O(identities) round trips into O(1).
        self._key_by_id: dict = {}
        self._id_by_key: dict = {}
        self._held: set = set()  # ref keys this node wrote (keepalive)
        self._cancel = kv.watch_prefix(f"{self.prefix}/id/",
                                       self._on_id_event, replay=True)

    def _on_id_event(self, ev: KVEvent) -> None:
        try:
            num = int(ev.key.rsplit("/", 1)[1])
        except ValueError:
            return
        with self._lock:
            if ev.kind == "delete":
                old = self._key_by_id.pop(num, None)
                if old is not None and self._id_by_key.get(old) == num:
                    del self._id_by_key[old]
            else:
                key = ev.value.decode()
                self._key_by_id[num] = key
                self._id_by_key[key] = num

    def close(self) -> None:
        self._cancel()

    def _id_key(self, num: int) -> str:
        return f"{self.prefix}/id/{num}"

    def _value_prefix(self, key: str) -> str:
        return f"{self.prefix}/value/{key}/"

    def allocate(self, key: str) -> int:
        """Return the cluster-wide numeric id for a label key —
        reusing the existing id when one exists, claiming a fresh one
        (create-only on the master key) otherwise."""
        while True:
            # reuse path 1: a node currently references this key
            existing = self.kv.list_prefix(self._value_prefix(key))
            for _, raw in existing.items():
                return self._adopt(key, int(raw))
            # reuse path 2: an unreferenced MASTER key still maps this
            # label set (all node refs released but identity GC has not
            # swept it) — minting a fresh id here would make nodes
            # that replayed the master disagree on the numeric.  The
            # local mirror is the index; the store read re-verifies it
            # (the mirror can lag a GC delete over a networked
            # transport).
            with self._lock:
                hint = self._id_by_key.get(key)
            if hint is not None:
                raw = self.kv.get(self._id_key(hint))
                if raw is not None and raw.decode() == key:
                    return self._adopt(key, hint)
            num = self._claim(key)
            if num is not None:
                return num
            # fencing breach (lock lease expired mid-claim): retry —
            # the rescan adopts whatever master the interim winner
            # minted, or re-mints

    def _adopt(self, key: str, num: int) -> int:
        """Take this node's ref on an existing id, then repair the
        master key if identity GC swept it in the meantime
        (reference: pkg/allocator recreateMasterKey).  REF FIRST: once
        the ref exists, gc() (which only sweeps masters with zero
        refs) can no longer race the repair."""
        ref_key = self._value_prefix(key) + self.node
        self.kv.update(ref_key, str(num).encode(),
                       lease_ttl=self.lease_ttl)
        self.kv.create_only(self._id_key(num), key.encode())
        with self._lock:
            self._held.add(ref_key)
        return num

    def _claim(self, key: str) -> Optional[int]:
        """Mint (or adopt) the master key for ``key`` under the
        per-key cluster lock.  Returns None on a fencing breach (the
        caller retries).

        The lock (reference: pkg/kvstore LockPath around
        pkg/allocator claims) serializes same-key minting: without
        it, two nodes whose watch mirrors lag differently can each
        miss the other's freshly-minted master and claim DIFFERENT
        numerics for one label set.  Inside the lock one
        authoritative prefix scan replaces the mirror (the scan is
        O(identities) but only fresh mints pay it; reuse hits stay
        O(1))."""
        lock_key = f"{self.prefix}/locks/{key}"
        # unique token per ACQUISITION: the bare node name would make
        # the fencing check / release match a different acquisition by
        # another thread of this same daemon
        me = f"{self.node}:{uuid.uuid4().hex}".encode()
        ttl = self.lease_ttl if self.lease_ttl is not None else 10.0
        deadline = time.time() + 4 * ttl
        while not self.kv.create_only(lock_key, me, lease_ttl=ttl):
            if time.time() > deadline:
                raise TimeoutError(f"allocator lock stuck: {lock_key}")
            time.sleep(0.005)
        try:
            for id_key, raw in self.kv.list_prefix(
                    f"{self.prefix}/id/").items():
                if raw.decode() == key:
                    return self._adopt(key, int(id_key.rsplit("/", 1)[1]))
            num = self._first_free()
            while num < self.max_id:
                # create_only still arbitrates cross-KEY races (two
                # nodes minting different label sets probe the same
                # candidate); same-key races are excluded by the lock
                if self.kv.create_only(self._id_key(num), key.encode()):
                    if self.kv.get(lock_key) != me:
                        # Fencing: our lock lease expired before the
                        # mint — another same-key claimant may have
                        # minted concurrently.  Undo — but never
                        # delete a master another node has already
                        # adopted (its live ref would point at a
                        # numeric invisible to scans/GC, and the slot
                        # could be re-minted for a different key).
                        if self._ref_exists(key, num):
                            return self._adopt(key, num)
                        self.kv.delete(self._id_key(num))
                        if self._ref_exists(key, num):
                            # adopted during the delete window:
                            # resurrect the master (recreateMasterKey)
                            return self._adopt(key, num)
                        return None
                    ref_key = self._value_prefix(key) + self.node
                    self.kv.update(ref_key, str(num).encode(),
                                   lease_ttl=self.lease_ttl)
                    with self._lock:
                        self._held.add(ref_key)
                    return num
                cur = self.kv.get(self._id_key(num))
                if cur is not None:
                    if cur.decode() == key:
                        # Our own mint surfaced as a conflict: a
                        # concurrent ref() repair re-created it, or a
                        # RemoteKVStore retry-after-reconnect applied
                        # the create server-side and replayed False.
                        # Probing onward would mint a SECOND master
                        # for this label set — adopt instead.
                        return self._adopt(key, num)
                    with self._lock:  # learn the foreign conflict
                        self._key_by_id.setdefault(num, cur.decode())
                # cur None means created-and-GC'd: just move on
                num = self._first_free(num + 1)
            raise RuntimeError("identity space exhausted")
        finally:
            # release only OUR acquisition: compare-and-delete (a
            # get-then-delete could remove the lock a successor
            # acquired after our lease expired)
            if hasattr(self.kv, "delete_if"):
                self.kv.delete_if(lock_key, me)
            elif self.kv.get(lock_key) == me:
                self.kv.delete(lock_key)

    def refresh_refs(self) -> int:
        """Keepalive every value ref this node holds (the etcd lease
        heartbeat analogue); driven by the daemon's identity-keepalive
        controller when refs are leased.  Iterates the locally-held
        ref set — O(own refs), no cluster-wide prefix scan."""
        if self.lease_ttl is None:
            return 0
        with self._lock:
            held = list(self._held)
        n = 0
        for ref_key in held:
            if self.kv.keepalive(ref_key, self.lease_ttl):
                n += 1
            else:  # expired or released elsewhere: stop tracking
                with self._lock:
                    self._held.discard(ref_key)
        return n

    def _ref_exists(self, key: str, num: int) -> bool:
        return any(int(raw) == num for raw in
                   self.kv.list_prefix(self._value_prefix(key)).values())

    def _first_free(self, start: Optional[int] = None) -> int:
        """Lowest id ≥ start not in the local mirror — GC'd holes are
        reused instead of growing max+1 forever."""
        num = self.min_id if start is None else max(start, self.min_id)
        with self._lock:
            while num in self._key_by_id:
                num += 1
        return num

    def ref(self, key: str, num: int) -> None:
        """Write this node's reference for an id learned by watch
        replay (a replayed master key conveys no liveness; the first
        local use must take a ref or identity GC could sweep an id
        this node actively enforces with).  Repairs a missing master
        on the way (recreateMasterKey analogue)."""
        self._adopt(key, num)

    def release(self, key: str) -> None:
        """Drop this node's reference (master key stays; identity GC —
        the operator's job in the reference — sweeps orphans)."""
        ref_key = self._value_prefix(key) + self.node
        with self._lock:
            self._held.discard(ref_key)
        self.kv.delete(ref_key)

    def gc(self) -> int:
        """Operator-style sweep: delete master keys with no node refs.
        Returns the number of identities collected."""
        n = 0
        for id_key, raw in self.kv.list_prefix(
                f"{self.prefix}/id/").items():
            key = raw.decode()
            if not self.kv.list_prefix(self._value_prefix(key)):
                if self.kv.delete(id_key):
                    n += 1
        return n


class ClusterIdentitySync:
    """Watch the id/ prefix and replay remote allocations into the
    local allocator (the ClusterMesh identity-replication analogue).

    A remote agent's allocation appears as an ``id/<n>`` create; the
    local allocator registers it under the SAME numeric id
    (restore_identity), its observers fire, and the incremental patch
    path updates the device tensors — remote identity churn costs this
    node one row patch, no recompile."""

    def __init__(self, kv: InMemoryKVStore, allocator,
                 prefix: str = DEFAULT_PREFIX):
        self.prefix = prefix.rstrip("/")
        self._allocator = allocator
        self._cancel = kv.watch_prefix(f"{self.prefix}/id/",
                                       self._on_event, replay=True)

    def _on_event(self, ev: KVEvent) -> None:
        num = int(ev.key.rsplit("/", 1)[1])
        if ev.kind == "delete":
            # identity GC swept the master: drop the unreferenced
            # local replica, or a reused numeric (hole reuse) would
            # keep its STALE labels here while the cluster rebinds it
            # (ABA) — locally-referenced identities stay (refcount-
            # driven release)
            self._allocator.watch_remove(num)
            return
        labels = LabelSet.parse(
            *[s for s in ev.value.decode().split(";") if s])
        self._allocator.watch_update(num, labels)

    def close(self) -> None:
        self._cancel()
