"""Warm-standby replication + failover for the networked kvstore.

Reference: upstream cilium's availability story for cluster state is
etcd raft.  DIVERGENCES #14 deliberately keeps a single leader here;
this module adds the availability layer around it: a
:class:`WarmStandby` seeds itself from the primary's ``snapshot`` op
(data + revisions + remaining lease TTLs), tails the primary's watch
stream (every mutation replays into the standby's own store), and
polls ``lease_dump`` so keepalives — which extend leases WITHOUT
emitting watch events — keep the standby's lease copies alive.
Clients carry a failover address list (``RemoteKVStore`` walks it on
every re-dial), so killing the primary lands them on the standby with
their watches re-subscribed and replayed.

Divergence vs raft (documented, deliberate): replication is
asynchronous — a write acknowledged by the primary in the instant
before it dies can be lost.  The allocator's claim discipline
(create-only + write-then-verify + lease fencing) re-converges after
failover; what raft would add is durability of that last instant, not
correctness of the survivors.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .remote import KVStoreServer, RemoteKVStore
from .store import InMemoryKVStore, KVEvent

__all__ = ["WarmStandby"]


class WarmStandby:
    """A live KVStoreServer mirroring a primary until it dies.

    The standby SERVES from birth (clients only dial it once the
    primary stops answering, so pre-failover staleness is invisible);
    ``promoted`` flips when replication loses the primary for longer
    than ``grace`` seconds, after which the standby is authoritative.
    """

    def __init__(self, primary_address, path: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lease_poll: float = 0.2, grace: float = 1.0,
                 lease_tick: float = 0.2):
        self.store = InMemoryKVStore()
        self.server = KVStoreServer(self.store, path=path, host=host,
                                    port=port, lease_tick=lease_tick)
        self.address = self.server.address
        self.promoted = False
        self._closed = False
        self._grace = grace
        self._lease_poll = lease_poll
        # the replication client's timeouts bound promotion latency:
        # a dead primary must fail lease_dump within ~grace, not a
        # 5 s dial budget (first dial still gets a real budget via
        # the constructor's blocking snapshot call)
        self._repl = RemoteKVStore(primary_address,
                                   dial_timeout=max(grace, 0.2),
                                   call_timeout=max(grace, 0.5),
                                   reconnect=True, max_backoff=0.2)
        # subscribe FIRST (replay=False), buffering events, then seed
        # from the snapshot, then apply the buffer — no mutation can
        # fall between the snapshot and the watch subscription
        self._buffer: list = []
        self._buffering = True
        self._buf_lock = threading.Lock()
        # per-key applied revision: the buffer drain (main thread) can
        # interleave with live dispatch; an older event must never
        # clobber a newer applied state (create rev5 after delete rev7
        # would resurrect the key)
        self._key_rev: dict = {}
        self._repl.watch_prefix("", self._apply, replay=False)
        snap = self._repl.snapshot()
        now = time.time()
        with self.store._lock:
            for k, (v, rev) in snap["data"].items():
                self.store._data[k] = (v, rev)
            for k, ttl in snap["leases"].items():
                self.store._leases[k] = now + ttl
            self.store._revision = max(self.store._revision,
                                       snap["revision"])
        with self._buf_lock:
            buffered, self._buffering = self._buffer, False
            self._buffer = []
        for ev in buffered:
            if ev.revision > snap["revision"]:
                self._apply(ev)
        threading.Thread(target=self._lease_loop, daemon=True).start()

    # -- replication ---------------------------------------------------
    def _apply(self, ev: KVEvent) -> None:
        if self.promoted or self._closed:
            return
        with self._buf_lock:
            if self._buffering:
                self._buffer.append(ev)
                return
        with self.store._lock:
            if ev.revision <= self._key_rev.get(ev.key, 0):
                return
            self._key_rev[ev.key] = ev.revision
            if ev.kind == "delete":
                self.store._data.pop(ev.key, None)
                self.store._leases.pop(ev.key, None)
            else:
                self.store._data[ev.key] = (ev.value, ev.revision)
                if ev.ttl is not None:
                    self.store._leases[ev.key] = time.time() + ev.ttl
            self.store._revision = max(self.store._revision,
                                       ev.revision)

    def _lease_loop(self) -> None:
        last_ok = time.time()
        while not self._closed and not self.promoted:
            time.sleep(self._lease_poll)
            try:
                leases = self._repl.lease_dump()
                last_ok = time.time()
            except (ConnectionError, TimeoutError, RuntimeError):
                if time.time() - last_ok > self._grace:
                    self.promote()
                continue
            now = time.time()
            with self.store._lock:
                for k, ttl in leases.items():
                    if k in self.store._data:
                        self.store._leases[k] = now + ttl

    # -- lifecycle -----------------------------------------------------
    def promote(self) -> None:
        """Become authoritative: stop replicating, keep serving."""
        if self.promoted:
            return
        self.promoted = True
        self._repl.close()

    def close(self) -> None:
        self._closed = True
        self._repl.close()
        self.server.close()
