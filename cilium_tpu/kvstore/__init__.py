"""kvstore: cluster state store with watches (etcd analogue).

Reference: upstream cilium ``pkg/kvstore`` — the etcd client behind
identity allocation, node discovery, and ClusterMesh, with the
``store`` shared-store pattern (watch a prefix, mirror into memory).

The in-memory backend serves a single host (tests, single-node runs);
the same interface backs the multi-host store when processes join via
``jax.distributed`` (one process elected writer; replicas mirror by
watch replay — the ClusterMesh analogue).
"""

from .allocator import (  # noqa: F401
    ClusterIdentitySync,
    KVStoreAllocatorBackend,
)
from .store import InMemoryKVStore, KVEvent, SharedStore  # noqa: F401
