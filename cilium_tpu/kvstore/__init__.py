"""kvstore: cluster state store with watches (etcd analogue).

Reference: upstream cilium ``pkg/kvstore`` — the etcd client behind
identity allocation, node discovery, and ClusterMesh, with the
``store`` shared-store pattern (watch a prefix, mirror into memory).

The in-memory backend serves a single process (tests, single-node
runs); ``KVStoreServer``/``RemoteKVStore`` (remote.py) serve the SAME
interface over a unix/TCP socket so separate OS processes — agents,
the operator, remote clusters — share one store the way the
reference's components share etcd.
"""

from .allocator import (  # noqa: F401
    ClusterIdentitySync,
    KVStoreAllocatorBackend,
)
from .remote import KVStoreServer, RemoteKVStore  # noqa: F401
from .store import InMemoryKVStore, KVEvent, SharedStore  # noqa: F401
