"""Networked kvstore transport: the in-memory store served over a
socket.

Reference: upstream cilium ``pkg/kvstore/etcd.go`` — every distributed
subsystem (identity allocator, ClusterMesh, operator, node registry,
IPAM) talks to etcd over the network with watches, leases, and
create-only transactions.  Here the SAME protocol surface that
``InMemoryKVStore`` exposes in-process (get/update/create_only/delete/
list_prefix/keepalive/watch_prefix, revisions, lease TTLs) is served
over a unix or TCP socket by :class:`KVStoreServer` and consumed
through :class:`RemoteKVStore`, a drop-in client: the allocator,
clustermesh, operator and health registry run UNCHANGED against it —
the proof that the protocol layer was transport-agnostic.

Wire format: newline-delimited JSON frames (values base64).

- request   ``{"i": n, "op": "...", ...args}``
- response  ``{"i": n, "r": <result>}`` or ``{"i": n, "e": "msg"}``
- watch push ``{"w": wid, "k": kind, "key": k, "v": b64, "rev": n}``
- watch batch ``{"wb": [push, push, ...]}`` — CONSECUTIVE watch
  pushes found in one writer-drain are coalesced into one frame
  (ISSUE 17 — the cluster data channel's coalesced-ack idea applied
  to watch fan-out: a policy publish fanning to N watchers pays one
  syscall + one frame per drain, not one per event).  Only adjacent
  pushes merge, so ordering against responses is preserved; a lone
  push keeps the PR 8 single-frame format byte-identical.

The client reconnects with backoff on connection loss and re-subscribes
its watches with replay (consumers are idempotent: allocator mirrors,
``watch_update``, SharedStore).  Server-side lease expiry runs on a
ticker so a crashed client's leases die even when the store is idle —
the failure-detection path the reference gets from etcd lease expiry.
"""

from __future__ import annotations

import base64
import json
import os
import queue
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

# the framing + close discipline is shared with the cluster serving
# transport (cluster/transport.py, ISSUE 13): one LineFramer for
# newline-delimited JSON reassembly, one shutdown-before-close
# definition (the PR 8 close-vs-blocked-syscall fix) for every socket
from ..cluster.transport import LineFramer, shutdown_close
from .store import InMemoryKVStore, KVEvent, Watcher

__all__ = ["KVStoreServer", "RemoteKVStore"]


def _enc(value: bytes) -> str:
    return base64.b64encode(value).decode("ascii")


def _dec(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


class _Conn:
    """One client connection on the server: a reader loop dispatching
    ops + a writer thread draining an outbound queue (watch events are
    pushed from store-mutation threads and must never block the store
    lock on a slow client socket)."""

    def __init__(self, server: "KVStoreServer", sock: socket.socket):
        self.server = server
        self.sock = sock
        self._out: list = []
        self._out_lock = threading.Lock()
        self._out_ready = threading.Event()
        self._closed = False
        self._watches: Dict[int, Callable[[], None]] = {}
        threading.Thread(target=self._read_loop, daemon=True).start()
        threading.Thread(target=self._write_loop, daemon=True).start()

    def _send(self, obj: dict) -> None:
        # objects, not bytes: the writer decides the framing at drain
        # time (consecutive watch pushes coalesce into one "wb" frame)
        with self._out_lock:
            if self._closed:
                return
            self._out.append(obj)
        self._out_ready.set()

    @staticmethod
    def _frame_batch(objs: list) -> bytes:
        """One writer-drain's objects -> wire bytes.  Runs of >= 2
        consecutive watch pushes (have "w", no "i") become one
        ``{"wb": [...]}`` line; everything else — responses, and a
        LONE watch push — keeps its own line unchanged.  Merging only
        adjacent pushes preserves order against responses."""
        lines = []
        run: list = []

        def flush_run() -> None:
            if not run:
                return
            if len(run) == 1:
                lines.append(json.dumps(run[0]))
            else:
                lines.append(json.dumps({"wb": list(run)}))
            run.clear()

        for obj in objs:
            if "w" in obj and "i" not in obj:
                run.append(obj)
            else:
                flush_run()
                lines.append(json.dumps(obj))
        flush_run()
        return ("\n".join(lines) + "\n").encode()

    def _write_loop(self) -> None:
        while True:
            self._out_ready.wait()
            with self._out_lock:
                objs, self._out = self._out, []
                self._out_ready.clear()
                if self._closed and not objs:
                    return
            try:
                if objs:
                    self.sock.sendall(self._frame_batch(objs))
            except OSError:
                self.close()
                return

    def _read_loop(self) -> None:
        framer = LineFramer()
        try:
            while True:
                data = self.sock.recv(1 << 16)
                if not data:
                    break
                for line in framer.feed(data):
                    self._handle(json.loads(line))
        except (OSError, ValueError):
            pass
        finally:
            self.close()

    def _handle(self, req: dict) -> None:
        store = self.server.store
        i = req.get("i")
        op = req.get("op")
        try:
            if op == "get":
                v = store.get(req["key"])
                r = None if v is None else _enc(v)
            elif op == "update":
                r = store.update(req["key"], _dec(req["v"]),
                                 lease_ttl=req.get("ttl"))
            elif op == "create_only":
                r = store.create_only(req["key"], _dec(req["v"]),
                                      lease_ttl=req.get("ttl"))
            elif op == "delete":
                r = store.delete(req["key"])
            elif op == "delete_if":
                r = store.delete_if(req["key"], _dec(req["v"]))
            elif op == "list_prefix":
                r = {k: _enc(v)
                     for k, v in store.list_prefix(req["prefix"]).items()}
            elif op == "keepalive":
                r = store.keepalive(req["key"], req["ttl"])
            elif op == "watch":
                wid = req["wid"]

                def push(ev: KVEvent, _wid=wid) -> None:
                    frame = {"w": _wid, "k": ev.kind, "key": ev.key,
                             "v": _enc(ev.value), "rev": ev.revision}
                    # lease TTL rides along so a replicating standby
                    # re-arms its copy (benign unlocked read: worst
                    # case the standby holds a lease a tick long)
                    exp = store._leases.get(ev.key)
                    if exp is not None and ev.kind != "delete":
                        frame["ttl"] = max(exp - time.time(), 0.001)
                    self._send(frame)

                cancel = store.watch_prefix(req["prefix"], push,
                                            replay=req.get("replay", True))
                self._watches[wid] = cancel
                r = wid
            elif op == "unwatch":
                cancel = self._watches.pop(req["wid"], None)
                if cancel:
                    cancel()
                r = True
            elif op == "snapshot":
                # full dump for standby seeding: data + revisions +
                # remaining lease TTLs (failover.py WarmStandby)
                with store._lock:
                    store._expire_leases()
                    now = time.time()
                    r = {
                        "data": {k: [_enc(v), rev]
                                 for k, (v, rev) in store._data.items()},
                        "leases": {k: max(exp - now, 0.001)
                                   for k, exp in store._leases.items()},
                        "revision": store._revision,
                    }
            elif op == "lease_dump":
                # keepalives extend leases WITHOUT watch events; the
                # standby polls this to keep its lease copies live
                with store._lock:
                    now = time.time()
                    r = {k: max(exp - now, 0.001)
                         for k, exp in store._leases.items()}
            elif op == "ping":
                r = "pong"
            else:
                raise ValueError(f"unknown op {op!r}")
            self._send({"i": i, "r": r})
        except Exception as exc:  # surface to the caller, keep serving
            self._send({"i": i, "e": f"{type(exc).__name__}: {exc}"})

    def close(self) -> None:
        with self._out_lock:
            if self._closed:
                return
            self._closed = True
        self._out_ready.set()
        for cancel in self._watches.values():
            cancel()
        self._watches.clear()
        # shutdown BEFORE close (transport.shutdown_close, the one
        # definition): this conn's reader thread is blocked in recv()
        # on the same fd, and POSIX close() neither wakes it nor
        # sends FIN while the fd is pinned in that syscall — so a
        # killed server's clients would never see EOF, and their
        # watches would stay silently dead until their next RPC (an
        # idle watch-only replica missing every event across a
        # failover).  shutdown() delivers both halves immediately.
        shutdown_close(self.sock)
        self.server._conns.discard(self)


class KVStoreServer:
    """Serve an :class:`InMemoryKVStore` over a unix or TCP socket.

    The cluster's single etcd analogue: start one (its own process in
    production — see ``python -m cilium_tpu.kvstore.remote``), point
    every agent/operator's :class:`RemoteKVStore` at its address."""

    def __init__(self, store: Optional[InMemoryKVStore] = None,
                 path: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lease_tick: float = 0.2):
        self.store = store or InMemoryKVStore()
        self._conns: set = set()
        self._closed = False
        if path is not None:
            self.address: Tuple[str, ...] = ("unix", path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            if os.path.exists(path):
                os.unlink(path)
            self._sock.bind(path)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self.address = ("tcp", host, self._sock.getsockname()[1])
        self._sock.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        # lease expiry must fire without client traffic (a crashed
        # client stops calling; its leases still have to die)
        self._lease_tick = lease_tick
        threading.Thread(target=self._tick_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            if self._closed:
                # close() raced an in-flight accept: the kernel can
                # hand us one last connection — refusing it here is
                # what makes a "killed" server actually dead (a
                # zombie acceptor would capture failover clients'
                # watch re-subscriptions onto the corpse's store)
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) \
                if self.address[0] == "tcp" else None
            self._conns.add(_Conn(self, sock))

    def _tick_loop(self) -> None:
        while not self._closed:
            time.sleep(self._lease_tick)
            with self.store._lock:
                self.store._expire_leases()

    def close(self) -> None:
        self._closed = True
        # shutdown BEFORE close: the accept loop is blocked in
        # accept() on this fd, and close() alone neither wakes it nor
        # releases the listening socket while the fd is pinned in
        # that syscall — the "killed" server would keep ACCEPTING,
        # and a failover client re-dialing its address list would
        # reconnect to the corpse (and re-subscribe its watches onto
        # a store nobody mutates any more).  shutdown() fails the
        # blocked accept immediately.
        shutdown_close(self._sock)
        for c in list(self._conns):
            c.close()
        if self.address[0] == "unix" and os.path.exists(self.address[1]):
            try:
                os.unlink(self.address[1])
            except OSError:
                pass


class RemoteKVStore:
    """Drop-in ``InMemoryKVStore`` replacement speaking to a
    :class:`KVStoreServer` — the etcd-client analogue.

    Reconnect semantics (reference: pkg/kvstore etcd client): on
    connection loss every in-flight call fails over to one retry after
    re-dial, and every watch re-subscribes WITH replay — consumers are
    idempotent, so replayed creates are absorbed; a key deleted during
    the outage simply stops appearing in lookups (its delete event is
    lost, matching a compacted etcd watch re-sync via list+watch)."""

    def __init__(self, address, dial_timeout: float = 5.0,
                 call_timeout: float = 30.0, reconnect: bool = True,
                 max_backoff: float = 2.0):
        # ``address`` is one ("unix", path) / ("tcp", host, port)
        # tuple OR a failover list of them (primary first): every
        # (re)dial walks the list in order, so clients of a killed
        # primary land on the warm standby (failover.WarmStandby)
        if address and isinstance(address[0], (list, tuple)):
            self._addresses = [tuple(a) for a in address]
        else:
            self._addresses = [tuple(address)]
        self.address = self._addresses[0]
        self._dial_timeout = dial_timeout
        self._call_timeout = call_timeout
        self._reconnect = reconnect
        self._max_backoff = max_backoff
        self._lock = threading.Lock()  # pending/watch bookkeeping
        self._send_lock = threading.Lock()  # sendall may block; never
        self._next_id = 0                   # hold _lock across it
        self._pending: Dict[int, list] = {}
        self._watches: Dict[int, Tuple[str, Watcher]] = {}
        self._next_wid = 0
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._connected = threading.Event()
        # Watch callbacks run on their OWN thread, not the reader:
        # a callback may block on an application lock held by a
        # caller that is itself waiting for a response only the
        # reader can demux (allocator watch-mirror updates do exactly
        # this).  One dispatcher thread preserves event order.
        self._events: "queue.Queue" = queue.Queue()
        self._dial()
        threading.Thread(target=self._read_loop, daemon=True).start()
        threading.Thread(target=self._event_loop, daemon=True).start()

    # -- transport ---------------------------------------------------
    def _dial(self) -> None:
        deadline = time.time() + self._dial_timeout
        delay = 0.02
        last: Optional[Exception] = None
        while time.time() < deadline:
            for addr in self._addresses:
                try:
                    if addr[0] == "unix":
                        s = socket.socket(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
                        s.settimeout(2.0)
                        s.connect(addr[1])
                    else:
                        s = socket.create_connection(
                            (addr[1], addr[2]), timeout=2.0)
                        s.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                    s.settimeout(None)
                    self._sock = s
                    self.address = addr
                    self._connected.set()
                    return
                except OSError as exc:
                    last = exc
            time.sleep(min(delay, self._max_backoff))
            delay *= 2
        raise ConnectionError(
            f"kvstore server unreachable at {self._addresses}: {last}")

    def _read_loop(self) -> None:
        framer = LineFramer()
        while not self._closed:
            sock = self._sock
            if sock is None:
                time.sleep(0.01)
                continue
            try:
                data = sock.recv(1 << 16)
            except OSError:
                data = b""
            if not data:
                if self._closed:
                    return
                self._on_disconnect()
                framer = LineFramer()
                continue
            for line in framer.feed(data):
                msg = json.loads(line)
                if "wb" in msg:
                    # coalesced watch batch: unpack in order — the
                    # single dispatcher queue keeps delivery order
                    # identical to the unbatched protocol's
                    for ev in msg["wb"]:
                        self._dispatch_watch(ev)
                elif "w" in msg and "i" not in msg:
                    self._dispatch_watch(msg)
                else:
                    with self._lock:
                        slot = self._pending.get(msg["i"])
                    if slot is not None:
                        slot[1] = msg
                        slot[0].set()

    def _on_disconnect(self) -> None:
        self._connected.clear()
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        # fail in-flight calls so callers can retry
        with self._lock:
            for slot in self._pending.values():
                slot[1] = {"e": "ConnectionError: connection lost"}
                slot[0].set()
            self._pending.clear()
        if not self._reconnect or self._closed:
            return
        while not self._closed:
            try:
                self._dial()
                break
            except ConnectionError:
                time.sleep(self._max_backoff / 4)
        if self._closed:
            return
        # re-subscribe watches with replay (list+watch re-sync) — from
        # a SEPARATE thread: this method runs on the reader thread,
        # which must get back to demuxing responses or the watch calls
        # below would wait on themselves
        with self._lock:
            watches = dict(self._watches)

        def resubscribe() -> None:
            for wid, (prefix, _fn) in watches.items():
                try:
                    self._call("watch", wid=wid, prefix=prefix,
                               replay=True)
                except (ConnectionError, TimeoutError):
                    pass  # next disconnect cycle retries

        if watches:
            threading.Thread(target=resubscribe, daemon=True).start()

    def _dispatch_watch(self, msg: dict) -> None:
        self._events.put(msg)

    def _event_loop(self) -> None:
        while True:
            msg = self._events.get()
            if msg is None:
                return
            with self._lock:
                entry = self._watches.get(msg["w"])
            if entry is None:
                continue
            _prefix, fn = entry
            try:
                fn(KVEvent(msg["k"], msg["key"], _dec(msg["v"]),
                           msg["rev"], ttl=msg.get("ttl")))
            except Exception:
                pass  # a broken observer must not kill the dispatcher

    def _call(self, op: str, **args):
        """One request/response round trip; one transparent retry
        after a reconnect."""
        for attempt in (0, 1):
            self._connected.wait(self._dial_timeout)
            slot = [threading.Event(), None]
            with self._lock:
                self._next_id += 1
                i = self._next_id
                self._pending[i] = slot
                sock = self._sock
            frame = dict(args)
            frame["i"] = i
            frame["op"] = op
            data = (json.dumps(frame) + "\n").encode()
            try:
                if sock is None:
                    raise OSError("not connected")
                with self._send_lock:
                    sock.sendall(data)
            except OSError:
                with self._lock:
                    self._pending.pop(i, None)
                if attempt == 0 and self._reconnect and not self._closed:
                    # the send hit the dead socket before the reader
                    # noticed EOF: wait for the reader's re-dial to
                    # install a FRESH socket before retrying (retrying
                    # on the same object would just fail again).  If
                    # no fresh socket appears within the dial budget,
                    # fail now — falling through to attempt 1 would
                    # block a SECOND dial_timeout in _connected.wait.
                    deadline = time.time() + self._dial_timeout
                    fresh = False
                    while time.time() < deadline and not self._closed:
                        cur = self._sock
                        if cur is not None and cur is not sock:
                            fresh = True
                            break
                        time.sleep(0.005)
                    if fresh:
                        continue
                raise ConnectionError("kvstore connection lost")
            if not slot[0].wait(self._call_timeout):
                with self._lock:
                    self._pending.pop(i, None)
                raise TimeoutError(f"kvstore call {op} timed out")
            with self._lock:
                self._pending.pop(i, None)
            msg = slot[1]
            if "e" in msg:
                if msg["e"].startswith("ConnectionError") \
                        and attempt == 0 and self._reconnect \
                        and not self._closed:
                    continue
                raise RuntimeError(msg["e"])
            return msg["r"]
        raise ConnectionError("kvstore connection lost")

    # -- InMemoryKVStore interface ------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        r = self._call("get", key=key)
        return None if r is None else _dec(r)

    def update(self, key: str, value: bytes,
               lease_ttl: Optional[float] = None) -> int:
        return self._call("update", key=key, v=_enc(value), ttl=lease_ttl)

    def create_only(self, key: str, value: bytes,
                    lease_ttl: Optional[float] = None) -> bool:
        return self._call("create_only", key=key, v=_enc(value),
                          ttl=lease_ttl)

    def delete(self, key: str) -> bool:
        return self._call("delete", key=key)

    def delete_if(self, key: str, expected: bytes) -> bool:
        return self._call("delete_if", key=key, v=_enc(expected))

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        return {k: _dec(v) for k, v in
                self._call("list_prefix", prefix=prefix).items()}

    def keepalive(self, key: str, lease_ttl: float) -> bool:
        return self._call("keepalive", key=key, ttl=lease_ttl)

    def watch_prefix(self, prefix: str, fn: Watcher,
                     replay: bool = True) -> Callable[[], None]:
        with self._lock:
            self._next_wid += 1
            wid = self._next_wid
            self._watches[wid] = (prefix, fn)
        self._call("watch", wid=wid, prefix=prefix, replay=replay)

        def cancel() -> None:
            with self._lock:
                self._watches.pop(wid, None)
            try:
                self._call("unwatch", wid=wid)
            except (ConnectionError, TimeoutError, RuntimeError):
                pass

        return cancel

    def ping(self) -> bool:
        return self._call("ping") == "pong"

    # -- replication surface (failover.WarmStandby) --------------------
    def snapshot(self) -> dict:
        r = self._call("snapshot")
        return {
            "data": {k: (_dec(v), rev)
                     for k, (v, rev) in r["data"].items()},
            "leases": dict(r["leases"]),
            "revision": r["revision"],
        }

    def lease_dump(self) -> Dict[str, float]:
        return dict(self._call("lease_dump"))

    def close(self) -> None:
        self._closed = True
        self._connected.set()
        self._events.put(None)
        # same shutdown-before-close as _Conn.close: the reader
        # thread is blocked in recv() on this fd and plain close()
        # would leave it wedged forever
        shutdown_close(self._sock)


def main() -> None:
    """Standalone server process:
    ``python -m cilium_tpu.kvstore.remote --socket /path`` or
    ``--port N``."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--socket", default=None,
                   help="unix socket path (preferred)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args()
    srv = KVStoreServer(path=args.socket, host=args.host, port=args.port)
    print(json.dumps({"address": list(srv.address)}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()


if __name__ == "__main__":
    main()
