"""In-memory kvstore with revisioned watches + the shared-store mirror.

Reference: upstream cilium ``pkg/kvstore`` (etcd ``Get/Update/Delete``
+ ``Watch`` with mod-revisions, lease TTLs for liveness) and
``pkg/kvstore/store`` (``SharedStore``: local keys written by this
node, remote keys mirrored from watch events).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class KVEvent:
    kind: str  # "create" | "modify" | "delete"
    key: str
    value: bytes
    revision: int
    # remaining lease TTL at emit time (replication transport only:
    # the standby re-arms its copy of the lease from this)
    ttl: Optional[float] = None


Watcher = Callable[[KVEvent], None]


class InMemoryKVStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._data: Dict[str, Tuple[bytes, int]] = {}  # key -> (val, rev)
        self._leases: Dict[str, float] = {}  # key -> expiry
        self._revision = 0
        self._watchers: List[Tuple[str, Watcher]] = []

    # -- kv ops ------------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._expire_leases()
            v = self._data.get(key)
            return v[0] if v else None

    def update(self, key: str, value: bytes,
               lease_ttl: Optional[float] = None) -> int:
        with self._lock:
            self._revision += 1
            kind = "modify" if key in self._data else "create"
            self._data[key] = (value, self._revision)
            if lease_ttl is not None:
                self._leases[key] = time.time() + lease_ttl
            rev = self._revision
            self._notify(KVEvent(kind, key, value, rev))
            return rev

    def create_only(self, key: str, value: bytes,
                    lease_ttl: Optional[float] = None) -> bool:
        """Atomic create-if-absent (the allocator's claim op)."""
        with self._lock:
            self._expire_leases()
            if key in self._data:
                return False
            self.update(key, value, lease_ttl)
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            self._revision += 1
            self._data.pop(key)
            self._leases.pop(key, None)
            self._notify(KVEvent("delete", key, b"", self._revision))
            return True

    def delete_if(self, key: str, expected: bytes) -> bool:
        """Atomic compare-and-delete (etcd txn analogue): delete only
        while the stored value still equals ``expected``.  The safe
        lock-release primitive — a plain get-then-delete could remove
        a lock a successor acquired after the caller's lease expired."""
        with self._lock:
            self._expire_leases()
            v = self._data.get(key)
            if v is None or v[0] != expected:
                return False
            return self.delete(key)

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        with self._lock:
            self._expire_leases()
            return {k: v for k, (v, _) in self._data.items()
                    if k.startswith(prefix)}

    def keepalive(self, key: str, lease_ttl: float) -> bool:
        """Refresh a lease (the heartbeat path)."""
        with self._lock:
            if key not in self._data:
                return False
            self._leases[key] = time.time() + lease_ttl
            return True

    # -- watches -----------------------------------------------------
    def watch_prefix(self, prefix: str, fn: Watcher,
                     replay: bool = True) -> Callable[[], None]:
        """Subscribe; optionally replay existing keys as creates.
        Returns an unsubscribe function."""
        with self._lock:
            if replay:
                for k, (v, rev) in sorted(self._data.items()):
                    if k.startswith(prefix):
                        fn(KVEvent("create", k, v, rev))
            entry = (prefix, fn)
            self._watchers.append(entry)

        def cancel() -> None:
            with self._lock:
                if entry in self._watchers:
                    self._watchers.remove(entry)

        return cancel

    def _notify(self, ev: KVEvent) -> None:
        for prefix, fn in list(self._watchers):
            if ev.key.startswith(prefix):
                fn(ev)

    def _expire_leases(self) -> None:
        now = time.time()
        dead = [k for k, exp in self._leases.items() if exp < now]
        for k in dead:
            self._leases.pop(k, None)
            if k in self._data:
                self._revision += 1
                self._data.pop(k)
                self._notify(KVEvent("delete", k, b"", self._revision))


class SharedStore:
    """Prefix mirror: local writes + remote watch replay into one view.

    Reference: pkg/kvstore/store.SharedStore — each node writes its own
    keys under a shared prefix and observes everyone's."""

    def __init__(self, kv: InMemoryKVStore, prefix: str, node: str):
        self.kv = kv
        self.prefix = prefix.rstrip("/") + "/"
        self.node = node
        self._mirror: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._cancel = kv.watch_prefix(self.prefix, self._on_event)

    def _on_event(self, ev: KVEvent) -> None:
        with self._lock:
            if ev.kind == "delete":
                self._mirror.pop(ev.key, None)
            else:
                self._mirror[ev.key] = ev.value

    def update_local(self, name: str, value: bytes,
                     lease_ttl: Optional[float] = None) -> None:
        self.kv.update(f"{self.prefix}{self.node}/{name}", value,
                       lease_ttl)

    def delete_local(self, name: str) -> None:
        self.kv.delete(f"{self.prefix}{self.node}/{name}")

    def snapshot(self) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._mirror)

    def close(self) -> None:
        self._cancel()
