"""Transparent node-to-node encryption — the WireGuard analogue.

Reference: upstream cilium's ``--enable-wireguard`` (pkg/wireguard):
each agent generates a Curve25519 keypair, publishes the public key on
its CiliumNode resource, adds every remote node as a wireguard peer,
and the datapath marks pod-to-remote-pod traffic to route through the
``cilium_wg0`` device, which encrypts per packet with
ChaCha20-Poly1305.

TPU-first redesign: packets cross nodes HERE as packed header batches
(the comm-backend plane, SURVEY §5), so the unit of encryption is the
BATCH buffer, not the packet — ONE X25519-derived session key per node
pair and ONE AEAD seal per batch (amortizing the per-message cost
~batch-size-fold; upstream pays it per packet because the wire
delivers packets individually).  The key exchange mirrors upstream:

- :class:`NodeKeypair` — the agent's Curve25519 keypair; the public
  key publishes through the node registry (the CiliumNode annotation
  analogue) as ``encryption-pubkey``.
- :func:`derive_session_keys` — X25519 shared secret, then an
  HKDF-style BLAKE2s expansion bound to (both pubkeys, epoch,
  direction): each pair holds distinct A->B and B->A keys, and bumping
  ``epoch`` rotates every key without re-publishing (upstream rotates
  by replacing the node keypair).
- :class:`EncryptedChannel` — seal/open of batch buffers with a
  sequence-number nonce and strictly-monotone replay protection
  (batches are ordered per channel; a reordered/duplicated frame is
  REJECTED, matching wireguard's sliding-window intent for an
  in-order transport).

Crypto primitives: ``native/crypto.cpp`` (RFC 7748 + RFC 8439,
validated against the RFC vectors and a pure-Python cross-check).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..infra import faults
from ..native import crypto

PUBKEY_FIELD = "encryption-pubkey"  # node-registry info key (hex)
MAGIC = 0xC17E
HDR = struct.Struct("<HHIQ")  # magic, epoch, reserved, seq
OVERHEAD = HDR.size + 16  # header + poly1305 tag
# rotation grace: how many superseded epochs a channel will keep
# receive state for at once (each with its own replay window).  A
# serving rotation keeps at most ONE epoch in flight; the bound only
# matters under rotation storms, where the oldest key ages out.
GRACE_MAX = 4


class DecryptError(Exception):
    """A sealed frame that must not be admitted.  ``reason`` is the
    machine-readable flavor: short | magic | epoch-old | epoch-ahead |
    replay | auth."""

    def __init__(self, msg: str, reason: str = "auth"):
        super().__init__(msg)
        self.reason = reason


class NodeKeypair:
    """The agent's Curve25519 identity (pkg/wireguard keypair)."""

    def __init__(self, private: Optional[bytes] = None):
        self.private = private if private is not None else os.urandom(32)
        if len(self.private) != 32:
            raise ValueError("private key must be 32 bytes")
        self.public = crypto.x25519_base(self.private)

    @staticmethod
    def load_or_create(path: Optional[str]) -> "NodeKeypair":
        """Persist the node key across agent restarts (upstream keeps
        it on the wireguard device)."""
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                return NodeKeypair(f.read())
        kp = NodeKeypair()
        if path:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(kp.private)
        return kp


def derive_session_keys(local: NodeKeypair, peer_public: bytes,
                        epoch: int = 0) -> Tuple[bytes, bytes]:
    """-> (send_key, recv_key) for this node against ``peer_public``.

    Both sides derive the same pair of keys: direction is bound to the
    ORDER of the public keys, so A's send key IS B's recv key.  The
    shared secret never leaves this function."""
    shared = crypto.x25519(local.private, peer_public)
    lo, hi = sorted((local.public, peer_public))

    def kdf(direction: bytes) -> bytes:
        return hashlib.blake2s(
            shared + lo + hi + epoch.to_bytes(4, "little") + direction,
            digest_size=32, person=b"ctpu-wg").digest()

    k_lo_to_hi = kdf(b"lo->hi")
    k_hi_to_lo = kdf(b"hi->lo")
    if local.public == lo:
        return k_lo_to_hi, k_hi_to_lo
    return k_hi_to_lo, k_lo_to_hi


class EncryptedChannel:
    """One node pair's transport: seal/open batch buffers.

    Frame layout: ``magic | epoch | reserved | seq`` (16 B, rides as
    AAD) + ciphertext + tag.  The nonce is the little-endian sequence
    number (12 B) — unique per key because seq is strictly monotone
    and keys rotate with epoch.

    Rotation grace: ``rotate(epoch, grace_s=G)`` keeps the superseded
    epoch's receive key alive for G seconds, with ITS OWN replay
    window — frames sealed just before a peer rotated still open
    (wireguard keeps the previous session key for exactly this
    reason), while a replayed old-epoch frame is still rejected by
    that epoch's window and an EXPIRED old epoch rejects outright.
    The default ``grace_s=0`` preserves the strict behavior: any
    non-current epoch rejects immediately."""

    def __init__(self, local: NodeKeypair, peer_public: bytes,
                 epoch: int = 0):
        self.peer_public = peer_public
        self.epoch = epoch
        self._local = local
        self._send_key, self._recv_key = derive_session_keys(
            local, peer_public, epoch)
        self._send_seq = 0
        self._recv_seq = 0  # highest accepted (current epoch)
        # guarded-by: _lock — superseded-epoch receive state,
        # epoch16 -> [recv_key, recv_seq, expiry_monotonic]
        self._grace: Dict[int, List] = {}
        # guarded-by: _lock — NEXT-epoch receive state installed by
        # prepare_recv() ahead of a rotation,
        # [epoch16, recv_key, recv_seq]
        self._pending: Optional[List] = None
        self._lock = threading.Lock()
        self.sealed = 0
        self.opened = 0
        self.rejected = 0
        self.replays = 0  # subset of rejected: replay-window hits
        self.rotations = 0

    def prepare_recv(self, epoch: int) -> None:
        """Pre-install the RECEIVE half of ``epoch`` ahead of a
        rotation (wireguard installs the responder's receiving key
        before it ever sends with it, for the same reason): frames
        the peer seals at the new epoch in the gap between ITS
        rotation and ours open here instead of rejecting
        ``epoch-ahead``.  Without this, a coalesced ack sealed at
        e+1 right after the worker's rotate — before the parent's —
        is discarded, and if it covered the whole send window the
        credit never returns (a wedged channel the stop-sweep then
        double-counts).  Send stays at the CURRENT epoch; a later
        :meth:`rotate` to the same epoch adopts the pending replay
        window so early frames stay unreplayable."""
        with self._lock:
            e16 = epoch & 0xFFFF
            if e16 == (self.epoch & 0xFFFF):
                return
            if self._pending is not None and self._pending[0] == e16:
                return  # keep the already-advanced replay window
            _send, recv = derive_session_keys(
                self._local, self.peer_public, epoch)
            self._pending = [e16, recv, 0]

    def rotate(self, epoch: int, grace_s: float = 0.0) -> None:
        """Key rotation: new epoch -> new session keys, sequence
        numbers restart (the nonce space is per-key).  With
        ``grace_s > 0`` the outgoing epoch's RECEIVE side survives
        that long (bounded to :data:`GRACE_MAX` epochs), so in-flight
        peer frames are not lost to the flip.  A matching
        :meth:`prepare_recv` hands its replay window over — frames
        accepted at the new epoch BEFORE the flip stay
        unreplayable after it."""
        with self._lock:
            old16 = self.epoch & 0xFFFF
            if grace_s > 0 and epoch != self.epoch:
                self._grace[old16] = [
                    self._recv_key, self._recv_seq,
                    time.monotonic() + grace_s]
                while len(self._grace) > GRACE_MAX:
                    oldest = min(self._grace,
                                 key=lambda e: self._grace[e][2])
                    del self._grace[oldest]
            self.epoch = epoch
            self._send_key, self._recv_key = derive_session_keys(
                self._local, self.peer_public, epoch)
            self._send_seq = 0
            self._recv_seq = 0
            pend = self._pending
            if pend is not None and pend[0] == (epoch & 0xFFFF):
                self._recv_seq = pend[2]
            self._pending = None  # stale prepares (a rotation that
            # skipped past them) die here too
            # a 16-bit collision with the new epoch would shadow the
            # live key — the fresh epoch always wins
            self._grace.pop(epoch & 0xFFFF, None)
            self.rotations += 1

    def seal(self, buf: bytes) -> bytes:
        faults.check(faults.SITE_CRYPTO_SEAL)
        with self._lock:
            self._send_seq += 1
            seq = self._send_seq
            key = self._send_key
            epoch = self.epoch
            self.sealed += 1
        aad = HDR.pack(MAGIC, epoch & 0xFFFF, 0, seq)
        nonce = seq.to_bytes(8, "little") + b"\x00\x00\x00\x00"
        return aad + crypto.aead_seal(key, nonce, aad, bytes(buf))

    def open(self, frame: bytes) -> bytes:
        faults.check(faults.SITE_CRYPTO_OPEN)
        if len(frame) < OVERHEAD:
            raise DecryptError("frame too short", "short")
        aad = frame[:HDR.size]
        magic, epoch, _res, seq = HDR.unpack(aad)
        with self._lock:
            if magic != MAGIC:
                self.rejected += 1
                raise DecryptError("bad magic", "magic")
            cur16 = self.epoch & 0xFFFF
            now = time.monotonic()
            for e in [e for e, g in self._grace.items()
                      if g[2] <= now]:
                del self._grace[e]
            pend = grace = None
            if epoch == cur16:
                if seq <= self._recv_seq:
                    self.rejected += 1
                    self.replays += 1
                    raise DecryptError(
                        f"replayed/reordered seq {seq}", "replay")
                key = self._recv_key
            elif self._pending is not None \
                    and epoch == self._pending[0]:
                # peer rotated first; we pre-installed its next
                # epoch's recv key (prepare_recv) — its own replay
                # window, handed to rotate() at the flip
                pend = self._pending
                if seq <= pend[2]:
                    self.rejected += 1
                    self.replays += 1
                    raise DecryptError(
                        f"replayed/reordered seq {seq} "
                        f"(pending epoch {epoch})", "replay")
                key = pend[1]
            else:
                grace = self._grace.get(epoch)
                if grace is None:
                    self.rejected += 1
                    # 16-bit wraparound ordering: "ahead" means the
                    # peer rotated first and we have not caught up yet
                    if ((epoch - cur16) & 0xFFFF) < 0x8000:
                        raise DecryptError(
                            f"epoch {epoch} ahead of local {cur16} "
                            "(peer rotated first?)", "epoch-ahead")
                    raise DecryptError(
                        f"epoch {epoch} != local {cur16} "
                        "(grace expired?)", "epoch-old")
                if seq <= grace[1]:
                    self.rejected += 1
                    self.replays += 1
                    raise DecryptError(
                        f"replayed/reordered seq {seq} "
                        f"(grace epoch {epoch})", "replay")
                key = grace[0]
        nonce = seq.to_bytes(8, "little") + b"\x00\x00\x00\x00"
        pt = crypto.aead_open(key, nonce, aad, frame[HDR.size:])
        if pt is None:
            with self._lock:
                self.rejected += 1
            raise DecryptError("authentication failed", "auth")
        with self._lock:
            # accept AFTER authentication: a forged seq must not
            # advance the replay window.  Re-resolve the window — a
            # concurrent rotate may have moved this epoch to grace
            # (or promoted the pending epoch to current).
            if grace is not None:
                if seq > grace[1]:
                    grace[1] = seq
            elif pend is not None and self._pending is pend:
                if seq > pend[2]:
                    pend[2] = seq
            elif epoch == (self.epoch & 0xFFFF):
                if seq > self._recv_seq:
                    self._recv_seq = seq
            elif epoch in self._grace:
                g = self._grace[epoch]
                if seq > g[1]:
                    g[1] = seq
            self.opened += 1
        return pt


class EncryptionManager:
    """Publishes this node's pubkey, tracks peers' keys from the node
    registry, hands out channels (pkg/wireguard agent half).

    ``advertise`` augments the info dict the daemon registers; call
    ``refresh`` after node churn (or rely on lazy channel creation)."""

    def __init__(self, node_name: str, registry,
                 key_path: Optional[str] = None, epoch: int = 0,
                 keypair: Optional[NodeKeypair] = None):
        self.node_name = node_name
        self.registry = registry
        self.keypair = (keypair if keypair is not None
                        else NodeKeypair.load_or_create(key_path))
        self.epoch = epoch
        self._channels: Dict[str, EncryptedChannel] = {}
        self._lock = threading.Lock()

    def advertise(self, info: dict) -> dict:
        info = dict(info)
        info[PUBKEY_FIELD] = self.keypair.public.hex()
        return info

    def peer_public(self, node: str) -> Optional[bytes]:
        for n in self.registry.nodes():
            if n.get("name") == node and n.get(PUBKEY_FIELD):
                return bytes.fromhex(n[PUBKEY_FIELD])
        return None

    def channel(self, node: str) -> EncryptedChannel:
        with self._lock:
            ch = self._channels.get(node)
            if ch is not None:
                return ch
        pub = self.peer_public(node)
        if pub is None:
            raise KeyError(f"node {node!r} has no published "
                           f"{PUBKEY_FIELD}")
        ch = EncryptedChannel(self.keypair, pub, self.epoch)
        with self._lock:
            return self._channels.setdefault(node, ch)

    def rotate(self, epoch: int, grace_s: float = 0.0) -> None:
        """Bump the key epoch for every channel (both sides must
        rotate; with ``grace_s=0`` frames sealed under the old epoch
        reject afterward, with a grace they keep opening until it
        expires)."""
        with self._lock:
            self.epoch = epoch
            for ch in self._channels.values():
                ch.rotate(epoch, grace_s)

    def drop(self, node: str) -> None:
        with self._lock:
            self._channels.pop(node, None)

    def status(self) -> dict:
        with self._lock:
            return {
                "public-key": self.keypair.public.hex(),
                "epoch": self.epoch,
                "peers": {
                    n: {"sealed": c.sealed, "opened": c.opened,
                        "rejected": c.rejected,
                        "replays": c.replays,
                        "rotations": c.rotations}
                    for n, c in self._channels.items()},
            }
