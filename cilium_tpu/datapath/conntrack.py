"""Connection tracking: fixed-capacity open-addressing hash in HBM.

Reference: upstream cilium ``bpf/lib/conntrack.h`` (``ct_lookup4/6``,
``ct_create4/6``, TCP state handling, per-proto lifetimes) and
``pkg/maps/ctmap`` (GC).  TPU-first redesign: the kernel's per-packet
hash probe becomes a **batched** probe — every packet in the header
tensor probes concurrently via gathers; inserts use a vectorized
write-then-verify claim (scatter the whole row, re-gather the key,
check who won) instead of a CAS loop, giving lock-free semantics
across the batch.  Key and value words live in ONE row of one table so
an insert is a single scatter — no torn entries between concurrent
claimants of the same slot.

Static shapes: capacity is fixed at construction (power of two); a full
probe window drops new inserts (counted, like the reference's CT map
pressure) rather than reallocating.  Aging is a vectorized sweep
(``ctmap.GC``); expired entries are lookup misses immediately and their
slots are reclaimable by inserts.

Known deliberate divergences from eBPF (documented for the divergence
suite): duplicate tuples in one batch collapse to one entry with
last-writer counters (the kernel, processing serially, would count
both; the accounting delta is bounded by batch size and reconciled at
the flow layer).  Per-flow tx/rx packet and byte counters are uint32
table words and WRAP at 2^32 (the reference ctmap uses u64) — a
deliberate trade: one uint32 row keeps insert a single scatter; flows
past 4 GiB show wrapped accounting in ``bpf ct list`` (the flow layer
aggregates per-batch deltas host-side in uint64 and is unaffected).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import (
    COL_DPORT,
    COL_DST_IP0,
    COL_FLAGS,
    COL_LEN,
    COL_PROTO,
    COL_SPORT,
    COL_SRC_IP0,
    TCP_FIN,
    TCP_RST,
)

# Lookup results (reference: bpf/lib/common.h CT_* codes).
CT_NEW = 0
CT_ESTABLISHED = 1
CT_REPLY = 2
CT_RELATED = 3

# Entry states stored in the table.
ST_FREE = 0
ST_SYN_SENT = 1  # open, no reply seen yet
ST_ESTABLISHED = 2
ST_CLOSING = 3  # FIN/RST seen

# Lifetimes in seconds (reference: bpf CT_CONNECTION_LIFETIME_TCP/
# NONTCP, CT_SYN_TIMEOUT, CT_CLOSE_TIMEOUT defaults).
LIFETIME_TCP = 21600
LIFETIME_NONTCP = 60
LIFETIME_SYN = 60
LIFETIME_CLOSE = 10

KEY_WORDS = 10  # src[4] dst[4] ports proto
N_PROBE = 16  # linear probe window
N_CAND = 4  # full rows fetched per fingerprint-filtered probe
N_CAND_INS = 4  # claim attempts against fingerprint-filtered slots

# value columns (offsets within the combined row, after the key words)
V_STATE = KEY_WORDS + 0
V_EXPIRES = KEY_WORDS + 1
V_TX_PKTS = KEY_WORDS + 2
V_RX_PKTS = KEY_WORDS + 3
V_TX_BYTES = KEY_WORDS + 4
V_RX_BYTES = KEY_WORDS + 5
V_PROXY = KEY_WORDS + 6  # proxy redirect port (reference: proxy_redirect)
ROW_WORDS = KEY_WORDS + 7


@jax.tree_util.register_pytree_node_class
@dataclass
class CTTable:
    """Device CT state (a pytree threading functionally through jit).

    ``fp`` is a per-slot 1-byte key fingerprint (0 = free slot) kept in
    its own HBM array: probes gather the 16-slot fingerprint window
    first (64 B/packet) and fetch full 68 B rows only for the few
    fingerprint-matching candidates — a ~3x probe-byte diet over
    loading the whole [N, 16, ROW_WORDS] window.  The fingerprint is a
    pure function of the stored key (``_fp_mix`` of the slot hash), so
    snapshots stay placement-free and restores recompute it."""

    table: jnp.ndarray  # [C, ROW_WORDS] uint32
    fp: jnp.ndarray  # [C] uint32 — key fingerprint per slot, 0 = free
    dropped: jnp.ndarray  # [] uint32 — failed inserts (map pressure)

    @staticmethod
    def create(capacity: int = 1 << 20, shards: int = 1) -> "CTTable":
        """``capacity`` is the GLOBAL entry count; when the table is
        sharded over ``shards`` chips each shard's slice must be a
        power of two (the probe mask is per-shard)."""
        per_shard, rem = divmod(capacity, shards)
        assert rem == 0, "capacity must divide evenly across shards"
        assert per_shard & (per_shard - 1) == 0, \
            "per-shard capacity must be 2^k"
        return CTTable(
            table=jnp.zeros((capacity, ROW_WORDS), dtype=jnp.uint32),
            fp=jnp.zeros((capacity,), dtype=jnp.uint32),
            dropped=jnp.zeros((), dtype=jnp.uint32),
        )

    @property
    def capacity(self) -> int:
        return self.table.shape[0]

    def tree_flatten(self):
        return ((self.table, self.fp, self.dropped), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def ct_keys_from_headers(hdr: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Header tensor [N, N_COLS] -> (forward, reverse) CT key tensors.

    The key carries the hook direction like the reference's
    ``TUPLE_F_OUT``/``TUPLE_F_IN`` (word 9 = proto | dir << 8), so an
    egress-created entry never satisfies an ingress lookup of the same
    5-tuple on another endpoint.  The reverse (reply) key flips both
    the tuple AND the direction bit — a reply to an ingress-created
    flow is seen at the egress hook (reference:
    ``ipv4_ct_tuple_reverse``).  ICMP zeroes the port word so echo
    request/reply share a tuple modulo the swap.
    """
    from ..core.packets import COL_DIR, FLAG_RELATED, normalize_ports

    src = hdr[:, COL_SRC_IP0:COL_SRC_IP0 + 4].astype(jnp.uint32)
    dst = hdr[:, COL_DST_IP0:COL_DST_IP0 + 4].astype(jnp.uint32)
    proto = hdr[:, COL_PROTO].astype(jnp.uint32)
    dirn = hdr[:, COL_DIR].astype(jnp.uint32)
    sport, dport = normalize_ports(jnp, proto, hdr[:, COL_SPORT],
                                   hdr[:, COL_DPORT])
    sport = sport.astype(jnp.uint32)
    dport = dport.astype(jnp.uint32)
    fwd_ports = (sport << 16) | dport
    rev_ports = (dport << 16) | sport
    fwd_pd = proto | (dirn << 8)
    rev_pd = proto | ((1 - dirn) << 8)
    fwd = jnp.concatenate(
        [src, dst, fwd_ports[:, None], fwd_pd[:, None]], axis=1)
    rev = jnp.concatenate(
        [dst, src, rev_ports[:, None], rev_pd[:, None]], axis=1)
    # RELATED rows (ICMP errors) carry the EMBEDDED original tuple; the
    # entry to relate to was created with that SAME tuple under either
    # hook direction, so the "reverse" probe flips only the direction
    # bit instead of swapping the tuple (reference: the kernel looks up
    # the inner tuple for icmp errors)
    related = ((hdr[:, COL_FLAGS] & FLAG_RELATED) != 0)[:, None]
    rev_rel = jnp.concatenate(
        [src, dst, fwd_ports[:, None], rev_pd[:, None]], axis=1)
    rev = jnp.where(related, rev_rel, rev)
    return fwd, rev


def _hash(keys: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a over the key words + murmur3 finalizer:
    [N, KEY_WORDS] uint32 -> [N] uint32.

    The finalizer is load-bearing: word-FNV's low product bits depend
    ONLY on low input bits (low16(h*p) = low16(low16(h)*low16(p))), and
    the ports word packs sport into the HIGH half — without avalanche,
    home slots collapse to |srcs|*|dports| distinct values and probe
    windows chain to overflow at a few percent occupancy."""
    h = jnp.full(keys.shape[0], 0x811C9DC5, dtype=jnp.uint32)
    for w in range(KEY_WORDS):
        h = (h ^ keys[:, w]) * jnp.uint32(0x01000193)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def _fp_mix(h):
    """Key hash -> fingerprint byte in 1..255 (0 is the free marker).

    The slot index consumes the LOW bits of ``h``, so the fingerprint
    runs the murmur3 finalizer over it and takes the TOP byte — within
    one probe window (slots that differ only in low bits) fingerprints
    of distinct keys are ~independent, giving a 1/255 false-candidate
    rate per live slot."""
    g = h ^ (h >> 16)
    g = g * jnp.uint32(0x85EBCA6B)
    g = g ^ (g >> 13)
    g = g * jnp.uint32(0xC2B2AE35)
    return (g >> 24) % jnp.uint32(255) + jnp.uint32(1)


def _probe(table: jnp.ndarray, keys: jnp.ndarray, now: jnp.ndarray
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe the window for each key: -> (found [N] bool, slot [N] i32).

    Expired entries don't match (an expired entry is a miss; GC frees
    the slot later, and inserts may reclaim it immediately).

    The whole window loads as ONE [N, N_PROBE, ROW_WORDS] gather
    (instead of N_PROBE dependent gathers) so the memory system
    pipelines the probe; first-match selection is an argmax over the
    window axis."""
    c = table.shape[0]
    if c & (c - 1):
        raise ValueError(
            f"CT probe needs 2^k capacity, got {c} — a multi-shard "
            "table must be probed inside shard_map (per-shard slice)")
    mask = table.shape[0] - 1
    h = _hash(keys)
    steps = jnp.arange(N_PROBE, dtype=jnp.uint32)
    slots = ((h[:, None] + steps[None, :]) & mask).astype(jnp.int32)
    rows = table[slots]  # [N, N_PROBE, ROW_WORDS] — one gather
    live = (rows[:, :, V_STATE] != ST_FREE) & (rows[:, :, V_EXPIRES]
                                               >= now)
    match = live & jnp.all(rows[:, :, :KEY_WORDS]
                           == keys[:, None, :], axis=2)  # [N, N_PROBE]
    found = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)  # first True (0 when none)
    slot = jnp.take_along_axis(slots, first[:, None], axis=1)[:, 0]
    return found, jnp.where(found, slot, 0).astype(jnp.int32)


def _fp_window(fp: jnp.ndarray, keys: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Gather each key's fingerprint window: -> (slots [N, N_PROBE],
    window fingerprints [N, N_PROBE], key fingerprint [N])."""
    mask = fp.shape[0] - 1
    h = _hash(keys)
    steps = jnp.arange(N_PROBE, dtype=jnp.uint32)
    slots = ((h[:, None] + steps[None, :]) & mask).astype(jnp.int32)
    return slots, fp[slots], _fp_mix(h)


def _first_k(mask: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """First ``k`` True positions per row of [N, N_PROBE] ``mask`` in
    window order: -> (positions [N, k] int32, valid [N, k] bool)."""
    steps = jnp.arange(N_PROBE, dtype=jnp.int32)
    rank = jnp.where(mask, steps[None, :], N_PROBE)
    order = jnp.sort(rank, axis=1)[:, :k]
    return jnp.minimum(order, N_PROBE - 1), order < N_PROBE


def _probe_fp(table: jnp.ndarray, fp: jnp.ndarray, keys: jnp.ndarray,
              now: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fingerprint-filtered probe: -> (found, slot, overflow).

    Gathers the 16-slot fingerprint window (16 words/key), then full
    rows for only the first ``N_CAND`` fingerprint matches.  Exactness:
    a miss with more than ``N_CAND`` fingerprint matches in the window
    is flagged ``overflow`` — the true entry could hide past the
    candidate budget (P ~ (occupancy/255)^N_CAND per probe), and the
    caller reruns the full-window probe under ``lax.cond``.  Stale
    fingerprints of expired-but-unswept entries only cost a candidate
    slot; the liveness check on the gathered row rejects them."""
    slots, win_fp, key_fp = _fp_window(fp, keys)
    fmatch = win_fp == key_fp[:, None]  # [N, N_PROBE]
    pos, cand_valid = _first_k(fmatch, N_CAND)
    cand_slots = jnp.take_along_axis(slots, pos, axis=1)  # [N, N_CAND]
    rows = table[cand_slots]  # [N, N_CAND, ROW_WORDS]
    live = (rows[:, :, V_STATE] != ST_FREE) & (rows[:, :, V_EXPIRES]
                                               >= now)
    match = cand_valid & live & jnp.all(
        rows[:, :, :KEY_WORDS] == keys[:, None, :], axis=2)
    found = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)
    slot = jnp.take_along_axis(cand_slots, first[:, None], axis=1)[:, 0]
    overflow = ~found & (jnp.sum(fmatch, axis=1) > N_CAND)
    return found, jnp.where(found, slot, 0).astype(jnp.int32), overflow


def ct_lookup(ct: CTTable, fwd: jnp.ndarray, rev: jnp.ndarray,
              now: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched ``ct_lookup4`` equivalent.

    Returns (result [N] int32 in CT_*, slot [N] int32, is_reply [N]
    bool).  ``slot`` is valid only where result != CT_NEW.

    Fast path: fingerprint-filtered probes (:func:`_probe_fp`).  If
    ANY packet's fingerprint candidates overflowed without a match,
    the whole batch reruns the exact full-window probe — semantics are
    bit-identical to the unfiltered probe, the filter is purely a
    memory-traffic optimization.
    """
    f_found, f_slot, f_ovf = _probe_fp(ct.table, ct.fp, fwd, now)
    r_found, r_slot, r_ovf = _probe_fp(ct.table, ct.fp, rev, now)

    def _full(_):
        ff, fs = _probe(ct.table, fwd, now)
        rf, rs = _probe(ct.table, rev, now)
        return ff, fs, rf, rs

    def _fast(_):
        return f_found, f_slot, r_found, r_slot

    f_found, f_slot, r_found, r_slot = jax.lax.cond(
        jnp.any(f_ovf | r_ovf), _full, _fast, None)
    is_reply = ~f_found & r_found
    slot = jnp.where(f_found, f_slot, r_slot)
    result = jnp.where(f_found, CT_ESTABLISHED,
                       jnp.where(is_reply, CT_REPLY, CT_NEW))
    return result.astype(jnp.int32), slot, is_reply


def ct_update(ct: CTTable, hdr: jnp.ndarray, fwd: jnp.ndarray,
              result: jnp.ndarray, slot: jnp.ndarray,
              is_reply: jnp.ndarray, do_create: jnp.ndarray,
              proxy_port: jnp.ndarray, now: jnp.ndarray,
              valid: jnp.ndarray = None) -> CTTable:
    """Refresh hit entries, apply the TCP state machine, insert NEW.

    ``do_create`` marks NEW packets whose policy verdict allowed them
    (reference: ``ct_create4`` is called on the allow path only).
    ``valid`` masks out padding rows (batch routing pads shards to a
    common size); invalid rows touch nothing.
    """
    proto = hdr[:, COL_PROTO].astype(jnp.uint32)
    flags = hdr[:, COL_FLAGS].astype(jnp.uint32)
    length = hdr[:, COL_LEN].astype(jnp.uint32)
    is_tcp = proto == 6
    closing = is_tcp & ((flags & (TCP_FIN | TCP_RST)) != 0)

    table = ct.table
    capacity = ct.capacity

    # --- refresh existing entries (hits) -------------------------------
    # State transitions are MONOTONE upgrades (SYN_SENT < ESTABLISHED <
    # CLOSING, no downgrades), so concurrent refreshes of one slot by
    # several packets of the same flow in one batch combine with
    # scatter-max — matching the oracle's sequential result regardless
    # of intra-batch order.  Expiry is then recomputed from the POST-max
    # state so the lifetime matches the winning state.
    hit = result != CT_NEW
    if valid is not None:
        hit = hit & valid
    hslot = jnp.where(hit, slot, 0)
    old_state = table[hslot, V_STATE]
    # reply seen -> ESTABLISHED; FIN/RST -> CLOSING
    new_state = jnp.where(is_reply & (old_state == ST_SYN_SENT),
                          ST_ESTABLISHED, old_state)
    new_state = jnp.where(closing, ST_CLOSING, new_state)
    upd_rows = jnp.where(hit, hslot, capacity)  # OOB rows dropped
    table = table.at[upd_rows, V_STATE].max(
        new_state.astype(jnp.uint32), mode="drop")
    final_state = table[hslot, V_STATE]
    lifetime = jnp.where(
        final_state == ST_CLOSING, LIFETIME_CLOSE,
        jnp.where(is_tcp,
                  jnp.where(final_state >= ST_ESTABLISHED, LIFETIME_TCP,
                            LIFETIME_SYN),
                  LIFETIME_NONTCP)).astype(jnp.uint32)
    table = table.at[upd_rows, V_EXPIRES].set(now + lifetime, mode="drop")
    pkt_col = jnp.where(is_reply, V_RX_PKTS, V_TX_PKTS)
    byte_col = jnp.where(is_reply, V_RX_BYTES, V_TX_BYTES)
    table = table.at[upd_rows, pkt_col].add(1, mode="drop")
    table = table.at[upd_rows, byte_col].add(length, mode="drop")

    # --- insert NEW entries (write-then-verify claim) ------------------
    pending = do_create & (result == CT_NEW)
    if valid is not None:
        pending = pending & valid
    init_state = jnp.where(is_tcp, ST_SYN_SENT, ST_ESTABLISHED)
    init_life = jnp.where(is_tcp, LIFETIME_SYN, LIFETIME_NONTCP)
    new_row = jnp.concatenate([
        fwd,
        jnp.stack([
            init_state.astype(jnp.uint32),
            now + init_life.astype(jnp.uint32),
            jnp.ones_like(length),  # tx_pkts
            jnp.zeros_like(length),
            length,  # tx_bytes
            jnp.zeros_like(length),
            proxy_port.astype(jnp.uint32),
        ], axis=1),
    ], axis=1)  # [N, ROW_WORDS]

    fp = ct.fp
    slots_w, win_fp, key_fp = _fp_window(fp, fwd)

    def _claim(table, fp, pending, s, also_try=None):
        stored = table[s]
        claimable = ((stored[:, V_STATE] == ST_FREE)
                     | (stored[:, V_EXPIRES] < now)
                     | jnp.all(stored[:, :KEY_WORDS] == fwd, axis=1))
        trying = pending & claimable
        if also_try is not None:
            trying = trying & also_try
        rows = jnp.where(trying, s, capacity)
        table = table.at[rows].set(new_row, mode="drop")
        won = trying & jnp.all(table[s, :KEY_WORDS] == fwd, axis=1)
        fp = fp.at[jnp.where(won, s, capacity)].set(key_fp, mode="drop")
        return table, fp, pending & ~won

    # fast path: claim among fingerprint-filtered candidates only —
    # free slots (fp 0) and same-fingerprint slots (own key re-claim,
    # expired twins).  Probe-byte diet: N_CAND_INS row gathers instead
    # of N_PROBE.
    cand_mask = (win_fp == 0) | (win_fp == key_fp[:, None])
    pos, cand_valid = _first_k(cand_mask, N_CAND_INS)
    for k in range(N_CAND_INS):
        s = jnp.take_along_axis(slots_w, pos[:, k:k + 1], axis=1)[:, 0]
        table, fp, pending = _claim(table, fp, pending, s,
                                    cand_valid[:, k])

    # exact fallback: a still-pending insert might claim an
    # expired-other-key slot the fingerprint can't identify, or lost
    # every candidate to same-window racers — rerun the full-window
    # loop for the batch (rare: needs >= N_CAND_INS contenders or an
    # exhausted window, so steady state never pays it)
    def _full(args):
        table, fp, pending = args
        for step in range(N_PROBE):
            table, fp, pending = _claim(table, fp, pending,
                                        slots_w[:, step])
        return table, fp, pending

    table, fp, pending = jax.lax.cond(
        jnp.any(pending), _full, lambda a: a, (table, fp, pending))

    dropped = ct.dropped + jnp.sum(pending).astype(jnp.uint32)
    return CTTable(table=table, fp=fp, dropped=dropped)


def ct_gc(ct: CTTable, now: jnp.ndarray) -> Tuple[CTTable, jnp.ndarray]:
    """Age out expired entries (reference: pkg/maps/ctmap.GC interval
    sweep).  Returns (table, n_evicted)."""
    live = ct.table[:, V_STATE] != ST_FREE
    expired = live & (ct.table[:, V_EXPIRES] < now)
    n = jnp.sum(expired).astype(jnp.uint32)
    state = jnp.where(expired, ST_FREE, ct.table[:, V_STATE])
    table = ct.table.at[:, V_STATE].set(state.astype(jnp.uint32))
    fp = jnp.where(expired, jnp.uint32(0), ct.fp)
    return CTTable(table=table, fp=fp, dropped=ct.dropped), n


@partial(jax.jit, donate_argnums=0)
def ct_gc_jit(ct: CTTable, now: jnp.ndarray) -> Tuple[CTTable, jnp.ndarray]:
    return ct_gc(ct, now)


# Jitted entry points: each eager scatter/gather costs a separate XLA
# compile, so callers outside the fused datapath_step use these.
ct_lookup_jit = jax.jit(ct_lookup)
ct_update_jit = jax.jit(ct_update, donate_argnums=0)
ct_keys_jit = jax.jit(ct_keys_from_headers)


def ct_live_count(ct: CTTable) -> int:
    return int(np.asarray(jnp.sum(ct.table[:, V_STATE] != ST_FREE)))


_STATE_NAMES = {ST_SYN_SENT: "SYN_SENT", ST_ESTABLISHED: "ESTABLISHED",
                ST_CLOSING: "CLOSING"}


def _hash_np(keys: np.ndarray) -> np.ndarray:
    """Host-side hash identical to :func:`_hash` (for re-placement)."""
    keys = keys.astype(np.uint32)
    h = np.full(keys.shape[0], 0x811C9DC5, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for w in range(KEY_WORDS):
            h = (h ^ keys[:, w]) * np.uint32(0x01000193)
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _fp_mix_np(h: np.ndarray) -> np.ndarray:
    """Host mirror of :func:`_fp_mix`."""
    with np.errstate(over="ignore"):
        g = h ^ (h >> np.uint32(16))
        g = g * np.uint32(0x85EBCA6B)
        g = g ^ (g >> np.uint32(13))
        g = g * np.uint32(0xC2B2AE35)
    return (g >> np.uint32(24)) % np.uint32(255) + np.uint32(1)


def ct_fp_from_table(table: np.ndarray) -> np.ndarray:
    """Recompute the per-slot fingerprint array from a placed table.

    The fingerprint is derived state (a pure function of each live
    slot's key), so restores rebuild it instead of persisting it."""
    table = np.asarray(table, dtype=np.uint32)
    fp = np.zeros(table.shape[0], dtype=np.uint32)
    live = table[:, V_STATE] != ST_FREE
    if live.any():
        fp[live] = _fp_mix_np(_hash_np(table[live, :KEY_WORDS]))
    return fp


def ct_rows_from_table(table: np.ndarray) -> np.ndarray:
    """Live rows of a (hashed) CT table -> dense [n, ROW_WORDS] array.

    The dense form is the portable snapshot format: it carries no slot
    placement, so it can be restored into a table of ANY capacity (or
    into the interpreter backend's dict)."""
    table = np.asarray(table)
    return table[table[:, V_STATE] != ST_FREE].copy()


def ct_table_from_rows(rows: np.ndarray,
                       capacity: int) -> Tuple[np.ndarray, int]:
    """Rebuild a hashed CT table from dense snapshot rows.

    Re-places every entry with the same FNV hash + linear probe the
    device uses, so a snapshot taken at one capacity (or from the
    interpreter oracle) restores correctly into another.  Returns
    ``(table, n_dropped)``: entries that cannot be placed within the
    probe window are dropped and counted — seed ``CTTable.dropped``
    with the count so restore-time map pressure shows in metrics like
    live-insert pressure does."""
    assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
    table = np.zeros((capacity, ROW_WORDS), dtype=np.uint32)
    rows = np.asarray(rows, dtype=np.uint32)
    if rows.size == 0:
        return table, 0
    mask = np.uint32(capacity - 1)
    hs = _hash_np(rows[:, :KEY_WORDS])
    # vectorized placement: per probe step, every still-pending row
    # bids for its slot; the first bidder (original row order) of each
    # free slot wins — restart restores of ~1M flows stay sub-second
    pending = np.arange(len(rows))
    for step in range(N_PROBE):
        if not len(pending):
            break
        slots = (hs[pending] + np.uint32(step)) & mask
        free = table[slots, V_STATE] == ST_FREE
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        first = np.ones(len(order), dtype=bool)
        first[1:] = s_sorted[1:] != s_sorted[:-1]
        win = np.zeros(len(pending), dtype=bool)
        win[order] = first
        place = free & win
        table[slots[place]] = rows[pending[place]]
        pending = pending[~place]
    return table, len(pending)


def ct_entries_from_snapshot(table: np.ndarray,
                             limit: int = 1000) -> list:
    """Decode live CT rows for display (`cilium bpf ct list`)."""
    from ..core.packets import words_to_ip

    table = np.asarray(table)
    live = np.nonzero(table[:, V_STATE] != ST_FREE)[0][:limit]
    out = []
    for i in live:
        row = table[i]
        proto = int(row[9]) & 0xFF
        dirn = (int(row[9]) >> 8) & 1
        fam = 4 if not row[0:3].any() else 6
        out.append({
            "src": words_to_ip(row[0:4], fam),
            "dst": words_to_ip(row[4:8], fam),
            "sport": int(row[8]) >> 16,
            "dport": int(row[8]) & 0xFFFF,
            "proto": proto,
            "dir": "ingress" if dirn == 0 else "egress",
            "state": _STATE_NAMES.get(int(row[V_STATE]),
                                      str(int(row[V_STATE]))),
            "expires": int(row[V_EXPIRES]),
            "tx_packets": int(row[V_TX_PKTS]),
            "rx_packets": int(row[V_RX_PKTS]),
            "tx_bytes": int(row[V_TX_BYTES]),
            "rx_bytes": int(row[V_RX_BYTES]),
            "proxy_port": int(row[V_PROXY]),
        })
    return out
