"""The Loader seam: agent-facing datapath interface + backends.

Reference: upstream cilium ``pkg/datapath`` — the ``Loader`` /
``Datapath`` interfaces that ``daemon`` drives ("compile + attach"
eBPF), with ``pkg/datapath/fake`` proving the seam supports non-eBPF
backends.  BASELINE.md's north-star gates the TPU path behind exactly
this seam: "compile+attach" becomes "compile policy/ipcache tensors +
bind device buffers".

Backends:
- :class:`TPULoader` — device tensors + the fused jit pipeline.
- :class:`InterpreterLoader` — the sequential oracle; runs the whole
  agent without any accelerator (the fake-datapath analogue; also the
  divergence-checking reference).

Policy/ipcache updates swap tensors while KEEPING the live conntrack
table and metric counters — the analogue of cilium replacing pinned
BPF programs while maps persist in bpffs (SURVEY.md §5).
"""

from __future__ import annotations

import abc
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..policy.compiler import IdentityRowMap, compile_policy
from ..policy.resolve import EndpointPolicy
from .lpm import compile_lpm
from .verdict import MAX_ENDPOINTS, DatapathState, DevicePolicy

# Jitted DONATING dynamic_update_slice — the table patch paths'
# workhorse.  Two design points, both load-bearing at scale:
#
# - ``.at[idx].set`` lowers to XLA SCATTER (measured ~20x slower than
#   a slice update on CPU); patches are row/block-contiguous by
#   construction, so DUS is always expressible.
# - ``donate_argnums=0``: the update aliases the live buffer IN
#   PLACE, so a patch costs O(row), not a full-tensor copy — the r05
#   audit's verdict tensor is GBs at production scale, and copying
#   it per identity churn op would make "incremental" a lie.
#   Donation is safe under the SAME discipline the serve step's own
#   ``donate_argnums=0`` relies on: device-stream ordering sequences
#   the in-place write after every already-enqueued dispatch's
#   reads, and the caller (``_publish_tables``) swaps the state
#   reference in the same locked region with nothing fallible in
#   between, so no dispatch can ever be handed the consumed handle.
#
# Lazy: CPU-only tools import this module without jax.
_dus_jit = None


def _dus(arr, upd, starts):
    global _dus_jit
    if _dus_jit is None:
        import jax

        _dus_jit = jax.jit(
            lambda a, u, s: jax.lax.dynamic_update_slice(a, u, s),
            donate_argnums=0)
    return _dus_jit(arr, upd, tuple(starts))


# Jitted CT-occupancy reduction (the map-pressure sample's one piece
# of device math).  MODULE-level like _dus: one executable per
# (capacity, placement) per process, shared across loaders, so the
# periodic pressure sample never pays — or worse, races a serving
# dispatch's compile-log window with — a fresh XLA compile after the
# first warm call (Daemon.start() / serving_shard warm it).
_occ_jit = None


def _ct_occupied(fp):
    """Occupied CT slots (live + expired-but-unswept): fp != 0 —
    the per-slot key fingerprint's free marker doubles as the
    occupancy bitmap, so the sample reduces 4 B/slot instead of
    loading the 68 B rows."""
    global _occ_jit
    if _occ_jit is None:
        import jax
        import jax.numpy as jnp

        _occ_jit = jax.jit(
            lambda f: jnp.sum(f != 0, dtype=jnp.uint32))
    return _occ_jit(fp)


class Loader(abc.ABC):
    """What the agent needs from a datapath (pkg/datapath.Loader)."""

    @abc.abstractmethod
    def attach(self, policies: Sequence[EndpointPolicy],
               ipcache: Dict[str, int], ep_policy: Dict[int, int],
               row_map: IdentityRowMap) -> None:
        """Full (re)compile + swap — endpoint regeneration's final step.

        ``ipcache`` maps cidr -> NUMERIC identity; ``ep_policy`` maps
        endpoint id -> row index into ``policies``."""

    @abc.abstractmethod
    def step(self, hdr: np.ndarray, now: int, pre_drop=None,
             pre_drop_reason=None, lb_drop=None, audit=False):
        """Verdict one batch.

        Returns ``(out, row_map)``: the out tensor [N, N_OUT] plus the
        IdentityRowMap snapshot that produced it.  The snapshot is
        taken under the same lock as the device step so a concurrent
        ``attach`` can never make the caller decode OUT_ID_ROW values
        through the wrong row table.  ``pre_drop`` ([N] bool) is the
        SNAT stage's exhaustion mask from :meth:`masquerade`."""

    @abc.abstractmethod
    def gc(self, now: int) -> int:
        """Expire CT entries; returns eviction count."""

    # -- mutual authentication (pkg/auth authmap analogue) ------------
    @abc.abstractmethod
    def auth_upsert(self, ep_id: int, remote_id: int,
                    expires: int) -> bool:
        """Grant (subject endpoint's identity, remote identity) until
        ``expires``.  Entries are identity-granular: endpoints sharing
        a policy row (same labels) share the grant, exactly upstream's
        {local identity, remote identity} authmap key."""

    @abc.abstractmethod
    def auth_entries(self) -> list:
        """Live grants for `cilium-tpu bpf auth list`."""

    @abc.abstractmethod
    def auth_gc(self, now: int) -> int:
        """Drop expired grants; returns eviction count."""

    @abc.abstractmethod
    def metrics(self) -> np.ndarray:
        """[N_REASONS, 2] per-reason/direction packet counters."""

    @abc.abstractmethod
    def ct_snapshot(self) -> np.ndarray:
        """CT table contents for checkpoint / `bpf ct list`."""

    @abc.abstractmethod
    def ct_restore(self, table: np.ndarray) -> None:
        """Reload a CT snapshot (agent restart keeps connections)."""

    # -- incremental updates (SURVEY.md §7 hard part #3) --------------
    # Identity churn must NOT cost a full compile_policy + upload; the
    # default False sends callers down the full-attach path, backends
    # that can patch in place override.

    def patch_identity(self, kind: str, numeric_id: int,
                       policies) -> bool:
        """Patch one identity's verdict row in place (peer sets in
        ``policies`` must already reflect the change — see
        policy.incremental.update_contributions).  Returns False when
        a full attach is required instead."""
        return False

    def patch_ipcache(self, cidr: str, numeric_id: int) -> bool:
        """Patch one ipcache prefix -> identity mapping in place."""
        return False

    def delete_ipcache(self, cidr: str) -> bool:
        """Remove one ipcache prefix in place (fqdn TTL expiry)."""
        return False

    # -- map pressure (ISSUE 12: pkg/maps ctmap pressure analogue;
    # ISSUE 19 widened the sample beyond CT: LPM/ipcache prefix
    # occupancy and policy-table row occupancy ride the same
    # snapshot, feeding cilium_lpm_occupancy /
    # cilium_policy_map_occupancy and the map-headroom SLO) ----------
    def map_pressure(self, now: int) -> dict:
        """Point-in-time map-pressure snapshot: CT occupancy +
        cumulative insert drops, NAT pool failures, LPM/ipcache and
        policy-table occupancy.  Backends override; the default
        reports an unmeasurable world (the monitor then keys on the
        counters alone)."""
        return {"ct": {"capacity": 0, "occupied": 0,
                       "occupancy": None, "insert-drops": 0},
                "nat": {"capacity": None, "failures": 0},
                "lpm": {"capacity": 0, "entries": 0,
                        "occupancy": None},
                "policy": {"capacity": 0, "rows": 0,
                           "occupancy": None}}


class TPULoader(Loader):
    """The real datapath: device tensors + fused jit pipeline.

    TABLE GENERATION DISCIPLINE (ISSUE 10; datapath/tables.py): the
    published policy/ipcache tables are versioned behind a
    double-buffered slot pair with a monotonic generation tag.  Every
    mutation — full/delta ``attach``, ``patch_identity``,
    ``patch_ipcache``, ``delete_ipcache``, ``auth_upsert`` — is a
    BUILDER: it assembles the successor tables holding only the
    builder lock (host compile + ``.at[].set`` device work happen off
    the dispatch path) and publishes through ``_publish_tables``,
    which takes the dispatch lock ONLY for the generation flip.  The
    attrs below are the published tables + their host mirrors; the
    static CTA009 checker (analysis/generation.py) flags any write to
    them outside a ``# table-swap-ok`` method, so a shortcut that
    mutates a live table in place cannot land silently.
    """
    # active-tables: state, tensors, _lpm_tensors, _lpm_entries,
    # active-tables: _epp, _policies

    def __init__(self, ct_capacity: int = 1 << 20,
                 delta_compile: bool = True,
                 swap_warn_ms: float = 0.0,
                 nat_capacity: Optional[int] = None):
        import jax.numpy as jnp  # deferred so CPU-only tools can import

        from ..infra.lockdebug import make_lock
        from .tables import TableVersioner

        self._jnp = jnp
        self.ct_capacity = ct_capacity
        # SNAT port-pool size (service/nat.py NATTable); None = the
        # NAT_DEFAULT_CAPACITY.  Small pools are the nat_exhaustion
        # scenario's pressure shape
        self.nat_capacity = nat_capacity
        self.state: Optional[DatapathState] = None
        self.nat_state = None  # NATTable, created on first masquerade
        self.row_map: Optional[IdentityRowMap] = None
        self.attach_count = 0
        # mutual-auth grants, host-authoritative: (ep_id, remote
        # numeric identity) -> expires.  The device [n_pol, n_rows]
        # tensor is a projection rebuilt on every attach (rows and
        # policy indices shift; the dict keys are stable)
        self._auth: Dict[Tuple[int, int], int] = {}
        self._epp = None  # ep -> policy row, mirrors the device table
        # attach() runs on API/regeneration threads while the serve
        # loop is in step(); every state swap must be atomic or a
        # concurrent step would resurrect the pre-attach tensors.
        # make_lock: plain Lock normally, order-checked DebugLock
        # under CILIUM_TPU_LOCKDEBUG=1 (SURVEY §5 race detection)
        #
        # Lock discipline for the hot path: ALL host-side staging
        # (np.ascontiguousarray + the h2d jnp.asarray/device_put)
        # happens BEFORE the lock is taken; the lock covers only the
        # async dispatch + state swap, so attach/auth/API calls never
        # stall behind a host->device copy, and host assembly of
        # batch N+1 overlaps device execution of batch N.
        self._lock = make_lock("datapath-loader")
        # guarded-by: datapath-loader: state
        # (the runtime lockdebug name resolves to _lock in the static
        # checker's alias map too — one identity, both worlds)
        #
        # Table versioning (datapath/tables.py): the slot pair +
        # generation tag + the BUILDER lock serializing every table
        # mutation.  Lock order: table-builder BEFORE datapath-loader
        # (builders publish under the dispatch lock while holding the
        # build lock; nothing acquires them the other way around).
        self.tables = TableVersioner(warn_ms=swap_warn_ms)
        # delta attach (policy.incremental.delta_compile): repaint
        # only fingerprint-changed policies.  _policy_fps is the
        # previous attach's fingerprints (None until the first one)
        self.delta_compile = bool(delta_compile)
        self._policy_fps: Optional[list] = None
        # DUS executable warm set (see _warm_dus) and the
        # incomplete-swap flag the disaster-recovery path keys on
        # (see _building / _heal_incomplete_swap)
        self._dus_warm: set = set()
        self._swap_incomplete = False
        # host-drop counts awaiting a free dispatch lock (see
        # add_host_drops: the watchdog must never block on _lock)
        self._host_drops: Dict[int, int] = {}
        self._host_drops_lock = make_lock("loader-host-drops")
        # guarded-by: loader-host-drops: _host_drops
        # multi-chip serving (parallel/mesh.py): serving_shard()
        # installs the mesh and re-places state (CT sharded per chip,
        # tables replicated); sharded serve steps are cached per
        # (packed, trace_sample, audit) so one serving session
        # compiles exactly one executable per ladder rung and mode
        self._serving_mesh = None
        self._sharded_steps: Dict[tuple, object] = {}
        # compile introspection (obs/compile_log.py): every XLA
        # retrace on the serving path is recorded with shape/mode and
        # the one-executable-per-(rung, mode) invariant asserted at
        # runtime — the jit-cache sizes are sampled around each
        # dispatch (two dict-len reads; noise against the dispatch)
        from ..obs.compile_log import CompileLog

        self.compile_log = CompileLog()

    def _serving_cache_size(self, mode: str) -> int:
        """Executable count backing one serving mode RIGHT NOW."""
        from ..monitor.ring import (serve_step_jit,
                                    serve_step_packed_jit,
                                    serve_superbatch_jit,
                                    serve_superbatch_packed_jit)

        if mode == "wide":
            fn = serve_step_jit
        elif mode == "packed":
            fn = serve_step_packed_jit
        elif mode == "super-wide":
            fn = serve_superbatch_jit
        elif mode == "super-packed":
            fn = serve_superbatch_packed_jit
        else:  # sharded steps are per-(packed, sample, audit) jits
            return sum(
                getattr(f, "_cache_size", lambda: 1)()
                for f in self._sharded_steps.values())
        size = getattr(fn, "_cache_size", None)
        return size() if size is not None else 0

    def _record_compile(self, mode: str, shape, ring_cap: int,
                        statics: tuple, before: int, after: int,
                        elapsed_s: float) -> None:
        """Key the invariant on everything that LEGITIMATELY selects
        a distinct executable — shape, ring capacity, static args,
        and the attach generation (a policy-world change retraces by
        design) — so a growth on an already-seen key is a genuine
        retrace (e.g. the P(axis) vs P(axis, None) sharding-spelling
        trap), not a config change."""
        self.compile_log.record_dispatch(
            mode, tuple(shape), before, after, elapsed_s,
            key_extra=(int(ring_cap),) + tuple(statics)
            + (self.attach_count,))

    def _rekeep_serving_placement(self) -> None:
        # holds: datapath-loader
        # table-swap-ok: placement-only re-put of the CURRENT state
        # (no table content changes; sharded serving must not see
        # fresh leaves land single-device)
        """Call (under the lock) after ANY state swap that introduces
        fresh arrays: during sharded serving the swap must not
        silently unshard the CT or leave new tensors single-device —
        the next sharded step would either recompile or, worse, run
        against an implicitly resharded CT.  No-op outside sharded
        serving; device_put is a no-op on already-placed leaves."""
        if self._serving_mesh is None:
            return
        from ..parallel.mesh import shard_state

        self.state = shard_state(self.state, self._serving_mesh)

    @contextmanager
    def _building(self):
        """tables.building() plus disaster recovery: a builder that
        dies INSIDE the locked publish window — after a donating
        device_patch consumed live buffers, or after the state swap
        but before the flip (placement failure) — re-uploads the
        published content from the host mirrors, which the builder's
        own rollback has just restored.  Serving dispatches therefore
        never see a consumed handle or an unflipped half-publish;
        the publish-or-nothing contract survives even failures inside
        the lock."""
        with self.tables.building() as b:
            try:
                yield b
            except BaseException:
                self._heal_incomplete_swap()
                raise

    def _warm_dus(self, arr, upd, starts) -> None:
        """Pre-compile the donating DUS executable for this (array,
        update) shape pair OFF the dispatch lock: the first call per
        shape pays an XLA trace+compile (tens of ms) that must never
        run inside the locked publish window.  ``arr`` may be a
        consumed handle — only its shape/dtype metadata is read; the
        warm call donates a throwaway zeros array.  One-time per
        shape pair (shapes change only on capacity growth)."""
        key = (tuple(arr.shape), str(arr.dtype),
               tuple(upd.shape), str(upd.dtype))
        if key in self._dus_warm:
            return
        _dus(self._jnp.zeros(arr.shape, arr.dtype), upd,
             tuple(0 for _ in starts))
        self._dus_warm.add(key)

    def _project_auth(self, epp, row_map, n_pol: int,
                      n_rows: int) -> np.ndarray:
        """The host-authoritative auth grants projected onto the
        device [n_pol, n_rows] table — ONE definition shared by the
        full attach and disaster recovery, so a republished-from-
        mirrors world can never carry different grant rules than a
        normal attach (patch_identity's single-COLUMN re-projection
        mirrors the same bounds/merge rules for one numeric).
        ``row_map`` is explicit: attach projects through its ARGUMENT
        map (self.row_map is still the previous one pre-publish)."""
        auth_np = np.zeros((n_pol, n_rows), dtype=np.uint32)
        with self._lock:  # _auth shares the dispatch lock
            auth_items = list(self._auth.items())
        for (ep, rem), exp in auth_items:
            pr = (epp[ep] if epp is not None
                  and 0 <= ep < MAX_ENDPOINTS else -1)
            r = row_map.row(rem) if row_map is not None else 0
            if pr >= 0 and 0 < r < auth_np.shape[1]:
                auth_np[pr, r] = max(auth_np[pr, r], exp)
        return auth_np

    def _heal_incomplete_swap(self) -> None:
        # table-swap-ok: disaster recovery — re-uploads the PUBLISHED
        # content from the host mirrors after a failure inside the
        # locked publish window; no generation bump (content is
        # exactly as published)
        """No-op unless a publish died mid-window (the
        ``_swap_incomplete`` flag).  Rebuilds the device tables from
        the host mirrors — pre-patch by the rollback contract — so
        the datapath serves exactly the published generation again,
        whatever a partial donating chain or placement failure left
        behind."""
        if not self._swap_incomplete:
            return
        from .lpm import DeviceLPM

        tensors = getattr(self, "tensors", None)
        if tensors is None or self._published_state() is None:
            self._swap_incomplete = False
            return
        epp = self._epp
        policy = DevicePolicy.from_tensors(
            tensors, epp,
            auth=self._project_auth(epp, self.row_map,
                                    tensors.verdict.shape[0],
                                    tensors.verdict.shape[2]))
        lpm = DeviceLPM.from_tensors(self._lpm_tensors)
        with self._lock:
            self.state = DatapathState(
                policy=policy, ipcache=lpm,
                ct=self.state.ct, metrics=self.state.metrics)
            self._rekeep_serving_placement()
            self._swap_incomplete = False

    def _published_state(self) -> Optional[DatapathState]:
        # thread-affinity: any
        """Locked point read of the published state.  Builders (under
        the build lock) use it to capture the ACTIVE policy/ipcache:
        those fields are stable until the builder itself publishes —
        every publisher serializes on the build lock — while ct/
        metrics keep advancing under dispatches (the publish flip
        re-reads them under the dispatch lock)."""
        with self._lock:
            return self.state

    def _publish_tables(self, build, policy=None, lpm=None,
                        device_patch=None, row_map=None,
                        mirrors=None, attach: bool = False) -> int:
        # table-swap-ok: THE swap helper — the only site that exposes
        # a new table generation to dispatches.  The dispatch lock is
        # held for the pointer swap + generation flip, plus — for
        # derived-array patches — the ``device_patch`` enqueue:
        # every dispatch DONATES the whole state (donate_argnums=0),
        # so device arrays derived from the live tables must be
        # re-derived from the CURRENT state under the lock (a handle
        # captured off-lock dies at the next dispatch).  The patch
        # itself is an async ``.at[].set`` enqueue — microseconds of
        # lock hold; the device copy overlaps later dispatches.
        # Mirrors are painted after the flip (build lock still
        # held), so a crash anywhere earlier leaves the published
        # generation AND its host mirrors untouched.
        # every caller is inside tables.building() (the build
        # lock lives on self.tables); the dispatch lock is taken here
        from ..infra import faults
        from .conntrack import CTTable

        with self._lock:
            # the mid-swap crash site: fires at the last instant
            # before the flip, with the dispatch lock held — a raise
            # here must still publish NOTHING (chaos-gate regression)
            faults.check(faults.SITE_CHURN_SWAP)
            t_lock = time.monotonic()
            if row_map is not None:
                self.row_map = row_map
            # from here to the flip, a failure leaves live state
            # possibly consumed or half-swapped: flag it so the
            # builder wrapper (_building) heals from the mirrors
            self._swap_incomplete = True
            if device_patch is not None:
                p2, l2 = device_patch(self.state)
                policy = p2 if p2 is not None else policy
                lpm = l2 if l2 is not None else lpm
            if policy is None:
                policy = self.state.policy
            if lpm is None:
                lpm = self.state.ipcache
            if self.state is None:  # keep live CT + counters otherwise
                self.state = DatapathState.create(
                    policy=policy, ipcache=lpm,
                    ct=CTTable.create(self.ct_capacity))
            else:
                self.state = DatapathState(
                    policy=policy, ipcache=lpm,
                    ct=self.state.ct, metrics=self.state.metrics)
            self._rekeep_serving_placement()
            if attach:
                self.attach_count += 1
            # the slot records the PLACED arrays (sharded serving
            # re-places fresh leaves above), so a recycled slot can
            # never hand back unplaced tensors
            gen = self.tables.flip(build, self.state.policy,
                                   self.state.ipcache, t_lock)
            self._swap_incomplete = False
        if mirrors is not None:
            mirrors()
        return gen

    def table_stats(self) -> dict:
        # thread-affinity: any
        """The ``tables`` stats block: generation, swap/update
        latency, delta-compile scoreboard (serving stats -> GET
        /serving -> CLI -> registry)."""
        return self.tables.snapshot()

    def attach(self, policies, ipcache, ep_policy, row_map) -> None:
        # table-swap-ok: full/delta (re)compile builder — device
        # arrays assembled off the dispatch path, published through
        # _publish_tables, host mirrors swapped post-flip
        """Full (re)compile + swap.  When the previous attach's
        per-policy fingerprints are available and the tensor shapes
        still fit, only the policies whose fingerprints changed are
        repainted (``policy.incremental.delta_compile``) — rule and
        selector churn then costs O(changed policies), not O(world),
        and the serving executables never retrace (shapes are
        byte-stable, which the compile log's one-executable guard
        asserts at runtime)."""
        from ..infra import faults
        from ..policy.compiler import policy_fingerprint
        from ..policy.incremental import delta_compile
        from .lpm import DeviceLPM

        jnp = self._jnp
        with self._building() as build:
            policies = list(policies)
            fps = [policy_fingerprint(p) for p in policies]
            published = self._published_state()
            plan = None
            if (self.delta_compile and published is not None
                    and row_map is self.row_map):
                plan = delta_compile(getattr(self, "tensors", None),
                                     policies, row_map,
                                     self._policy_fps, fps)
            # -1 = lxcmap-miss sentinel: a packet with an unregistered
            # endpoint id DROPS (REASON_NO_ENDPOINT) instead of being
            # judged under endpoint 0's policy (reference: bpf_lxc
            # drops on endpoint lookup failure)
            epp = np.full(MAX_ENDPOINTS, -1, dtype=np.int32)
            for ep_id, pol_row in ep_policy.items():
                if not 0 <= ep_id < MAX_ENDPOINTS:
                    # on-device gathers clamp out-of-range ids to the
                    # last row, silently diverging from the oracle —
                    # reject here
                    raise ValueError(
                        f"endpoint id {ep_id} out of range "
                        f"[0, {MAX_ENDPOINTS})")
                epp[ep_id] = pol_row
            tensors = None
            if plan is None:
                # compile first: it may GROW the row map's capacity,
                # which sizes the auth projection below
                tensors = compile_policy(policies, row_map)
                n_rows = tensors.verdict.shape[2]
            else:
                n_rows = self.tensors.verdict.shape[2]
            auth_np = self._project_auth(epp, row_map,
                                         len(policies), n_rows)
            policy, device_patch = None, None
            if plan is None:
                policy = DevicePolicy.from_tensors(tensors, epp,
                                                   auth=auth_np)
            else:
                # delta: ship only the changed policies' slices (and
                # the class maps when the global partition moved).
                # h2d uploads are staged HERE (fresh arrays, immune
                # to dispatch donation); the ``.at[].set`` against
                # the live verdict tensor is deferred to the publish
                # step — dispatches donate the state, so the live
                # arrays must be re-derived under the dispatch lock
                slices_dev = {pi: jnp.asarray(plan.slices[pi][None])
                              for pi in plan.changed}
                pc_dev = cm_dev = None
                if plan.class_structure_changed:
                    pc_dev = jnp.asarray(plan.struct.port_class)
                    cm_dev = jnp.asarray(plan.struct.class_map)
                epp_dev = jnp.asarray(epp)
                auth_dev = jnp.asarray(auth_np)
                for sl in slices_dev.values():  # compile off-lock
                    # every slice: the _dus_warm set dedups same-
                    # shape updates, so this stays O(changed) cheap
                    # and never bets the lock-hold budget on an
                    # all-slices-same-shape assumption
                    self._warm_dus(published.policy.verdict, sl,
                                   (0, 0, 0, 0))

                def device_patch(state):
                    pol = state.policy
                    verdict = pol.verdict
                    for pi, sl in slices_dev.items():
                        verdict = _dus(verdict, sl, (pi, 0, 0, 0))
                    return DevicePolicy(
                        proto_table=pol.proto_table,
                        port_class=(pc_dev if pc_dev is not None
                                    else pol.port_class),
                        class_map=(cm_dev if cm_dev is not None
                                   else pol.class_map),
                        verdict=verdict,
                        ep_policy=epp_dev,
                        auth=auth_dev), None
            # the LPM recompiles every attach (the ipcache map is an
            # arbitrary diff; /32 churn goes through patch_ipcache,
            # never here) — milliseconds, and never a policy compile
            lpm = compile_lpm({c: row_map.row(i)
                               for c, i in ipcache.items()})
            device_lpm = DeviceLPM.from_tensors(lpm)
            faults.check(faults.SITE_CHURN_BUILD)

            def mirrors():
                self._epp = epp
                self._policies = policies
                self._policy_fps = fps
                self._lpm_entries = dict(ipcache)  # cidr -> numeric
                self._lpm_tensors = lpm  # host mirror for patches
                if plan is None:
                    self.tensors = tensors
                else:
                    for pi in plan.changed:
                        self.tensors.verdict[pi] = plan.slices[pi]
                    self.tensors = plan.apply_structure(self.tensors)

            self._publish_tables(build, policy=policy,
                                 lpm=device_lpm,
                                 device_patch=device_patch,
                                 row_map=row_map, mirrors=mirrors,
                                 attach=True)
            # scoreboard bumps only AFTER the publish: a fault-
            # aborted attach counts as a failed build, never as a
            # completed (full or delta) attach
            if plan is None:
                self.tables.full_attaches += 1
                self.tables.policies_recompiled += len(policies)
            else:
                self.tables.delta_attaches += 1
                self.tables.policies_recompiled += len(plan.changed)

    def auth_upsert(self, ep_id: int, remote_id: int,
                    expires: int) -> bool:
        # table-swap-ok: auth-plane builder — the device grant cell
        # is built off the dispatch path and published via
        # _publish_tables (the host-authoritative dict write keeps
        # the dispatch lock it shares with auth_gc/auth_entries)
        jnp = self._jnp
        with self._building() as build:
            with self._lock:
                self._auth[(int(ep_id), int(remote_id))] = int(expires)
            published = self._published_state()
            if published is None or self._epp is None:
                return False
            pr = (self._epp[ep_id]
                  if 0 <= ep_id < MAX_ENDPOINTS else -1)
            r = self.row_map.row(remote_id) if self.row_map else 0
            # shape validation against the active policy (shape
            # metadata survives dispatch donation; the ARRAYS are
            # re-derived under the dispatch lock below)
            if pr < 0 or not 0 < r < published.policy.auth.shape[1]:
                # unknown endpoint/identity row: the grant stays
                # host-side and lands at the next attach
                return False
            exp_dev = jnp.full((1, 1), expires, jnp.uint32)
            self._warm_dus(published.policy.auth, exp_dev, (0, 0))

            def device_patch(state):
                pol = state.policy
                return DevicePolicy(
                    proto_table=pol.proto_table,
                    port_class=pol.port_class,
                    class_map=pol.class_map,
                    verdict=pol.verdict,
                    ep_policy=pol.ep_policy,
                    auth=_dus(pol.auth, exp_dev,
                              (int(pr), int(r)))), None

            self._publish_tables(build, device_patch=device_patch)
        return True

    def auth_entries(self) -> list:
        with self._lock:
            return [{"endpoint": ep, "remote_identity": rem,
                     "expires": exp}
                    for (ep, rem), exp in sorted(self._auth.items())]

    def auth_gc(self, now: int) -> int:
        with self._lock:
            dead = [k for k, exp in self._auth.items() if exp <= now]
            for k in dead:
                del self._auth[k]
        return len(dead)

    def step(self, hdr, now: int, pre_drop=None,
             pre_drop_reason=None, lb_drop=None, audit=False):
        # table-swap-ok: dispatch-result swap — CT/metrics advance,
        # policy+ipcache references carried unchanged
        """``hdr`` may be a numpy array OR an already-on-device jax
        array (the LB stage hands its output over without a host
        round trip).  ``pre_drop`` is the SNAT stage's exhaustion
        mask (rows drop with REASON_NAT_EXHAUSTED);
        ``pre_drop_reason`` carries per-row REASON codes (bandwidth
        manager)."""
        from .verdict import datapath_step_jit

        jnp = self._jnp
        # host staging OUT from under the lock (see __init__ lock
        # discipline): the lock protects dispatch + state swap only,
        # never an h2d copy
        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        if isinstance(pre_drop, np.ndarray):
            pre_drop = jnp.asarray(pre_drop)
        if isinstance(pre_drop_reason, np.ndarray):
            pre_drop_reason = jnp.asarray(pre_drop_reason)
        if isinstance(lb_drop, np.ndarray):
            lb_drop = jnp.asarray(lb_drop)
        now = jnp.uint32(now)
        with self._lock:
            out, self.state = datapath_step_jit(
                self.state, hdr, now, pre_drop=pre_drop,
                pre_drop_reason=pre_drop_reason, lb_drop=lb_drop,
                audit=audit)
            row_map = self.row_map
        return np.asarray(out), row_map

    def serve(self, ring, hdr, now: int, batch_id: int,
              trace_sample: int = 1024, proxy_ports=None,
              audit: bool = False, valid=None):
        # thread-affinity: drain, api
        # table-swap-ok: dispatch-result swap — CT/metrics advance,
        # policy+ipcache references carried unchanged
        """The SERVING-path step: fused datapath + event-ring append
        in one dispatch, NO host fetch (monitor/ring.py serve_step).
        Returns (ring', row_map); events reach the host when the
        caller drains the ring at its own cadence — the perf-ring
        economics, vs :meth:`step`'s fetch-per-batch debug path.

        ``valid`` ([N] bool, optional) masks the adaptive batcher's
        padding rows: masked rows touch neither CT, metrics, nor the
        event ring, so one bucket size stays one compiled shape."""
        from ..infra import faults
        from ..monitor.ring import serve_step_jit

        faults.check(faults.SITE_LOADER_SERVE)
        jnp = self._jnp
        # staging before the lock: only the async dispatch is
        # serialized (lock discipline in __init__)
        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        if isinstance(valid, np.ndarray):
            valid = jnp.asarray(valid)
        now, batch_id = jnp.uint32(now), jnp.uint32(batch_id)
        with self._lock:
            before = self._serving_cache_size("wide")
            t0 = time.monotonic()
            self.state, ring = serve_step_jit(
                self.state, ring, hdr, now, batch_id,
                trace_sample=trace_sample,
                valid=valid, proxy_ports=proxy_ports, audit=audit)
            after = self._serving_cache_size("wide")
            row_map = self.row_map
        if after > before:
            self._record_compile(
                "wide", hdr.shape, ring.capacity,
                (int(trace_sample), bool(audit),
                 proxy_ports is not None, valid is not None),
                before, after, time.monotonic() - t0)
        return ring, row_map

    def serve_packed(self, ring, packed, now: int, batch_id: int,
                     ep: int, dirn: int, trace_sample: int = 1024,
                     proxy_ports=None, audit: bool = False,
                     valid=None):
        # thread-affinity: drain, api
        # table-swap-ok: dispatch-result swap — CT/metrics advance,
        # policy+ipcache references carried unchanged
        """The packed serving fast path: [N, 4] uint32 rows —
        16 B/packet on the h2d link instead of :meth:`serve`'s 64 B —
        with on-device unpack + datapath + event-ring append fused in
        ONE dispatch (monitor/ring.py serve_step_packed).  ``ep`` /
        ``dirn`` are per-batch stream metadata scalars;  ``valid``
        masks the adaptive batcher's padding rows exactly like the
        wide path, so each bucket size stays one compiled shape."""
        from ..infra import faults
        from ..monitor.ring import serve_step_packed_jit

        faults.check(faults.SITE_LOADER_SERVE_PACKED)
        jnp = self._jnp
        if isinstance(packed, np.ndarray):
            packed = jnp.asarray(np.ascontiguousarray(packed))
        if isinstance(valid, np.ndarray):
            valid = jnp.asarray(valid)
        now, batch_id = jnp.uint32(now), jnp.uint32(batch_id)
        ep, dirn = jnp.uint32(ep), jnp.uint32(dirn)
        with self._lock:
            before = self._serving_cache_size("packed")
            t0 = time.monotonic()
            self.state, ring = serve_step_packed_jit(
                self.state, ring, packed, now, batch_id, ep, dirn,
                trace_sample=trace_sample, valid=valid,
                proxy_ports=proxy_ports, audit=audit)
            after = self._serving_cache_size("packed")
            row_map = self.row_map
        if after > before:
            self._record_compile(
                "packed", packed.shape, ring.capacity,
                (int(trace_sample), bool(audit),
                 proxy_ports is not None, valid is not None),
                before, after, time.monotonic() - t0)
        return ring, row_map

    def serve_superbatch(self, ring, hdr, now: int, batch_id0: int,
                         eps=None, dirns=None,
                         trace_sample: int = 1024,
                         proxy_ports=None, audit: bool = False,
                         valid=None, packed: bool = False):
        # thread-affinity: drain, api
        # table-swap-ok: dispatch-result swap — CT/metrics advance,
        # policy+ipcache references carried unchanged
        """The K-batch superbatch dispatch (ISSUE 11): ``hdr`` is
        [K, bucket, 4] packed rows (``packed=True``, with ``eps``/
        ``dirns`` [K] per-step stream scalars) or [K, bucket, N_COLS]
        wide rows; ``valid`` [K, bucket] masks padding rows AND whole
        empty trailing steps.  One lock window, one h2d staging copy,
        one jit call for K batches — the Python per-dispatch cost the
        drain loop pays is amortized K-fold
        (monitor/ring.py serve_superbatch*).

        Generation pinning: the scan captures ONE DatapathState, so
        the whole superbatch serves a single table generation — a
        concurrent publish flips wholly before or wholly after this
        dispatch (re-proven at K>1 by the churn chaos gate)."""
        from ..infra import faults
        from ..monitor.ring import (serve_superbatch_jit,
                                    serve_superbatch_packed_jit)

        faults.check(faults.SITE_LOADER_SERVE_SUPER)
        jnp = self._jnp
        # staging before the lock: only the async dispatch is
        # serialized (lock discipline in __init__)
        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        if isinstance(valid, np.ndarray):
            valid = jnp.asarray(valid)
        if packed:
            eps = jnp.asarray(
                np.ascontiguousarray(eps, dtype=np.uint32))
            dirns = jnp.asarray(
                np.ascontiguousarray(dirns, dtype=np.uint32))
        now, batch_id0 = jnp.uint32(now), jnp.uint32(batch_id0)
        mode = "super-packed" if packed else "super-wide"
        with self._lock:
            before = self._serving_cache_size(mode)
            t0 = time.monotonic()
            if packed:
                self.state, ring = serve_superbatch_packed_jit(
                    self.state, ring, hdr, now, batch_id0, eps,
                    dirns, trace_sample=trace_sample, valid=valid,
                    proxy_ports=proxy_ports, audit=audit)
            else:
                self.state, ring = serve_superbatch_jit(
                    self.state, ring, hdr, now, batch_id0,
                    trace_sample=trace_sample, valid=valid,
                    proxy_ports=proxy_ports, audit=audit)
            after = self._serving_cache_size(mode)
            row_map = self.row_map
        if after > before:
            # hdr.shape is (K, bucket, cols): K rides the shape, so
            # the one-executable invariant keys on (rung, mode, K)
            self._record_compile(
                mode, hdr.shape, ring.capacity,
                (int(trace_sample), bool(audit),
                 proxy_ports is not None),
                before, after, time.monotonic() - t0)
        return ring, row_map

    # -- multi-chip serving (parallel/mesh.py) ------------------------
    def serving_shard(self, mesh) -> None:
        # thread-affinity: drain, api
        # table-swap-ok: placement-only swap (mesh enter) — table
        # contents unchanged, every leaf re-placed for the mesh
        """Enter sharded-serving mode: place the live state for the
        mesh (CT private per chip, policy/ipcache/metrics replicated)
        and route subsequent :meth:`serve_sharded` dispatches through
        per-shard serve steps.  attach()/gc()/ct_restore() keep the
        placement across swaps until :meth:`serving_unshard`."""
        from ..parallel.mesh import shard_state

        with self._lock:
            self._serving_mesh = mesh
            self._sharded_steps = {}
            self.state = shard_state(self.state, mesh)
            # warm the map-pressure occupancy executable for the NEW
            # placement NOW (start_serving runs before tests/benches
            # freeze compile counts): a first pressure sample landing
            # mid-dispatch would otherwise charge its compile to the
            # serving executables' one-per-(rung, mode) window
            _ct_occupied(self.state.ct.fp)

    def serving_unshard(self) -> None:
        # thread-affinity: drain, api
        # table-swap-ok: placement-only swap (mesh exit) — table
        # contents unchanged, gathered back to single-device
        """Leave sharded-serving mode: gather state back to the
        default single-device placement (host round trip — cold path,
        stop_serving only)."""
        import jax

        jnp = self._jnp
        with self._lock:
            if self._serving_mesh is None:
                return
            self._serving_mesh = None
            self._sharded_steps = {}
            self.state = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)), self.state)
            _ct_occupied(self.state.ct.fp)  # re-warm single-device

    def serve_sharded(self, ring, hdr, now: int, batch_id: int,
                      trace_sample: int = 1024, proxy_ports=None,
                      audit: bool = False, valid=None,
                      packed_meta=None):
        # thread-affinity: drain, api
        # table-swap-ok: dispatch-result swap — CT/metrics advance,
        # policy+ipcache references carried unchanged
        """One flow-routed batch through the multi-chip serve step.

        ``hdr`` is the ``route_by_flow`` output — wide
        [n_shards*block, N_COLS], or packed [n_shards*block, 4] with
        ``packed_meta=(ep, dirn)`` for the 16 B/packet link format —
        and ``ring`` a :func:`parallel.mesh.make_sharded_ring` pair
        (per-chip private rings).  Each chip runs datapath + ring
        append on its own block; counters psum to global totals."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..infra import faults
        from ..parallel.mesh import make_sharded_serve_step

        # the shard-unavailable failure mode: a chip dropping off the
        # mesh surfaces as the sharded dispatch raising — exactly
        # where the degraded-mode ladder catches it
        faults.check(faults.SITE_LOADER_SERVE_SHARDED)
        jnp = self._jnp
        mesh = self._serving_mesh
        assert mesh is not None, "serving_shard(mesh) first"
        packed = packed_meta is not None
        # explicit per-chip placement of the batch OUTSIDE the lock:
        # the h2d copy lands each shard's block on its own chip.
        # P("data") spelling matters for the compile cache — see
        # parallel.mesh.make_sharded_ring
        row_sh = NamedSharding(mesh, P("data"))
        if isinstance(hdr, np.ndarray):
            hdr = jax.device_put(np.ascontiguousarray(hdr), row_sh)
        if isinstance(valid, np.ndarray):
            # reuse row_sh: sharding-spelling identity is load-bearing
            # for the compile cache (see make_sharded_ring)
            valid = jax.device_put(valid, row_sh)
        if proxy_ports is None:
            proxy_ports = jnp.zeros((0,), jnp.uint32)
        now, batch_id = jnp.uint32(now), jnp.uint32(batch_id)
        key = (packed, int(trace_sample), bool(audit))
        mode = "sharded-packed" if packed else "sharded"
        with self._lock:
            step = self._sharded_steps.get(key)
            if step is None:
                step = make_sharded_serve_step(
                    mesh, packed=packed, trace_sample=trace_sample,
                    audit=audit)
                self._sharded_steps[key] = step
            before = self._serving_cache_size(mode)
            t0 = time.monotonic()
            if packed:
                ep, dirn = packed_meta
                self.state, ring = step(
                    self.state, ring, hdr, now, batch_id, valid,
                    proxy_ports, jnp.uint32(ep), jnp.uint32(dirn))
            else:
                self.state, ring = step(self.state, ring, hdr, now,
                                        batch_id, valid, proxy_ports)
            after = self._serving_cache_size(mode)
            row_map = self.row_map
        if after > before:
            self._record_compile(
                mode, hdr.shape, ring.buf.shape[0],
                key + (valid is not None,),
                before, after, time.monotonic() - t0)
        return ring, row_map

    def add_route_overflow(self, n: int) -> None:
        # thread-affinity: any
        """Account host-side flow-router overflow in the device
        metricsmap (REASON_ROUTE_OVERFLOW) — the RSS-queue-overflow
        counter; sharding-preserving (.at on the replicated array)."""
        from .verdict import REASON_ROUTE_OVERFLOW

        self.add_host_drops(REASON_ROUTE_OVERFLOW, n)

    def add_host_drops(self, reason: int, n: int) -> None:
        # thread-affinity: any
        """Account host-side drops under ``reason`` in the device
        metricsmap — the serving recovery plane's counterpart of
        :meth:`add_route_overflow`: batches lost to a dead/hung
        dispatch (REASON_DISPATCH_TIMEOUT / REASON_RECOVERY_DROP)
        must show up where operators look, exactly like datapath
        drops.

        NEVER BLOCKS on the dispatch lock: the caller may be the
        serving WATCHDOG accounting a dispatch that is hung INSIDE
        that very lock — waiting here would deadlock recovery
        against the wedge it is recovering from.  When the lock is
        busy the count lands in a host-side pending buffer that
        :meth:`metrics` folds into every read and later calls flush
        opportunistically, so totals are exact either way."""
        if n == 0:
            return
        r = int(reason)
        with self._host_drops_lock:
            self._host_drops[r] = self._host_drops.get(r, 0) + int(n)
        self._flush_host_drops()

    def _flush_host_drops(self) -> None:
        # holds: datapath-loader -- acquired NON-BLOCKING at entry
        # (the early return when busy); every state touch sits inside
        # the acquire/release window the try/finally pins
        # table-swap-ok: metrics-only swap — host-drop counters
        # folded into the metricsmap, tables carried unchanged
        """Move pending host-drop counts into the device metricsmap
        if the dispatch lock is free RIGHT NOW (non-blocking)."""
        from ..parallel.mesh import add_host_drops

        if not self._lock.acquire(blocking=False):
            return
        try:
            with self._host_drops_lock:
                pending = self._host_drops
                if not pending:
                    return
                self._host_drops = {}
            for reason, n in pending.items():
                self.state = add_host_drops(self.state, reason, n)
        finally:
            self._lock.release()

    def masquerade(self, nat, hdr, now: int):
        """CT-aware egress SNAT with port allocation (service/nat.py
        snat_egress); returns (rewritten device hdr, exhaustion drop
        mask) — the mask feeds ``step(pre_drop=...)``.  The NAT table
        lives with the loader like the CT table does (the pkg/maps/nat
        analogue)."""
        from ..service.nat import NATTable, snat_egress_jit

        jnp = self._jnp
        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        # dispatch INSIDE the lock: step() donates state.ct
        # (donate_argnums=0), so reading it here must not race a
        # concurrent step that would invalidate the buffer between
        # capture and dispatch
        with self._lock:
            if self.nat_state is None:
                self.nat_state = (NATTable.create(self.nat_capacity)
                                  if self.nat_capacity
                                  else NATTable.create())
            hdr, self.nat_state, dropped = snat_egress_jit(
                self.nat_state, nat, self.state.ct, hdr,
                jnp.uint32(now))
            return hdr, dropped

    def reverse_nat(self, nat, hdr, now: int):
        """Ingress reverse translation (post-verdict delivery rewrite:
        replies to allocated node ports restore the original pod
        destination)."""
        from ..service.nat import NATTable, snat_reverse_jit

        jnp = self._jnp
        if isinstance(hdr, np.ndarray):
            hdr = jnp.asarray(np.ascontiguousarray(hdr))
        with self._lock:
            if self.nat_state is None:
                self.nat_state = (NATTable.create(self.nat_capacity)
                                  if self.nat_capacity
                                  else NATTable.create())
            hdr, self.nat_state = snat_reverse_jit(
                self.nat_state, nat, hdr, jnp.uint32(now))
            return hdr

    # -- incremental patching (no recompile, no full upload) ----------
    def patch_identity(self, kind: str, numeric_id: int,
                       policies) -> bool:
        # table-swap-ok: identity-row builder — the patched verdict/
        # auth arrays are built off the dispatch path and published
        # via _publish_tables; the host mirror row is painted only
        # AFTER the flip, so a mid-build crash (churn.* fault sites)
        # leaves both the published generation and the mirror intact
        with self._building() as build:
            published = self._published_state()
            if published is None or self.row_map is None:
                return False
            if len(policies) != self.tensors.verdict.shape[0]:
                return False  # policy list changed shape: full attach
            if kind == "remove" and self.row_map.row(numeric_id) == 0:
                return True  # identity never had a row; nothing to patch
            fresh_row = self.row_map.row(numeric_id) == 0
            row = self.row_map.add(numeric_id)
            if row >= self.tensors.verdict.shape[2]:
                if fresh_row:
                    self.row_map.remove(numeric_id)
                return False  # row capacity grew past the tensor
            try:
                return self._patch_identity_build(
                    build, kind, numeric_id, policies, published,
                    row)
            except BaseException:
                # failed build: the published generation and every
                # mirror stay untouched — including the row map (a
                # freshly-allocated row must not leak per aborted
                # churn op, or chaos-rate faults would fill the
                # verdict tensor's row space)
                if fresh_row:
                    self.row_map.remove(numeric_id)
                raise

    def _patch_identity_build(self, build, kind, numeric_id,
                              policies, published, row) -> bool:
        # table-swap-ok: patch_identity's builder body (split out so
        # the row-map rollback wraps it); publishes via
        # _publish_tables exactly like every other builder.  Called
        # only from patch_identity inside tables.building() (the
        # build lock lives on self.tables)
        from ..infra import faults
        from ..policy.incremental import compose_row
        from .verdict import DevicePolicy

        jnp = self._jnp
        # host compose + h2d staging OFF the dispatch lock
        # (fresh arrays, immune to dispatch donation); the
        # ``.at[].set`` against the live tensors is deferred to
        # the publish step's device_patch (dispatches donate the
        # state, so live arrays re-derive under the lock)
        vals = compose_row(policies, numeric_id, self.tensors)
        # staged as the [n_pol, 2, 1, n_cls] row-slice update the
        # publish-time dynamic_update_slice writes in one pass
        vals_dev = jnp.asarray(vals[:, :, None, :])
        # the auth column must track the row's OCCUPANT: a
        # recycled row would otherwise hand the previous
        # identity's live grant to the newcomer (no-handshake
        # forward).  Re-project this numeric's grants from the
        # host dict; zero on remove.
        auth_col = np.zeros(published.policy.auth.shape[0],
                            dtype=np.uint32)
        if kind == "add" and self._epp is not None:
            with self._lock:  # _auth shares the dispatch lock
                auth_items = list(self._auth.items())
            for (ep, rem), exp in auth_items:
                if rem != numeric_id:
                    continue
                pr = (self._epp[ep]
                      if 0 <= ep < MAX_ENDPOINTS else -1)
                if pr >= 0:
                    auth_col[pr] = max(auth_col[pr], exp)
        auth_dev = jnp.asarray(auth_col[:, None])
        self._warm_dus(published.policy.verdict, vals_dev,
                       (0, 0, 0, 0))
        self._warm_dus(published.policy.auth, auth_dev, (0, 0))
        faults.check(faults.SITE_CHURN_BUILD)

        def device_patch(state):
            pol = state.policy
            return DevicePolicy(
                proto_table=pol.proto_table,
                port_class=pol.port_class,
                class_map=pol.class_map,
                verdict=_dus(pol.verdict, vals_dev,
                             (0, 0, row, 0)),
                ep_policy=pol.ep_policy,
                auth=_dus(pol.auth, auth_dev, (0, row))), None

        def mirrors():
            self.tensors.verdict[:, :, row, :] = vals
            self._policies = list(policies)
            if (kind == "remove" and numeric_id
                    not in self._lpm_entries.values()):
                # row contents are back to defaults and nothing
                # maps to it: recycle (unbounded churn must not
                # grow rows)
                self.row_map.remove(numeric_id)

        self._publish_tables(build, device_patch=device_patch,
                             mirrors=mirrors)
        self.tables.patches += 1
        return True

    def patch_ipcache(self, cidr: str, numeric_id: int) -> bool:
        # table-swap-ok: LPM builder — device patch arrays built off
        # the dispatch path, published via _publish_tables.  The /32
        # fast path must mutate the host mirror BEFORE publishing
        # (lpm_upsert plans and paints in one pass), so a failed
        # build rolls the mirror back via LPMUndo — the published
        # generation and the mirror stay in lockstep either way.
        from ..infra import faults
        from .lpm import DeviceLPM, LPMUndo, lpm_upsert

        jnp = self._jnp
        with self._building() as build:
            published = self._published_state()
            if published is None or self.row_map is None:
                return False
            fresh_row = self.row_map.row(numeric_id) == 0
            row = self.row_map.add(numeric_id)
            if row >= self.tensors.verdict.shape[2]:
                if fresh_row:
                    self.row_map.remove(numeric_id)
                return False
            undo = LPMUndo(self._lpm_tensors, cidr)
            had_entry = cidr in self._lpm_entries
            prev_entry = self._lpm_entries.get(cidr)
            self._lpm_entries[cidr] = numeric_id
            try:
                patches = lpm_upsert(self._lpm_tensors, cidr, row)
                staged_t = None
                new_lpm = device_patch = None
                if patches is None:
                    # padding exhausted / shadowing rebuild: recompile
                    # the LPM alone (never the policy tensors), swap
                    # the mirror object post-flip.  Fresh arrays:
                    # published directly, no live-array derivation
                    staged_t = compile_lpm(
                        {c: self.row_map.row(i)
                         for c, i in self._lpm_entries.items()})
                    new_lpm = DeviceLPM.from_tensors(staged_t)
                else:
                    # stage the payload uploads off-lock; the
                    # ``.at[].set`` against the live LPM re-derives
                    # under the dispatch lock (dispatch donation)
                    staged = [
                        (f, i,
                         jnp.asarray(np.atleast_1d(p)[None]
                                     if f != "l1"
                                     else np.atleast_1d(p)))
                        for f, i, p in patches]
                    for f, _i, pl in staged:  # compile off-lock
                        self._warm_dus(
                            getattr(published.ipcache, f), pl,
                            (0,) if f == "l1" else (0, 0))

                    def device_patch(state):
                        lpm = state.ipcache
                        l1, l2, l3 = lpm.l1, lpm.l2, lpm.l3
                        for field, idx, payload in staged:
                            if field == "l1":
                                l1 = _dus(l1, payload, (idx,))
                            elif field == "l2":
                                l2 = _dus(l2, payload, (idx, 0))
                            else:
                                l3 = _dus(l3, payload, (idx, 0))
                        return None, DeviceLPM(
                            l1=l1, l2=l2, l3=l3, v6_net=lpm.v6_net,
                            v6_mask=lpm.v6_mask,
                            v6_value=lpm.v6_value,
                            v6_plen=lpm.v6_plen, default=lpm.default)
                faults.check(faults.SITE_CHURN_BUILD)

                def mirrors():
                    if staged_t is not None:
                        self._lpm_tensors = staged_t

                self._publish_tables(build, lpm=new_lpm,
                                     device_patch=device_patch,
                                     mirrors=mirrors)
            except BaseException:
                # failed build: the flip never happened, so the host
                # mirror must roll back to exactly the published
                # state (entry map + the upsert's painted cells)
                if had_entry:
                    self._lpm_entries[cidr] = prev_entry
                else:
                    self._lpm_entries.pop(cidr, None)
                undo.restore(self._lpm_tensors)
                if fresh_row:
                    self.row_map.remove(numeric_id)
                raise
            self.tables.patches += 1
        return True

    def delete_ipcache(self, cidr: str) -> bool:
        # table-swap-ok: LPM builder (delete) — same build-off-path /
        # publish-flip / rollback-on-failure structure as
        # patch_ipcache; the /32 fast path paints one mirror cell,
        # restored from a saved copy if the build dies pre-flip
        """Remove one prefix (fqdn TTL expiry).  A /32 is patched in
        place — the slot reverts to the longest remaining covering
        prefix's value, computed from the host entry mirror; anything
        else rebuilds the LPM tensors (never the policy)."""
        import ipaddress

        from ..infra import faults
        from .lpm import DeviceLPM

        jnp = self._jnp
        with self._building() as build:
            published = self._published_state()
            if published is None or self.row_map is None:
                return False
            if cidr not in self._lpm_entries:
                return True  # unknown entry: nothing to do
            prev_entry = self._lpm_entries.pop(cidr)
            net = ipaddress.ip_network(cidr, strict=False)
            saved_row = None  # (blk3, row copy) for rollback
            try:
                in_place = net.version == 4 and net.prefixlen == 32
                if in_place:
                    addr = int(net.network_address)
                    t = self._lpm_tensors
                    hi16, mid8, lo8 = (addr >> 16, (addr >> 8) & 0xFF,
                                       addr & 0xFF)
                    cur1 = int(t.l1[hi16])
                    cur2 = (int(t.l2[-cur1 - 1, mid8]) if cur1 < 0
                            else 0)
                    if cur1 >= 0 or cur2 >= 0:
                        # the /32 was never expanded into an l3 slot
                        # (it came in via a full compile that merged
                        # it, or was shadowed) — too ambiguous to
                        # patch: rebuild
                        in_place = False
                staged_t = new_lpm = device_patch = None
                if in_place:
                    # longest remaining covering v4 prefix -> value
                    best_len, best_num = -1, None
                    for c, num in self._lpm_entries.items():
                        n2 = ipaddress.ip_network(c, strict=False)
                        if n2.version != 4 or n2.prefixlen <= best_len:
                            continue
                        shift = 32 - n2.prefixlen
                        if n2.prefixlen == 0 or (
                                addr >> shift) == (
                                    int(n2.network_address) >> shift):
                            best_len, best_num = n2.prefixlen, num
                    value = (self._lpm_tensors.default
                             if best_num is None
                             else self.row_map.row(best_num))
                    blk3 = -cur2 - 1
                    saved_row = (blk3, t.l3[blk3].copy())
                    t.l3[blk3, lo8] = value
                    # payload staged off-lock; the live-LPM derive
                    # happens under the dispatch lock (donation)
                    row_dev = jnp.asarray(t.l3[blk3][None])
                    self._warm_dus(published.ipcache.l3, row_dev,
                                   (0, 0))

                    def device_patch(state):
                        lpm = state.ipcache
                        return None, DeviceLPM(
                            l1=lpm.l1, l2=lpm.l2,
                            l3=_dus(lpm.l3, row_dev, (blk3, 0)),
                            v6_net=lpm.v6_net, v6_mask=lpm.v6_mask,
                            v6_value=lpm.v6_value,
                            v6_plen=lpm.v6_plen,
                            default=lpm.default)
                else:
                    staged_t = compile_lpm(
                        {c: self.row_map.row(i)
                         for c, i in self._lpm_entries.items()})
                    new_lpm = DeviceLPM.from_tensors(staged_t)
                faults.check(faults.SITE_CHURN_BUILD)

                def mirrors():
                    if staged_t is not None:
                        self._lpm_tensors = staged_t

                self._publish_tables(build, lpm=new_lpm,
                                     device_patch=device_patch,
                                     mirrors=mirrors)
            except BaseException:
                self._lpm_entries[cidr] = prev_entry
                if saved_row is not None:
                    self._lpm_tensors.l3[saved_row[0]] = saved_row[1]
                raise
            self.tables.patches += 1
        return True

    def nat_snapshot(self) -> Optional[np.ndarray]:
        with self._lock:
            if self.nat_state is None:
                return None
            return np.asarray(self.nat_state.table)

    def nat_restore(self, table: np.ndarray) -> None:
        from ..service.nat import NATTable

        table = np.ascontiguousarray(table, dtype=np.uint32)
        with self._lock:
            self.nat_state = NATTable(table=self._jnp.asarray(table),
                                      failed=self._jnp.uint32(0))

    def nat_status(self, now: int) -> Optional[dict]:
        from ..service.nat import NAT_PORT_MIN, nat_live_count

        with self._lock:
            if self.nat_state is None:
                return None
            return {
                "capacity": self.nat_state.capacity,
                "port-min": NAT_PORT_MIN,
                "live": nat_live_count(self.nat_state, now),
                "alloc-failed": int(np.asarray(self.nat_state.failed)),
            }

    def map_pressure(self, now: int) -> dict:
        # thread-affinity: api, offline, cli -- the map-pressure
        # controller / query threads; NEVER the drain thread (the
        # occupancy reduction + scalar fetches block on the device)
        """The map-pressure sample (datapath/pressure.py): occupied
        CT slots via the fingerprint bitmap (one warmed jitted
        reduction, ~4 B/slot), cumulative insert drops
        (``CTTable.dropped`` — restore-time drops included), and
        SNAT pool failures.  Runs under the dispatch lock like gc():
        the state capture must not race a donating dispatch."""
        from .lpm import LPM_NOMINAL_CAPACITY

        with self._lock:
            ct = self.state.ct
            occupied = int(np.asarray(_ct_occupied(ct.fp)))
            drops = int(np.asarray(ct.dropped))
            nat_cap = (self.nat_state.capacity
                       if self.nat_state is not None else None)
            nat_failed = (int(np.asarray(self.nat_state.failed))
                          if self.nat_state is not None else 0)
            # host mirrors only from here down: programmed prefixes
            # and identity-row headroom never touch the device
            lpm_entries = len(self._lpm_entries)
            rows, rows_cap = (self.row_map.row_occupancy()
                              if self.row_map is not None else (0, 0))
        return {
            "ct": {"capacity": self.ct_capacity,
                   "occupied": occupied,
                   "occupancy": round(occupied / self.ct_capacity,
                                      4),
                   "insert-drops": drops},
            "nat": {"capacity": nat_cap, "failures": nat_failed},
            "lpm": {"capacity": LPM_NOMINAL_CAPACITY,
                    "entries": lpm_entries,
                    "occupancy": round(
                        lpm_entries / LPM_NOMINAL_CAPACITY, 6)},
            "policy": {"capacity": rows_cap, "rows": rows,
                       "occupancy": (round(rows / rows_cap, 4)
                                     if rows_cap else None)},
        }

    def gc(self, now: int) -> int:
        # table-swap-ok: CT-only swap (expiry sweep) — tables carried
        # unchanged
        from .conntrack import ct_gc_jit

        with self._lock:
            ct, n = ct_gc_jit(self.state.ct, self._jnp.uint32(now))
            self.state = DatapathState(
                policy=self.state.policy, ipcache=self.state.ipcache,
                ct=ct, metrics=self.state.metrics)
            self._rekeep_serving_placement()
        return int(n)

    def metrics(self) -> np.ndarray:
        with self._lock:
            out = np.array(np.asarray(self.state.metrics))
        # fold in host drops still awaiting a lock-free flush (NOT
        # zeroed here — display-only add keeps flush idempotent)
        with self._host_drops_lock:
            for reason, n in self._host_drops.items():
                out[reason, 0] += n
        return out

    def ct_snapshot(self) -> np.ndarray:
        # thread-affinity: drain, api, watchdog
        """Dense live rows — the canonical (placement-free) snapshot
        format, restorable into any capacity or backend."""
        from .conntrack import ct_rows_from_table

        with self._lock:
            return ct_rows_from_table(np.asarray(self.state.ct.table))

    def ct_restore(self, table: np.ndarray) -> None:
        # thread-affinity: drain, api, offline
        # table-swap-ok: CT-only swap (snapshot restore) — tables
        # carried unchanged
        from .conntrack import (CTTable, ROW_WORDS, ct_fp_from_table,
                                ct_rows_from_table, ct_table_from_rows)

        jnp = self._jnp
        table = np.asarray(table)
        assert table.ndim == 2 and table.shape[1] == ROW_WORDS
        # normalize (accepts dense rows OR a full hashed table — live
        # rows are extracted either way), then re-place with the device
        # hash so probes find every entry at this capacity
        table, n_dropped = ct_table_from_rows(ct_rows_from_table(table),
                                              self.ct_capacity)
        with self._lock:
            self.state = DatapathState(
                policy=self.state.policy, ipcache=self.state.ipcache,
                ct=CTTable(table=jnp.asarray(table),
                           fp=jnp.asarray(ct_fp_from_table(table)),
                           dropped=jnp.uint32(n_dropped)),
                metrics=self.state.metrics)
            self._rekeep_serving_placement()


class InterpreterLoader(Loader):
    """Oracle-backed datapath — no accelerator needed (fake datapath).

    Table updates apply structurally to the oracle (no device slots
    to double-buffer), but the generation tag and swap counters keep
    parity with :class:`TPULoader` so every surface (serving stats,
    registry, CLI) and every backend-agnostic test reads one shape.
    """
    # active-tables: oracle

    def __init__(self, ct_capacity: int = 0,
                 nat_capacity: Optional[int] = None):
        from .tables import TableVersioner
        from .verdict import N_REASONS

        self.oracle = None
        self.nat_state = None  # numpy NAT table (port-pool mirror)
        self.nat_failed = 0
        self.nat_capacity = nat_capacity  # None = default pool
        self.row_map: Optional[IdentityRowMap] = None
        self._metrics = np.zeros((N_REASONS, 2), dtype=np.uint64)
        self.attach_count = 0
        self._auth_display: Dict[Tuple[int, int], int] = {}
        self.tables = TableVersioner()

    def table_stats(self) -> dict:
        # thread-affinity: any
        return self.tables.snapshot()

    def map_pressure(self, now: int) -> dict:
        # thread-affinity: any
        """TPULoader.map_pressure parity.  The oracle CT is an
        unbounded dict (no probe window), so occupancy is None and
        insert drops stay 0 — the pressure monitor then keys on the
        NAT counters alone, which DO mirror the device pool."""
        from .lpm import LPM_NOMINAL_CAPACITY

        live = len(self.oracle.ct) if self.oracle is not None else 0
        lpm_entries = (len(self.oracle.ipcache)
                       + len(self.oracle._exact)
                       if self.oracle is not None else 0)
        rows, rows_cap = (self.row_map.row_occupancy()
                          if self.row_map is not None else (0, 0))
        return {
            "ct": {"capacity": 0, "occupied": live,
                   "occupancy": None, "insert-drops": 0},
            "nat": {"capacity": (self.nat_state.shape[0]
                                 if self.nat_state is not None
                                 else None),
                    "failures": self.nat_failed},
            "lpm": {"capacity": LPM_NOMINAL_CAPACITY,
                    "entries": lpm_entries,
                    "occupancy": round(
                        lpm_entries / LPM_NOMINAL_CAPACITY, 6)},
            "policy": {"capacity": rows_cap, "rows": rows,
                       "occupancy": (round(rows / rows_cap, 4)
                                     if rows_cap else None)},
        }

    def nat_snapshot(self) -> Optional[np.ndarray]:
        return None if self.nat_state is None else self.nat_state.copy()

    def nat_restore(self, table: np.ndarray) -> None:
        self.nat_state = np.ascontiguousarray(table, dtype=np.uint32)

    def nat_status(self, now: int) -> Optional[dict]:
        from ..service.nat import NAT_PORT_MIN, NV_EXPIRES

        if self.nat_state is None:
            return None
        return {
            "capacity": self.nat_state.shape[0],
            "port-min": NAT_PORT_MIN,
            "live": int((self.nat_state[:, NV_EXPIRES] >= now).sum()),
            "alloc-failed": self.nat_failed,
        }

    def attach(self, policies, ipcache, ep_policy, row_map) -> None:
        # table-swap-ok: the oracle world swap (structural apply);
        # generation bumped for TPULoader parity
        from ..testing.oracle import OracleDatapath

        with self.tables.building() as build:
            old_ct = self.oracle.ct if self.oracle is not None else None
            self.row_map = row_map
            # endpoints not listed are lxcmap misses: the oracle drops
            # them (REASON_NO_ENDPOINT), matching the device's -1
            # sentinel
            pol_by_ep = {ep: policies[row]
                         for ep, row in ep_policy.items()}
            old_auth = (self.oracle.auth if self.oracle is not None
                        else None)
            self.oracle = OracleDatapath(pol_by_ep, dict(ipcache))
            if old_ct is not None:
                self.oracle.ct = old_ct
            if old_auth is not None:  # grants survive attach (authmap)
                self.oracle.auth = old_auth
            self.attach_count += 1
            self.tables.full_attaches += 1
            self.tables.note_publish(build)

    def auth_upsert(self, ep_id: int, remote_id: int,
                    expires: int) -> bool:
        # table-swap-ok: auth-grant apply on the oracle (keyed by
        # subject labels); no generation bump — grants are queried
        # live, never snapshot-compiled here
        if self.oracle is None:
            return False
        pol = self.oracle.ep_policies.get(int(ep_id))
        if pol is None:
            return False
        # keyed by SUBJECT LABELS, not endpoint id: label-identical
        # endpoints share grants exactly like the device's shared
        # policy row (upstream: authmap keys the local IDENTITY)
        self.oracle.auth[(pol.subject_labels.sorted_key(),
                          int(remote_id))] = int(expires)
        self._auth_display[(int(ep_id), int(remote_id))] = int(expires)
        return True

    def auth_entries(self) -> list:
        return [{"endpoint": ep, "remote_identity": rem,
                 "expires": exp}
                for (ep, rem), exp in sorted(
                    self._auth_display.items())]

    def auth_gc(self, now: int) -> int:
        # table-swap-ok: auth-grant expiry sweep on the oracle
        if self.oracle is None:
            return 0
        dead = [k for k, exp in self.oracle.auth.items()
                if exp <= now]
        for k in dead:
            del self.oracle.auth[k]
        for k in [k for k, exp in self._auth_display.items()
                  if exp <= now]:
            del self._auth_display[k]
        return len(dead)

    def step(self, hdr: np.ndarray, now: int, pre_drop=None,
             pre_drop_reason=None, lb_drop=None, audit=False):
        from ..core.packets import HeaderBatch, COL_DIR
        from .verdict import N_OUT

        results = self.oracle.step(
            HeaderBatch(np.asarray(hdr)), now, pre_drop=pre_drop,
            pre_drop_reason=(None if pre_drop_reason is None
                             else np.asarray(pre_drop_reason)),
            lb_drop=(None if lb_drop is None
                     else np.asarray(lb_drop)),
            audit=audit)
        out = np.zeros((len(results), N_OUT), dtype=np.uint32)
        for i, r in enumerate(results):
            out[i] = (r.verdict, r.proxy, r.ct,
                      self.row_map.row(r.identity), r.reason, r.event)
            self._metrics[r.reason, int(hdr[i][COL_DIR])] += 1
        return out, self.row_map

    def gc(self, now: int) -> int:
        return self.oracle.gc(now)

    # -- incremental patching -----------------------------------------
    # The oracle evaluates MapState.lookup over the live contribution
    # lists (already updated by update_contributions), so the policy
    # side needs only a row for event decode; ipcache patches edit the
    # oracle's prefix list directly.

    def patch_identity(self, kind: str, numeric_id: int,
                       policies) -> bool:
        # table-swap-ok: row-map-only apply (the oracle evaluates the
        # live contribution lists); generation bumped for parity —
        # including the NO-OP early returns, which must not bump
        # (TPULoader publishes nothing for them either)
        if self.oracle is None or self.row_map is None:
            return False
        if kind == "remove" and self.row_map.row(numeric_id) == 0:
            return True  # identity never had a row; nothing to patch
        with self.tables.building() as build:
            if kind == "remove":
                self.row_map.remove(numeric_id)
            else:
                self.row_map.add(numeric_id)
            self.tables.patches += 1
            self.tables.note_publish(build)
        return True

    def _nat_table(self):
        from ..service.nat import NAT_DEFAULT_CAPACITY, NAT_ROW_WORDS

        if self.nat_state is None:
            self.nat_state = np.zeros(
                (self.nat_capacity or NAT_DEFAULT_CAPACITY,
                 NAT_ROW_WORDS), dtype=np.uint32)
        return self.nat_state

    def masquerade(self, nat, hdr, now: int) -> np.ndarray:
        """Mirror of service.nat.snat_egress over a numpy NAT table +
        the oracle's CT dict.  Same FNV hash, same window, and the
        SAME two-phase order as the device kernel — full-window match
        scan first, then a step-outer/row-inner claim loop (the
        device awards contended slots to the lowest batch row, which
        is exactly what the inner row loop does here) — so allocated
        ports are bit-equal across backends."""
        from ..core.packets import (COL_DIR, COL_DPORT, COL_DST_IP3,
                                    COL_FAMILY, COL_PROTO, COL_SPORT,
                                    COL_SRC_IP3)
        from ..service.nat import (NAT_PORT_MIN, NAT_PROBE, NV_DP,
                                   NV_DST, NV_EXPIRES, NV_SNAT_IP,
                                   NV_SPORT, NV_SRC, _nat_hash_py,
                                   _nat_lifetime_py)
        from ..testing.oracle import OracleDatapath

        hdr = np.array(hdr, dtype=np.uint32)
        dropped = np.zeros(len(hdr), dtype=bool)
        if not nat.enabled:
            return hdr, dropped
        table = self._nat_table()
        P = table.shape[0]
        nets = [(int(n), int(m)) for n, m in
                zip(np.asarray(nat.net), np.asarray(nat.mask))]
        node_ip = int(np.asarray(nat.node_ip))
        egw = list(zip(np.asarray(nat.egw_src).tolist(),
                       np.asarray(nat.egw_net).tolist(),
                       np.asarray(nat.egw_mask).tolist(),
                       np.asarray(nat.egw_ip).tolist()))

        def r_key(s):
            r = table[s]
            return (int(r[NV_SRC]), int(r[NV_SPORT]), int(r[NV_DST]),
                    int(r[NV_DP]))

        claimants = []  # (hdr_row_index, key, h)
        for i in range(len(hdr)):
            row = hdr[i]
            if row[COL_DIR] != 1 or row[COL_FAMILY] != 4:
                continue
            dst = int(row[COL_DST_IP3])
            src0 = int(row[COL_SRC_IP3])
            # egress-gateway policy: first (src, destCIDR) match wins
            # and overrides the non-masquerade exemption
            rewrite_ip = node_ip
            gw = False
            for g_src, g_net, g_mask, g_ip in egw:
                if src0 == g_src and (dst & g_mask) == g_net:
                    rewrite_ip, gw = g_ip, True
                    break
            if not gw and any((dst & m) == n for n, m in nets):
                continue
            rev = OracleDatapath._rev(OracleDatapath._tuple(row))
            e = self.oracle.ct.get(rev)
            if e is not None and e.expires >= now:
                continue  # reply of an inbound connection
            src, sport = src0, int(row[COL_SPORT])
            proto = int(row[COL_PROTO])
            if proto not in (6, 17, 132):
                # portless: port-preserving rewrite only
                row[COL_SRC_IP3] = rewrite_ip
                continue
            dp = (int(row[COL_DPORT]) << 8) | proto
            key = (src, sport, dst, dp)
            h = _nat_hash_py(key)
            # phase 1: full-window scan for a live same-tuple mapping
            hit = None
            for step in range(NAT_PROBE):
                s = (h + step) % P
                if (int(table[s][NV_EXPIRES]) >= now
                        and r_key(s) == key):
                    hit = s
                    break
            if hit is not None:
                # a live mapping keeps its recorded SNAT ip (device
                # parity: policy churn must not flip a flow's ip)
                kept = int(table[hit][NV_SNAT_IP]) or node_ip
                table[hit] = (*key, now + _nat_lifetime_py(proto),
                              kept)
                row[COL_SRC_IP3] = kept
                row[COL_SPORT] = NAT_PORT_MIN + hit
            else:
                row[COL_SRC_IP3] = rewrite_ip
                claimants.append((i, key, h, proto, rewrite_ip))
        # phase 2: lockstep claim rounds (device parity)
        for step in range(NAT_PROBE):
            if not claimants:
                break
            still = []
            for i, key, h, proto, rewrite_ip in claimants:
                s = (h + step) % P
                if (int(table[s][NV_EXPIRES]) < now
                        or r_key(s) == key):
                    table[s] = (*key, now + _nat_lifetime_py(proto),
                                rewrite_ip)
                    hdr[i][COL_SPORT] = NAT_PORT_MIN + s
                else:
                    still.append((i, key, h, proto, rewrite_ip))
            claimants = still
        # leftover claimants: pool exhaustion — DROP (parity with
        # snat_egress's `dropped` mask; reference DROP_NAT_NO_MAPPING)
        self.nat_failed += len(claimants)
        for i, _key, _h, _proto, _rip in claimants:
            dropped[i] = True
        return hdr, dropped

    def reverse_nat(self, nat, hdr, now: int) -> np.ndarray:
        """Sequential mirror of service.nat.snat_reverse."""
        from ..core.packets import (COL_DIR, COL_DPORT, COL_DST_IP3,
                                    COL_FAMILY, COL_PROTO, COL_SPORT,
                                    COL_SRC_IP3)
        from ..service.nat import (NAT_PORT_MIN, NV_DP, NV_DST,
                                   NV_EXPIRES, NV_SNAT_IP, NV_SPORT,
                                   NV_SRC, _nat_lifetime_py)

        hdr = np.array(hdr, dtype=np.uint32)
        if not nat.enabled:
            return hdr
        table = self._nat_table()
        P = table.shape[0]
        node_ip = int(np.asarray(nat.node_ip))
        for i in range(len(hdr)):
            row = hdr[i]
            dport = int(row[COL_DPORT])
            if (row[COL_DIR] != 0 or row[COL_FAMILY] != 4
                    or not NAT_PORT_MIN <= dport < NAT_PORT_MIN + P):
                continue
            s = dport - NAT_PORT_MIN
            r = table[s]
            row_ip = int(r[NV_SNAT_IP]) or node_ip
            if int(row[COL_DST_IP3]) != row_ip:
                continue
            rdp = (int(row[COL_SPORT]) << 8) | int(row[COL_PROTO])
            if (int(r[NV_EXPIRES]) >= now
                    and int(r[NV_DST]) == int(row[COL_SRC_IP3])
                    and int(r[NV_DP]) == rdp):
                row[COL_DST_IP3] = r[NV_SRC]
                row[COL_DPORT] = r[NV_SPORT]
                table[s][NV_EXPIRES] = now + _nat_lifetime_py(
                    int(row[COL_PROTO]))
        return hdr

    def patch_ipcache(self, cidr: str, numeric_id: int) -> bool:
        # table-swap-ok: oracle prefix-list apply; generation bumped
        # for parity
        import ipaddress

        if self.oracle is None:
            return False
        with self.tables.building() as build:
            net = ipaddress.ip_network(cidr, strict=False)
            host_bits = 32 if net.version == 4 else 128
            addr = int(net.network_address)
            if net.prefixlen == host_bits:
                self.oracle._exact[(net.version, addr)] = numeric_id
            else:
                key = (net.version, addr, net.prefixlen)
                self.oracle.ipcache = [
                    e for e in self.oracle.ipcache if e[:3] != key]
                self.oracle.ipcache.append((net.version, addr,
                                            net.prefixlen,
                                            numeric_id))
            self.oracle._lpm_memo.clear()
            self.tables.patches += 1
            self.tables.note_publish(build)
        return True

    def delete_ipcache(self, cidr: str) -> bool:
        # table-swap-ok: oracle prefix-list apply (delete); generation
        # bumped for parity — an UNKNOWN entry is a no-op on both
        # backends and must not bump (TPULoader publishes nothing)
        import ipaddress

        if self.oracle is None:
            return False
        net = ipaddress.ip_network(cidr, strict=False)
        host_bits = 32 if net.version == 4 else 128
        addr = int(net.network_address)
        key = (net.version, addr, net.prefixlen)
        if net.prefixlen == host_bits:
            if (net.version, addr) not in self.oracle._exact:
                return True  # unknown entry: nothing to do
        elif all(e[:3] != key for e in self.oracle.ipcache):
            return True  # unknown entry: nothing to do
        with self.tables.building() as build:
            if net.prefixlen == host_bits:
                self.oracle._exact.pop((net.version, addr), None)
            else:
                self.oracle.ipcache = [
                    e for e in self.oracle.ipcache if e[:3] != key]
            self.oracle._lpm_memo.clear()
            self.tables.patches += 1
            self.tables.note_publish(build)
        return True

    def add_host_drops(self, reason: int, n: int) -> None:
        """Host-side drop accounting (ingress column), mirroring
        :meth:`TPULoader.add_host_drops`."""
        if n:
            self._metrics[int(reason), 0] += int(n)

    def metrics(self) -> np.ndarray:
        return self._metrics.copy()

    def ct_snapshot(self) -> np.ndarray:
        """Oracle CT dict -> dense snapshot rows (the portable format;
        restorable into either backend).  The oracle tracks no per-flow
        packet/byte counters, so those words are zero."""
        from .conntrack import ROW_WORDS, V_EXPIRES, V_PROXY, V_STATE

        rows = np.zeros((len(self.oracle.ct), ROW_WORDS), dtype=np.uint32)
        for i, (key, e) in enumerate(self.oracle.ct.items()):
            src, dst, sport, dport, proto, dirn = key
            rows[i, 0:4] = src
            rows[i, 4:8] = dst
            rows[i, 8] = (sport << 16) | dport
            rows[i, 9] = proto | (dirn << 8)
            rows[i, V_STATE] = e.state
            rows[i, V_EXPIRES] = e.expires
            rows[i, V_PROXY] = e.proxy
        return rows

    def ct_restore(self, table: np.ndarray) -> None:
        # table-swap-ok: CT-only apply (snapshot restore) — policy/
        # ipcache untouched, no generation bump
        """Accepts dense rows or a full hashed table from either
        backend; live rows decode back into the oracle dict."""
        from ..testing.oracle import _CTEntry
        from .conntrack import (V_EXPIRES, V_PROXY, V_STATE,
                                ct_rows_from_table)

        assert self.oracle is not None, "attach() before ct_restore()"
        self.oracle.ct.clear()
        for row in ct_rows_from_table(np.asarray(table)):
            key = (tuple(int(x) for x in row[0:4]),
                   tuple(int(x) for x in row[4:8]),
                   int(row[8]) >> 16, int(row[8]) & 0xFFFF,
                   int(row[9]) & 0xFF, (int(row[9]) >> 8) & 1)
            self.oracle.ct[key] = _CTEntry(state=int(row[V_STATE]),
                                           expires=int(row[V_EXPIRES]),
                                           proxy=int(row[V_PROXY]))
