"""The fused per-packet verdict pipeline — ``bpf_lxc.c`` as one jit fn.

Reference: upstream cilium ``bpf/bpf_lxc.c`` ``handle_xgress``: parse ->
ipcache LPM (``lib/eps.h``) -> ``ct_lookup4`` (``lib/conntrack.h``) ->
``policy_can_access_ingress`` (``lib/policy.h``) -> ``ct_create4`` ->
emit trace/drop/policy-verdict events.  TPU-first redesign: the whole
stack is ONE jitted function over the ``[N, N_COLS]`` header tensor;
every stage is gathers/elementwise so XLA fuses it into a handful of
kernels, and the batch axis shards across chips with ``shard_map``
(tables replicated, packets split).

State (policy tensors, ipcache LPM, conntrack) threads functionally:
``datapath_step(state, hdr, now) -> (out, state')`` where ``out`` is the
per-packet event tensor the monitor layer decodes (the perf-ringbuffer
analogue, returned via outfeed/device->host copy).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import (
    COL_DIR,
    COL_DPORT,
    COL_DST_IP0,
    COL_EP,
    COL_FAMILY,
    COL_PROTO,
    COL_SRC_IP0,
)
from ..policy.compiler import (AUTH_SHIFT, PolicyTensors, PROXY_MASK,
                               PROXY_SHIFT, VERDICT_MASK)
from ..policy.mapstate import (
    VERDICT_ALLOW,
    VERDICT_DEFAULT_DENY,
    VERDICT_DENY,
    VERDICT_REDIRECT,
)
from .conntrack import (
    CT_ESTABLISHED,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CTTable,
    V_PROXY,
    ct_keys_from_headers,
    ct_lookup,
    ct_update,
)
from .lpm import DeviceLPM, LPMTensors, lpm_lookup

# Drop reasons (reference: bpf/lib/drop.h DROP_* codes, renumbered).
REASON_FORWARDED = 0
REASON_POLICY_DENY = 1  # explicit deny rule
REASON_POLICY_DEFAULT_DENY = 2  # no rule allowed it (default deny)
REASON_ROUTE_OVERFLOW = 3  # flow-router shard block overflow (RSS queue)
REASON_NO_ENDPOINT = 4  # unregistered endpoint id (lxcmap miss)
REASON_NAT_EXHAUSTED = 5  # SNAT port pool exhausted (DROP_NAT_NO_MAPPING)
REASON_BANDWIDTH = 6  # egress rate limit (bandwidth manager / EDT)
REASON_NO_SERVICE = 7  # service frontend with no backend (DROP_NO_SERVICE)
REASON_AUTH_REQUIRED = 8  # policy allows, mutual auth missing (pkg/auth)
# admission-queue shed at the serving front door (cilium_tpu/serving):
# the XDP-ring-overflow analogue.  Host-synthesized (the row never
# reached the device), but numbered in this space so every decode
# table — monitor, flow layer, ring wire format (4-bit field) — names
# it like any datapath drop.
REASON_INGRESS_OVERFLOW = 9
# serving fault recovery (host-synthesized, like INGRESS_OVERFLOW):
# the dispatch watchdog deadlined a hung device dispatch and dropped
# its in-flight batch...
REASON_DISPATCH_TIMEOUT = 10
# ...or a dead/failed dispatch's rows (and any rows still queued when
# a dead drain loop stops) were accounted by the recovery supervisor
# instead of silently vanishing — admitted traffic is ALWAYS one of
# completed / shed / recovery-dropped (serving/runtime.py invariant)
REASON_RECOVERY_DROP = 11
# cluster front-end router shed (cilium_tpu/cluster/router.py): a
# node replica's bounded forward queue was full, so the packet never
# reached that node's admission queue.  Host-synthesized like
# INGRESS_OVERFLOW, one level further out — the cluster tier's entry
# in the cluster-wide ledger (submitted == per-node accounted
# + router_overflow + failover_dropped).
REASON_CLUSTER_OVERFLOW = 12
N_REASONS = 13

# Event types in the out tensor (monitor vocabulary).
EV_TRACE = 0  # TraceNotify: forwarded established/reply traffic
EV_VERDICT = 1  # PolicyVerdictNotify: NEW connection decision
EV_DROP = 2  # DropNotify

# Out tensor columns.
OUT_VERDICT = 0  # final VERDICT_* code
OUT_PROXY = 1  # proxy port when redirected
OUT_CT = 2  # CT_* lookup result
OUT_ID_ROW = 3  # remote identity row (host maps to numeric id)
OUT_REASON = 4  # drop reason (REASON_*)
OUT_EVENT = 5  # EV_*
N_OUT = 6

MAX_ENDPOINTS = 4096


@jax.tree_util.register_pytree_node_class
@dataclass
class DevicePolicy:
    """Compiled policy tensors on device + endpoint->policy-row map
    (the policymap + lxcmap of the TPU datapath)."""

    proto_table: jnp.ndarray  # [256] int32
    port_class: jnp.ndarray  # [N_PROTO, 65536] int32 -> GLOBAL class
    class_map: jnp.ndarray  # [n_pol, n_cls_global] int32 -> LOCAL
    verdict: jnp.ndarray  # [n_pol, 2, n_rows, n_local] int32
    ep_policy: jnp.ndarray  # [MAX_ENDPOINTS] int32 endpoint -> policy row
    # [n_pol, n_rows] uint32 mutual-auth expiries (the authmap
    # analogue, pkg/auth: keyed local identity x remote identity —
    # policy rows ARE identity-granular via the distillery)
    auth: jnp.ndarray

    @staticmethod
    def from_tensors(t: PolicyTensors,
                     ep_policy: np.ndarray = None,
                     auth: np.ndarray = None) -> "DevicePolicy":
        if ep_policy is None:
            # default matches TPULoader.attach: every endpoint id is
            # an lxcmap miss until registered (callers that want the
            # all-registered single-policy shape pass explicit zeros)
            ep_policy = np.full(MAX_ENDPOINTS, -1, dtype=np.int32)
        if auth is None:
            auth = np.zeros((t.verdict.shape[0], t.verdict.shape[2]),
                            dtype=np.uint32)
        return DevicePolicy(
            proto_table=jnp.asarray(t.proto_table),
            port_class=jnp.asarray(t.port_class),
            class_map=jnp.asarray(t.class_map),
            verdict=jnp.asarray(t.verdict),
            ep_policy=jnp.asarray(ep_policy),
            auth=jnp.asarray(auth),
        )

    def tree_flatten(self):
        return ((self.proto_table, self.port_class, self.class_map,
                 self.verdict, self.ep_policy, self.auth), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class DatapathState:
    """Full device datapath state — the BPF-maps bundle as a pytree."""

    policy: DevicePolicy
    ipcache: DeviceLPM
    ct: CTTable
    metrics: jnp.ndarray  # [N_REASONS, 2] uint32: [reason, dir] counts

    @staticmethod
    def create(policy: DevicePolicy, ipcache: DeviceLPM,
               ct: CTTable) -> "DatapathState":
        return DatapathState(
            policy=policy, ipcache=ipcache, ct=ct,
            metrics=jnp.zeros((N_REASONS, 2), dtype=jnp.uint32))

    def tree_flatten(self):
        return ((self.policy, self.ipcache, self.ct, self.metrics), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def datapath_step(state: DatapathState, hdr: jnp.ndarray,
                  now: jnp.ndarray, valid: jnp.ndarray = None,
                  pre_drop: jnp.ndarray = None,
                  pre_drop_reason: jnp.ndarray = None,
                  lb_drop: jnp.ndarray = None,
                  audit: bool = False
                  ) -> Tuple[jnp.ndarray, DatapathState]:
    """One batched pass of the full verdict pipeline (see module doc).

    ``valid`` (optional [N] bool) masks padding rows added by the
    multi-chip flow router; masked rows produce output rows but touch
    neither CT state nor metrics.

    ``pre_drop`` (optional [N] bool) marks rows an earlier stage
    already condemned — today the SNAT stage on port-pool exhaustion
    (reference: DROP_NAT_NO_MAPPING; the reference DROPS rather than
    emit a colliding node-side tuple).  Policy/lxcmap verdicts keep
    precedence (upstream order: bpf_lxc judges before host SNAT);
    rows that would otherwise forward drop with
    ``REASON_NAT_EXHAUSTED`` and create no CT entry.

    ``pre_drop_reason`` (optional [N] uint32, 0 = none) is the
    generalized form: rows carry their own REASON_* code (today the
    bandwidth manager's ``REASON_BANDWIDTH``), with the same
    precedence and CT semantics as ``pre_drop``.

    ``audit`` (static): policy-audit-mode (reference:
    --policy-audit-mode): NEW flows the POLICY stage would deny
    (explicit deny, default deny, missing mutual auth) FORWARD and
    create CT state, while the emitted verdict event keeps the
    would-be reason (verdict ALLOW + reason POLICY_*/AUTH_* is the
    audit signature the flow layer renders).  Non-policy drops
    (lxcmap miss, NAT exhaustion, bandwidth, NO_SERVICE) still drop.

    ``lb_drop`` (optional [N] bool) marks LB frontend hits with no
    backend.  Unlike the two channels above this is a PRE-policy
    drop: upstream's LB lookup (bpf/lib/lb.h, bpf_sock) runs before
    the endpoint program ever judges the packet, so these rows report
    ``REASON_NO_SERVICE`` regardless of what policy (or the lxcmap
    gate) would have said, and touch no CT state."""
    hdr = hdr.astype(jnp.uint32)
    dirn = hdr[:, COL_DIR].astype(jnp.int32)
    fam = hdr[:, COL_FAMILY].astype(jnp.int32)

    # 1. ipcache: remote IP -> identity row (src for ingress, dst for
    #    egress — reference: lookup_ip4_remote_endpoint on the peer).
    src_words = hdr[:, COL_SRC_IP0:COL_SRC_IP0 + 4]
    dst_words = hdr[:, COL_DST_IP0:COL_DST_IP0 + 4]
    remote = jnp.where((dirn == 0)[:, None], src_words, dst_words)
    id_row = lpm_lookup(state.ipcache, remote, fam)

    # 2. conntrack lookup.  RELATED rows (ICMP errors carrying the
    #    embedded original tuple, core/packets.py FLAG_RELATED) probe
    #    the original flow's entry; a hit is CT_RELATED — forwarded
    #    like established traffic, never refreshed, never created.
    from ..core.packets import COL_FLAGS, FLAG_RELATED

    fwd, rev = ct_keys_from_headers(hdr)
    ct_res, slot, is_reply = ct_lookup(state.ct, fwd, rev, now)
    related_hint = (hdr[:, COL_FLAGS] & FLAG_RELATED) != 0
    is_related = related_hint & (ct_res != CT_NEW)

    # 3. policy map lookup (two gathers; all precedence precompiled).
    #    ep_policy row -1 = unregistered endpoint (the lxcmap-miss
    #    sentinel): the reference DROPS when the endpoint lookup fails
    #    (bpf_lxc lxcmap miss) instead of judging under some other
    #    endpoint's policy.
    ep_col = hdr[:, COL_EP]  # uint32: range-check BEFORE the int32
    pol_row_raw = state.policy.ep_policy[ep_col.astype(jnp.int32)]
    # out-of-range ids would clamp onto the boundary rows in the
    # gather (>= 4096 -> 4095; >= 2^31 -> wraps negative -> 0) and be
    # judged under whatever endpoint lives there — a forged ep id must
    # be a miss, not a clamp
    no_ep = (pol_row_raw < 0) | (ep_col >= MAX_ENDPOINTS)
    pol_row = jnp.maximum(pol_row_raw, 0)
    proto_idx = state.policy.proto_table[hdr[:, COL_PROTO].astype(jnp.int32)]
    gcls = state.policy.port_class[proto_idx, hdr[:, COL_DPORT].astype(jnp.int32)]
    # global -> per-policy local class (compiler class_map): the
    # verdict tensor's class axis is sized to ONE policy's boundaries,
    # not the union of every policy's (the 17 GB failure mode)
    cls = state.policy.class_map[pol_row, gcls]
    packed = state.policy.verdict[pol_row, dirn, id_row, cls]
    p_verdict = (packed & VERDICT_MASK).astype(jnp.int32)
    p_proxy = ((packed >> PROXY_SHIFT) & PROXY_MASK).astype(jnp.int32)
    p_auth = ((packed >> AUTH_SHIFT) & 1) != 0

    # 4. final verdict: established/reply bypass policy (reference: the
    #    CT fast path — policy applies to NEW connections only).
    is_new = ct_res == CT_NEW
    ct_proxy = state.ct.table[slot, V_PROXY].astype(jnp.int32)
    allowed_new = (p_verdict == VERDICT_ALLOW) | (p_verdict == VERDICT_REDIRECT)
    # no_ep drops even ESTABLISHED traffic: the endpoint is gone/never
    # existed, so its CT fast path must not forward either
    allowed = (~is_new | allowed_new) & ~no_ep
    # mutual auth (pkg/auth): a NEW flow whose winning allow carries
    # the auth bit forwards only with a live authmap entry; otherwise
    # it drops AUTH_REQUIRED (and the agent's auth manager observes
    # the drop and handshakes).  EST flows ride the CT fast path —
    # upstream judges auth at policy time only.
    auth_exp = state.policy.auth[pol_row, id_row]
    auth_drop = allowed & is_new & p_auth & (auth_exp <= now)
    allowed = allowed & ~auth_drop
    audit_fwd = None
    if audit:
        # policy-audit-mode: would-be policy/auth denials forward
        audit_fwd = is_new & ~allowed & ~no_ep
        allowed = allowed | audit_fwd
    nat_drop = None
    if pre_drop is not None:
        nat_drop = pre_drop & allowed  # policy/no_ep drops win
        allowed = allowed & ~nat_drop
    stage_drop = None
    if pre_drop_reason is not None:
        stage_drop = (pre_drop_reason != 0) & allowed
        allowed = allowed & ~stage_drop
    proxy = jnp.where(is_new, jnp.where(p_verdict == VERDICT_REDIRECT,
                                        p_proxy, 0),
                      ct_proxy)
    # an ICMP error related to a proxied flow is forwarded, not
    # redirected (the proxy speaks the flow's L7, not ICMP)
    proxy = jnp.where(is_related, 0, proxy)
    verdict = jnp.where(
        allowed,
        jnp.where(proxy > 0, VERDICT_REDIRECT, VERDICT_ALLOW),
        jnp.where(no_ep, VERDICT_DENY, p_verdict))
    reason_allowed = (allowed if audit_fwd is None
                      else allowed & ~audit_fwd)
    reason = jnp.where(
        reason_allowed, REASON_FORWARDED,
        jnp.where(no_ep, REASON_NO_ENDPOINT,
                  jnp.where(p_verdict == VERDICT_DENY, REASON_POLICY_DENY,
                            REASON_POLICY_DEFAULT_DENY)))
    # auth_drop rows carry p_verdict == ALLOW, so the base chain
    # mislabels them — override both verdict and reason
    verdict = jnp.where(auth_drop, VERDICT_DENY, verdict)
    reason = jnp.where(auth_drop, REASON_AUTH_REQUIRED, reason)
    proxy = jnp.where(auth_drop, 0, proxy)
    if audit_fwd is not None:
        # the ACTION is forward; the reason above keeps the would-be
        # decision (rows a later NAT/bandwidth/LB stage drops get
        # their reason overridden by that stage, as they really drop)
        verdict = jnp.where(audit_fwd & allowed, VERDICT_ALLOW,
                            verdict)
    if nat_drop is not None:
        verdict = jnp.where(nat_drop, VERDICT_DENY, verdict)
        reason = jnp.where(nat_drop, REASON_NAT_EXHAUSTED, reason)
        proxy = jnp.where(nat_drop, 0, proxy)
    if stage_drop is not None:
        verdict = jnp.where(stage_drop, VERDICT_DENY, verdict)
        reason = jnp.where(stage_drop, pre_drop_reason, reason)
        proxy = jnp.where(stage_drop, 0, proxy)
    if lb_drop is not None:
        # pre-policy: wins over policy/no_ep/NAT/bandwidth reasons
        allowed = allowed & ~lb_drop
        verdict = jnp.where(lb_drop, VERDICT_DENY, verdict)
        reason = jnp.where(lb_drop, REASON_NO_SERVICE, reason)
        proxy = jnp.where(lb_drop, 0, proxy)

    # 5. conntrack create/refresh (create only on allowed NEW; related
    #    rows neither create nor refresh — the ICMP error is evidence
    #    about a flow, not flow traffic; no_ep rows touch nothing).
    untouched = is_related | no_ep
    if nat_drop is not None:
        untouched = untouched | nat_drop  # dropped rows refresh nothing
    if stage_drop is not None:
        untouched = untouched | stage_drop
    if lb_drop is not None:
        untouched = untouched | lb_drop
    ct = ct_update(state.ct, hdr, fwd,
                   jnp.where(untouched, CT_NEW, ct_res), slot,
                   is_reply,
                   do_create=allowed & is_new & ~related_hint,
                   proxy_port=proxy.astype(jnp.uint32),
                   now=now, valid=valid)

    # 6. metrics (reference: bpf metricsmap per-reason counters).
    m_reason = reason if valid is None else jnp.where(valid, reason,
                                                     N_REASONS)
    metrics = state.metrics.at[m_reason, dirn].add(1, mode="drop")

    event = jnp.where(~allowed, EV_DROP,
                      jnp.where(is_new, EV_VERDICT, EV_TRACE))
    out = jnp.stack([
        verdict.astype(jnp.uint32),
        proxy.astype(jnp.uint32),
        jnp.where(is_related, CT_RELATED, ct_res).astype(jnp.uint32),
        id_row.astype(jnp.uint32),
        reason.astype(jnp.uint32),
        event.astype(jnp.uint32),
    ], axis=1)
    return out, DatapathState(policy=state.policy, ipcache=state.ipcache,
                              ct=ct, metrics=metrics)


def apply_masquerade(ct: CTTable, nat, hdr: jnp.ndarray,
                     now: jnp.ndarray) -> jnp.ndarray:
    """CONNTRACK-AWARE egress masquerade: egress-to-world sources
    rewrite to the node IP UNLESS the row's reverse CT entry exists —
    that row replies to a connection a remote originated INTO us and
    must keep its source (reference: the bpf masquerade path consults
    CT before SNAT).  Runs as its own stage before datapath_step so
    event decode sees the post-NAT rows; the CT entry of a
    masqueraded flow carries the post-NAT tuple (reverse-translation
    anchor)."""
    from ..core.packets import COL_DST_IP3, COL_SRC_IP3
    from .conntrack import _probe, ct_keys_from_headers

    hdr = hdr.astype(jnp.uint32)
    if not nat.enabled:  # static pytree aux: baked in at trace time
        return hdr
    dst = hdr[:, COL_DST_IP3]
    internal = jnp.any(
        (dst[:, None] & nat.mask[None, :]) == nat.net[None, :], axis=1)
    egress = hdr[:, COL_DIR] == 1
    v4 = hdr[:, COL_FAMILY] == 4
    _fwd, rev = ct_keys_from_headers(hdr)
    r_found, _slot = _probe(ct.table, rev, now)
    masq = egress & v4 & ~internal & ~r_found
    new_src = jnp.where(masq, nat.node_ip, hdr[:, COL_SRC_IP3])
    return hdr.at[:, COL_SRC_IP3].set(new_src)


apply_masquerade_jit = jax.jit(apply_masquerade)

datapath_step_jit = jax.jit(datapath_step, donate_argnums=0,
                            static_argnames=("audit",))


def datapath_step_packed(state: DatapathState, packed: jnp.ndarray,
                         now: jnp.ndarray, ep, dirn,
                         valid: jnp.ndarray = None,
                         audit: bool = False
                         ) -> Tuple[jnp.ndarray, DatapathState]:
    """The ingest fast path: packed IPv4 rows (16 B/packet on the h2d
    link — see core/packets.py PACKED_*) unpack on device and run the
    same fused pipeline.  ``ep``/``dirn`` are per-stream scalars, like
    the per-endpoint tc hook in the reference."""
    from ..core.packets import unpack_hdr

    return datapath_step(state, unpack_hdr(packed, ep, dirn), now,
                         valid=valid, audit=audit)


datapath_step_packed_jit = jax.jit(datapath_step_packed, donate_argnums=0,
                                   static_argnames=("audit",))


def build_state(policy_tensors: PolicyTensors, lpm_tensors: LPMTensors,
                ep_policy: np.ndarray = None,
                ct_capacity: int = 1 << 20,
                ct_shards: int = 1) -> DatapathState:
    """Assemble a fresh device state from host-compiled tensors."""
    return DatapathState.create(
        policy=DevicePolicy.from_tensors(policy_tensors, ep_policy),
        ipcache=DeviceLPM.from_tensors(lpm_tensors),
        ct=CTTable.create(ct_capacity, shards=ct_shards),
    )
