"""Bandwidth manager: per-endpoint egress rate limiting on device.

Reference: upstream cilium's ``pkg/bandwidth`` + the EDT (earliest
departure time) logic in ``bpf_lxc.c`` — pods annotated with
``kubernetes.io/egress-bandwidth`` get their egress paced by stamping
packet departure times against a per-endpoint token aggregate (the fq
qdisc then holds packets to their timestamps).

TPU-first redesign: there is no queue between batches to hold packets
in, so pacing becomes PROPORTIONAL POLICING at batch granularity —
each endpoint accrues a byte budget (token bucket: ``rate`` bytes/s,
capped at ``burst``), a batch spends it, and when a batch's egress
bytes exceed the budget a deterministic per-row hash keeps exactly the
budget's fraction of rows and drops the rest with
``REASON_BANDWIDTH``.  Long-run throughput converges to the
configured rate; what upstream achieves by DELAYING (EDT + fq) this
achieves by dropping, which is the only batch-semantics-preserving
enforcement (DIVERGENCES #20).  Everything is segment_sum / gather —
one fused stage, no scalar loops.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..core.packets import COL_DIR, COL_EP, COL_LEN, COL_SPORT, COL_SRC_IP3
from .verdict import MAX_ENDPOINTS, REASON_BANDWIDTH

# default burst: one second's worth of the configured rate (upstream
# bandwidth manager derives burst from rate as well)
BURST_SECONDS = 1


@jax.tree_util.register_pytree_node_class
@dataclass
class BandwidthState:
    """Per-endpoint token buckets (bytes) + the last accrual tick."""

    tokens: jnp.ndarray  # [MAX_ENDPOINTS] uint32 — available bytes
    last: jnp.ndarray  # [] uint32 — last accrual `now`

    @staticmethod
    def create() -> "BandwidthState":
        return BandwidthState(
            tokens=jnp.zeros((MAX_ENDPOINTS,), dtype=jnp.uint32),
            last=jnp.uint32(0))

    def tree_flatten(self):
        return ((self.tokens, self.last), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def bw_stage(state: BandwidthState, hdr: jnp.ndarray, now: jnp.ndarray,
             rates: jnp.ndarray):
    """Police one batch: -> (reasons [N] uint32, state').

    ``rates`` is [MAX_ENDPOINTS] uint32 bytes/s (0 = unlimited).
    ``reasons`` carries ``REASON_BANDWIDTH`` on rows to drop and 0
    elsewhere — feed it to ``datapath_step(pre_drop_reason=...)``.
    """
    hdr = hdr.astype(jnp.uint32)
    ep = jnp.minimum(hdr[:, COL_EP], MAX_ENDPOINTS - 1).astype(jnp.int32)
    # accrue: tokens += rate * dt, capped at the burst allowance.
    # dt clamps to the burst window FIRST: accrual past the cap is
    # discarded anyway, and an unclamped rates*dt wraps u32 after
    # long idle gaps (under-filling the bucket it should have filled)
    dt = jnp.minimum(now - state.last, jnp.uint32(BURST_SECONDS))
    burst = rates * jnp.uint32(BURST_SECONDS)
    tokens = jnp.minimum(state.tokens + rates * dt, burst)

    limited = rates[ep] > 0
    policed = limited & (hdr[:, COL_DIR] == 1)  # egress only
    length = jnp.where(policed, hdr[:, COL_LEN], 0)
    batch_bytes = jax.ops.segment_sum(length, ep,
                                      num_segments=MAX_ENDPOINTS)

    # keep-fraction per endpoint: the budget's share of this batch's
    # bytes.  Row selection is a deterministic per-flow hash, so one
    # flow's packets keep/drop consistently within the batch and the
    # kept fraction converges to tokens/batch_bytes.
    frac = jnp.where(
        batch_bytes > 0,
        jnp.minimum(tokens.astype(jnp.float32)
                    / jnp.maximum(batch_bytes, 1).astype(jnp.float32),
                    1.0),
        1.0)
    h = (hdr[:, COL_SRC_IP3] * jnp.uint32(0x9E3779B1)
         ^ hdr[:, COL_SPORT] * jnp.uint32(0x85EBCA6B)
         ^ (ep.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)))
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x2C1B3C6D)
    u = (h >> 8).astype(jnp.float32) / jnp.float32(1 << 24)  # [0, 1)
    drop = policed & (u >= frac[ep])
    reasons = jnp.where(drop, jnp.uint32(REASON_BANDWIDTH),
                        jnp.uint32(0))

    consumed = jax.ops.segment_sum(jnp.where(drop, 0, length), ep,
                                   num_segments=MAX_ENDPOINTS)
    tokens = tokens - jnp.minimum(consumed, tokens)
    return reasons, BandwidthState(tokens=tokens, last=now)


bw_stage_jit = jax.jit(bw_stage, donate_argnums=0)


def rates_array(limits: dict) -> np.ndarray:
    """{endpoint id -> bytes/s} -> the [MAX_ENDPOINTS] rates tensor."""
    rates = np.zeros(MAX_ENDPOINTS, dtype=np.uint32)
    for ep_id, bps in limits.items():
        if 0 <= int(ep_id) < MAX_ENDPOINTS and bps:
            # clamp so tokens + rate*dt can NEVER wrap u32: tokens
            # caps at burst and the accrual at burst, so burst must
            # stay under 2^31 (~17 Gbit/s ceiling; a pod faster than
            # that is effectively unlimited here)
            rates[int(ep_id)] = min(int(bps),
                                    0x7FFFFFFF // BURST_SECONDS)
    return rates
