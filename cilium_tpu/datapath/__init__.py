"""TPU datapath: the eBPF hot path as batched JAX kernels.

Reference: upstream cilium ``bpf/`` (bpf_lxc.c + bpf/lib) and
``pkg/datapath``.  See ``verdict.datapath_step`` for the fused
pipeline and ``loader.Loader`` for the agent-facing seam.
"""

from .conntrack import (  # noqa: F401
    CT_ESTABLISHED,
    CT_NEW,
    CT_RELATED,
    CT_REPLY,
    CTTable,
    ct_gc,
    ct_keys_from_headers,
    ct_lookup,
    ct_update,
)
from .lpm import DeviceLPM, LPMTensors, compile_lpm, lpm_lookup  # noqa: F401
from .verdict import (  # noqa: F401
    EV_DROP,
    EV_TRACE,
    EV_VERDICT,
    OUT_CT,
    OUT_EVENT,
    OUT_ID_ROW,
    OUT_PROXY,
    OUT_REASON,
    OUT_VERDICT,
    REASON_FORWARDED,
    REASON_POLICY_DEFAULT_DENY,
    REASON_POLICY_DENY,
    DatapathState,
    DevicePolicy,
    build_state,
    datapath_step,
    datapath_step_jit,
)
