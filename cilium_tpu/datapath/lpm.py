"""Longest-prefix-match as gather tables (the ipcache LPM map).

Reference: upstream cilium's ipcache is a kernel ``LPM_TRIE`` BPF map
(``bpf/lib/eps.h`` ``lookup_ip4_remote_endpoint`` /
``pkg/maps/ipcache``).  TPU-first redesign: a trie walk is
branch-heavy and pointer-chasing — hostile to XLA.  Instead the host
compiles all prefixes into a DIR-16-8-8 multibit table so the device
lookup is **three gathers** with no data-dependent control flow:

    a = l1[ip >> 16]           # [65536]
    b = a>=0 ? a : l2[-a-1, (ip >> 8) & 0xFF]
    c = b>=0 ? b : l3[-b-1, ip & 0xFF]

Non-negative entries are values (identity rows); negative entries are
``-(block+1)`` pointers into the next level.  IPv6 uses a masked-compare
TCAM over the (typically small) v6 prefix set.

Rebuild cost is O(prefixes + painted slots) on host; the tensors are
swapped atomically on the device (the BPF map-replace analogue).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# nominal prefix budget for the map-pressure occupancy fraction
# (ISSUE 19): the DIR-16-8-8 tables grow on demand, but operators
# need a headroom signal like upstream's fixed-size ipcache map —
# this is the declared comfortable ceiling the pressure monitor and
# the map-headroom SLO measure against
LPM_NOMINAL_CAPACITY = 1 << 16


@dataclass
class LPMTensors:
    """Compiled device LPM state (host numpy; uploaded by the loader)."""

    l1: np.ndarray  # [65536] int32
    l2: np.ndarray  # [n_l2, 256] int32
    l3: np.ndarray  # [n_l3, 256] int32
    v6_net: np.ndarray  # [K, 4] uint32
    v6_mask: np.ndarray  # [K, 4] uint32
    v6_value: np.ndarray  # [K] int32
    v6_plen: np.ndarray  # [K] int32
    default: int = 0


def compile_lpm(entries: Dict[str, int], default: int = 0,
                block_pad: int = 8) -> LPMTensors:
    """Compile {cidr_string: value} into DIR-16-8-8 tables.

    Values must be >= 0 (they share sign space with block pointers).
    Longest prefix wins, implemented by painting shortest-first.
    """
    v4: List[Tuple[int, int, int]] = []  # (plen, net, value)
    v6: List[Tuple[int, int, int]] = []
    for cidr, value in entries.items():
        if value < 0:
            raise ValueError(f"LPM value must be >= 0, got {value}")
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version == 4:
            v4.append((net.prefixlen, int(net.network_address), value))
        else:
            v6.append((net.prefixlen, int(net.network_address), value))
    v4.sort(key=lambda t: t[0])

    l1 = np.full(1 << 16, default, dtype=np.int32)
    l2_blocks: List[np.ndarray] = []
    l3_blocks: List[np.ndarray] = []

    def l2_block_for(hi16: int) -> np.ndarray:
        cur = l1[hi16]
        if cur < 0:
            return l2_blocks[-cur - 1]
        blk = np.full(256, cur, dtype=np.int32)  # inherit shorter prefix
        l2_blocks.append(blk)
        l1[hi16] = -len(l2_blocks)
        return blk

    def l3_block_for(blk2: np.ndarray, mid8: int) -> np.ndarray:
        cur = blk2[mid8]
        if cur < 0:
            return l3_blocks[-cur - 1]
        blk = np.full(256, cur, dtype=np.int32)
        l3_blocks.append(blk)
        blk2[mid8] = -len(l3_blocks)
        return blk

    # Shortest-first processing means child blocks never exist when a
    # shorter prefix paints its range (blocks are only created by the
    # longer prefixes processed later), so painting never has to
    # descend into existing blocks — plain range writes suffice.
    for plen, net, value in v4:
        if plen <= 16:
            lo = net >> 16
            l1[lo:lo + (1 << (16 - plen))] = value
        elif plen <= 24:
            blk2 = l2_block_for(net >> 16)
            lo = (net >> 8) & 0xFF
            blk2[lo:lo + (1 << (24 - plen))] = value
        else:
            blk2 = l2_block_for(net >> 16)
            blk3 = l3_block_for(blk2, (net >> 8) & 0xFF)
            lo = net & 0xFF
            blk3[lo:lo + (1 << (32 - plen))] = value

    v6.sort(key=lambda t: t[0])
    k = max(len(v6), 1)
    v6_net = np.zeros((k, 4), dtype=np.uint32)
    v6_mask = np.zeros((k, 4), dtype=np.uint32)
    v6_value = np.full(k, default, dtype=np.int32)
    v6_plen = np.full(k, -1, dtype=np.int32)
    for i, (plen, net, value) in enumerate(v6):
        mask = ((1 << plen) - 1) << (128 - plen) if plen else 0
        for w in range(4):
            sh = 96 - 32 * w
            v6_net[i, w] = (net >> sh) & 0xFFFFFFFF
            v6_mask[i, w] = (mask >> sh) & 0xFFFFFFFF
        v6_value[i] = value
        v6_plen[i] = plen

    def pad_blocks(blocks: List[np.ndarray]) -> np.ndarray:
        n = -(-max(len(blocks), 1) // block_pad) * block_pad
        out = np.full((n, 256), default, dtype=np.int32)
        for i, b in enumerate(blocks):
            out[i] = b
        return out

    return LPMTensors(
        l1=l1,
        l2=pad_blocks(l2_blocks),
        l3=pad_blocks(l3_blocks),
        v6_net=v6_net,
        v6_mask=v6_mask,
        v6_value=v6_value,
        v6_plen=v6_plen,
        default=default,
    )


def lpm_used_blocks(t: LPMTensors) -> Tuple[int, int]:
    """(n_l2_used, n_l3_used) — block-pad headroom is what makes
    incremental upserts possible without reshaping device tensors."""
    # pointers encode block b as -(b+1): the used count is determined
    # by the MOST NEGATIVE pointer
    n_l2 = int(-(t.l1[t.l1 < 0]).min()) if (t.l1 < 0).any() else 0
    n_l3 = int(-(t.l2[t.l2 < 0]).min()) if (t.l2 < 0).any() else 0
    return n_l2, n_l3


def lpm_upsert(t: LPMTensors, cidr: str,
               value: int) -> Optional[List[tuple]]:
    """Insert/overwrite one HOST ROUTE (/32) in place.

    Returns the device patch list [(field, index, payload), ...] —
    ``("l1", slot, scalar)`` / ``("l2"|"l3", block, row[256])``,
    ordered children-first so a step between patch applications never
    follows a pointer into an unwritten block — or None when the entry
    needs a full recompile+upload of the LPM tensors (still never a
    policy recompile).

    ONLY /32s patch in place: the compiled tables store no per-slot
    prefix lengths, so painting a shorter prefix's range could
    overwrite longer (more-specific) sibling values and break
    longest-prefix-match — those go down the rebuild path.  A /32 is
    always the most specific, and identity churn (pod IPs, fqdn IPs)
    is host routes, so the hot path is covered.

    This is the ipcache analogue of a BPF LPM-map update: one map
    entry changes, nothing re-attaches.
    """
    if value < 0:
        raise ValueError(f"LPM value must be >= 0, got {value}")
    net = ipaddress.ip_network(cidr, strict=False)
    if net.version != 4 or net.prefixlen != 32:
        return None  # rebuild path (v6 TCAM swap / non-host-route)
    addr = int(net.network_address)
    n_l2, n_l3 = lpm_used_blocks(t)
    hi16, mid8, lo8 = addr >> 16, (addr >> 8) & 0xFF, addr & 0xFF

    # Plan the whole insert BEFORE mutating anything: a partial
    # mutation followed by a None return would leak a block per failed
    # upsert and make correctness depend on the caller discarding the
    # host mirror.
    cur1 = int(t.l1[hi16])
    l1_created = cur1 >= 0
    blk2 = n_l2 if l1_created else -cur1 - 1
    # a freshly-created l2 block inherits cur1 everywhere, so its
    # mid8 slot is cur1 (a leaf >= 0) and an l3 block is needed too
    cur2 = cur1 if l1_created else int(t.l2[blk2, mid8])
    l2_changed = cur2 >= 0
    if l1_created and n_l2 >= t.l2.shape[0]:
        return None  # l2 padding exhausted
    if l2_changed and n_l3 >= t.l3.shape[0]:
        return None  # l3 padding exhausted

    if l1_created:
        t.l2[blk2, :] = cur1  # inherit the shorter prefix's value
        t.l1[hi16] = -(blk2 + 1)
    if l2_changed:
        blk3 = n_l3
        t.l3[blk3, :] = cur2
        t.l2[blk2, mid8] = -(blk3 + 1)
    else:
        blk3 = -cur2 - 1

    t.l3[blk3, lo8] = value
    patches: List[tuple] = [("l3", blk3, t.l3[blk3].copy())]
    if l2_changed or l1_created:
        patches.append(("l2", blk2, t.l2[blk2].copy()))
    if l1_created:
        patches.append(("l1", hi16, np.int32(-(blk2 + 1))))
    return patches


class LPMUndo:
    """Rollback snapshot for ONE :func:`lpm_upsert` against the host
    mirror — the crash-safety half of the loader's table-versioning
    contract: a build that fails AFTER the mirror upsert but BEFORE
    the generation flip (the seeded ``churn.*`` fault sites) must
    leave the mirror exactly as published, or the next rebuild would
    resurrect an entry the datapath never served.

    Snapshots the same (l1 slot, l2 block, l3 block) the upsert's
    plan derives — the derivation here MUST mirror ``lpm_upsert``'s;
    both live in this file so they cannot drift apart silently."""

    def __init__(self, t: LPMTensors, cidr: str):
        self.cells: List[tuple] = []  # ("l1"|"l2"|"l3", idx, payload)
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version != 4 or net.prefixlen != 32:
            return  # rebuild path: the mirror object is REPLACED,
            # not mutated — nothing to snapshot
        addr = int(net.network_address)
        n_l2, n_l3 = lpm_used_blocks(t)
        hi16, mid8 = addr >> 16, (addr >> 8) & 0xFF
        cur1 = int(t.l1[hi16])
        blk2 = n_l2 if cur1 >= 0 else -cur1 - 1
        cur2 = cur1 if cur1 >= 0 else int(t.l2[blk2, mid8])
        blk3 = n_l3 if cur2 >= 0 else -cur2 - 1
        self.cells.append(("l1", hi16, np.int32(cur1)))
        if blk2 < t.l2.shape[0]:
            self.cells.append(("l2", blk2, t.l2[blk2].copy()))
        if blk3 < t.l3.shape[0]:
            self.cells.append(("l3", blk3, t.l3[blk3].copy()))

    def restore(self, t: LPMTensors) -> None:
        for field, idx, payload in self.cells:
            getattr(t, field)[idx] = payload


def lookup_v4(t_l1: jnp.ndarray, t_l2: jnp.ndarray, t_l3: jnp.ndarray,
              ip: jnp.ndarray) -> jnp.ndarray:
    """Batched IPv4 LPM: [N] uint32 -> [N] int32 values. Three gathers."""
    ip = ip.astype(jnp.uint32)
    a = t_l1[(ip >> 16).astype(jnp.int32)]
    mid = ((ip >> 8) & 0xFF).astype(jnp.int32)
    b = jnp.where(a < 0, t_l2[jnp.maximum(-a - 1, 0), mid], a)
    lo = (ip & 0xFF).astype(jnp.int32)
    c = jnp.where(b < 0, t_l3[jnp.maximum(-b - 1, 0), lo], b)
    return c


def lookup_v6(v6_net: jnp.ndarray, v6_mask: jnp.ndarray,
              v6_value: jnp.ndarray, v6_plen: jnp.ndarray,
              ip_words: jnp.ndarray, default: int) -> jnp.ndarray:
    """Batched IPv6 TCAM LPM: [N, 4] uint32 words -> [N] int32 values."""
    # [N, K, 4]: (ip & mask) == net per word
    masked = ip_words[:, None, :] & v6_mask[None, :, :]
    hit = jnp.all(masked == v6_net[None, :, :], axis=-1)  # [N, K]
    score = jnp.where(hit, v6_plen[None, :], -1)
    best = jnp.argmax(score, axis=-1)
    found = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0] >= 0
    val = v6_value[best]
    return jnp.where(found, val, default)


def lpm_lookup(t: "DeviceLPM", ip_words: jnp.ndarray,
               family: jnp.ndarray) -> jnp.ndarray:
    """Family-dispatched lookup over the [N, 4] IP word tensor."""
    v4 = lookup_v4(t.l1, t.l2, t.l3, ip_words[:, 3])
    v6 = lookup_v6(t.v6_net, t.v6_mask, t.v6_value, t.v6_plen,
                   ip_words, t.default)
    return jnp.where(family == 4, v4, v6)


lpm_lookup_jit = jax.jit(lpm_lookup)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceLPM:
    """LPM tensors living on device (a pytree; threads through jit)."""

    l1: jnp.ndarray
    l2: jnp.ndarray
    l3: jnp.ndarray
    v6_net: jnp.ndarray
    v6_mask: jnp.ndarray
    v6_value: jnp.ndarray
    v6_plen: jnp.ndarray
    default: int

    @staticmethod
    def from_tensors(t: LPMTensors) -> "DeviceLPM":
        return DeviceLPM(
            l1=jnp.asarray(t.l1),
            l2=jnp.asarray(t.l2),
            l3=jnp.asarray(t.l3),
            v6_net=jnp.asarray(t.v6_net),
            v6_mask=jnp.asarray(t.v6_mask),
            v6_value=jnp.asarray(t.v6_value),
            v6_plen=jnp.asarray(t.v6_plen),
            default=t.default,
        )

    def tree_flatten(self):
        return ((self.l1, self.l2, self.l3, self.v6_net, self.v6_mask,
                 self.v6_value, self.v6_plen), self.default)

    @classmethod
    def tree_unflatten(cls, default, children):
        return cls(*children, default=default)
