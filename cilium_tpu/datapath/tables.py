"""Versioned, double-buffered device table slots (ISSUE 10 tentpole).

Reference: upstream cilium's SelectorCache-driven incremental updates
mutate pinned BPF maps while traffic flows — the datapath always sees
either the pre-change or the post-change entry, never a torn hybrid.
The TPU analogue has to provide the same guarantee for the DENSE
tables (verdict tensor, LPM, ep_policy, auth): this module is the
publication protocol every table mutation in ``datapath/loader.py``
goes through.

The idiom is BucketArena's recycling-horizon ownership handoff,
applied to device tables:

- TWO SLOTS, one ACTIVE: the slot pair holds the published table
  bundle (``DevicePolicy`` + ``DeviceLPM``) for the current and the
  previous generation.  Builders assemble the successor bundle OFF
  the dispatch path (host compile + ``.at[].set`` device work happen
  with only the BUILD lock held, never the loader's dispatch lock).
- ONE FLIP: publication is :meth:`flip` — an index swap plus a
  monotonic ``generation`` bump — executed while the caller holds the
  loader's dispatch lock, so a concurrent serving dispatch captures
  either the old bundle or the new one, whole.  The dispatch lock is
  held only for the flip (a pointer swap), never the rebuild.
- RECYCLING HORIZON: after a flip the demoted slot keeps the previous
  generation's bundle until the NEXT build overwrites it.  After an
  ATTACH flip those are live arrays (an in-flight dispatch that
  captured them holds its own references); after a PATCH flip the
  previous generation's patched arrays are CONSUMED handles — the
  loader's donating in-place update (``loader._dus``) recycled their
  buffers into the new generation, sequenced after every in-flight
  read by device-stream order.  Either way the spare slot is
  BOOKKEEPING (generation tags, test assertions), never a read path
  — the same ownership handoff BucketArena slots make at their
  recycling horizon.

A failed build (exception anywhere before :meth:`flip`, including the
seeded ``churn.build`` / ``churn.swap`` fault sites) leaves the
active slot, the generation, and every published table byte exactly
as they were: half-built generations are unreachable by construction
because nothing exposes the spare slot until the flip.

Builders serialize on :attr:`build_lock` (lock order: table-builder
BEFORE datapath-loader — the publish step takes the dispatch lock
while holding the build lock, never the reverse).
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Optional


class TableSlot:
    """One published table bundle: the device arrays plus the
    generation they were published as (0 = never published)."""

    __slots__ = ("policy", "lpm", "gen")

    def __init__(self, policy=None, lpm=None, gen: int = 0):
        self.policy = policy
        self.lpm = lpm
        self.gen = gen


class _Build:
    """Handle for one builder pass (see :meth:`TableVersioner.building`).
    ``published`` carries the generation the pass flipped to, or None
    when the builder bailed out without publishing (validation
    ``return False`` paths)."""

    __slots__ = ("t0", "published")

    def __init__(self, t0: float):
        self.t0 = t0
        self.published: Optional[int] = None


class TableVersioner:
    """Double-buffered table slot pair + monotonic generation tag.

    Written by builder threads (API / regeneration / allocator
    observers) under :attr:`build_lock`; the flip itself additionally
    runs under the loader's dispatch lock.  Counters and histograms
    are read lock-free by stats/registry scrapes (single-writer
    ints/log2-buckets — the same torn-read tolerance every serving
    histogram has)."""

    def __init__(self, warn_ms: float = 0.0):
        # deferred: keeps this module importable without the serving
        # package on pure-analysis boxes (scripts/lint.py discipline)
        from ..infra.lockdebug import make_lock
        from ..serving.stats import LatencyHistogram

        # serializes builders end to end (compute + publish + mirror
        # writes); the flip additionally holds the dispatch lock
        self.build_lock = make_lock("table-builder")
        # guarded-by: table-builder: _slots, _spare_dirty
        self._slots = [TableSlot(), TableSlot()]
        self._active = 0
        # marks the spare slot's arrays as overwritten by an ABORTED
        # build since the last flip (test surface: proves a failed
        # build never reached the active index)
        self._spare_dirty = False
        self.generation = 0  # monotonic; bumps ONLY at flip
        self.swaps = 0
        self.last_swap_us: Optional[float] = None
        # dispatch-lock hold for one flip (the drain thread's swap
        # stall ceiling) and mutation-entry -> published latency (the
        # operator-visible "policy update latency")
        self.swap_stall = LatencyHistogram()
        self.update_visible = LatencyHistogram()
        # delta-compile scoreboard (TPULoader.attach)
        self.full_attaches = 0
        self.delta_attaches = 0
        self.policies_recompiled = 0
        self.patches = 0  # in-place row/LPM patch publishes
        self.failed_builds = 0  # builder passes that raised
        self.warn_ms = float(warn_ms)

    # -- builder side ---------------------------------------------------
    @contextmanager
    def building(self):
        """One serialized builder pass.  Records update-visible
        latency on publish, counts a failed build on exception (the
        publish-or-nothing contract: an exception before the flip
        leaves the active generation untouched)."""
        t0 = time.monotonic()  # BEFORE the lock: update-visible
        # latency includes builder contention — the operator waits
        # through a slow attach ahead in line too
        with self.build_lock:
            b = _Build(t0)
            try:
                yield b
            except BaseException:
                self.failed_builds += 1
                self._spare_dirty = True
                raise
            if b.published is not None:
                self.update_visible.record(
                    (time.monotonic() - b.t0) * 1e6)

    @property
    def active(self) -> TableSlot:
        # holds: build_lock -- builders; other callers accept a
        # point-in-time read (slots hold immutable array bundles)
        return self._slots[self._active]

    @property
    def spare(self) -> TableSlot:
        # holds: build_lock -- builders; other callers accept a
        # point-in-time read (slots hold immutable array bundles)
        """The previous generation's slot (recycled at the next flip)."""
        return self._slots[1 - self._active]

    @property
    def spare_dirty(self) -> bool:
        # holds: build_lock -- builders; other callers accept a
        # point-in-time read of the flag
        """True when the last builder pass aborted after staging work:
        the spare holds half-built state the flip never exposed."""
        return self._spare_dirty

    def flip(self, build: _Build, policy, lpm, t_lock: float) -> int:
        # holds: build_lock -- builders call this via the loader's
        # _publish_tables while additionally holding the dispatch lock
        """Publish: write the successor bundle into the spare slot,
        swap the active index, bump the generation.  ``t_lock`` is
        when the caller acquired the dispatch lock — the stall clock.
        MUST be called with the loader's dispatch lock held."""
        spare = 1 - self._active
        self.generation += 1
        slot = self._slots[spare]
        slot.policy = policy
        slot.lpm = lpm
        slot.gen = self.generation
        self._active = spare
        self._spare_dirty = False
        self.swaps += 1
        stall_us = (time.monotonic() - t_lock) * 1e6
        self.last_swap_us = round(stall_us, 3)
        self.swap_stall.record(stall_us)
        build.published = self.generation
        if self.warn_ms > 0 and stall_us > self.warn_ms * 1e3:
            # hot-path-ok: operator-armed slow-swap warning
            # (policy_swap_warn_ms, default off) — fires only when a
            # flip exceeds the configured budget, never steady state
            logging.getLogger(__name__).warning(
                "table publish held the dispatch lock %.1fms "
                "(policy_swap_warn_ms=%.1f) at generation %d",
                stall_us / 1e3, self.warn_ms, self.generation)
        return self.generation

    def note_publish(self, build: _Build) -> int:
        """The InterpreterLoader's flip: no device slots to buffer
        (the oracle applies updates structurally), but the generation
        tag / swap counters keep parity so every surface and test
        reads the same shape from either backend."""
        return self.flip(build, None, None, time.monotonic())

    # -- read side ------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``tables`` stats block (serving stats -> GET /serving
        -> CLI -> registry)."""
        return {
            "generation": self.generation,
            "swaps": self.swaps,
            "last-swap-us": self.last_swap_us,
            "swap-stall-us": self.swap_stall.snapshot(),
            "update-visible-us": self.update_visible.snapshot(),
            "full-attaches": self.full_attaches,
            "delta-attaches": self.delta_attaches,
            "policies-recompiled": self.policies_recompiled,
            "patches": self.patches,
            "failed-builds": self.failed_builds,
        }
