"""Map-pressure monitor + graceful-degradation controller (ISSUE 12).

Reference: upstream cilium exports per-map pressure gauges
(``cilium_bpf_map_pressure``), runs the conntrack GC on an ADAPTIVE
interval (``pkg/maps/ctmap``: the sweep accelerates while the map is
under pressure and relaxes when it drains), and degrades by counting
drops (``DROP_NAT_NO_MAPPING``) instead of failing.  This repo
already COUNTS those pressures — ``CTTable.dropped`` (failed CT
inserts), ``NATTable.failed`` (SNAT pool exhaustion) — but nothing
reacted to them.  This module is the reaction:

- :class:`MapPressureMonitor` samples the loader's
  :meth:`~cilium_tpu.datapath.loader.Loader.map_pressure` snapshot on
  a named controller (``map-pressure``, the existing
  ``infra/controller`` infra) — OFF the drain thread by construction;
- crossing a threshold (CT occupancy >= ``ct_pressure_threshold``,
  or any NEW insert drops / NAT pool failures inside a sample
  window) enters the PRESSURE state: the CT aging sweep is
  re-scheduled at ``ct_gc_pressure_interval`` (an immediate sweep
  triggered), and ONE ``map-pressure`` incident is recorded (flight-
  recorder capture) per episode — hysteresis (occupancy back under
  ``ct_pressure_clear`` AND a quiet window) exits the state and
  restores the normal cadence, so a storm cannot flap incidents;
- the last sample is cached for the registry collectors
  (``cilium_ct_occupancy`` / ``cilium_ct_insert_drops_total`` /
  ``cilium_nat_pool_failures_total``) and the serving-stats /
  ``GET /serving`` / CLI Pressure block — scrapes never touch the
  device.

Occupancy counts OCCUPIED slots (live + expired-but-unswept): that
is what the map actually has left for inserts, and it is exactly the
number the accelerated sweep visibly drives back down.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

STATE_OK = "ok"
STATE_PRESSURE = "pressure"


def validate_pressure_config(interval_s, ct_threshold, ct_clear,
                             gc_pressure_interval_s) -> tuple:
    """Validate the map-pressure DaemonConfig knobs (the
    validate_serving_config contract: fail at construction)."""
    interval_s = float(interval_s)
    if interval_s < 0:
        raise ValueError("map_pressure_interval must be >= 0 "
                         "(0 disables the monitor)")
    ct_threshold = float(ct_threshold)
    ct_clear = float(ct_clear)
    if not 0.0 < ct_threshold <= 1.0:
        raise ValueError("ct_pressure_threshold must be in (0, 1]")
    if not 0.0 < ct_clear <= ct_threshold:
        raise ValueError("ct_pressure_clear must be in (0, "
                         "ct_pressure_threshold] (the hysteresis "
                         "band)")
    gc_pressure_interval_s = float(gc_pressure_interval_s)
    if gc_pressure_interval_s <= 0:
        raise ValueError("ct_gc_pressure_interval must be > 0")
    return (interval_s, ct_threshold, ct_clear,
            gc_pressure_interval_s)


def validate_relax_config(relax_after_s, relax_factor,
                          relax_max) -> tuple:
    """Validate the adaptive GC-relaxation knobs (ISSUE 19
    satellite; same fail-at-construction contract)."""
    relax_after_s = float(relax_after_s)
    if relax_after_s < 0:
        raise ValueError("ct_gc_relax_after must be >= 0 "
                         "(0 disables relaxation)")
    relax_factor = float(relax_factor)
    if relax_factor <= 1.0:
        raise ValueError("ct_gc_relax_factor must be > 1 (a "
                         "non-stretching relax step would spin the "
                         "multiplier without changing the cadence)")
    relax_max = float(relax_max)
    if relax_max < relax_factor:
        raise ValueError("ct_gc_relax_max must be >= "
                         "ct_gc_relax_factor (the bound must admit "
                         "at least one step)")
    return relax_after_s, relax_factor, relax_max


class MapPressureMonitor:
    """Samples map pressure, drives the graceful-degradation
    response.  ``sample_fn()`` returns the loader's map_pressure
    snapshot; ``on_accelerate(interval_s)`` re-schedules the CT GC
    controller (and triggers an immediate sweep);
    ``record_incident(kind, detail)`` is ``Daemon.record_incident``.
    """

    def __init__(self, sample_fn: Callable[[], Dict],
                 on_accelerate: Callable[[float], None],
                 on_restore: Callable[[], None],
                 record_incident: Optional[Callable] = None,
                 ct_threshold: float = 0.85,
                 ct_clear: float = 0.70,
                 gc_pressure_interval_s: float = 1.0,
                 relax_after_s: float = 0.0,
                 relax_factor: float = 2.0,
                 relax_max: float = 4.0,
                 on_relax: Optional[Callable[[float], None]] = None):
        self._sample_fn = sample_fn
        self._on_accelerate = on_accelerate
        self._on_restore = on_restore
        self._record_incident = record_incident
        self.ct_threshold = float(ct_threshold)
        self.ct_clear = float(ct_clear)
        self.gc_pressure_interval_s = float(gc_pressure_interval_s)
        # adaptive relaxation (ISSUE 19 satellite): after every
        # relax_after_s of CONTINUOUS calm the normal GC cadence
        # stretches by relax_factor (compounding, bounded by
        # relax_max); any episode snaps the multiplier back to 1.
        # 0 disables.  on_relax(multiplier) re-schedules the sweep
        self.relax_after_s = float(relax_after_s)
        self.relax_factor = float(relax_factor)
        self.relax_max = float(relax_max)
        self._on_relax = on_relax
        self._lock = threading.Lock()
        # guarded-by: _lock: state, episodes, samples, last,
        # guarded-by: _lock: _prev_drops, _prev_nat, last_episode,
        # guarded-by: _lock: relax_mult, relaxes, _calm_since
        self.state = STATE_OK
        self.episodes = 0  # completed ENTRIES into pressure
        self.samples = 0
        self.last: Optional[Dict] = None  # the cached sample the
        # registry/CLI collectors read (scrapes never touch the
        # device)
        self.last_episode: Optional[Dict] = None
        self._prev_drops: Optional[int] = None
        self._prev_nat: Optional[int] = None
        self.relax_mult = 1.0
        self.relaxes = 0  # completed relax STEPS
        self._calm_since: Optional[float] = None

    # -- the controller body -------------------------------------------
    def sample(self, now: Optional[float] = None) -> Dict:
        # thread-affinity: api -- the map-pressure controller thread
        # (plus Daemon.start()'s synchronous warm call); never the
        # drain thread
        """One monitor tick: fetch the pressure snapshot, update the
        per-window rates, and walk the state machine.  ``now`` is the
        monotonic clock the relaxation streak measures against —
        injectable so tests pin the never-mid-episode guarantee on a
        fake timeline."""
        if now is None:
            now = time.monotonic()
        snap = self._sample_fn()
        ct = snap["ct"]
        nat = snap["nat"]
        episode_detail = None
        with self._lock:
            self.samples += 1
            drops = int(ct["insert-drops"])
            natf = int(nat["failures"])
            d_drops = (drops - self._prev_drops
                       if self._prev_drops is not None else 0)
            d_nat = (natf - self._prev_nat
                     if self._prev_nat is not None else 0)
            self._prev_drops, self._prev_nat = drops, natf
            occ = ct.get("occupancy")
            snap["ct"]["insert-drop-delta"] = d_drops
            snap["nat"]["failure-delta"] = d_nat
            hot = ((occ is not None and occ >= self.ct_threshold)
                   or d_drops > 0 or d_nat > 0)
            calm = ((occ is None or occ < self.ct_clear)
                    and d_drops == 0 and d_nat == 0)
            if self.state == STATE_OK and hot:
                self.state = STATE_PRESSURE
                self.episodes += 1
                # entering an episode snaps relaxation back: the
                # accelerated cadence takes over, and whatever calm
                # streak was building is void
                self.relax_mult = 1.0
                self._calm_since = None
                episode_detail = {
                    "occupancy": occ,
                    "insert-drop-delta": d_drops,
                    "nat-failure-delta": d_nat,
                    "episode": self.episodes,
                }
                self.last_episode = dict(episode_detail)
                snap["state"] = self.state
                self.last = snap
                # the response runs UNDER the lock so a concurrent
                # resync() (patch_config) serializes against the
                # transition — an unsynchronized check-then-act
                # could cancel the accelerated cadence mid-episode.
                # Safe to nest: the ct-gc controller body never
                # takes this lock (its join cannot deadlock), and
                # incident capture only SPAWNS its thread here (the
                # capture thread's stats() read waits out the
                # remainder of this sample, nothing more)
                self._on_accelerate(self.gc_pressure_interval_s)
                if self._record_incident is not None:
                    self._record_incident("map-pressure",
                                          episode_detail)
            elif self.state == STATE_PRESSURE and calm:
                self.state = STATE_OK
                snap["state"] = self.state
                self.last = snap
                # the episode just closed: the calm streak starts
                # NOW — relaxation needs a full relax_after_s of
                # post-episode calm before its first step, so it can
                # never fire mid-episode (test-pinned)
                self._calm_since = now
                self._on_restore()
            else:
                if self.state == STATE_OK and self.relax_after_s > 0:
                    if not calm:
                        # sub-threshold heat (occupancy inside the
                        # hysteresis band, or deltas on an already-
                        # pressured map shape) resets the streak
                        # without opening an episode
                        self._calm_since = None
                    elif self._calm_since is None:
                        self._calm_since = now
                    elif (now - self._calm_since >= self.relax_after_s
                          and self.relax_mult < self.relax_max):
                        self.relax_mult = min(
                            self.relax_max,
                            self.relax_mult * self.relax_factor)
                        self.relaxes += 1
                        self._calm_since = now
                        if self._on_relax is not None:
                            # under the lock like on_accelerate: a
                            # concurrent resync() serializes against
                            # the stretched cadence
                            self._on_relax(self.relax_mult)
                snap["state"] = self.state
                self.last = snap
        return snap

    def resync(self, normal_interval_s: float, schedule) -> None:
        # thread-affinity: any
        """Re-apply the CT-GC cadence for the CURRENT state under
        the monitor lock — the race-free path for config changes
        (``patch_config``): a concurrent sample's state transition
        serializes against this, so a mid-episode reconfigure can
        neither cancel the accelerated sweep nor leave it stuck
        after the episode exits."""
        with self._lock:
            schedule(self.gc_pressure_interval_s
                     if self.state == STATE_PRESSURE
                     else normal_interval_s * self.relax_mult)

    # -- reading --------------------------------------------------------
    def stats(self) -> Dict:
        # thread-affinity: any
        """The serving-stats / GET /serving / CLI Pressure block."""
        with self._lock:
            out = {
                "state": self.state,
                "episodes": self.episodes,
                "samples": self.samples,
                "ct-threshold": self.ct_threshold,
                "ct-clear": self.ct_clear,
                "gc-pressure-interval-s": self.gc_pressure_interval_s,
                "accelerated": self.state == STATE_PRESSURE,
                "relax": {
                    "after-s": self.relax_after_s,
                    "factor": self.relax_factor,
                    "max": self.relax_max,
                    "multiplier": self.relax_mult,
                    "steps": self.relaxes,
                },
            }
            if self.last is not None:
                out["ct"] = dict(self.last["ct"])
                out["nat"] = dict(self.last["nat"])
            if self.last_episode is not None:
                out["last-episode"] = dict(self.last_episode)
            return out
