"""Health: node registry + inter-node probe mesh.

Reference: upstream ``cilium-health`` / ``pkg/health`` — every node
registers itself, a prober sweeps all known nodes (ICMP + TCP to node
and endpoint IPs), and ``cilium status`` / ``cilium-health status``
report per-node reachability and latency.

TPU-first mapping: node discovery rides the kvstore (the same plane
identities replicate over); the probe transport is pluggable — the
default probes the peer agent's AF_UNIX API socket (the in-process/
single-host deployment), a TCP prober covers multi-host.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

NODES_PREFIX = "cilium/state/nodes/v1"


class NodeRegistry:
    """Node announcements over the kvstore (pkg/node discovery)."""

    def __init__(self, kv, lease_ttl: Optional[float] = 60.0):
        self.kv = kv
        self.lease_ttl = lease_ttl

    def register(self, name: str, info: dict) -> None:
        self.kv.update(f"{NODES_PREFIX}/{name}",
                       json.dumps({"name": name, **info}).encode(),
                       lease_ttl=self.lease_ttl)

    def heartbeat(self, name: str) -> None:
        if self.lease_ttl:
            self.kv.keepalive(f"{NODES_PREFIX}/{name}", self.lease_ttl)

    def annotate(self, name: str, extra: dict) -> None:
        """Merge ``extra`` keys into the node's advertised info (a
        re-register preserving existing keys).  The serving plane's
        fault state rides here — mode, restarts, CT-snapshot age —
        so `cilium-health`-style consumers see a DEGRADED node, not
        just a reachable one.  No-op keys-wise for an unregistered
        node (it becomes a registration)."""
        if not extra:
            return
        cur = {}
        raw = self.kv.get(f"{NODES_PREFIX}/{name}")
        if raw:
            cur = json.loads(raw)
        self.kv.update(f"{NODES_PREFIX}/{name}",
                       json.dumps({"name": name, **cur,
                                   **extra}).encode(),
                       lease_ttl=self.lease_ttl)

    def unregister(self, name: str) -> None:
        self.kv.delete(f"{NODES_PREFIX}/{name}")

    def nodes(self) -> List[dict]:
        return [json.loads(v) for v in
                self.kv.list_prefix(NODES_PREFIX + "/").values()]


@dataclass
class NodeHealth:
    name: str
    reachable: bool = False
    latency_ms: float = 0.0
    last_probe: float = 0.0
    consecutive_failures: int = 0
    error: str = ""


def unix_socket_prober(info: dict) -> float:
    """Default probe: connect to the node's API socket (AF_UNIX) and
    time it.  Raises on unreachable."""
    path = info.get("api_socket")
    if not path:
        raise ValueError("node advertises no api_socket")
    t0 = time.perf_counter()
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(2.0)
    try:
        s.connect(path)
    finally:
        s.close()
    return (time.perf_counter() - t0) * 1e3


def tcp_prober(info: dict) -> float:
    """Multi-host probe: TCP connect to the node's health address."""
    host, port = info["health_addr"].rsplit(":", 1)
    t0 = time.perf_counter()
    s = socket.create_connection((host, int(port)), timeout=2.0)
    s.close()
    return (time.perf_counter() - t0) * 1e3


class HealthMesh:
    """The probe mesh: sweep every registered node, keep per-node
    status (drive ``probe_all`` from a controller)."""

    def __init__(self, registry: NodeRegistry, local_name: str,
                 prober: Callable[[dict], float] = unix_socket_prober):
        self.registry = registry
        self.local_name = local_name
        self.prober = prober
        self._lock = threading.Lock()
        self._status: Dict[str, NodeHealth] = {}

    def probe_all(self) -> None:
        now = time.time()
        seen = set()
        for info in self.registry.nodes():
            name = info["name"]
            seen.add(name)
            if name == self.local_name:
                continue  # self is reported by liveness, not probes
            with self._lock:
                h = self._status.setdefault(name, NodeHealth(name))
            try:
                latency = self.prober(info)
                with self._lock:
                    h.reachable = True
                    h.latency_ms = round(latency, 3)
                    h.consecutive_failures = 0
                    h.error = ""
                    h.last_probe = now
            except Exception as e:
                with self._lock:
                    h.reachable = False
                    h.consecutive_failures += 1
                    h.error = f"{type(e).__name__}: {e}"[:200]
                    h.last_probe = now
        with self._lock:
            for name in list(self._status):
                if name not in seen:  # node lease expired: drop it
                    del self._status[name]

    def statuses(self) -> List[NodeHealth]:
        with self._lock:
            return [self._status[k] for k in sorted(self._status)]

    def to_dict(self) -> dict:
        """`cilium-health status`-shaped rendering."""
        nodes = self.statuses()
        return {
            "local": self.local_name,
            "nodes": [{
                "name": h.name,
                "reachable": h.reachable,
                "latency-ms": h.latency_ms,
                **({"error": h.error} if h.error else {}),
            } for h in nodes],
            "reachable": sum(1 for h in nodes if h.reachable),
            "unreachable": sum(1 for h in nodes if not h.reachable),
        }
