"""Shared cluster socket transport: framing, row-batch encoding, and
the close discipline every socket in the repo must follow.

Reference: upstream cilium's per-node agents share nothing but the
kvstore and the wire; every cross-node byte rides a real socket.  The
repo already proved one networked transport in production shape —
``kvstore/remote.py`` survived the PR 8 close-vs-blocked-syscall
hardening (a killed server must actually die; an idle client must see
EOF) — and the process-per-node serving tier (ISSUE 13) needs a
second: the flow-affine router forwarding packed ``[n, 4]`` u32 row
batches into per-node worker processes.  This module lifts the shared
pieces out so BOTH transports run one implementation:

- :func:`shutdown_close` — shutdown-before-close (PR 8's fix, one
  definition): POSIX ``close()`` neither wakes a thread blocked in
  ``recv()``/``accept()`` on the same fd nor sends FIN while the fd
  is pinned in that syscall; ``shutdown()`` delivers both halves
  immediately.  Used by the kvstore server/client AND the cluster
  node channels.
- :class:`LineFramer` — the kvstore's newline-delimited JSON framing
  (partial-read reassembly) as a reusable buffer, consumed by both
  ``kvstore/remote.py`` read loops.
- length-prefixed binary frames (:func:`send_frame` /
  :func:`recv_frame`) — the row-batch wire: a 4-byte big-endian
  length then the payload.  ``recv_frame`` reassembles partial reads,
  returns ``None`` on a clean EOF at a frame boundary, and raises
  :class:`FrameError` on a torn prefix, a torn body, or a length
  past ``max_frame`` (a corrupted/hostile peer must not make the
  receiver allocate unbounded memory).
- row-batch encode/decode (:func:`encode_rows` / :func:`decode_rows`)
  — wide ``[n, N_COLS]`` u32 header rows or packed ``[n, 4]`` u32
  rows (with their ``(ep, dirn)`` stream scalars) in one frame, and
  the fixed-size binary ACK (:func:`pack_ack` / :func:`unpack_ack`)
  carrying the receiving node's running packet ledger — the piece
  that lets the cluster ledger close EXACTLY over a SIGKILLed
  worker (``cluster/process.py``).
- CROSS-PROCESS TRACE CONTEXT (ISSUE 14): a 1-in-N sampled forward
  frame carries ``(trace_id, t_enqueue, t_forward)`` router-side
  stamps ahead of its rows (the TRACED frame kinds), and its ACK
  echoes ``(trace_id, t_recv, t_admit)`` worker-side stamps back —
  the router stitches one span (router-queue -> forward ->
  worker-admit -> ack) with per-hop latency
  (``obs/relay.ClusterSpanStore``).  Timestamps are
  ``time.monotonic()`` on BOTH ends: on Linux that is the
  machine-wide CLOCK_MONOTONIC, so stamps from the parent and a
  worker process on the same host compare directly (the repo's
  cluster is same-host loopback by construction — DIVERGENCES #26).
- PIPELINED DATA CHANNEL (ISSUE 17): the SEQUENCED frame kinds carry
  a monotonic per-channel sequence number ahead of the (optional)
  trace block, and the CUMULATIVE ACK (:func:`pack_cum_ack` /
  :func:`unpack_cum_ack`) acknowledges every frame up to its highest
  contiguous sequence in ONE frame — admitted-row delta, the running
  packet ledger, and the per-frame trace echo LIST for any traced
  frames the window covered.  :class:`SendWindow` is the sender-side
  bookkeeping: frames in flight between send and cumulative ack,
  retained with their rows so a dead channel's unacked frames can be
  requeued to a failover peer (or counted ``crash_dropped``) —
  nothing in flight is ever silently lost.  The legacy unsequenced
  kinds and the per-frame ACK stay byte-identical: a window-1
  channel degenerates to the PR 13 protocol exactly.

THREAD AFFINITY: the ``transport`` domain (CTA002 vocabulary, a
CTA003 hot domain like ``drain``/``router``) covers the threads that
move frames: the router's per-node forwarders while inside a
send/recv, and the node host's data-channel reader.  Functions here
are the domain's leaf surface — pure byte movement, no logging, no
file I/O, no device work.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import List, Optional, Tuple

import numpy as np

__all__ = [
    "FrameError", "LineFramer", "shutdown_close", "SendWindow",
    "send_frame", "recv_frame", "send_json_frame", "recv_json_frame",
    "encode_rows", "decode_rows", "decode_rows_ex", "decode_rows_seq",
    "pack_ack", "unpack_ack", "unpack_ack_ex",
    "pack_cum_ack", "unpack_cum_ack",
    "pack_crypto_reject", "unpack_crypto_reject", "is_crypto_reject",
    "rows_to_b64", "rows_from_b64",
    "MAX_FRAME", "ACK_SIZE", "ACK_TRACED_SIZE", "CUM_ACK_MIN_SIZE",
    "CRYPTO_REJECT_SIZE", "CRYPTO_REJECT_REASONS",
]

# frame length prefix: 4-byte big-endian unsigned
_LEN = struct.Struct(">I")

# default per-frame byte ceiling: comfortably above the largest row
# batch the serving tier ships (a 2^15-row wide chunk is 2 MiB) while
# bounding what a torn/hostile prefix can make the receiver allocate
MAX_FRAME = 1 << 24

# ACK: admitted u32, then the node's running packet-ledger counters
# (submitted, verdicts, shed, recovery_dropped) as u64 — see
# module doc and cluster/process.py
_ACK = struct.Struct(">IQQQQ")
ACK_SIZE = _ACK.size
# traced ACK: the plain ACK followed by the trace echo
# (trace_id u64, t_recv f64, t_admit f64) — only on frames that
# carried trace context; the two sizes disambiguate on the wire
_ACK_TRACE = struct.Struct(">Qdd")
ACK_TRACED_SIZE = ACK_SIZE + _ACK_TRACE.size

# row-frame payload kinds
_ROWS_WIDE = 1  # [n, cols] u32 header rows
_ROWS_PACKED = 2  # [n, 4] u32 packed rows + (ep, dirn) stream scalars
# traced variants: same layout with a trace-context block
# (trace_id u64, t_enqueue f64, t_forward f64) between the fixed
# header and the rows (ISSUE 14 cross-process trace stitching)
_ROWS_WIDE_TRACED = 3
_ROWS_PACKED_TRACED = 4
# sequenced variants (ISSUE 17, the pipelined channel): a u64
# sequence number between the fixed header and the (optional) trace
# block.  Sequence numbers are per-channel monotonic starting at 1;
# the worker acks them CUMULATIVELY (pack_cum_ack below).
_ROWS_WIDE_SEQ = 5
_ROWS_PACKED_SEQ = 6
_ROWS_WIDE_TRACED_SEQ = 7
_ROWS_PACKED_TRACED_SEQ = 8
_SEQ_KINDS = (_ROWS_WIDE_SEQ, _ROWS_PACKED_SEQ,
              _ROWS_WIDE_TRACED_SEQ, _ROWS_PACKED_TRACED_SEQ)
_TRACED_KINDS = (_ROWS_WIDE_TRACED, _ROWS_PACKED_TRACED,
                 _ROWS_WIDE_TRACED_SEQ, _ROWS_PACKED_TRACED_SEQ)
_PACKED_KINDS = (_ROWS_PACKED, _ROWS_PACKED_TRACED,
                 _ROWS_PACKED_SEQ, _ROWS_PACKED_TRACED_SEQ)
_ROWS_HDR = struct.Struct(">BIIII")  # kind, n, cols, ep, dirn
_TRACE_HDR = struct.Struct(">Qdd")  # trace_id, t_enq, t_fwd
_SEQ = struct.Struct(">Q")  # per-channel frame sequence number

# cumulative ACK (ISSUE 17): one frame acknowledging every sequenced
# frame up to ``seq``.  Leading kind byte + highest contiguous seq
# u64 + frames-covered u32, then admitted-row DELTA for the covered
# frames u64 and the running packet ledger (same four counters as the
# legacy ACK), then an echo count u32 and that many trace echoes.
# Minimum size 57 bytes — never collides with the legacy 36/60-byte
# per-frame ACK sizes, so both can share a channel during tests.
CUM_ACK_KIND = 0xC5
_CUM_ACK = struct.Struct(">BQIQQQQQ")
_ECHO_N = struct.Struct(">I")
CUM_ACK_MIN_SIZE = _CUM_ACK.size + _ECHO_N.size

# the encrypted channel's TYPED REJECT record (ISSUE 18): sent by the
# worker in place of an ack when a sealed data frame fails to open
# (decrypt failure, replay, stale epoch, injected crypto fault).  The
# worker cannot read the frame's transport sequence — the whole
# payload including the seq block is sealed — so the record carries
# the frame's ORDINAL instead: the 1-based count of data frames
# received on the channel.  TCP preserves order and count, so the
# parent's Nth send IS the worker's Nth receipt, and the parent maps
# ordinal -> (its transport seq, row count) to drop the exact frame
# from its send window and count its rows ``crypto_dropped`` — a
# rejected frame is flow-visible loss, never silent and never a
# worker crash.  13 bytes: never collides with the 36/60-byte
# per-frame acks or the >= 57-byte cumulative ack.
CRYPTO_REJECT_KIND = 0xC6
_CRYPTO_REJECT = struct.Struct(">BQI")
CRYPTO_REJECT_SIZE = _CRYPTO_REJECT.size
# coded reject reasons (the wire carries an index; unknown indices
# decode as "other" — forward compatibility over a mixed-version pair)
CRYPTO_REJECT_REASONS = ("auth", "replay", "epoch-old", "epoch-ahead",
                         "short", "magic", "fault", "other")


class FrameError(Exception):
    """Torn or oversized frame: the connection is unusable (the
    length stream lost sync) — callers close it."""


def shutdown_close(sock: Optional[socket.socket]) -> None:
    # thread-affinity: any
    """Close ``sock`` with shutdown-before-close (the PR 8 fix, one
    definition): a peer's reader blocked in ``recv()`` — or our own
    reader/acceptor pinned in the syscall — sees EOF immediately
    instead of hanging on a silently-dead fd."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class LineFramer:
    """Newline-delimited framing with partial-read reassembly (the
    kvstore wire).  ``feed(data)`` returns the complete lines the
    bytes finish; the tail stays buffered for the next read."""

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, data: bytes) -> List[bytes]:
        # thread-affinity: transport, any -- kvstore reader threads
        # and the cluster channels share this buffer type; each
        # instance is single-reader by construction
        self._buf += data
        if b"\n" not in self._buf:
            return []
        *lines, self._buf = self._buf.split(b"\n")
        return [ln for ln in lines if ln.strip()]

    @property
    def pending(self) -> int:
        return len(self._buf)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # thread-affinity: transport, any
    """Read exactly ``n`` bytes reassembling partial reads.  Returns
    ``None`` on EOF before the FIRST byte (clean close); raises
    :class:`FrameError` on EOF mid-buffer (a torn frame)."""
    chunks = []
    got = 0
    while got < n:
        data = sock.recv(min(n - got, 1 << 16))
        if not data:
            if got == 0:
                return None
            raise FrameError(
                f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(data)
        got += len(data)
    return b"".join(chunks)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    # thread-affinity: transport, any
    """One length-prefixed frame.  A single ``sendall`` so two
    senders interleaving frames need only their own serialization."""
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket,
               max_frame: int = MAX_FRAME) -> Optional[bytes]:
    # thread-affinity: transport, any
    """One frame: ``None`` on clean EOF at a frame boundary,
    :class:`FrameError` on a torn prefix/body or a declared length
    past ``max_frame``."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (length,) = _LEN.unpack(hdr)
    if length > max_frame:
        raise FrameError(
            f"frame of {length} bytes exceeds max_frame {max_frame}")
    if length == 0:
        return b""
    body = _recv_exact(sock, length)
    if body is None:
        raise FrameError("connection closed between prefix and body")
    return body


def send_json_frame(sock: socket.socket, obj: dict) -> None:
    # thread-affinity: any -- control channels only (any caller
    # holding the per-conn serialization lock); the hot row path
    # rides the binary encoders below
    # hot-path-ok: control-channel serialization, never a row frame
    send_frame(sock, json.dumps(obj).encode())


def recv_json_frame(sock: socket.socket,
                    max_frame: int = MAX_FRAME) -> Optional[dict]:
    # thread-affinity: any
    payload = recv_frame(sock, max_frame)
    if payload is None:
        return None
    try:
        return json.loads(payload)
    except ValueError as e:
        raise FrameError(f"control frame is not JSON: {e}") from None


# -- row batches -------------------------------------------------------
def encode_rows(rows: np.ndarray,
                packed_meta: Optional[Tuple[int, int]] = None,
                trace: Optional[Tuple[int, float, float]] = None,
                seq: Optional[int] = None) -> bytes:
    # thread-affinity: transport, router
    """Row batch -> frame payload.  ``packed_meta=(ep, dirn)`` marks
    ``rows`` as packed ``[n, 4]`` u32 (the 16 B/packet wire format —
    the stream scalars ride the header); otherwise wide
    ``[n, cols]`` u32.  ``trace=(trace_id, t_enq, t_fwd)`` makes the
    frame a TRACED one: the receiver stamps its own stages and
    echoes the trace id on the ack (cross-process span stitching).
    ``seq`` makes the frame a SEQUENCED one (the pipelined channel,
    ISSUE 17): the receiver acks it cumulatively instead of
    per-frame.  ``seq=None`` keeps the PR 13 wire byte-identical."""
    rows = np.ascontiguousarray(rows, dtype=np.uint32)
    if rows.ndim != 2:
        raise ValueError(f"rows must be 2-D, got shape {rows.shape}")
    if packed_meta is not None:
        ep, dirn = packed_meta
        if seq is not None:
            kind = (_ROWS_PACKED_TRACED_SEQ if trace is not None
                    else _ROWS_PACKED_SEQ)
        else:
            kind = (_ROWS_PACKED_TRACED if trace is not None
                    else _ROWS_PACKED)
    else:
        ep = dirn = 0
        if seq is not None:
            kind = (_ROWS_WIDE_TRACED_SEQ if trace is not None
                    else _ROWS_WIDE_SEQ)
        else:
            kind = (_ROWS_WIDE_TRACED if trace is not None
                    else _ROWS_WIDE)
    hdr = _ROWS_HDR.pack(kind, rows.shape[0], rows.shape[1],
                         int(ep), int(dirn))
    if seq is not None:
        hdr += _SEQ.pack(int(seq))
    if trace is not None:
        tid, t_enq, t_fwd = trace
        hdr += _TRACE_HDR.pack(int(tid), float(t_enq), float(t_fwd))
    return hdr + rows.tobytes()


def decode_rows_seq(payload: bytes) -> Tuple[
        np.ndarray, Optional[Tuple[int, int]],
        Optional[Tuple[int, float, float]], Optional[int]]:
    # thread-affinity: transport, any
    """Frame payload -> (rows, packed_meta or None, trace context or
    None, sequence number or None).  Raises :class:`FrameError` when
    the declared shape disagrees with the byte count (a torn or
    corrupted frame must not become a misshapen submit)."""
    if len(payload) < _ROWS_HDR.size:
        raise FrameError(
            f"row frame of {len(payload)} bytes is shorter than its "
            f"header ({_ROWS_HDR.size})")
    kind, n, cols, ep, dirn = _ROWS_HDR.unpack_from(payload)
    if kind not in (_ROWS_WIDE, _ROWS_PACKED,
                    _ROWS_WIDE_TRACED, _ROWS_PACKED_TRACED,
                    *_SEQ_KINDS):
        raise FrameError(f"unknown row-frame kind {kind}")
    off = _ROWS_HDR.size
    seq = None
    if kind in _SEQ_KINDS:
        if len(payload) < off + _SEQ.size:
            raise FrameError(
                "sequenced row frame is shorter than its seq block")
        (seq,) = _SEQ.unpack_from(payload, off)
        off += _SEQ.size
    trace = None
    if kind in _TRACED_KINDS:
        if len(payload) < off + _TRACE_HDR.size:
            raise FrameError(
                "traced row frame is shorter than its trace block")
        trace = _TRACE_HDR.unpack_from(payload, off)
        off += _TRACE_HDR.size
    want = n * cols * 4
    body = payload[off:]
    if len(body) != want:
        raise FrameError(
            f"row frame declares [{n}, {cols}] u32 ({want} bytes) "
            f"but carries {len(body)}")
    rows = np.frombuffer(body, dtype=np.uint32).reshape(n, cols)
    if kind in _PACKED_KINDS:
        if cols != 4:
            raise FrameError(
                f"packed row frame must be [n, 4], got [{n}, {cols}]")
        return rows, (ep, dirn), trace, seq
    return rows, None, trace, seq


def decode_rows_ex(payload: bytes) -> Tuple[
        np.ndarray, Optional[Tuple[int, int]],
        Optional[Tuple[int, float, float]]]:
    # thread-affinity: transport, any
    """The pre-pipelining three-tuple surface (rows, packed_meta or
    None, trace or None); sequenced frames decode fine — the seq is
    simply dropped."""
    rows, packed_meta, trace, _seq = decode_rows_seq(payload)
    return rows, packed_meta, trace


def decode_rows(payload: bytes
                ) -> Tuple[np.ndarray, Optional[Tuple[int, int]]]:
    # thread-affinity: transport, any
    """The pre-trace two-tuple surface (rows, packed_meta or None);
    traced frames decode fine — the context is simply dropped."""
    rows, packed_meta, _trace, _seq = decode_rows_seq(payload)
    return rows, packed_meta


# -- control-channel row encoding (CT snapshots/merges) ----------------
# One codec for BOTH ends of the control wire (parent process.py,
# worker nodehost.py): u32 rows as base64 + shape.  JSON-embedded by
# design — CT migration is control-plane work, not the row hot path.
def rows_to_b64(rows: np.ndarray) -> dict:
    # thread-affinity: any
    import base64

    rows = np.ascontiguousarray(rows, dtype=np.uint32)
    return {"b64": base64.b64encode(rows.tobytes()).decode("ascii"),
            "shape": list(rows.shape)}


def rows_from_b64(obj: dict) -> np.ndarray:
    # thread-affinity: any
    import base64

    raw = base64.b64decode(obj["b64"].encode("ascii"))
    return np.frombuffer(raw, dtype=np.uint32).reshape(obj["shape"])


# -- the data-channel ACK ----------------------------------------------
def pack_ack(admitted: int, submitted: int, verdicts: int,
             shed: int, recovery_dropped: int,
             trace: Optional[Tuple[int, float, float]] = None
             ) -> bytes:
    # thread-affinity: transport
    """ACK for one row frame: how many rows the node ADMITTED, plus
    its running packet-ledger counters as of the ack.  The parent
    retains the newest ack per node; a SIGKILLed worker's final word
    is its last ack, which is exactly what lets the cluster ledger
    close over the corpse (``cluster/process.py``).
    ``trace=(trace_id, t_recv, t_admit)`` echoes a traced frame's
    worker-side stage stamps (span stitching)."""
    body = _ACK.pack(int(admitted), int(submitted), int(verdicts),
                     int(shed), int(recovery_dropped))
    if trace is not None:
        tid, t_recv, t_admit = trace
        body += _ACK_TRACE.pack(int(tid), float(t_recv),
                                float(t_admit))
    return body


def unpack_ack_ex(payload: bytes) -> Tuple[
        Tuple[int, int, int, int, int],
        Optional[Tuple[int, float, float]]]:
    # thread-affinity: transport, router
    """ACK payload -> (ledger 5-tuple, trace echo or None)."""
    if len(payload) == _ACK.size:
        return _ACK.unpack(payload), None
    if len(payload) == ACK_TRACED_SIZE:
        return (_ACK.unpack_from(payload),
                _ACK_TRACE.unpack_from(payload, _ACK.size))
    raise FrameError(
        f"ack frame is {len(payload)} bytes, want {_ACK.size} "
        f"or {ACK_TRACED_SIZE}")


def unpack_ack(payload: bytes) -> Tuple[int, int, int, int, int]:
    # thread-affinity: transport, router
    """The pre-trace five-tuple surface (trace echo dropped)."""
    ledger, _trace = unpack_ack_ex(payload)
    return ledger


# -- the cumulative ACK + send window (ISSUE 17) -----------------------
def pack_cum_ack(seq: int, frames: int, admitted: int,
                 submitted: int, verdicts: int, shed: int,
                 recovery_dropped: int,
                 echoes: Tuple[Tuple[int, float, float], ...] = ()
                 ) -> bytes:
    # thread-affinity: transport, ackflush -- the worker's data
    # thread packs acks at the cadence boundary; the flush-on-idle
    # timer packs the quiet-tail ack
    """One CUMULATIVE ack: every sequenced frame up to ``seq`` (the
    highest contiguous sequence admitted) is acknowledged at once.
    ``frames`` is how many frames this ack covers (since the previous
    ack), ``admitted`` the admitted-row delta across them, and the
    four ledger counters are the node's RUNNING packet ledger as of
    the last covered admit — the same final-word contract the
    per-frame ack carries, so a SIGKILLed worker's last cumulative
    ack still closes the cluster ledger exactly.  ``echoes`` is the
    per-frame trace echo list ``(trace_id, t_recv, t_admit)`` for
    any traced frames the ack covers (span stitching keeps working
    through coalescing)."""
    body = _CUM_ACK.pack(CUM_ACK_KIND, int(seq), int(frames),
                         int(admitted), int(submitted), int(verdicts),
                         int(shed), int(recovery_dropped))
    body += _ECHO_N.pack(len(echoes))
    for tid, t_recv, t_admit in echoes:
        body += _ACK_TRACE.pack(int(tid), float(t_recv),
                                float(t_admit))
    return body


def unpack_cum_ack(payload: bytes) -> Tuple[
        Tuple[int, int, int, int, int, int, int],
        List[Tuple[int, float, float]]]:
    # thread-affinity: transport, router
    """Cumulative-ack payload -> ((seq, frames, admitted, submitted,
    verdicts, shed, recovery_dropped), echo list)."""
    if len(payload) < CUM_ACK_MIN_SIZE:
        raise FrameError(
            f"cumulative ack is {len(payload)} bytes, want >= "
            f"{CUM_ACK_MIN_SIZE}")
    kind = payload[0]
    if kind != CUM_ACK_KIND:
        raise FrameError(f"cumulative ack kind {kind:#x}, want "
                         f"{CUM_ACK_KIND:#x}")
    hdr = _CUM_ACK.unpack_from(payload)
    (n_echo,) = _ECHO_N.unpack_from(payload, _CUM_ACK.size)
    off = _CUM_ACK.size + _ECHO_N.size
    want = off + n_echo * _ACK_TRACE.size
    if len(payload) != want:
        raise FrameError(
            f"cumulative ack declares {n_echo} echoes ({want} bytes) "
            f"but carries {len(payload)}")
    echoes = []
    for _ in range(n_echo):
        echoes.append(_ACK_TRACE.unpack_from(payload, off))
        off += _ACK_TRACE.size
    return hdr[1:], echoes


# -- the typed crypto-reject record (ISSUE 18) -------------------------
def pack_crypto_reject(ordinal: int, reason: str) -> bytes:
    # thread-affinity: transport
    """The worker's word for ONE undecryptable data frame: its
    ordinal (Nth data frame received on this channel) and the coded
    reject reason.  Travels sealed like any other ack."""
    try:
        code = CRYPTO_REJECT_REASONS.index(reason)
    except ValueError:
        code = CRYPTO_REJECT_REASONS.index("other")
    return _CRYPTO_REJECT.pack(CRYPTO_REJECT_KIND, int(ordinal), code)


def is_crypto_reject(payload: bytes) -> bool:
    # thread-affinity: transport, router, api -- api only via the
    # quiesced inject_replay test hook
    return (len(payload) == CRYPTO_REJECT_SIZE
            and payload[0] == CRYPTO_REJECT_KIND)


def unpack_crypto_reject(payload: bytes) -> Tuple[int, str]:
    # thread-affinity: transport, router, api -- api only via the
    # quiesced inject_replay test hook
    """Reject payload -> (frame ordinal, reason string)."""
    if not is_crypto_reject(payload):
        raise FrameError(
            f"crypto-reject record is {len(payload)} bytes / kind "
            f"{payload[0] if payload else None}, want "
            f"{CRYPTO_REJECT_SIZE} / {CRYPTO_REJECT_KIND:#x}")
    _kind, ordinal, code = _CRYPTO_REJECT.unpack(payload)
    if code >= len(CRYPTO_REJECT_REASONS):
        return ordinal, "other"
    return ordinal, CRYPTO_REJECT_REASONS[code]


class SendWindow:
    """Sender-side bookkeeping for the pipelined channel: the frames
    in flight between send and cumulative ack, in sequence order,
    RETAINED WITH THEIR ROWS — a dead channel's unacked frames are
    either requeued to the failover peer or counted ``crash_dropped``
    (cluster/process.py), never silently lost.

    Pure bookkeeping: callers (ProcessNode) hold their own lock; each
    instance is single-writer by construction."""

    __slots__ = ("window", "entries", "next_seq", "inflight_rows")

    def __init__(self, window: int) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        # (seq, rows, t_enq, ctx) in ascending seq order
        self.entries: List[tuple] = []
        self.next_seq = 1
        self.inflight_rows = 0

    @property
    def full(self) -> bool:
        return len(self.entries) >= self.window

    @property
    def inflight_frames(self) -> int:
        return len(self.entries)

    def add(self, rows, t_enq: float, ctx=None) -> int:
        # thread-affinity: router -- the forwarder registers the
        # frame it is about to send
        seq = self.next_seq
        self.next_seq += 1
        self.entries.append((seq, rows, t_enq, ctx))
        self.inflight_rows += len(rows)
        return seq

    def retire(self, up_to: int) -> List[tuple]:
        # thread-affinity: transport -- the ack reader retires the
        # contiguous prefix a cumulative ack covers
        out = []
        while self.entries and self.entries[0][0] <= up_to:
            ent = self.entries.pop(0)
            self.inflight_rows -= len(ent[1])
            out.append(ent)
        return out

    def pop(self, seq: int) -> Optional[tuple]:
        # thread-affinity: router, transport -- unregister one frame
        # and hand its entry back: the send-failure unwind (drop) and
        # the crypto-reject path (ISSUE 18 — the rejected frame's
        # rows are counted ``crypto_dropped`` from the entry) share
        # this removal
        for i, ent in enumerate(self.entries):
            if ent[0] == seq:
                self.inflight_rows -= len(ent[1])
                del self.entries[i]
                return ent
        return None

    def drop(self, seq: int) -> bool:
        # thread-affinity: router -- a frame whose SEND failed never
        # reached the worker: unregister it so the forwarder's
        # requeue-on-error does not double-count its rows
        return self.pop(seq) is not None

    def take_all(self) -> List[tuple]:
        # thread-affinity: any -- crash/teardown: every sent-but-
        # unacked frame, for requeue or counted loss
        out, self.entries = self.entries, []
        self.inflight_rows = 0
        return out
