"""Parent-side handles for process-per-node cluster serving: spawn,
channel brokerage, and the ``ProcessNode`` replica interface.

``ProcessNode`` presents the same duck-typed surface as the
in-process ``ClusterNode`` (``.name`` / ``.alive`` / ``.submit`` /
``.probe`` / ``.crash`` plus the node-interface methods the failover
and scale-out orchestrators call), so ``ClusterRouter`` /
``ClusterMembership`` / ``FailoverOrchestrator`` run UNCHANGED over
real worker processes — the composition proof the kvstore transport
already made for the identity plane.

Crash accounting (the piece SIGKILL makes hard): every data-channel
frame is acked with the worker's running packet ledger, and the
parent retains the newest ack.  A SIGKILLed worker's last ack is its
final word: ``final`` snapshots the acked counters, and the delta
between the acked ``submitted`` and the acked accounted counters
(verdicts + shed + recovery_dropped) — the rows the worker had
admitted but not yet resolved — is handed to
``router.account_crash_loss`` as ``crash_dropped``.  Rows in frames
the worker never acked are still the forwarder's (requeued on the
send/ack error, migrated or counted by failover), so::

    submitted == per-node accounted + router_overflow
                 + failover_dropped + crash_dropped

stays EXACT over a corpse.  (Between the last ack and the kill the
worker may have resolved a few more rows; the ledger attributes them
to ``crash_dropped`` instead of ``verdicts`` — loss is never
under-counted, which is the contract.)

PIPELINED MODE (ISSUE 17): with ``cluster_forward_window > 1`` the
router enables a SEND WINDOW on each process node — ``submit``
returns after the sequenced frame is on the wire (blocking only
while the window is full: credit backpressure), and a dedicated
ACK-READER thread retires in-flight frames as the worker's
CUMULATIVE acks arrive, returning credit to the forwarder through
the router's ``on_ack`` callback.  The crash contract is unchanged
because it never depended on synchrony: the last cumulative ack's
ledger covers exactly the frames the window has retired, and every
sent-but-unacked frame is retained WITH ITS ROWS in the window —
on channel death the ack reader hands them back to the router
(``on_broken``), where they are requeued for the failover peer or
counted, so the identity above closes at ANY kill point inside an
open window.  Window=1 keeps the PR 13 sync path byte-identical.
"""

from __future__ import annotations

import collections
import dataclasses
import secrets
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..encryption import DecryptError
from ..infra.faults import InjectedFault
from ..serving import ServingError
from .nodehost import OP_TIMEOUTS
from .transport import (SendWindow, encode_rows, is_crypto_reject,
                        recv_frame, recv_json_frame, rows_from_b64,
                        rows_to_b64, send_frame, send_json_frame,
                        shutdown_close, unpack_ack_ex,
                        unpack_crypto_reject, unpack_cum_ack)

__all__ = ["ProcessNode", "ProcessNodeSpawner", "spawn_available",
           "CRYPTO_DESYNC_THRESHOLD"]

# ENCRYPTED MODE (ISSUE 18): consecutive parent-side ack/NACK open
# failures in the KEY-MISMATCH class before the channel is declared
# desynced (crypto-desync incident + channel break -> the router's
# requeue/failover path).  The class is {"auth", "magic"} — wrong
# session keys fail AEAD verification on every frame, while rotation
# races surface as epoch-* rejects and injected faults as "fault",
# neither of which means the peer holds the wrong key.
CRYPTO_DESYNC_THRESHOLD = 3
_DESYNC_REASONS = frozenset({"auth", "magic"})

# one RPC may legitimately take this long (a worker's first RPC waits
# out its whole jax+daemon bring-up)
READY_TIMEOUT_S = 300.0
# the fallback bound for an op missing from nodehost.OP_TIMEOUTS —
# CTA011 keeps that table total, so this only covers test fakes
CTRL_TIMEOUT_S = 60.0


def spawn_available() -> bool:
    """Process mode needs the ``spawn`` start method (fork would
    duplicate the parent's jax runtime state into the child — the
    classic fork-after-init trap).  Tests skip cleanly when the
    platform lacks it."""
    try:
        import multiprocessing as mp

        return "spawn" in mp.get_all_start_methods()
    except Exception:  # noqa: BLE001 — no multiprocessing at all
        return False


class ProcessNodeSpawner:
    """Owns the cluster's rendezvous listener and spawns workers.

    One listener serves every node: each worker dials back twice
    (control + data) introducing itself with a hello frame carrying
    the cluster token (a secret minted per ``ClusterServing`` — a
    stray dialer on the loopback port cannot join the cluster)."""

    def __init__(self):
        self.token = secrets.token_hex(16)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET,
                              socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()

    def spawn(self, name: str, config, kv_addr,
              parent_pub: Optional[str] = None,
              epoch: int = 0) -> "ProcessNode":
        """Launch one worker process (daemon bring-up runs in the
        child; :meth:`ProcessNode.wait_ready` blocks on it).
        ``parent_pub`` (hex) arms the encrypted data channel: the
        worker mints its own X25519 keypair, advertises the pubkey in
        its hello frames, and seals/opens every data-channel frame;
        ``epoch`` is the cluster's CURRENT key epoch so a scale-out
        worker joins mid-rotation-history at the right keys."""
        import multiprocessing as mp

        from .nodehost import node_host_main

        # the worker's daemon must self-identify as ITS node (thread
        # mode does the same via dataclasses.replace): the flight
        # recorder stamps bundles with it, and a cluster sysdump
        # where every worker claims to be node0 is unusable
        cfg_fields = dataclasses.asdict(
            dataclasses.replace(config, node_name=name))
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=node_host_main,
            args=(self.host, self.port, self.token, name,
                  cfg_fields, tuple(kv_addr), parent_pub,
                  int(epoch)),
            daemon=True, name=f"cluster-node-{name}")
        proc.start()
        return ProcessNode(name, proc, self)

    def accept_channels(self, name: str, timeout: float = 60.0
                        ) -> Tuple[socket.socket, socket.socket,
                                   socket.socket, Optional[str]]:
        """Accept until all three of ``name``'s channels arrived
        (workers race; hellos disambiguate).  Returns the sockets
        plus the worker's advertised X25519 pubkey (hex, or None for
        a plaintext worker) — the spawn-handshake half of the
        encrypted-channel key exchange (ISSUE 18)."""
        got: Dict[str, socket.socket] = {}
        pubkey: Optional[str] = None
        deadline = time.monotonic() + timeout
        while not {"ctrl", "data", "obs"} <= set(got):
            self._sock.settimeout(max(deadline - time.monotonic(),
                                      0.01))
            try:
                sock, _addr = self._sock.accept()
            except socket.timeout:
                raise ServingError(
                    f"worker {name} never dialed home") from None
            sock.settimeout(30.0)
            try:
                hello = recv_json_frame(sock)
            except Exception:  # noqa: BLE001 — garbage dialer
                shutdown_close(sock)
                continue
            if (not hello or hello.get("token") != self.token
                    or hello.get("node") != name
                    or hello.get("role") not in ("ctrl", "data",
                                                 "obs")):
                shutdown_close(sock)
                continue
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            got[hello["role"]] = sock
            if hello.get("pubkey"):
                pubkey = hello["pubkey"]
        return got["ctrl"], got["data"], got["obs"], pubkey

    def close(self) -> None:
        shutdown_close(self._sock)


class ProcessNode:
    """One worker-process replica behind the ClusterNode interface.

    Control RPCs are strict request/response, serialized by
    ``_ctrl_lock`` (a timed-out call marks the channel broken — the
    byte stream has lost sync — and every later call fails fast,
    which is what turns a wedged worker into probe failures and so
    into membership death).  The data channel belongs to this node's
    router forwarder thread alone."""

    # guarded-by: _lock: alive, final, _ct_snap_rows, _last_ack,
    # guarded-by: _lock: _crash_loss_pending, _frames, _bytes,
    # guarded-by: _lock: _frames_packed, _acks, _acks_coalesced
    # guarded-by: _lock: _crypto_nacks, _crypto_replays,
    # guarded-by: _lock: _crypto_open_failures, _open_fail_run
    # guarded-by: _win_cv: _win, _win_broken, _window_stalls,
    # guarded-by: _win_cv: _ord_sent, _ord_map

    def __init__(self, name: str, proc, spawner: ProcessNodeSpawner):
        self.idx = -1  # assigned by ClusterServing
        self.name = name
        self.proc = proc
        self._spawner = spawner
        self._lock = threading.Lock()
        self._ctrl_lock = threading.Lock()
        # the OBS channel gets its own socket + lock + broken flag:
        # a slow/timed-out scrape desyncs (and so breaks) only the
        # obs stream — membership probes ride ctrl untouched, so
        # observability can never get a healthy node declared dead
        self._obs_lock = threading.Lock()
        self._ctrl: Optional[socket.socket] = None
        self._data: Optional[socket.socket] = None
        self._obs: Optional[socket.socket] = None
        self._ctrl_broken: Optional[str] = None
        self._obs_broken: Optional[str] = None
        self.alive = True
        self.final: Optional[dict] = None
        self.kv_client = None  # the worker owns its kv client
        self.policy_sync = None  # likewise (polled over control)
        self._ct_snap_rows: Optional[np.ndarray] = None
        # (submitted, verdicts, shed, recovery_dropped) at last ack
        self._last_ack: Tuple[int, int, int, int] = (0, 0, 0, 0)
        self._crash_loss_pending = 0
        self._frames = 0
        self._frames_packed = 0
        self._bytes = 0
        # -- pipelined mode (ISSUE 17): send window + ack reader
        self._win: Optional[SendWindow] = None
        self._win_cv = threading.Condition()
        self._win_broken: Optional[str] = None
        self._window_stalls = 0
        self._acks = 0
        self._acks_coalesced = 0
        self._on_ack = None
        self._on_broken = None
        self._ack_thread: Optional[threading.Thread] = None
        # -- encrypted mode (ISSUE 18): parent half of the sealed
        # data channel.  peer_pub_hex arrives with the spawn
        # handshake; enable_crypto builds the channel before any
        # frame flows.
        self.peer_pub_hex: Optional[str] = None
        self._crypto = None  # encryption.EncryptedChannel
        self._crypto_grace_s = 0.0
        self._on_reject = None  # router's crypto-drop accounting
        self._crypto_nacks = 0  # worker-side rejects (NACK records)
        self._crypto_replays = 0  # NACKs with reason "replay"
        self._crypto_open_failures = 0  # parent-side open failures
        self._open_fail_run = 0  # consecutive key-mismatch failures
        self._ord_sent = 0  # sealed data frames sent (NACK ordinals)
        # ordinal -> window seq for sealed windowed frames; the
        # worker cannot read a rejected frame's seq (it is inside the
        # sealed payload), so its NACK carries the frame's receipt
        # ORDINAL instead — TCP ordering makes the parent's Nth send
        # the worker's Nth receipt, and this map turns the ordinal
        # back into the window entry whose rows the reject dropped
        self._ord_map: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()
        # replay test hook + wire-identity probe (the exact bytes of
        # the most recent data frame as they left for the socket)
        self._last_wire: Optional[bytes] = None

    # -- bring-up ------------------------------------------------------
    def attach(self, timeout: float = 60.0) -> None:
        (self._ctrl, self._data, self._obs,
         self.peer_pub_hex) = self._spawner.accept_channels(
            self.name, timeout)

    # -- encrypted mode (ISSUE 18) -------------------------------------
    def enable_crypto(self, keypair, peer_pub: bytes,
                      grace_s: float = 0.0, epoch: int = 0) -> None:
        # thread-affinity: api -- ClusterServing._build_node, before
        # any data frame flows on the channel
        """Arm the parent half of the sealed data channel: every
        frame this node sends or receives on the data socket is one
        AEAD seal/open.  ``epoch`` > 0 joins the channel at the
        cluster's current key epoch (scale-out under rotation)."""
        from ..encryption import EncryptedChannel

        self._crypto = EncryptedChannel(keypair, peer_pub,
                                        epoch=int(epoch))
        self._crypto_grace_s = float(grace_s)

    def set_reject_cb(self, cb) -> None:
        # thread-affinity: api -- router.start, before frames flow.
        """``cb(n_rows, reason, ctx)`` per worker crypto-reject —
        the router's ``crypto_dropped`` ledger term."""
        self._on_reject = cb

    def rotate_channel(self, epoch: int,
                       grace_s: Optional[float] = None) -> None:
        # thread-affinity: api -- ClusterServing.rotate_epoch (the
        # channel's own lock serializes against in-flight seal/open)
        ch = self._crypto
        if ch is None:
            return
        ch.rotate(int(epoch), self._crypto_grace_s
                  if grace_s is None else float(grace_s))

    def rotate_epoch(self, epoch: int,
                     grace_s: Optional[float] = None) -> dict:
        """One node's leg of the cluster-wide key rotation, in the
        two-phase order that closes BOTH directions at every
        interleaving: (1) the parent PRE-INSTALLS the new epoch's
        receive key (``prepare_recv``) so an ack the worker seals
        at e+1 right after its own rotate — while this control call
        is still in flight — opens instead of rejecting
        ``epoch-ahead`` (a discarded cumulative ack that covered
        the whole send window would wedge the channel's credit);
        (2) the worker rotates, parking the old epoch in its grace
        window so the parent's in-flight e-sealed data frames still
        open, and acks over control; (3) the parent channel
        rotates, adopting the prepared replay window."""
        g = (self._crypto_grace_s if grace_s is None
             else float(grace_s))
        ch = self._crypto
        if ch is not None:
            ch.prepare_recv(int(epoch))
        resp = self.call("rotate_epoch", epoch=int(epoch), grace_s=g)
        self.rotate_channel(epoch, g)
        return resp

    def _note_open_failure(self, exc: Exception) -> bool:
        # thread-affinity: transport, api -- the data-channel reader
        # (forwarder in sync mode, ack reader in pipelined mode);
        # api only via the quiesced inject_replay test hook

        """Count one parent-side open failure; True when this one
        crossed the key-desync threshold (the caller breaks the
        channel — counted degradation, never a hang)."""
        reason = getattr(exc, "reason", "fault")
        with self._lock:
            self._crypto_open_failures += 1
            if reason in _DESYNC_REASONS:
                self._open_fail_run += 1
                run = self._open_fail_run
            else:
                run = 0
        if run == CRYPTO_DESYNC_THRESHOLD:
            from ..obs.flightrec import KIND_CRYPTO_DESYNC

            self.record_incident(KIND_CRYPTO_DESYNC, {
                "node": self.name, "consecutive-failures": run,
                "reason": reason})
            return True
        return False

    def _count_nack(self, reason: str) -> None:
        # thread-affinity: transport, api -- api only via the
        # quiesced inject_replay test hook
        with self._lock:
            self._crypto_nacks += 1
            if reason == "replay":
                self._crypto_replays += 1

    def _open_sync_ack(self, ack: bytes, n_rows: int, trace
                       ) -> Tuple[Optional[bytes], int]:
        # thread-affinity: transport -- the sync submit path
        """Open one sync-mode ack frame.  Returns ``(plaintext,
        0)`` when the caller should parse the ack, or ``(None,
        count)`` when the frame resolved the submit here: a worker
        crypto-reject (rows dropped and counted) or a parent-side
        open failure (counted; delivered-or-dropped decided by the
        failure class — see below)."""
        ch = self._crypto
        try:
            plain = ch.open(ack)
            with self._lock:
                self._open_fail_run = 0
        except (DecryptError, InjectedFault) as exc:
            if is_crypto_reject(ack):
                # RAW reject record: the worker's reject-seal leg
                # faulted and it shipped the record unauthenticated.
                # Accept it for LOSS ACCOUNTING only — a forged one
                # can reclassify loss, never admit traffic — because
                # dropping it here would leave the rejected frame's
                # rows in no counter at all (silent loss)
                plain = ack
            else:
                return self._account_sync_open_failure(exc, n_rows,
                                                       trace)
        if not is_crypto_reject(plain):
            return plain, 0
        _ordn, reason = unpack_crypto_reject(plain)
        self._count_nack(reason)
        cb = self._on_reject
        if cb is not None:
            cb(n_rows, reason, trace)
        return None, 0

    def _account_sync_open_failure(self, exc: Exception, n_rows: int,
                                   trace) -> Tuple[None, int]:
        # thread-affinity: transport -- _open_sync_ack's failure leg
        reason = getattr(exc, "reason", "fault")
        if self._note_open_failure(exc):
            with self._win_cv:
                if self._win_broken is None:
                    self._win_broken = "crypto-desync"
        if reason in _DESYNC_REASONS:
            # wrong keys are SYMMETRIC (both directions derive from
            # the same shared secret): the worker cannot have opened
            # our data frame either — this response is its NACK,
            # unreadable.  Count the rows dropped; sync mode's 1:1
            # frame:response keeps that exact.
            cb = self._on_reject
            if cb is not None:
                cb(n_rows, reason, trace)
            return None, 0
        # outside the key-mismatch class (an injected open fault, a
        # rotation-race epoch reject): the worker DID open and admit
        # the frame — its own counters own these rows.  Skip the
        # _last_ack update; acked ledgers are cumulative, so the
        # next readable ack repairs it.
        return None, n_rows

    def wait_ready(self, timeout: float = READY_TIMEOUT_S) -> None:
        self.call("ready", timeout=timeout)

    # -- control / obs RPC ---------------------------------------------
    def _rpc(self, channel: str, op: str,
             timeout: Optional[float], args: dict) -> dict:
        # thread-affinity: any -- the per-channel lock serializes
        # callers (control-plane threads and any-affine readers
        # alike); a broken channel fails every later call fast (the
        # byte stream lost sync)
        if timeout is None:
            # the per-op bound table (nodehost.OP_TIMEOUTS, CTA011-
            # enforced total): every control RPC is bounded even
            # when the caller states no deadline of its own
            timeout = OP_TIMEOUTS.get(op, CTRL_TIMEOUT_S)
        lock = self._obs_lock if channel == "obs" \
            else self._ctrl_lock
        broken_attr = ("_obs_broken" if channel == "obs"
                       else "_ctrl_broken")
        with lock:
            broken = getattr(self, broken_attr)
            if broken is not None:
                raise ServingError(
                    f"{channel} channel to {self.name} broken: "
                    f"{broken}")
            sock = self._obs if channel == "obs" else self._ctrl
            if sock is None:
                raise ServingError(
                    f"worker {self.name} not attached")
            req = dict(args)
            req["op"] = op
            try:
                sock.settimeout(timeout)
                send_json_frame(sock, req)
                resp = recv_json_frame(sock)
            except Exception as exc:  # noqa: BLE001 — timeout, EOF,
                # torn frame: the stream lost sync either way
                setattr(self, broken_attr,
                        f"{type(exc).__name__}: {exc}")
                raise ServingError(
                    f"{channel} call {op!r} to {self.name} failed: "
                    f"{getattr(self, broken_attr)}") from None
            if resp is None:
                setattr(self, broken_attr, "EOF")
                raise ServingError(
                    f"worker {self.name} hung up mid-call ({op})")
            if "e" in resp:
                raise ServingError(
                    f"worker {self.name} {op} error: {resp['e']}")
            return resp

    def call(self, op: str, timeout: Optional[float] = None,
             **args) -> dict:
        return self._rpc("ctrl", op, timeout, args)

    def obs_call(self, op: str, timeout: Optional[float] = None,
                 **args) -> dict:
        """Observability RPC on the DEDICATED obs channel: a scrape
        that times out breaks only this stream — probes and failover
        control keep their own (ISSUE 14 review hardening)."""
        return self._rpc("obs", op, timeout, args)

    # -- the ClusterNode interface ------------------------------------
    def submit(self, rows: np.ndarray, trace=None,
               t_enq: Optional[float] = None) -> int:
        # (unannotated on purpose: inherits the router forwarder's
        # affinity, like ClusterNode.submit — the socket leg is the
        # transport domain's territory via the framing helpers)
        """Forward one chunk over the data channel.  SYNC mode (no
        window enabled — the PR 13 protocol, byte-identical): send
        one unsequenced frame, block for its per-frame ack.
        PIPELINED mode (``enable_window`` called — ISSUE 17): block
        only while the send window is FULL (credit backpressure),
        then send a sequenced frame and return; the ack reader
        retires it when the worker's cumulative ack arrives.  In
        both modes the per-node forwarder is the only caller.  Packs
        eligible single-stream chunks to the 16 B/packet wire.
        ``trace`` (an ``obs.relay.TraceCtx`` with t_enq/t_fwd
        stamped) rides the frame; the worker's recv/admit stamps
        come back on the (possibly coalesced) ack echo (ISSUE 14
        cross-process span stitching)."""
        from ..core.packets import pack_eligibility, pack_rows

        sock = self._data
        if sock is None:
            raise ServingError(f"worker {self.name} not attached")
        with self._win_cv:
            win = self._win
            if self._win_broken is not None:
                # a desynced (or otherwise dead) channel fails every
                # submit fast — the forwarder's requeue owns the rows
                raise ServingError(
                    f"data channel to {self.name} broken: "
                    f"{self._win_broken}")
        ch = self._crypto
        wire_trace = ((trace.trace_id, trace.t_enq, trace.t_fwd)
                      if trace is not None else None)
        ok, ep, dirn = pack_eligibility(rows)
        wire_rows = pack_rows(rows) if ok else rows
        meta = (ep, dirn) if ok else None
        if win is None:
            payload = encode_rows(wire_rows, packed_meta=meta,
                                  trace=wire_trace)
            if ch is not None:
                try:
                    payload = ch.seal(payload)
                except InjectedFault as exc:
                    # the frame never reached the wire: the
                    # forwarder's requeue-on-error owns these rows
                    raise ServingError(
                        f"seal to {self.name} failed: "
                        f"{exc}") from None
            send_frame(sock, payload)
            if ch is not None:
                with self._win_cv:
                    self._ord_sent += 1
            self._last_wire = payload
            ack = recv_frame(sock)
            if ack is None:
                raise ServingError(
                    f"worker {self.name} closed the data channel")
            if ch is not None:
                ack, shortcut = self._open_sync_ack(ack, len(rows),
                                                    trace)
                if ack is None:
                    return shortcut
            (admitted, sub, ver, shed, rec), echo = unpack_ack_ex(ack)
            if trace is not None and echo is not None \
                    and echo[0] == trace.trace_id:
                trace.t_recv, trace.t_admit = echo[1], echo[2]
            with self._lock:
                self._last_ack = (sub, ver, shed, rec)
                self._frames += 1
                self._frames_packed += 1 if ok else 0
                self._bytes += len(payload)
            return admitted
        # pipelined: wait for credit, register, send, return.  The
        # entry registers BEFORE the send so a cumulative ack racing
        # the sendall's return can never arrive for a frame the
        # window does not know; a FAILED send unregisters it (the
        # frame never reached the worker — the forwarder's requeue
        # owns those rows alone).
        with self._win_cv:
            if win.full:
                self._window_stalls += 1
                while win.full and self._win_broken is None:
                    self._win_cv.wait(0.5)
            if self._win_broken is not None:
                raise ServingError(
                    f"data channel to {self.name} broken: "
                    f"{self._win_broken}")
            seq = win.add(rows, t_enq if t_enq is not None
                          else time.monotonic(), trace)
        payload = encode_rows(wire_rows, packed_meta=meta,
                              trace=wire_trace, seq=seq)
        ordn = None
        if ch is not None:
            try:
                payload = ch.seal(payload)
            except InjectedFault as exc:
                # never reached the wire: unwind the window entry
                # and let the forwarder's requeue own the rows
                with self._win_cv:
                    win.drop(seq)
                    self._win_cv.notify_all()
                raise ServingError(
                    f"seal to {self.name} failed: {exc}") from None
            # register BEFORE the send (like win.add): a NACK racing
            # the sendall's return must find its ordinal mapped
            with self._win_cv:
                self._ord_sent += 1
                ordn = self._ord_sent
                self._ord_map[ordn] = seq
        self._last_wire = payload
        try:
            send_frame(sock, payload)
        except Exception as exc:  # noqa: BLE001 — dead fd mid-send
            with self._win_cv:
                win.drop(seq)
                if ordn is not None:
                    self._ord_map.pop(ordn, None)
                self._win_cv.notify_all()
            raise ServingError(
                f"send to {self.name} failed: "
                f"{type(exc).__name__}: {exc}") from None
        with self._lock:
            self._frames += 1
            self._frames_packed += 1 if ok else 0
            self._bytes += len(payload)
        return len(rows)

    # -- pipelined mode (ISSUE 17) -------------------------------------
    def enable_window(self, window: int, on_ack=None,
                      on_broken=None) -> None:
        # thread-affinity: api -- router.start / router.add_node,
        # before any frame flows on the channel
        """Switch the data channel to pipelined mode: a send window
        of ``window`` frames and a dedicated ack-reader thread.
        ``on_ack(entries)`` fires with the retired
        ``(n_rows, t_enq, ctx)`` list per cumulative ack (the
        router's credit return + latency/span accounting);
        ``on_broken(entries)`` fires ONCE with every sent-but-unacked
        ``(rows, t_enq, ctx)`` when the channel dies (the router
        requeues them for failover)."""
        if window < 2:
            return  # window 1 IS the sync protocol; keep it exact
        with self._win_cv:
            if self._win is not None:
                return
            self._win = SendWindow(window)
            self._on_ack = on_ack
            self._on_broken = on_broken
        self._ack_thread = threading.Thread(
            target=self._ack_read_loop, daemon=True,
            name=f"cluster-ack-{self.name}")
        self._ack_thread.start()

    def _ack_read_loop(self) -> None:
        # thread-affinity: transport -- the parent's half of the
        # coalesced-ack channel: recv, retire, return credit.  On
        # ANY exit every in-flight frame is handed back to the
        # router exactly once (requeue or counted loss — never
        # silent).
        sock = self._data
        with self._win_cv:
            win = self._win
        ch = self._crypto
        try:
            while True:
                payload = recv_frame(sock)
                if payload is None:
                    break
                if ch is not None:
                    raw = payload
                    try:
                        payload = ch.open(payload)
                        with self._lock:
                            self._open_fail_run = 0
                    except (DecryptError, InjectedFault) as exc:
                        if is_crypto_reject(raw):
                            # RAW reject fallback (the worker's
                            # reject-seal leg faulted): accept it for
                            # loss accounting only — see
                            # _open_sync_ack — else the rejected
                            # frame's rows land in no counter
                            payload = raw
                        elif self._note_open_failure(exc):
                            # key desync: break the channel so the
                            # finally's take_all hands every
                            # in-flight frame back to the router
                            # (requeued and counted — never silent,
                            # never a hang)
                            with self._win_cv:
                                if self._win_broken is None:
                                    self._win_broken = "crypto-desync"
                            break
                        else:
                            continue
                    if is_crypto_reject(payload):
                        # the worker could not open our Nth data
                        # frame: pop exactly that window entry — its
                        # rows are a counted, flow-visible drop, NOT
                        # a requeue (the frame reached the worker)
                        ordn, reason = unpack_crypto_reject(payload)
                        with self._win_cv:
                            seq = self._ord_map.pop(ordn, None)
                            ent = (win.pop(seq) if seq is not None
                                   else None)
                            self._win_cv.notify_all()
                        self._count_nack(reason)
                        cb = self._on_reject
                        if cb is not None:
                            cb(len(ent[1]) if ent is not None else 0,
                               reason,
                               ent[3] if ent is not None else None)
                        continue
                (seq, frames, _admitted, sub, ver, shed,
                 rec), echoes = unpack_cum_ack(payload)
                with self._win_cv:
                    entries = win.retire(seq)
                    # ordinals the cumulative ack covered can never
                    # be NACKed again — prune the map from the front
                    # (insertion order == seq order)
                    while self._ord_map and next(iter(
                            self._ord_map.values())) <= seq:
                        self._ord_map.popitem(last=False)
                    self._win_cv.notify_all()
                with self._lock:
                    self._last_ack = (sub, ver, shed, rec)
                    self._acks += 1
                    self._acks_coalesced += max(int(frames) - 1, 0)
                if echoes:
                    by_tid = {e[0]: e for e in echoes}
                    for _s, _rows, _t_enq, ctx in entries:
                        if ctx is not None:
                            e = by_tid.get(ctx.trace_id)
                            if e is not None:
                                ctx.t_recv, ctx.t_admit = e[1], e[2]
                cb = self._on_ack
                if cb is not None and entries:
                    cb([(len(r), t_enq, ctx)
                        for _s, r, t_enq, ctx in entries])
        except Exception:  # noqa: BLE001 — torn frame/dead fd: the
            pass  # channel contract is dead; the finally owns the
            # in-flight hand-back
        finally:
            with self._win_cv:
                if self._win_broken is None:
                    self._win_broken = "data channel closed"
                entries = win.take_all()
                self._win_cv.notify_all()
            cb = self._on_broken
            if cb is not None and entries:
                cb([(r, t_enq, ctx)
                    for _s, r, t_enq, ctx in entries])

    def drain_window(self, timeout: float = 30.0) -> bool:
        # thread-affinity: api
        """Block until every in-flight frame is acked (True) or the
        channel broke / ``timeout`` ran out (False when frames were
        still pending).  The quiesce primitive for stop/scale-in:
        "drained" now means the WINDOW is empty, not just the
        queues."""
        deadline = time.monotonic() + timeout
        with self._win_cv:
            win = self._win
            if win is None:
                return True
            while win.inflight_frames and self._win_broken is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._win_cv.wait(min(left, 0.1))
            return win.inflight_frames == 0

    def window_inflight(self) -> Tuple[int, int]:
        # thread-affinity: any
        """(frames, rows) currently sent-but-unacked."""
        with self._win_cv:
            win = self._win
            if win is None:
                return (0, 0)
            return (win.inflight_frames, win.inflight_rows)

    def inject_replay(self) -> bool:
        # thread-affinity: api -- TEST HOOK (chaos gate): call only
        # on a quiesced channel (no forwarder traffic in flight)
        """Re-send the last sealed data frame VERBATIM — the
        replay-attack injection.  The worker's per-epoch replay
        window must reject it (counted, NACKed, zero rows dropped —
        the original already resolved).  True when the replay was
        rejected as a replay."""
        wire = self._last_wire
        sock = self._data
        if wire is None or self._crypto is None or sock is None:
            return False
        send_frame(sock, wire)
        with self._win_cv:
            self._ord_sent += 1
            win = self._win
        if win is not None:
            return True  # the ack reader counts the NACK
        # sync protocol: consume the reject reply in-line
        resp = recv_frame(sock)
        if resp is None:
            return False
        try:
            resp = self._crypto.open(resp)
        except (DecryptError, InjectedFault) as exc:
            self._note_open_failure(exc)
            return False
        if not is_crypto_reject(resp):
            return False
        _ordn, reason = unpack_crypto_reject(resp)
        self._count_nack(reason)
        cb = self._on_reject
        if cb is not None:
            cb(0, reason, None)
        return reason == "replay"

    def ack_flush(self) -> Optional[dict]:
        # thread-affinity: api
        """Ask the worker's coalescer to flush NOW (collapses the
        flush-timer tail out of a drain) and return its counters."""
        try:
            return self.call("ack_flush", timeout=10.0)
        except ServingError:
            return None

    def probe(self) -> bool:
        # thread-affinity: api
        """Liveness over the control channel: the worker process is
        running AND its drain loop answers.  A control timeout (a
        wedged worker) reads as dead — which is the point."""
        with self._lock:
            if not self.alive:
                return False
        if not self.proc.is_alive():
            return False
        try:
            return bool(self.call("probe", timeout=5.0)["ok"])
        except ServingError:
            return False

    def crash(self, cause: str) -> None:
        # thread-affinity: api
        """Real node death: SIGKILL the worker (no goodbye, no final
        snapshot — the honest failure mode).  ``final`` becomes the
        last ack's ledger; the admitted-but-unresolved delta parks in
        ``_crash_loss_pending`` for the failover path to hand to
        ``router.account_crash_loss``.  Closing the sockets wakes a
        forwarder blocked in the ack wait (shutdown-before-close),
        whose requeue-on-error path keeps its in-flight chunk
        counted."""
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            sub, ver, shed, rec = self._last_ack
            self.final = {"front-end": {
                "submitted": sub,
                "verdicts": ver,
                "shed": shed,
                "fault-tolerance": {"recovery-dropped": rec},
                "crash": cause,
            }}
            self._crash_loss_pending = max(
                sub - (ver + shed + rec), 0)
        try:
            self.proc.kill()  # SIGKILL — not terminate()'s SIGTERM
        except Exception:  # noqa: BLE001 — already gone
            pass
        shutdown_close(self._data)
        shutdown_close(self._obs)
        shutdown_close(self._ctrl)
        with self._ctrl_lock:
            self._ctrl_broken = f"killed: {cause}"
        with self._obs_lock:
            self._obs_broken = f"killed: {cause}"
        # pipelined mode: the closed fd EOFs the ack reader, whose
        # exit path hands every sent-but-unacked frame back to the
        # router (on_broken requeue).  JOIN it before returning so
        # the failover that called crash() migrates a queue that
        # already contains them — mid-window SIGKILL loses nothing.
        t = self._ack_thread
        if t is not None:
            t.join(timeout=10.0)
        self.proc.join(timeout=10.0)

    def take_crash_loss(self) -> int:
        # thread-affinity: api
        """The admitted-but-unresolved row count from the last ack,
        exactly once (the failover path feeds it to
        ``router.account_crash_loss``)."""
        with self._lock:
            n, self._crash_loss_pending = self._crash_loss_pending, 0
            return n

    def mode(self) -> Optional[str]:
        # thread-affinity: any
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("mode")
        try:
            return self.call("front_end", timeout=10.0).get("mode")
        except ServingError:
            return None

    # -- node interface (failover / scale-out / surfaces) --------------
    def start_node(self) -> None:
        self.call("start_node")

    def warm(self, bucket: int, ep: int, trace_sample: int = 0,
             ring_capacity: int = 1 << 15) -> None:
        self.call("warm", timeout=READY_TIMEOUT_S, bucket=int(bucket),
                  ep=int(ep), trace_sample=int(trace_sample),
                  ring_capacity=int(ring_capacity))

    def start_serving(self, **kwargs) -> None:
        self.call("start_serving", timeout=READY_TIMEOUT_S,
                  kwargs=kwargs)

    def stop_serving(self) -> Optional[dict]:
        with self._lock:
            if not self.alive:
                return self.final
        try:
            fin = self.call("stop_serving",
                            timeout=READY_TIMEOUT_S)
        except ServingError:
            with self._lock:
                return self.final
        with self._lock:
            self.final = fin
        return fin

    def add_endpoint(self, name: str, ips, labels) -> int:
        return int(self.call("add_endpoint", name=name,
                             ips=list(ips),
                             labels=list(labels))["id"])

    def applied_policy_rev(self) -> int:
        try:
            return int(self.call("policy_rev", timeout=10.0)["rev"])
        except ServingError:
            return -1

    def has_identity(self, numeric: int) -> bool:
        try:
            return bool(self.call("has_identity", timeout=10.0,
                                  numeric=int(numeric))["ok"])
        except ServingError:
            return False

    def front_end(self) -> Optional[dict]:
        with self._lock:
            if not self.alive or self.final is not None:
                fin = self.final
                return fin.get("front-end") if fin else None
        try:
            return self.call("front_end", timeout=30.0).get(
                "front-end")
        except ServingError:
            return None

    def node_ledgers(self) -> Optional[dict]:
        """event/span/agg ledger blocks; the packet ledger rides
        ``front_end``.  ``None`` for a corpse — SIGKILL erases the
        in-process planes, which is exactly what the thread-mode
        tier could pretend it didn't (DIVERGENCES rewrite)."""
        # `final`, not `alive`, selects the retained ledgers: crash()
        # sets both under one lock, and a clean stop retains final
        # while the worker lives on
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("ledgers")
        try:
            return self.call("front_end", timeout=30.0).get("ledgers")
        except ServingError:
            return None

    def worker_crypto(self) -> Optional[dict]:
        """The WORKER half's channel counters (rx frames, rejects,
        replays, epoch — the parent half rides
        :meth:`transport_stats`); ``None`` on a plaintext cluster.
        The retained final survives a clean stop; SIGKILL erases the
        worker's counters with the process (the parent half is then
        the only surviving record of the channel)."""
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("crypto")
        try:
            return self.call("front_end", timeout=30.0).get("crypto")
        except ServingError:
            return None

    def l7_stats(self) -> Optional[dict]:
        """The node's L7 proxy-plane block (the worker ships it with
        ``front_end``; the retained final survives a clean stop —
        SIGKILL erases the pool with the process)."""
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("l7")
        try:
            return self.call("front_end", timeout=30.0).get("l7")
        except ServingError:
            return None

    def snapshot_ct(self, trigger: str = "cluster") -> np.ndarray:
        """Fan-out snapshot: the worker snapshots AND ships the rows;
        the parent-side replica is what failover replays after a
        SIGKILL."""
        rows = rows_from_b64(self.call("ct_snapshot",
                                   timeout=READY_TIMEOUT_S,
                                   trigger=trigger)["rows"])
        with self._lock:
            self._ct_snap_rows = rows
        return rows

    def ct_rows_for_failover(self) -> np.ndarray:
        from ..datapath.conntrack import ROW_WORDS

        with self._lock:
            snap = self._ct_snap_rows
        if snap is not None:
            return snap
        # no replicated snapshot: the corpse's device CT died with
        # its process — pre-failover connections re-establish
        return np.zeros((0, ROW_WORDS), dtype=np.uint32)

    def merge_ct(self, rows: np.ndarray) -> None:
        self.call("ct_merge", timeout=READY_TIMEOUT_S,
                  rows=rows_to_b64(rows))

    def record_incident(self, kind: str, rec: dict) -> None:
        try:
            self.call("record_incident", kind=kind, rec=rec)
        except ServingError:
            pass  # incident surfacing is advisory

    def publish_cluster_drops(self, rows: Optional[np.ndarray],
                              count: int) -> None:
        try:
            self.call("publish_drops", count=int(count),
                      rows=(rows_to_b64(rows) if rows is not None
                            and len(rows) else None))
        except ServingError:
            pass  # best-effort surfacing; the exact count lives in
            # router_overflow

    def metrics(self) -> Optional[np.ndarray]:
        try:
            return np.asarray(self.call("metricsmap",
                                        timeout=30.0)["metrics"])
        except ServingError:
            return None

    def metrics_text(self) -> Optional[str]:
        """The worker's self-describing registry exposition (the
        ``metrics`` op's ISSUE 14 shape)."""
        try:
            return self.call("metrics", timeout=30.0)["text"]
        except ServingError:
            return None

    # -- node obs interface (the relay's scrape surface) ---------------
    def obs_scrape(self, cursor: int = 0, flows: int = 512,
                   top: int = 16) -> dict:
        """One observability scrape over the DEDICATED obs channel —
        raises on failure (the relay counts it and serves
        last-known-good; swallowing here would make a dead worker
        look healthily empty)."""
        return self.obs_call("obs_scrape", cursor=int(cursor),
                             flows=int(flows), top=int(top))

    def sysdump_bundle(self, trigger: str = "cluster-sysdump"
                       ) -> dict:
        return self.obs_call("sysdump", trigger=trigger)["bundle"]

    def slo(self) -> dict:
        """This worker's node-stamped SLO verdict — raises on
        failure, like ``obs_scrape``: the relay's cluster verdict
        must COUNT an unreachable node, not skip it."""
        return self.obs_call("slo")

    def history(self, series=None, since: float = 0.0) -> dict:
        return self.obs_call(
            "history",
            series=list(series) if series is not None else None,
            since=float(since))

    def map_pressure(self) -> Optional[dict]:
        try:
            return self.call("map_pressure",
                             timeout=30.0)["pressure"]
        except ServingError:
            return None

    def dispatch_compiles(self) -> Optional[dict]:
        try:
            return self.call("compile_stats", timeout=30.0)
        except ServingError:
            return None

    def transport_stats(self) -> dict:
        with self._lock:
            out = {"frames": self._frames,
                   "frames-packed": self._frames_packed,
                   "bytes": self._bytes,
                   "acks": self._acks,
                   "acks-coalesced": self._acks_coalesced}
        with self._win_cv:
            win = self._win
            out["window"] = win.window if win is not None else 1
            out["inflight-frames"] = (win.inflight_frames
                                      if win is not None else 0)
            out["window-stalls"] = self._window_stalls
        ch = self._crypto
        if ch is not None:
            with self._lock:
                out["crypto"] = {
                    "epoch": ch.epoch,
                    "sealed": ch.sealed,
                    "opened": ch.opened,
                    # worker NACKs + every parent-side open failure
                    # (channel rejects and injected faults alike)
                    "rejected": (self._crypto_nacks
                                 + self._crypto_open_failures),
                    "nacks": self._crypto_nacks,
                    "open-failures": self._crypto_open_failures,
                    "replays": ch.replays + self._crypto_replays,
                    "rotations": ch.rotations,
                }
        return out

    def shutdown(self) -> None:
        with self._lock:
            was_alive = self.alive
        if was_alive:
            try:
                self.call("shutdown", timeout=30.0)
            except ServingError:
                pass
        shutdown_close(self._data)
        shutdown_close(self._obs)
        shutdown_close(self._ctrl)
        t = self._ack_thread
        if t is not None:
            t.join(timeout=5.0)
        self.proc.join(timeout=30.0)
        if self.proc.is_alive():
            self.proc.kill()
            self.proc.join(timeout=10.0)
