"""Clustermesh serving tier: N daemon replicas behind one flow-affine
front-end router, with kvstore identity/policy propagation, CT-replay
node failover, and live scale-out.

Reference: upstream cilium's horizontal story — per-node agent
PROCESSES, identities/state fanned through the kvstore
(clustermesh-apiserver / kvstoremesh), health probing, and connection
ownership pinned to the node that saw the flow.  PRs 1-7 built a
production-grade SINGLE-node serving plane; PR 8 composed the
multi-node tier from the repo's existing parts (``kvstore/remote.py``
networked store, ``health/`` node registry, ``parallel.flow_shard_ids``
routing hash, PR 3 CT snapshot/restore); ISSUE 13 makes it honest and
elastic (PARITY rows 61/65):

- :class:`ClusterServing` / :func:`start_cluster_serving` — build N
  daemon replicas in one of two modes (``cluster_mode``):
  ``"thread"`` (in-process replicas, the PR 8 shape — cheapest
  tests, but N nodes share one GIL) or ``"process"`` (one spawned
  worker PROCESS per node hosting a full Daemon + serving runtime —
  ``cluster/nodehost.py`` / ``cluster/process.py`` — forwarding over
  real sockets on the shared ``cluster/transport.py`` framing, so N
  nodes buy N cores).  Either way each replica runs its own kvstore
  CLIENT against one shared :class:`KVStoreServer`, so identity
  mints and policy publishes propagate node-to-node over the REAL
  networked transport, not object sharing;
- :mod:`.router` — the flow-affine front end: a 4-tuple's forward
  and reply packets pin to one node via a FIXED slot space
  (``cluster_slot_factor`` slots per initial node) and a mutable
  slot->owner table; bounded per-node forward queues shed with
  counted ``REASON_CLUSTER_OVERFLOW`` drops;
- :mod:`.membership` — liveness sweep + injectable node death
  (``cluster.probe`` fault site) + the kvstore policy plane;
- :mod:`.failover` — CT-replay failover onto a designated peer:
  replies for pre-failover connections keep passing egress
  enforcement on the peer.  In process mode the dead node is a real
  SIGKILLed process: its CT replays from the parent-retained
  snapshot replica, its final ledger is its last data-channel ACK,
  and the admitted-but-unresolved delta is counted
  ``crash_dropped``;
- :mod:`.scale` — LIVE SCALE-OUT (``add_node()``): a fresh replica
  joins a serving cluster, a fair slot share re-pins to it, the
  moved slots' CT migrates via the snapshot/merge/restore path (the
  failover proof run in reverse), ledger exact across the
  transition; plus a queue-depth-driven autoscale controller.

The cluster-wide no-silent-loss ledger (asserted exact in every
cluster test)::

    submitted == sum over nodes (verdicts + shed + recovery_dropped)
                 + router_overflow + failover_dropped + crash_dropped
                 + crypto_dropped

ISSUE 18 rides the data channel on the crypto plane: with
``cluster_encrypt=True`` (process mode) every router->worker frame
and every ack travels as one AEAD seal over the PR 17 wire
(``encryption.EncryptedChannel``; keys exchanged through the spawn
handshake + node registry), rejects are counted ``crypto_dropped``
(typed NACKs, never a worker crash), and :meth:`ClusterServing.
rotate_epoch` re-keys the LIVE cluster under a bounded grace window
with the ledger exact across the rotation.  With the knob off the
wire is byte-identical to PR 17.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..serving import ServingError
from .failover import FailoverOrchestrator
from .membership import (ClusterMembership, ClusterPolicySync,
                         publish_policy)
from .router import SLOT_FACTOR, ClusterRouter

__all__ = [
    "ClusterServing", "ClusterNode", "ClusterRouter",
    "ClusterMembership", "ClusterPolicySync", "FailoverOrchestrator",
    "start_cluster_serving", "validate_cluster_config",
]

_KVSTORE_MODES = ("remote", "memory")
_CLUSTER_MODES = ("thread", "process")


def validate_cluster_config(nodes, forward_depth, probe_interval_s,
                            death_threshold, convergence_deadline_s,
                            kvstore_mode, mode="thread",
                            slot_factor=SLOT_FACTOR,
                            autoscale_max_nodes=8,
                            autoscale_high_frac=0.5,
                            autoscale_ticks=3,
                            autoscale_interval_s=0.5,
                            obs_interval_s=1.0,
                            obs_stale_after_s=30.0,
                            trace_sample=0,
                            forward_window=8,
                            ack_every=4,
                            ack_flush_ms=2.0,
                            autoscale_min_nodes=1,
                            autoscale_low_frac=0.0,
                            encrypt=False,
                            epoch_grace_s=2.0):
    """Normalize + validate the cluster knobs (the serving-knob
    discipline: a typo'd cluster config fails at construction, not as
    a silent misroute under load)."""
    nodes = int(nodes)
    if nodes < 1:
        raise ValueError("cluster needs nodes >= 1")
    forward_depth = int(forward_depth)
    if forward_depth < 1:
        raise ValueError("cluster_forward_depth must be >= 1")
    probe_interval_s = float(probe_interval_s)
    if probe_interval_s <= 0:
        raise ValueError("cluster_probe_interval_s must be > 0")
    death_threshold = int(death_threshold)
    if death_threshold < 1:
        raise ValueError("cluster_death_threshold must be >= 1")
    convergence_deadline_s = float(convergence_deadline_s)
    if convergence_deadline_s <= 0:
        raise ValueError("cluster_convergence_deadline_s must be > 0")
    kvstore_mode = str(kvstore_mode)
    if kvstore_mode not in _KVSTORE_MODES:
        raise ValueError(
            f"cluster_kvstore must be one of {_KVSTORE_MODES}, got "
            f"{kvstore_mode!r}")
    mode = str(mode)
    if mode not in _CLUSTER_MODES:
        raise ValueError(
            f"cluster_mode must be one of {_CLUSTER_MODES}, got "
            f"{mode!r}")
    if mode == "process" and kvstore_mode != "remote":
        raise ValueError(
            "cluster_mode='process' requires cluster_kvstore="
            "'remote': worker processes cannot share an in-memory "
            "store object")
    slot_factor = int(slot_factor)
    if slot_factor < 1:
        raise ValueError("cluster_slot_factor must be >= 1")
    autoscale_max_nodes = int(autoscale_max_nodes)
    if autoscale_max_nodes < 1:
        raise ValueError("cluster_autoscale_max_nodes must be >= 1")
    autoscale_high_frac = float(autoscale_high_frac)
    if not 0.0 < autoscale_high_frac <= 1.0:
        raise ValueError(
            "cluster_autoscale_high_frac must be in (0, 1]")
    autoscale_ticks = int(autoscale_ticks)
    if autoscale_ticks < 1:
        raise ValueError("cluster_autoscale_ticks must be >= 1")
    autoscale_interval_s = float(autoscale_interval_s)
    if autoscale_interval_s <= 0:
        raise ValueError("cluster_autoscale_interval_s must be > 0")
    obs_interval_s = float(obs_interval_s)
    if obs_interval_s < 0:
        raise ValueError("cluster_obs_interval_s must be >= 0 "
                         "(0 disables the periodic scrape; queries "
                         "then scrape on demand)")
    obs_stale_after_s = float(obs_stale_after_s)
    if obs_stale_after_s <= 0:
        raise ValueError("cluster_obs_stale_after_s must be > 0")
    trace_sample = int(trace_sample)
    if trace_sample < 0:
        raise ValueError("cluster_trace_sample must be >= 0 "
                         "(0 disables cross-process span stitching)")
    forward_window = int(forward_window)
    if forward_window < 1:
        raise ValueError("cluster_forward_window must be >= 1 "
                         "(1 = synchronous per-frame acks, the "
                         "PR 13 protocol)")
    ack_every = int(ack_every)
    if ack_every < 1:
        raise ValueError("cluster_ack_every must be >= 1")
    ack_flush_ms = float(ack_flush_ms)
    if ack_flush_ms <= 0:
        raise ValueError("cluster_ack_flush_ms must be > 0 (the "
                         "coalescer's flush-on-idle timer)")
    autoscale_min_nodes = int(autoscale_min_nodes)
    if autoscale_min_nodes < 1:
        raise ValueError("cluster_autoscale_min_nodes must be >= 1")
    autoscale_low_frac = float(autoscale_low_frac)
    if not 0.0 <= autoscale_low_frac < autoscale_high_frac:
        raise ValueError(
            "cluster_autoscale_low_frac must be in [0, high_frac) "
            "(0 disables autoscale scale-down)")
    encrypt = bool(encrypt)
    epoch_grace_s = float(epoch_grace_s)
    if epoch_grace_s < 0:
        raise ValueError("cluster_epoch_grace_s must be >= 0 "
                         "(0 = strict epoch equality: any in-flight "
                         "old-epoch frame rejects at rotation)")
    return (nodes, forward_depth, probe_interval_s, death_threshold,
            convergence_deadline_s, kvstore_mode, mode, slot_factor,
            autoscale_max_nodes, autoscale_high_frac, autoscale_ticks,
            autoscale_interval_s, obs_interval_s, obs_stale_after_s,
            trace_sample, forward_window, ack_every, ack_flush_ms,
            autoscale_min_nodes, autoscale_low_frac,
            encrypt, epoch_grace_s)


def warm_serving_session(daemon, bucket: int, ep: int,
                         trace_sample: int,
                         ring_capacity: int) -> bool:
    """The ONE warm-up recipe (ISSUE 13 satellite — the PR 12 gate's
    inline workaround made cluster infrastructure): compile the
    packed+wide × full/valid-masked serving executables in a
    throwaway non-ingress session BEFORE a real session starts.
    ``trace_sample`` and ``ring_capacity`` are compile-key statics
    and MUST mirror the real session's values — the zero-recompile
    regression pins catch a drift.  One definition for both modes:
    the thread branch of ``ClusterServing._warm_nodes`` calls it on
    node0 (jit caches are process-global); every worker process runs
    it on itself (``nodehost._op_warm``).  Returns whether the
    packed path was warmable."""
    from ..core.packets import (COL_DST_IP3, COL_EP, COL_FAMILY,
                                COL_LEN, COL_PROTO, COL_SPORT,
                                COL_SRC_IP3, N_COLS,
                                pack_eligibility, pack_rows)

    rows = np.zeros((bucket, N_COLS), dtype=np.uint32)
    rows[:, COL_SRC_IP3] = 1
    rows[:, COL_DST_IP3] = 2
    rows[:, COL_SPORT] = 1024 + (np.arange(bucket) % 4096)
    rows[:, COL_PROTO] = 6
    rows[:, COL_LEN] = 64
    rows[:, COL_FAMILY] = 4
    rows[:, COL_EP] = ep
    ok, wep, wdirn = pack_eligibility(rows)
    vfull = np.ones(bucket, dtype=bool)
    vpart = vfull.copy()
    vpart[bucket // 2:] = False
    daemon.start_serving(ring_capacity=ring_capacity, drain_every=2,
                         trace_sample=trace_sample, packed=True)
    try:
        if ok:
            daemon.serve_batch(pack_rows(rows), valid=vfull,
                               packed_meta=(wep, wdirn))
            daemon.serve_batch(pack_rows(rows), valid=vpart,
                               packed_meta=(wep, wdirn))
        daemon.serve_batch(rows.copy(), valid=vfull)
        daemon.serve_batch(rows.copy(), valid=vpart)
    finally:
        daemon.stop_serving()
    return bool(ok)


@dataclasses.dataclass(frozen=True)
class _EndpointRef:
    """What ``add_endpoint`` returns in process mode: workers own the
    Endpoint objects; callers only ever need the agreed id."""

    id: int
    name: str


class ClusterNode:
    """One in-process replica (``cluster_mode="thread"``): a full
    Daemon with its own serving runtime and kvstore client.
    ``alive`` flips exactly once (True -> False) on crash; the final
    front-end snapshot is retained so the cluster ledger can close
    over a corpse.

    Presents the NODE INTERFACE the tier's orchestrators (failover,
    scale-out, ledgers, surfaces) are written against —
    ``cluster/process.py``'s :class:`~.process.ProcessNode` is the
    other implementation, so everything above this layer runs
    unchanged in either mode."""

    # guarded-by: _lock: alive, final

    def __init__(self, idx: int, name: str, daemon, kv_client=None,
                 policy_sync=None):
        self.idx = idx
        self.name = name
        self.daemon = daemon
        self.kv_client = kv_client
        self.policy_sync = policy_sync
        self._lock = threading.Lock()
        self.alive = True
        self.final: Optional[dict] = None
        # span-tracer / event-plane refs captured at start_serving
        # (stop_serving clears daemon._serving; node_ledgers() closes
        # those ledgers post-stop through these)
        self._tracer = None
        self._eventplane = None

    def submit(self, rows: np.ndarray, trace=None) -> int:
        # (unannotated on purpose: inherits the router forwarder's
        # affinity; Daemon.submit is any-affine)
        if trace is not None:
            # in-process span stitching: recv==frame arrival and
            # admit==runtime accepted collapse around the direct call
            trace.t_recv = time.monotonic()
            n = self.daemon.submit(rows)
            trace.t_admit = time.monotonic()
            return n
        return self.daemon.submit(rows)

    def probe(self) -> bool:
        # thread-affinity: api
        """In-process liveness: the node is alive and its drain loop
        is running.  (Process mode probes over the control socket —
        ``ProcessNode.probe``.)"""
        with self._lock:
            if not self.alive:
                return False
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        return rt is not None and rt.running

    def crash(self, cause: str) -> None:
        # thread-affinity: api
        """Simulated node death: the serving runtime is crash-stopped
        (no drain — queued rows become counted recovery drops in this
        node's own ledger) and the node stops probing healthy.
        Idempotent."""
        with self._lock:
            if not self.alive:
                return
            self.alive = False
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        # kill OUTSIDE the node lock: it joins the drain thread, and
        # a probe blocked behind that join would stall the sweep.
        # Wrapped in the same {"front-end": ...} shape stop_serving
        # returns — per_node_stats/ledger read n.final through that
        # key, and a bare runtime snapshot here would make the dead
        # node's verdicts + recovery drops VANISH from every surface
        # between failover and cluster stop
        final = rt.kill(cause) if rt is not None else None
        with self._lock:
            self.final = ({"front-end": final} if final is not None
                          else None)

    def take_crash_loss(self) -> int:
        # thread-affinity: api
        """Thread-mode corpses yield a FULL final snapshot
        (``kill()`` sweeps queued rows as counted recovery drops), so
        there is never an unaccounted admitted-row delta — the
        process-mode SIGKILL term is structurally zero here."""
        return 0

    def mode(self) -> Optional[str]:
        # thread-affinity: any
        s = self.daemon._serving
        lad = s.get("ladder") if s is not None else None
        return lad.rung if lad is not None else None

    # -- node interface: bring-up --------------------------------------
    def start_node(self) -> None:
        self.daemon.start()

    def start_serving(self, **kwargs) -> None:
        self.daemon.start_serving(ingress=True, **kwargs)
        self._tracer = self.daemon._serving.get("tracer")
        self._eventplane = self.daemon._serving.get("eventplane")

    def stop_serving(self) -> Optional[dict]:
        with self._lock:
            if not self.alive and self.final is not None:
                return self.final
        fin = self.daemon.stop_serving()
        with self._lock:
            if self.final is None:
                self.final = fin
            return self.final

    def add_endpoint(self, name: str, ips, labels) -> int:
        return int(self.daemon.add_endpoint(
            name, tuple(ips), list(labels)).id)

    def applied_policy_rev(self) -> int:
        return (self.policy_sync.applied_rev
                if self.policy_sync is not None else -1)

    def has_identity(self, numeric: int) -> bool:
        return self.daemon.allocator.lookup_by_id(
            int(numeric)) is not None

    # -- node interface: reading ---------------------------------------
    def front_end(self) -> Optional[dict]:
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("front-end")
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        return rt.snapshot() if rt is not None else None

    def node_ledgers(self) -> Optional[dict]:
        out: Dict[str, dict] = {}
        if self._eventplane is not None:
            out["event"] = self._eventplane.stats()
        if self._tracer is not None:
            out["span"] = self._tracer.stats()
        out["agg"] = self.daemon.analytics.stats()
        return out

    def l7_stats(self) -> Optional[dict]:
        """The node's L7 proxy-plane block: the retained stop
        snapshot once serving stopped (or the node crashed), else
        the live pool."""
        with self._lock:
            fin = self.final
        if fin is not None:
            return fin.get("l7")
        l7 = self.daemon._l7plane
        return l7.stats() if l7 is not None else None

    def metrics(self) -> Optional[np.ndarray]:
        return np.asarray(self.daemon.loader.metrics())

    def metrics_text(self) -> Optional[str]:
        return self.daemon.registry.render()

    # -- node obs interface (the ClusterObsRelay scrape surface;
    # ProcessNode implements the same methods over the control
    # channel) ----------------------------------------------------------
    def obs_scrape(self, cursor: int = 0, flows: int = 512,
                   top: int = 16) -> dict:
        # thread-affinity: api, cli
        return self.daemon.obs_scrape_snapshot(cursor=cursor,
                                               flows=flows, top=top)

    def sysdump_bundle(self, trigger: str = "cluster-sysdump"
                       ) -> dict:
        # thread-affinity: api, cli, capture
        return self.daemon.flightrec.collect_bundle(trigger=trigger)

    def slo(self) -> dict:
        # thread-affinity: api, cli
        return self.daemon.slo_snapshot()

    def history(self, series=None, since: float = 0.0) -> dict:
        # thread-affinity: api, cli
        return self.daemon.history_snapshot(series=series,
                                            since=since)

    def map_pressure(self) -> Optional[dict]:
        return self.daemon.loader.map_pressure(self.daemon._now())

    def dispatch_compiles(self) -> Optional[dict]:
        return self.daemon.loader.compile_log.dispatch_summary()

    def transport_stats(self) -> dict:
        return {}  # in-process forwarding: no wire

    # -- node interface: CT migration + surfacing ----------------------
    def snapshot_ct(self, trigger: str = "cluster") -> np.ndarray:
        self.daemon.ct_snapshot_now(trigger)
        return self.daemon._ct_snap["rows"]

    def ct_rows_for_failover(self) -> np.ndarray:
        """The latest retained CT snapshot; in-process fallback reads
        the corpse's device CT directly (possible here because
        "nodes" are threads sharing the host — a SIGKILLed process
        node gets only the parent-retained replica)."""
        snap = self.daemon._ct_snap
        if snap is not None:
            return snap["rows"]
        try:
            return self.daemon.loader.ct_snapshot()
        except Exception:  # noqa: BLE001 — an unreadable corpse CT
            # degrades to an empty replay: pre-failover connections
            # then re-establish instead of resuming (counted by the
            # policy plane, never silent)
            from ..datapath.conntrack import ROW_WORDS

            return np.zeros((0, ROW_WORDS), dtype=np.uint32)

    def merge_ct(self, rows: np.ndarray) -> None:
        """Merge foreign CT rows with the live table — snapshot +
        concat + restore (flow-affine routing keeps the two tables
        disjoint; the device re-hash resolves any residue)."""
        if not len(rows):
            return
        merged = np.concatenate([
            self.daemon.loader.ct_snapshot(), np.asarray(rows)])
        self.daemon.loader.ct_restore(merged)

    def record_incident(self, kind: str, rec: dict) -> None:
        self.daemon.record_incident(kind, rec)

    def publish_cluster_drops(self, rows: Optional[np.ndarray],
                              count: int) -> None:
        self.daemon._publish_cluster_drops(rows, count)

    def shutdown(self) -> None:
        if self.policy_sync is not None:
            self.policy_sync.close()
        self.daemon.shutdown()
        if self.kv_client is not None:
            self.kv_client.close()


class ClusterServing:
    """The cluster serving tier facade: construct -> add endpoints /
    import policy (fan-out + kvstore propagation) -> :meth:`start`
    (node bring-up + warm-up + router + membership) -> :meth:`submit`
    from any thread -> :meth:`add_node` to grow live ->
    :meth:`stop`.

    Thread-mode node daemons get ``daemon._cluster = self`` so the
    per-node surfaces (serving stats Cluster block, GET
    /cluster/status, the ``cilium_cluster_*`` registry series) can
    reach the tier from any node's API socket."""

    def __init__(self, nodes: int = 3, config=None,
                 node_prefix: str = "node"):
        from ..agent.daemon import DaemonConfig

        template = config or DaemonConfig()
        self._template = template
        self._node_prefix = node_prefix
        (self.n_nodes, self.forward_depth, self.probe_interval_s,
         self.death_threshold, self.convergence_deadline_s,
         self.kvstore_mode, self.mode, self.slot_factor,
         self.autoscale_max_nodes, self.autoscale_high_frac,
         self.autoscale_ticks, self.autoscale_interval_s,
         self.obs_interval_s, self.obs_stale_after_s,
         self.trace_sample, self.forward_window, self.ack_every,
         self.ack_flush_ms, self.autoscale_min_nodes,
         self.autoscale_low_frac, self.encrypt, self.epoch_grace_s
         ) = validate_cluster_config(
            nodes, template.cluster_forward_depth,
            template.cluster_probe_interval_s,
            template.cluster_death_threshold,
            template.cluster_convergence_deadline_s,
            template.cluster_kvstore,
            mode=template.cluster_mode,
            slot_factor=template.cluster_slot_factor,
            autoscale_max_nodes=template.cluster_autoscale_max_nodes,
            autoscale_high_frac=template.cluster_autoscale_high_frac,
            autoscale_ticks=template.cluster_autoscale_ticks,
            autoscale_interval_s=(
                template.cluster_autoscale_interval_s),
            obs_interval_s=template.cluster_obs_interval_s,
            obs_stale_after_s=template.cluster_obs_stale_after_s,
            trace_sample=template.cluster_trace_sample,
            forward_window=template.cluster_forward_window,
            ack_every=template.cluster_ack_every,
            ack_flush_ms=template.cluster_ack_flush_ms,
            autoscale_min_nodes=(
                template.cluster_autoscale_min_nodes),
            autoscale_low_frac=template.cluster_autoscale_low_frac,
            encrypt=template.cluster_encrypt,
            epoch_grace_s=template.cluster_epoch_grace_s)
        # -- the crypto plane (ISSUE 18) --------------------------------
        # one parent keypair, one EncryptedChannel per forwarder;
        # the epoch is CLUSTER state owned here (kvstore-published by
        # rotate_epoch, handed to joiners at spawn).  Thread mode has
        # no wire to seal: cluster_encrypt is a documented no-op
        # there (in-process submits never leave the address space).
        # guarded-by: _rotate_lock -- epoch bump + per-node rotation
        # fan-out + _rotations append (reads of self.epoch elsewhere
        # are single-word and tolerate staleness by design: a joiner
        # racing a rotation lands one epoch behind, inside grace,
        # and the next rotation re-keys it)
        self._crypto_kp = None
        self.epoch = 0
        self._rotations: List[dict] = []
        self._rotate_lock = threading.Lock()
        if self.encrypt and self.mode == "process":
            from ..encryption import NodeKeypair

            self._crypto_kp = NodeKeypair()
        # -- the shared identity/policy plane ---------------------------
        self._kv_server = None
        self._kv_store = None
        self._spawner = None
        if self.kvstore_mode == "remote":
            from ..kvstore.remote import KVStoreServer, RemoteKVStore

            self._kv_server = KVStoreServer(host="127.0.0.1", port=0)

            def client():
                return RemoteKVStore([self._kv_server.address])
        else:
            from ..kvstore import InMemoryKVStore

            self._kv_store = InMemoryKVStore()

            def client():
                return self._kv_store

        self._kv_client_factory = client
        # -- the replicas ----------------------------------------------
        # partial construction must not leak: a failed spawn/attach
        # mid-loop tears down the kvstore server, the rendezvous
        # listener, and every already-built replica (daemonic worker
        # processes only die with the PARENT process — a long-lived
        # test runner or API server would accumulate them otherwise)
        self.nodes: List = []
        try:
            if self.mode == "process":
                from .process import (ProcessNodeSpawner,
                                      spawn_available)

                if not spawn_available():
                    raise ServingError(
                        "cluster_mode='process' needs the "
                        "multiprocessing 'spawn' start method, "
                        "unavailable here")
                self._spawner = ProcessNodeSpawner()
            for i in range(self.n_nodes):
                self.nodes.append(self._build_node(i))
            if self.mode == "process":
                for n in self.nodes:
                    n.wait_ready()
        except BaseException:
            for n in self.nodes:
                try:
                    n.shutdown()
                except Exception:  # noqa: BLE001 — best-effort
                    pass  # teardown of a half-built replica
            if self._spawner is not None:
                self._spawner.close()
            if self._kv_server is not None:
                self._kv_server.close()
            raise
        self._by_name = {n.name: n for n in self.nodes}
        self._policy_rev = 0
        # the control-plane journal add_node replays onto a joining
        # replica (endpoints registered in order => ids agree)
        self._endpoints: List[tuple] = []
        self._first_ep_id: Optional[int] = None
        self._serving_kwargs: Optional[dict] = None
        self.router: Optional[ClusterRouter] = None
        self.failover = FailoverOrchestrator(self)
        node0 = self.nodes[0]
        self.membership = ClusterMembership(
            self.nodes, self.probe_interval_s, self.death_threshold,
            on_death=self._on_node_death,
            node_registry=(node0.daemon.node_registry
                           if isinstance(node0, ClusterNode)
                           else None))
        self.autoscaler = None
        self._scale_lock = threading.Lock()
        self.scale_events: List[dict] = []
        self._started = False
        self._stopped = False
        self._final: Optional[dict] = None
        # -- the cluster observability relay (ISSUE 14, obs/relay.py):
        # periodic low-duty scrape of every node's registry/flows/
        # top-K/tracer/incidents into the merged cluster views, plus
        # the cross-process span store when trace sampling is armed.
        # peers_fn reads self.nodes LIVE so scale-out replicas join
        # the scrape set without registration.
        from ..obs.relay import ClusterObsRelay, ClusterSpanStore

        self.span_store = (ClusterSpanStore()
                           if self.trace_sample > 0 else None)
        self.obs = ClusterObsRelay(
            peers_fn=lambda: list(self.nodes),
            interval_s=self.obs_interval_s,
            stale_after_s=self.obs_stale_after_s,
            span_store=self.span_store,
            parent_collect=self._parent_obs_collect)

    def _build_node(self, idx: int, name: Optional[str] = None):
        """One replica, either mode — construction (here) is separate
        from bring-up (:meth:`start` / ``scale.scale_out``), so
        scale-out can build a node while the cluster serves."""
        name = name or f"{self._node_prefix}{idx}"
        if self.mode == "process":
            node = self._spawner.spawn(
                name, self._template, self._kv_server.address,
                parent_pub=(self._crypto_kp.public.hex()
                            if self._crypto_kp is not None else None),
                epoch=self.epoch)
            node.idx = idx
            node.attach()
            if self._crypto_kp is not None:
                # the worker minted its keypair in-process and
                # advertised only the PUBLIC half in its hello
                # (nodehost.node_host_main); arm the parent half of
                # the channel at the cluster's CURRENT epoch so a
                # scale-out joiner lands in key agreement immediately
                if not node.peer_pub_hex:
                    raise ServingError(
                        f"cluster_encrypt=True but worker {name} "
                        f"advertised no pubkey in its hello")
                node.enable_crypto(
                    self._crypto_kp,
                    bytes.fromhex(node.peer_pub_hex),
                    grace_s=self.epoch_grace_s,
                    epoch=self.epoch)
            return node
        from ..agent.daemon import Daemon

        cfg = dataclasses.replace(self._template, node_name=name)
        kv = self._kv_client_factory()
        daemon = Daemon(cfg, kvstore=kv)
        sync = ClusterPolicySync(kv, daemon)
        node = ClusterNode(idx, name, daemon,
                           kv_client=(kv if self._kv_server
                                      is not None else None),
                           policy_sync=sync)
        daemon._cluster = self
        return node

    # -- topology ------------------------------------------------------
    def node(self, name: str):
        return self._by_name[name]

    def designated_peer(self, dead_idx: int):
        """Next LIVE node in ring order after the dead one — the
        deterministic failover target every test and operator can
        predict."""
        n = len(self.nodes)
        for step in range(1, n):
            cand = self.nodes[(dead_idx + step) % n]
            if cand.alive:
                return cand
        return None

    # -- control plane (fan-out + kvstore propagation) -----------------
    def add_endpoint(self, name: str, ips, labels):
        """Register one logical endpoint on EVERY replica (same id
        everywhere — the router may pin any flow to any node).  The
        registration is journaled so a scale-out replica replays it
        in the same order."""
        ids = {n.add_endpoint(name, tuple(ips), list(labels))
               for n in self.nodes}
        if len(ids) != 1:
            raise ServingError(
                f"endpoint id diverged across replicas: {sorted(ids)}"
                f" (register endpoints in the same order everywhere)")
        ep_id = ids.pop()
        self._endpoints.append((name, tuple(ips), list(labels)))
        if self._first_ep_id is None:
            self._first_ep_id = ep_id
        if self.mode == "process":
            return _EndpointRef(ep_id, name)
        # thread mode keeps returning the node0 Endpoint object (the
        # PR 8 surface tests and callers use)
        return self.nodes[0].daemon.endpoints.get(ep_id)

    def _policy_kv(self):
        if self._kv_server is not None:
            # the server's own store: an update triggers every
            # replica's watch over the socket transport (the parent
            # needs no client of its own)
            return self._kv_server.store
        return self._kv_store

    def policy_import(self, rules) -> int:
        """Publish one ruleset revision through the kvstore; every
        node (the publisher included) applies it exactly once via its
        watch.  Returns the revision — :meth:`wait_policy` blocks on
        cluster-wide convergence."""
        self._policy_rev += 1
        publish_policy(self._policy_kv(), self._policy_rev, rules)
        return self._policy_rev

    def wait_policy(self, rev: Optional[int] = None,
                    timeout: Optional[float] = None) -> bool:
        rev = self._policy_rev if rev is None else rev
        timeout = (self.convergence_deadline_s if timeout is None
                   else timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(n.applied_policy_rev() >= rev
                   for n in self.nodes if n.alive):
                return True
            time.sleep(0.005)
        return False

    def snapshot_now(self, trigger: str = "cluster") -> None:
        """Fan out a CT snapshot on every live replica — the failover
        replay source.  In process mode the rows also SHIP to the
        parent (``ProcessNode.snapshot_ct``): after a SIGKILL the
        parent-side replica is all that is left to replay.
        Production deployments get the same cadence from
        ``ct_snapshot_interval`` + ``Daemon.start()`` (the periodic
        snapshot controller); tests and the bench drive it
        explicitly."""
        for n in self.nodes:
            if n.alive:
                n.snapshot_ct(trigger)

    def wait_identity(self, numeric: int,
                      timeout: Optional[float] = None) -> bool:
        """Block until every live replica's allocator mirrors the
        identity (the kvstore convergence window made testable)."""
        timeout = (self.convergence_deadline_s if timeout is None
                   else timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(n.has_identity(numeric)
                   for n in self.nodes if n.alive):
                return True
            time.sleep(0.005)
        return False

    # -- lifecycle -----------------------------------------------------
    def _warm_nodes(self, nodes: Sequence,
                    trace_sample: int = 0,
                    ring_capacity: int = 1 << 15) -> None:
        """The bring-up warm discipline (ISSUE 13 satellite — the
        PR 12 gate's inline workaround moved into the tier): compile
        packed+wide × full/masked serving executables in a throwaway
        non-ingress session BEFORE the real sessions start.  Thread
        mode warms once (jit caches are process-global, and the
        kvstore-propagated world makes state shapes identical across
        replicas); process mode warms every worker in parallel (each
        owns its own cache)."""
        bucket = max(self._template.serving_bucket_ladder)
        # trace_sample AND ring_capacity are part of the serving
        # executables' compile keys (device-side sampling; the
        # ring rides the dispatch): the warm session must mirror
        # the real session's values or it warms the wrong keys
        ep = self._first_ep_id if self._first_ep_id is not None else 0
        if self.mode == "process":
            errs: List[BaseException] = []

            def _w(n):
                try:
                    n.warm(bucket, ep, trace_sample, ring_capacity)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=_w, args=(n,), daemon=True)
                  for n in nodes]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errs:
                raise ServingError(f"cluster warm-up failed: "
                                   f"{errs[0]}")
            return
        # thread mode: one throwaway session on the first node warms
        # every replica's executables (jit caches are process-global)
        warm_serving_session(nodes[0].daemon, bucket, ep,
                             trace_sample, ring_capacity)

    def start(self, trace_sample: int = 0, packed: bool = True,
              ring_capacity: int = 1 << 15, drain_every: int = 4,
              span_sample: Optional[int] = None,
              warm: bool = True) -> None:
        """Cluster bring-up proper (ISSUE 13 satellite): START every
        node daemon (background controllers, map-pressure monitor,
        and — critically — the post-start identity patch path, which
        the pre-start cache-only path silently isn't), run the
        warm-up discipline, start every serving session, then the
        router, membership, and (when configured) the autoscaler.
        Every construction path gets started nodes — the PR 12 gate's
        inline workaround is retired."""
        if self._started:
            raise ServingError("cluster already started")
        for n in self.nodes:
            n.start_node()
        if warm:
            self._warm_nodes(self.nodes, trace_sample,
                             ring_capacity)
        kwargs = dict(ring_capacity=ring_capacity,
                      drain_every=drain_every,
                      trace_sample=trace_sample,
                      packed=packed, span_sample=span_sample)
        self._serving_kwargs = kwargs
        for n in self.nodes:
            n.start_serving(**kwargs)
        self.router = ClusterRouter(
            self.nodes, self.forward_depth,
            on_overflow=self._surface_overflow,
            slot_factor=self.slot_factor,
            trace_sample=self.trace_sample,
            span_store=self.span_store,
            # the credit window is a process-mode (socket transport)
            # concept; thread-mode submits are already synchronous
            # in-process calls with nothing to pipeline
            forward_window=(self.forward_window
                            if self.mode == "process" else 1))
        self.router.start()
        self.membership.start()
        self.obs.start()  # no-op when cluster_obs_interval_s == 0
        if self._template.cluster_autoscale:
            from .scale import ClusterAutoscaler

            self.autoscaler = ClusterAutoscaler(
                self,
                high_frac=self.autoscale_high_frac,
                ticks=self.autoscale_ticks,
                max_nodes=self.autoscale_max_nodes,
                interval_s=self.autoscale_interval_s,
                low_frac=self.autoscale_low_frac,
                min_nodes=self.autoscale_min_nodes)
            self.autoscaler.start()
        self._started = True

    def submit(self, rows: np.ndarray) -> int:
        # (the cluster tier's enqueue entry; the annotated router
        # hot path is ClusterRouter._route)
        r = self.router
        if r is None:
            raise ServingError("call ClusterServing.start() first")
        return r.submit(rows)

    # -- live scale-out -------------------------------------------------
    def add_node(self) -> dict:
        """Grow a SERVING cluster by one replica: build + converge +
        warm the newcomer, freeze/quiesce the router, re-pin a fair
        slot share, migrate the moved slots' CT (the failover proof
        run in reverse), resume.  Returns the scale-out record
        (moved slots, migrated CT entries, pause window).  See
        ``cluster/scale.py``."""
        from .scale import scale_out

        return scale_out(self)

    def remove_node(self, name: Optional[str] = None) -> dict:
        """Shrink a SERVING cluster by one replica (ROADMAP item 3
        residue b — failover minus the death): freeze, drain the
        victim's forward queue AND its open send window, re-pin its
        slots onto the survivors, migrate the moved slots' CT to
        each slot's new owner, retire the worker cleanly.  ``name``
        defaults to the last live node.  Returns the scale-in record.
        See ``cluster/scale.py``."""
        from .scale import scale_in

        return scale_in(self, name=name)

    def stop(self) -> dict:
        """Drain the router and every replica; returns (and retains)
        the final cluster stats with the ledger closed."""
        if self._stopped:
            return self._final or self.stats()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.obs.stop()  # the scrape loop must not race teardown
        self.membership.stop()
        if self.router is not None:
            self.router.stop(drain=True)
        for n in self.nodes:
            # a crashed node's stop_serving is idempotent over the
            # corpse: its retained final (swept queue included, or
            # the last ack for a SIGKILLed worker) is what the
            # ledger reads
            n.stop_serving()
        self._stopped = True
        self._final = self.stats()
        return self._final

    def shutdown(self) -> None:
        self.stop()
        for n in self.nodes:
            n.shutdown()
        if self._spawner is not None:
            self._spawner.close()
        if self._kv_server is not None:
            self._kv_server.close()

    # -- death handling -------------------------------------------------
    def _on_node_death(self, name: str, detail: dict) -> None:
        # thread-affinity: api -- membership prober thread
        self.failover.fail_over(name, detail)

    def kill_node(self, name: str) -> None:
        """Crash a node and let the HEALTH path find it (probe
        failures -> death threshold -> failover) — the organic-death
        shape.  In process mode this is a REAL SIGKILL."""
        self.node(name).crash("operator kill_node")

    def fail_node(self, name: str) -> dict:
        """Crash a node and fail it over immediately (deterministic
        test/bench path — no probe latency in the measurement)."""
        t0 = time.monotonic()
        self.node(name).crash("operator fail_node")
        self.membership.declare_dead(name, {
            "cause": "operator fail_node",
            "detect-ms": round((time.monotonic() - t0) * 1e3, 3)})
        recs = self.failover.snapshot()
        return recs[-1] if recs else {}

    # -- key rotation (ISSUE 18) ----------------------------------------
    def rotate_epoch(self, grace_s: Optional[float] = None) -> dict:
        # thread-affinity: api, cli
        """Cluster-wide key-epoch rotation DURING live serving: bump
        the epoch, publish it through the kvstore (cluster state any
        operator or late joiner can read — not a per-channel
        whisper), then rotate every live channel in the TWO-PHASE
        order (``ProcessNode.rotate_epoch``: parent pre-installs the
        new epoch's recv key, worker flushes pending acks under the
        OLD epoch and re-keys, parent re-keys — so neither side ever
        seals at an epoch the other cannot open, in EITHER
        direction).  In-flight frames sealed pre-rotation
        stay openable for ``grace_s`` via the channel's bounded
        previous-epoch grace window (its own replay state — see
        ``encryption.EncryptedChannel``), so not a single row is
        lost or double-counted at any interleaving.  A node whose
        rotation fails keeps serving at its old epoch (worker-first
        means neither half re-keyed) and is surfaced in the record —
        degraded and counted, never hung."""
        if self._crypto_kp is None:
            raise ServingError(
                "rotate_epoch needs cluster_encrypt=True in "
                "process mode")
        grace = (self.epoch_grace_s if grace_s is None
                 else float(grace_s))
        with self._rotate_lock:
            epoch = self.epoch + 1
            t0 = time.monotonic()
            self._policy_kv().update(
                "cilium/cluster/crypto/epoch",
                str(epoch).encode())
            acked: List[str] = []
            failed: List[dict] = []
            for n in self.nodes:
                if not n.alive:
                    continue
                try:
                    n.rotate_epoch(epoch, grace)
                    acked.append(n.name)
                except Exception as exc:  # noqa: BLE001 — a node
                    # that cannot rotate (crashed mid-op, control
                    # channel gone) is degraded, not fatal: its
                    # channel stays self-consistent at the old epoch
                    # and the next rotation (or failover) covers it
                    failed.append({"node": n.name,
                                   "error": str(exc)})
            self.epoch = epoch
            rec = {"epoch": epoch, "acked": acked, "grace-s": grace,
                   "ms": round((time.monotonic() - t0) * 1e3, 3)}
            if failed:
                rec["failed"] = failed
            self._rotations.append(rec)
            return rec

    # -- cluster observability (ISSUE 14) -------------------------------
    def _parent_obs_collect(self) -> dict:
        # thread-affinity: api, cli, capture
        """The PARENT's bundle half for the cluster sysdump archive:
        the cluster-level state no single node can see — router +
        slot table, membership, failover/scale-out history, the
        cluster ledger, and the relay's own scrape plane."""
        return {"cluster": self.stats()}

    def cluster_sysdump(self, out_dir: Optional[str] = None) -> dict:
        # thread-affinity: api, cli, capture
        """One archive: every node's flight-recorder bundle + the
        parent's cluster bundle + a manifest (``cilium-sysdump``
        parity for the serving tier).  ``out_dir`` defaults to the
        template's ``sysdump_dir``."""
        out_dir = out_dir or self._template.sysdump_dir
        if not out_dir:
            raise ServingError(
                "cluster sysdump needs a directory: pass out_dir or "
                "configure sysdump_dir")
        return self.obs.cluster_sysdump(out_dir)

    # -- shed surfacing -------------------------------------------------
    def _surface_overflow(self, idx: int,
                          rows: Optional[np.ndarray],
                          count: int) -> None:
        # thread-affinity: router, api
        """Router sheds -> REASON_CLUSTER_OVERFLOW metricsmap counts
        + decoded monitor DROP events, on the owning node (or, when
        it died, the first live node — the count must land
        SOMEWHERE operators look)."""
        node = self.nodes[idx]
        if not node.alive:
            node = next((n for n in self.nodes if n.alive), None)
        if node is None:
            return  # cluster-wide corpse: router_overflow holds the
            # exact count; there is no live surface left to decorate
        node.publish_cluster_drops(rows, count)

    # -- reading --------------------------------------------------------
    def router_overflow_total(self) -> int:
        r = self.router
        return r.router_overflow if r is not None else 0

    def failover_dropped_total(self) -> int:
        r = self.router
        return r.failover_dropped if r is not None else 0

    def crash_dropped_total(self) -> int:
        r = self.router
        return r.crash_dropped if r is not None else 0

    def failovers_total(self) -> int:
        return len(self.failover.snapshot())

    def scale_ins_total(self) -> int:
        return sum(1 for e in self.scale_events
                   if e.get("kind") == "scale-in")

    def _window_counters(self) -> dict:
        r = self.router
        if r is None:
            return {"acks": 0, "acks-coalesced": 0,
                    "window-stalls": 0, "inflight-frames": 0}
        return r.snapshot()["window"]

    def inflight_frames(self) -> int:
        """Frames sent but not yet cumulatively acked, cluster-wide
        (the pipelined channel's live credit debt)."""
        return self._window_counters()["inflight-frames"]

    def acks_coalesced_total(self) -> int:
        """Per-frame acks the coalescer ELIDED — each cumulative ack
        covering k frames counts k-1 (the round trips the window
        bought back)."""
        return self._window_counters()["acks-coalesced"]

    def window_stalls_total(self) -> int:
        """Times a forwarder ran out of credit (send window full) and
        had to wait for an ack — the backpressure signal that says
        the window, not the worker, is the bottleneck."""
        return self._window_counters()["window-stalls"]

    def crypto_dropped_total(self) -> int:
        r = self.router
        return r.crypto_dropped if r is not None else 0

    def _crypto_counters(self) -> Optional[dict]:
        r = self.router
        return r.snapshot().get("crypto") if r is not None else None

    def crypto_rejected_total(self) -> int:
        """Sealed frames some channel end REFUSED (auth / replay /
        epoch skew / injected fault), cluster-wide — every one is a
        counted NACK or a counted parent-side open failure, never a
        worker crash."""
        c = self._crypto_counters()
        return int(c["rejected"]) if c else 0

    def crypto_replays_total(self) -> int:
        c = self._crypto_counters()
        return int(c["replays"]) if c else 0

    def crypto_rotations_total(self) -> int:
        """Cluster-wide rotation OPERATIONS (one op re-keys every
        live channel — not the per-channel rotate count)."""
        return len(self._rotations)

    def live_dead_counts(self):
        live = sum(1 for n in self.nodes if n.alive)
        return live, len(self.nodes) - live

    def forward_pending(self) -> int:
        r = self.router
        return r.pending_total() if r is not None else 0

    def summary(self) -> dict:
        """The serving-stats Cluster block: cheap counters only (no
        per-node stats recursion — this renders inside every node's
        own serving_stats)."""
        live, dead = self.live_dead_counts()
        recs = self.failover.snapshot()
        out = {
            "nodes": len(self.nodes),
            "live": live,
            "dead": dead,
            "mode": self.mode,
            "kvstore": self.kvstore_mode,
            "router": (self.router.snapshot()
                       if self.router is not None else None),
            "failovers": len(recs),
            "scale-outs": sum(1 for e in self.scale_events
                              if e.get("kind") != "scale-in"),
            "scale-ins": sum(1 for e in self.scale_events
                             if e.get("kind") == "scale-in"),
        }
        if recs:
            out["last-failover"] = recs[-1]
        if self.scale_events:
            out["last-scale-out"] = self.scale_events[-1]
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.stats()
        if self._crypto_kp is not None:
            out["crypto"] = {"epoch": self.epoch,
                             "rotations": len(self._rotations),
                             "grace-s": self.epoch_grace_s}
            if self._rotations:
                out["last-rotation"] = self._rotations[-1]
        return out

    def per_node_stats(self) -> Dict[str, dict]:
        out = {}
        for n in self.nodes:
            out[n.name] = {
                "alive": n.alive,
                "mode": n.mode(),
                "front-end": n.front_end(),
                **({"l7": l7s} if (l7s := n.l7_stats()) else {}),
                **({"transport": ts}
                   if (ts := n.transport_stats()) else {}),
                **({"crypto": cb}
                   if (cb := (n.worker_crypto()
                              if hasattr(n, "worker_crypto")
                              else None)) else {}),
            }
        return out

    def ledger(self) -> dict:
        """The cluster-wide no-silent-loss ledger.  ``exact`` is
        meaningful after :meth:`stop` (while running, rows in
        forward/admission queues and in flight sit outside every
        counter, mirroring the node-level ledger's contract)."""
        r = self.router
        submitted = r.submitted if r is not None else 0
        overflow = r.router_overflow if r is not None else 0
        fo_dropped = r.failover_dropped if r is not None else 0
        crash = r.crash_dropped if r is not None else 0
        crypto = r.crypto_dropped if r is not None else 0
        pending = r.pending_total() if r is not None else 0
        per_node = 0
        for name, st in self.per_node_stats().items():
            fe = st.get("front-end")
            if fe is None:
                continue
            ft = fe.get("fault-tolerance", {})
            per_node += (fe.get("verdicts", 0) + fe.get("shed", 0)
                         + ft.get("recovery-dropped", 0))
        accounted = (per_node + overflow + fo_dropped + crash
                     + crypto + pending)
        return {
            "submitted": submitted,
            "per-node-accounted": per_node,
            "router-overflow": overflow,
            "failover-dropped": fo_dropped,
            "crash-dropped": crash,
            "crypto-dropped": crypto,
            "forward-pending": pending,
            "accounted": accounted,
            "exact": submitted == accounted,
        }

    def ledgers(self) -> dict:
        """EVERY no-silent-loss ledger the tier runs, closed in one
        read — the everything-on soak gate's assertion surface
        (ISSUE 12).  Five ledgers:

        - ``packet`` (per node): submitted == verdicts + shed +
          recovery_dropped (exact after stop);
        - ``event`` (per node): event-plane windows submitted ==
          joined + dropped;
        - ``span`` (per node, when tracing armed): spans started ==
          completed + dropped;
        - ``agg`` (per node): analytics batches submitted ==
          ingested + dropped;
        - ``cluster``: the router-level ledger (:meth:`ledger`).

        ``exact`` is the conjunction.  Meaningful after
        :meth:`stop`, like every in-flight-exclusive ledger here.
        A SIGKILLed process node contributes its packet ledger (the
        last-ack word, closed by ``crash_dropped``); its in-process
        event/span/agg planes died with it and are skipped — loss a
        thread-mode corpse never shows."""
        out: Dict[str, dict] = {"packet": {}, "event": {},
                                "span": {}, "agg": {}}
        ok = True
        per_node = self.per_node_stats()
        for n in self.nodes:
            fe = (per_node.get(n.name) or {}).get("front-end")
            if fe is not None:
                ft = fe.get("fault-tolerance", {})
                acc = (fe.get("verdicts", 0) + fe.get("shed", 0)
                       + ft.get("recovery-dropped", 0))
                exact = fe.get("submitted", 0) == acc \
                    or "crash" in ft or "crash" in fe
                out["packet"][n.name] = {
                    "submitted": fe.get("submitted", 0),
                    "accounted": acc, "exact": exact}
                ok = ok and exact
            led = n.node_ledgers() or {}
            ev = led.get("event")
            if ev is not None:
                exact = ev["windows-submitted"] == (
                    ev["windows-joined"] + ev["windows-dropped"])
                out["event"][n.name] = {
                    "submitted": ev["windows-submitted"],
                    "joined": ev["windows-joined"],
                    "dropped": ev["windows-dropped"], "exact": exact}
                ok = ok and exact
            ts = led.get("span")
            if ts is not None:
                exact = ts["started"] == (ts["completed"]
                                          + ts["dropped"])
                out["span"][n.name] = {
                    "started": ts["started"],
                    "completed": ts["completed"],
                    "dropped": ts["dropped"], "exact": exact}
                ok = ok and exact
            ag = led.get("agg")
            if ag is not None:
                exact = ag["batches-submitted"] == (
                    ag["batches-ingested"] + ag["batches-dropped"])
                out["agg"][n.name] = {
                    "submitted": ag["batches-submitted"],
                    "ingested": ag["batches-ingested"],
                    "dropped": ag["batches-dropped"], "exact": exact}
                ok = ok and exact
        out["cluster"] = self.ledger()
        out["exact"] = ok and bool(out["cluster"]["exact"])
        return out

    def stats(self) -> dict:
        return {
            "cluster": self.summary(),
            "membership": self.membership.statuses(),
            "per-node": self.per_node_stats(),
            "ledger": self.ledger(),
            "failovers": self.failover.snapshot(),
            "scale-outs": list(self.scale_events),
            "rotations": list(self._rotations),
            "obs": self.obs.stats(),
        }

    def status(self) -> dict:
        """GET /cluster/status — the operator view (`cilium-tpu
        cluster status`)."""
        return self.stats()


def start_cluster_serving(nodes: int = 3, config=None,
                          trace_sample: int = 0, packed: bool = True,
                          ring_capacity: int = 1 << 15,
                          drain_every: int = 4,
                          node_prefix: str = "node",
                          warm: bool = True) -> ClusterServing:
    """Build AND start a cluster serving tier in one call (the
    ``Daemon.start_serving`` analogue one level up): N replicas
    (threads or real worker processes per ``config.cluster_mode``),
    one shared kvstore plane, the flow-affine router, membership,
    failover, and — when configured — the autoscaler; ready for
    :meth:`ClusterServing.submit`."""
    c = ClusterServing(nodes=nodes, config=config,
                       node_prefix=node_prefix)
    c.start(trace_sample=trace_sample, packed=packed,
            ring_capacity=ring_capacity, drain_every=drain_every,
            warm=warm)
    return c
