"""Clustermesh serving tier: N daemon replicas behind one flow-affine
front-end router, with kvstore identity/policy propagation and
CT-replay node failover.

Reference: upstream cilium's horizontal story — per-node agents,
identities/state fanned through the kvstore (clustermesh-apiserver /
kvstoremesh), health probing, and connection ownership pinned to the
node that saw the flow.  PRs 1-7 built a production-grade SINGLE-node
serving plane; this package composes the repo's existing parts
(``kvstore/remote.py`` networked store, ``health/`` node registry,
``parallel.flow_shard_ids`` routing hash, PR 3 CT snapshot/restore)
into the multi-node tier (PARITY row 61):

- :class:`ClusterServing` / :func:`start_cluster_serving` — build N
  in-process daemon replicas ("nodes": threads, not processes — the
  CPU backend cannot run cross-process collectives; see
  DIVERGENCES), each with its own serving runtime and its own
  kvstore CLIENT against one shared :class:`KVStoreServer`, so
  identity mints and policy publishes propagate node-to-node over
  the REAL networked transport, not object sharing;
- :mod:`.router` — the flow-affine front end: a 4-tuple's forward
  and reply packets pin to one node; bounded per-node forward
  queues shed with counted ``REASON_CLUSTER_OVERFLOW`` drops;
- :mod:`.membership` — liveness sweep + injectable node death
  (``cluster.probe`` fault site) + the kvstore policy plane;
- :mod:`.failover` — CT-replay failover onto a designated peer:
  replies for pre-failover connections keep passing egress
  enforcement on the peer (the PR 3 demotion proof, extended to
  node death).

The cluster-wide no-silent-loss ledger (asserted exact in every
cluster test)::

    submitted == sum over nodes (verdicts + shed + recovery_dropped)
                 + router_overflow + failover_dropped
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..serving import ServingError
from .failover import FailoverOrchestrator
from .membership import (ClusterMembership, ClusterPolicySync,
                         publish_policy)
from .router import ClusterRouter

__all__ = [
    "ClusterServing", "ClusterNode", "ClusterRouter",
    "ClusterMembership", "ClusterPolicySync", "FailoverOrchestrator",
    "start_cluster_serving", "validate_cluster_config",
]

_KVSTORE_MODES = ("remote", "memory")


def validate_cluster_config(nodes, forward_depth, probe_interval_s,
                            death_threshold, convergence_deadline_s,
                            kvstore_mode):
    """Normalize + validate the cluster knobs (the serving-knob
    discipline: a typo'd cluster config fails at construction, not as
    a silent misroute under load)."""
    nodes = int(nodes)
    if nodes < 1:
        raise ValueError("cluster needs nodes >= 1")
    forward_depth = int(forward_depth)
    if forward_depth < 1:
        raise ValueError("cluster_forward_depth must be >= 1")
    probe_interval_s = float(probe_interval_s)
    if probe_interval_s <= 0:
        raise ValueError("cluster_probe_interval_s must be > 0")
    death_threshold = int(death_threshold)
    if death_threshold < 1:
        raise ValueError("cluster_death_threshold must be >= 1")
    convergence_deadline_s = float(convergence_deadline_s)
    if convergence_deadline_s <= 0:
        raise ValueError("cluster_convergence_deadline_s must be > 0")
    kvstore_mode = str(kvstore_mode)
    if kvstore_mode not in _KVSTORE_MODES:
        raise ValueError(
            f"cluster_kvstore must be one of {_KVSTORE_MODES}, got "
            f"{kvstore_mode!r}")
    return (nodes, forward_depth, probe_interval_s, death_threshold,
            convergence_deadline_s, kvstore_mode)


class ClusterNode:
    """One replica: a full Daemon with its own serving runtime and
    kvstore client.  ``alive`` flips exactly once (True -> False) on
    crash; the final front-end snapshot is retained so the cluster
    ledger can close over a corpse."""

    # guarded-by: _lock: alive, final

    def __init__(self, idx: int, name: str, daemon, kv_client=None,
                 policy_sync=None):
        self.idx = idx
        self.name = name
        self.daemon = daemon
        self.kv_client = kv_client
        self.policy_sync = policy_sync
        self._lock = threading.Lock()
        self.alive = True
        self.final: Optional[dict] = None

    def submit(self, rows: np.ndarray) -> int:
        # (unannotated on purpose: inherits the router forwarder's
        # affinity; Daemon.submit is any-affine)
        return self.daemon.submit(rows)

    def probe(self) -> bool:
        # thread-affinity: api
        """In-process liveness: the node is alive and its drain loop
        is running.  (Multi-host deployments swap in the health
        plane's socket probers — the membership layer only needs a
        bool.)"""
        with self._lock:
            if not self.alive:
                return False
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        return rt is not None and rt.running

    def crash(self, cause: str) -> None:
        # thread-affinity: api
        """Simulated node death: the serving runtime is crash-stopped
        (no drain — queued rows become counted recovery drops in this
        node's own ledger) and the node stops probing healthy.
        Idempotent."""
        with self._lock:
            if not self.alive:
                return
            self.alive = False
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        # kill OUTSIDE the node lock: it joins the drain thread, and
        # a probe blocked behind that join would stall the sweep.
        # Wrapped in the same {"front-end": ...} shape stop_serving
        # returns — per_node_stats/ledger read n.final through that
        # key, and a bare runtime snapshot here would make the dead
        # node's verdicts + recovery drops VANISH from every surface
        # between failover and cluster stop
        final = rt.kill(cause) if rt is not None else None
        with self._lock:
            self.final = ({"front-end": final} if final is not None
                          else None)

    def mode(self) -> Optional[str]:
        # thread-affinity: any
        s = self.daemon._serving
        lad = s.get("ladder") if s is not None else None
        return lad.rung if lad is not None else None


class ClusterServing:
    """The cluster serving tier facade: construct -> add endpoints /
    import policy (fan-out + kvstore propagation) -> :meth:`start`
    -> :meth:`submit` from any thread -> :meth:`stop`.

    Every node daemon gets ``daemon._cluster = self`` so the
    per-node surfaces (serving stats Cluster block, GET
    /cluster/status, the ``cilium_cluster_*`` registry series) can
    reach the tier from any node's API socket."""

    def __init__(self, nodes: int = 3, config=None,
                 node_prefix: str = "node"):
        from ..agent.daemon import Daemon, DaemonConfig

        template = config or DaemonConfig()
        (self.n_nodes, self.forward_depth, self.probe_interval_s,
         self.death_threshold, self.convergence_deadline_s,
         self.kvstore_mode) = validate_cluster_config(
            nodes, template.cluster_forward_depth,
            template.cluster_probe_interval_s,
            template.cluster_death_threshold,
            template.cluster_convergence_deadline_s,
            template.cluster_kvstore)
        # -- the shared identity/policy plane ---------------------------
        self._kv_server = None
        self._kv_store = None
        if self.kvstore_mode == "remote":
            from ..kvstore.remote import KVStoreServer, RemoteKVStore

            self._kv_server = KVStoreServer(host="127.0.0.1", port=0)

            def client():
                return RemoteKVStore([self._kv_server.address])
        else:
            from ..kvstore import InMemoryKVStore

            self._kv_store = InMemoryKVStore()

            def client():
                return self._kv_store

        # -- the replicas ----------------------------------------------
        self.nodes: List[ClusterNode] = []
        for i in range(self.n_nodes):
            cfg = dataclasses.replace(template,
                                      node_name=f"{node_prefix}{i}")
            kv = client()
            daemon = Daemon(cfg, kvstore=kv)
            sync = ClusterPolicySync(kv, daemon)
            node = ClusterNode(i, cfg.node_name, daemon,
                               kv_client=(kv if self._kv_server
                                          is not None else None),
                               policy_sync=sync)
            daemon._cluster = self
            self.nodes.append(node)
        self._by_name = {n.name: n for n in self.nodes}
        self._policy_rev = 0
        self.router: Optional[ClusterRouter] = None
        self.failover = FailoverOrchestrator(self)
        self.membership = ClusterMembership(
            self.nodes, self.probe_interval_s, self.death_threshold,
            on_death=self._on_node_death,
            node_registry=self.nodes[0].daemon.node_registry)
        self._started = False
        self._stopped = False
        self._final: Optional[dict] = None
        # per-node span-tracer / event-plane refs, captured at
        # start() (stop_serving clears daemon._serving; ledgers()
        # closes those ledgers post-stop through these)
        self._tracers: Dict[str, object] = {}
        self._eventplanes: Dict[str, object] = {}

    # -- topology ------------------------------------------------------
    def node(self, name: str) -> ClusterNode:
        return self._by_name[name]

    def designated_peer(self, dead_idx: int) -> Optional[ClusterNode]:
        """Next LIVE node in ring order after the dead one — the
        deterministic failover target every test and operator can
        predict."""
        for step in range(1, self.n_nodes):
            cand = self.nodes[(dead_idx + step) % self.n_nodes]
            if cand.alive:
                return cand
        return None

    # -- control plane (fan-out + kvstore propagation) -----------------
    def add_endpoint(self, name: str, ips, labels):
        """Register one logical endpoint on EVERY replica (same id
        everywhere — the router may pin any flow to any node)."""
        eps = [n.daemon.add_endpoint(name, tuple(ips), list(labels))
               for n in self.nodes]
        ids = {ep.id for ep in eps}
        if len(ids) != 1:
            raise ServingError(
                f"endpoint id diverged across replicas: {sorted(ids)}"
                f" (register endpoints in the same order everywhere)")
        return eps[0]

    def policy_import(self, rules) -> int:
        """Publish one ruleset revision through the kvstore; every
        node (the publisher included) applies it exactly once via its
        watch.  Returns the revision — :meth:`wait_policy` blocks on
        cluster-wide convergence."""
        self._policy_rev += 1
        kv = (self.nodes[0].kv_client
              if self._kv_server is not None else self._kv_store)
        publish_policy(kv, self._policy_rev, rules)
        return self._policy_rev

    def wait_policy(self, rev: Optional[int] = None,
                    timeout: Optional[float] = None) -> bool:
        rev = self._policy_rev if rev is None else rev
        timeout = (self.convergence_deadline_s if timeout is None
                   else timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(n.policy_sync.applied_rev >= rev
                   for n in self.nodes if n.alive):
                return True
            time.sleep(0.005)
        return False

    def snapshot_now(self, trigger: str = "cluster") -> None:
        """Fan out a CT snapshot on every live replica — the failover
        replay source.  Production deployments get the same cadence
        from ``ct_snapshot_interval`` + ``Daemon.start()`` (the
        periodic snapshot controller); tests and the bench drive it
        explicitly."""
        for n in self.nodes:
            if n.alive:
                n.daemon.ct_snapshot_now(trigger)

    def wait_identity(self, numeric: int,
                      timeout: Optional[float] = None) -> bool:
        """Block until every live replica's allocator mirrors the
        identity (the kvstore convergence window made testable)."""
        timeout = (self.convergence_deadline_s if timeout is None
                   else timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(n.daemon.allocator.lookup_by_id(numeric)
                   is not None for n in self.nodes if n.alive):
                return True
            time.sleep(0.005)
        return False

    # -- lifecycle -----------------------------------------------------
    def start(self, trace_sample: int = 0, packed: bool = True,
              ring_capacity: int = 1 << 15, drain_every: int = 4,
              span_sample: Optional[int] = None) -> None:
        if self._started:
            raise ServingError("cluster already started")
        for n in self.nodes:
            n.daemon.start_serving(ring_capacity=ring_capacity,
                                   drain_every=drain_every,
                                   trace_sample=trace_sample,
                                   ingress=True, packed=packed,
                                   span_sample=span_sample)
        # retain per-node span-tracer / event-plane references NOW:
        # stop_serving clears daemon._serving, and the everything-on
        # soak gate closes the span and event ledgers AFTER stop
        self._tracers = {
            n.name: n.daemon._serving.get("tracer")
            for n in self.nodes}
        self._eventplanes = {
            n.name: n.daemon._serving.get("eventplane")
            for n in self.nodes}
        self.router = ClusterRouter(self.nodes, self.forward_depth,
                                    on_overflow=self._surface_overflow)
        self.router.start()
        self.membership.start()
        self._started = True

    def submit(self, rows: np.ndarray) -> int:
        # (the cluster tier's enqueue entry; the annotated router
        # hot path is ClusterRouter._route)
        r = self.router
        if r is None:
            raise ServingError("call ClusterServing.start() first")
        return r.submit(rows)

    def stop(self) -> dict:
        """Drain the router and every replica; returns (and retains)
        the final cluster stats with the ledger closed."""
        if self._stopped:
            return self._final or self.stats()
        self.membership.stop()
        if self.router is not None:
            self.router.stop(drain=True)
        for n in self.nodes:
            # a crashed node's stop_serving is idempotent over the
            # corpse: its runtime snapshot (swept queue included)
            # is what the ledger reads
            n.final = n.daemon.stop_serving()
        self._stopped = True
        self._final = self.stats()
        return self._final

    def shutdown(self) -> None:
        self.stop()
        for n in self.nodes:
            if n.policy_sync is not None:
                n.policy_sync.close()
            n.daemon.shutdown()
            if n.kv_client is not None:
                n.kv_client.close()
        if self._kv_server is not None:
            self._kv_server.close()

    # -- death handling -------------------------------------------------
    def _on_node_death(self, name: str, detail: dict) -> None:
        # thread-affinity: api -- membership prober thread
        self.failover.fail_over(name, detail)

    def kill_node(self, name: str) -> None:
        """Crash a node and let the HEALTH path find it (probe
        failures -> death threshold -> failover) — the organic-death
        shape."""
        self.node(name).crash("operator kill_node")

    def fail_node(self, name: str) -> dict:
        """Crash a node and fail it over immediately (deterministic
        test/bench path — no probe latency in the measurement)."""
        t0 = time.monotonic()
        self.node(name).crash("operator fail_node")
        self.membership.declare_dead(name, {
            "cause": "operator fail_node",
            "detect-ms": round((time.monotonic() - t0) * 1e3, 3)})
        recs = self.failover.snapshot()
        return recs[-1] if recs else {}

    # -- shed surfacing -------------------------------------------------
    def _surface_overflow(self, idx: int,
                          rows: Optional[np.ndarray],
                          count: int) -> None:
        # thread-affinity: router, api
        """Router sheds -> REASON_CLUSTER_OVERFLOW metricsmap counts
        + decoded monitor DROP events, on the owning node (or, when
        it died, the first live node — the count must land
        SOMEWHERE operators look)."""
        node = self.nodes[idx]
        if not node.alive:
            node = next((n for n in self.nodes if n.alive), None)
        if node is None:
            return  # cluster-wide corpse: router_overflow holds the
            # exact count; there is no live surface left to decorate
        node.daemon._publish_cluster_drops(rows, count)

    # -- reading --------------------------------------------------------
    def router_overflow_total(self) -> int:
        r = self.router
        return r.router_overflow if r is not None else 0

    def failover_dropped_total(self) -> int:
        r = self.router
        return r.failover_dropped if r is not None else 0

    def failovers_total(self) -> int:
        return len(self.failover.snapshot())

    def live_dead_counts(self):
        live = sum(1 for n in self.nodes if n.alive)
        return live, self.n_nodes - live

    def forward_pending(self) -> int:
        r = self.router
        return r.pending_total() if r is not None else 0

    def summary(self) -> dict:
        """The serving-stats Cluster block: cheap counters only (no
        per-node stats recursion — this renders inside every node's
        own serving_stats)."""
        live, dead = self.live_dead_counts()
        recs = self.failover.snapshot()
        out = {
            "nodes": self.n_nodes,
            "live": live,
            "dead": dead,
            "kvstore": self.kvstore_mode,
            "router": (self.router.snapshot()
                       if self.router is not None else None),
            "failovers": len(recs),
        }
        if recs:
            out["last-failover"] = recs[-1]
        return out

    def per_node_stats(self) -> Dict[str, dict]:
        out = {}
        for n in self.nodes:
            if n.final is not None:
                fe = n.final.get("front-end")
            else:
                s = n.daemon._serving
                rt = s.get("runtime") if s is not None else None
                fe = rt.snapshot() if rt is not None else None
            out[n.name] = {
                "alive": n.alive,
                "mode": n.mode(),
                "front-end": fe,
            }
        return out

    def ledger(self) -> dict:
        """The cluster-wide no-silent-loss ledger.  ``exact`` is
        meaningful after :meth:`stop` (while running, rows in
        forward/admission queues and in flight sit outside every
        counter, mirroring the node-level ledger's contract)."""
        r = self.router
        submitted = r.submitted if r is not None else 0
        overflow = r.router_overflow if r is not None else 0
        fo_dropped = r.failover_dropped if r is not None else 0
        pending = r.pending_total() if r is not None else 0
        per_node = 0
        for name, st in self.per_node_stats().items():
            fe = st.get("front-end")
            if fe is None:
                continue
            ft = fe.get("fault-tolerance", {})
            per_node += (fe.get("verdicts", 0) + fe.get("shed", 0)
                         + ft.get("recovery-dropped", 0))
        accounted = per_node + overflow + fo_dropped + pending
        return {
            "submitted": submitted,
            "per-node-accounted": per_node,
            "router-overflow": overflow,
            "failover-dropped": fo_dropped,
            "forward-pending": pending,
            "accounted": accounted,
            "exact": submitted == accounted,
        }

    def ledgers(self) -> dict:
        """EVERY no-silent-loss ledger the tier runs, closed in one
        read — the everything-on soak gate's assertion surface
        (ISSUE 12).  Five ledgers:

        - ``packet`` (per node): submitted == verdicts + shed +
          recovery_dropped (exact after stop);
        - ``event`` (per node): event-plane windows submitted ==
          joined + dropped;
        - ``span`` (per node, when tracing armed): spans started ==
          completed + dropped;
        - ``agg`` (per node): analytics batches submitted ==
          ingested + dropped;
        - ``cluster``: the router-level ledger (:meth:`ledger`).

        ``exact`` is the conjunction.  Meaningful after
        :meth:`stop`, like every in-flight-exclusive ledger here."""
        out: Dict[str, dict] = {"packet": {}, "event": {},
                                "span": {}, "agg": {}}
        ok = True
        for name, st in self.per_node_stats().items():
            fe = st.get("front-end")
            if fe is not None:
                ft = fe.get("fault-tolerance", {})
                acc = (fe.get("verdicts", 0) + fe.get("shed", 0)
                       + ft.get("recovery-dropped", 0))
                exact = fe.get("submitted", 0) == acc
                out["packet"][name] = {
                    "submitted": fe.get("submitted", 0),
                    "accounted": acc, "exact": exact}
                ok = ok and exact
        for name, w in getattr(self, "_eventplanes", {}).items():
            if w is None:
                continue
            ev = w.stats()
            exact = ev["windows-submitted"] == (
                ev["windows-joined"] + ev["windows-dropped"])
            out["event"][name] = {
                "submitted": ev["windows-submitted"],
                "joined": ev["windows-joined"],
                "dropped": ev["windows-dropped"], "exact": exact}
            ok = ok and exact
        for name, tr in getattr(self, "_tracers", {}).items():
            if tr is None:
                continue
            ts = tr.stats()
            exact = ts["started"] == (ts["completed"]
                                      + ts["dropped"])
            out["span"][name] = {
                "started": ts["started"],
                "completed": ts["completed"],
                "dropped": ts["dropped"], "exact": exact}
            ok = ok and exact
        for n in self.nodes:
            ag = n.daemon.analytics.stats()
            exact = ag["batches-submitted"] == (
                ag["batches-ingested"] + ag["batches-dropped"])
            out["agg"][n.name] = {
                "submitted": ag["batches-submitted"],
                "ingested": ag["batches-ingested"],
                "dropped": ag["batches-dropped"], "exact": exact}
            ok = ok and exact
        out["cluster"] = self.ledger()
        out["exact"] = ok and bool(out["cluster"]["exact"])
        return out

    def stats(self) -> dict:
        return {
            "cluster": self.summary(),
            "membership": self.membership.statuses(),
            "per-node": self.per_node_stats(),
            "ledger": self.ledger(),
            "failovers": self.failover.snapshot(),
        }

    def status(self) -> dict:
        """GET /cluster/status — the operator view (`cilium-tpu
        cluster status`)."""
        return self.stats()


def start_cluster_serving(nodes: int = 3, config=None,
                          trace_sample: int = 0, packed: bool = True,
                          ring_capacity: int = 1 << 15,
                          drain_every: int = 4,
                          node_prefix: str = "node") -> ClusterServing:
    """Build AND start a cluster serving tier in one call (the
    ``Daemon.start_serving`` analogue one level up): N replicas, one
    shared kvstore plane, the flow-affine router, membership, and
    failover — ready for :meth:`ClusterServing.submit`."""
    c = ClusterServing(nodes=nodes, config=config,
                       node_prefix=node_prefix)
    c.start(trace_sample=trace_sample, packed=packed,
            ring_capacity=ring_capacity, drain_every=drain_every)
    return c
