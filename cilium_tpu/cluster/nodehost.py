"""The process-per-node cluster worker: one OS process hosting a full
``Daemon`` + serving runtime behind two sockets.

Reference: upstream cilium's horizontal story is one agent PROCESS
per node — nodes share nothing but the kvstore, which is why adding
nodes adds capacity.  PR 8's threads-as-nodes replicas shared one
GIL (DIVERGENCES #26: three "nodes" were slower than one); this
module is the honest shape (ISSUE 13): ``ClusterServing`` in
``cluster_mode="process"`` spawns one of these workers per node, and
N nodes run on N kernels-worth of cores.

Topology (all loopback TCP, ``cluster/transport.py`` framing):

- CONTROL channel — length-prefixed JSON frames, strict
  request/response (the parent serializes callers per node): daemon
  bring-up, endpoint registration, warm-up, serving lifecycle,
  stats/ledger reads, CT snapshot/merge (the failover and scale-out
  migration path), incident/drop surfacing on behalf of the router.
- OBS channel (ISSUE 14) — the same strict req/resp loop on a THIRD
  socket + its own worker thread, carrying the relay's scrape and
  sysdump ops.  Isolation is the point: a slow or timed-out scrape
  desyncs (and so breaks) only the obs stream — membership probes
  ride the control channel untouched, so observability can never
  get a healthy node declared dead.
- DATA channel — length-prefixed binary row frames (packed
  ``[n, 4]`` u32 when the chunk is pack-eligible, wide
  ``[n, N_COLS]`` otherwise).  Legacy unsequenced frames are each
  answered by a fixed-size ACK; SEQUENCED frames (the pipelined
  channel, ISSUE 17) are answered CUMULATIVELY — one ack per
  ``cluster_ack_every`` frames or ``cluster_ack_flush_ms`` of
  quiet, carrying the highest contiguous sequence admitted plus the
  node's RUNNING packet ledger (submitted, verdicts, shed,
  recovery_dropped).  The parent retains the newest ack; a
  SIGKILLed worker's last ack is its final word, which is exactly
  what closes the cluster ledger over a corpse
  (``cluster/process.py`` + ``router.account_crash_loss``).

Identities and policy are NOT pushed over these channels: the worker
runs its own ``RemoteKVStore`` client + ``ClusterPolicySync`` against
the cluster's kvstore server, exactly like PR 8 replicas — the
control channel only answers "which revision have you applied"
(``wait_policy`` / ``wait_identity`` poll it).

THREAD AFFINITY: the data-channel reader is the worker's ``transport``
thread (a CTA003 hot domain — recv/decode/submit/ack, nothing else);
the control loop is ``api``; the ack-coalescer's flush-on-idle timer
is the ``ackflush`` seam (ISSUE 17, CTA002 vocabulary) — it exists so
a sub-``ack_every`` trickle still gets acknowledged within the flush
window instead of waiting for frames that never come.
"""

from __future__ import annotations

import select
import socket
import threading
import time
from typing import Optional, Tuple

import numpy as np

from ..encryption import DecryptError
from ..infra.faults import InjectedFault
from .transport import (decode_rows_seq, pack_ack, pack_crypto_reject,
                        pack_cum_ack, recv_frame, recv_json_frame,
                        rows_from_b64, rows_to_b64, send_frame,
                        send_json_frame, shutdown_close)

__all__ = ["node_host_main", "connect_channels", "OP_TIMEOUTS"]

# The per-op control-RPC timeout bound, in seconds — the parent's
# ``ProcessNode.call`` defaults to this table, and the CTA011 checker
# (``analysis/nodehost_lint.py``) statically requires EVERY ``_OPS``
# entry to have a positive bound here plus a test referencing the op
# by name: an unbounded control RPC is a wedged-worker hang the
# membership prober cannot see past, and an untested op is a dead
# letter the next refactor silently breaks.  READY-class ops (those
# that may legitimately wait out a worker's whole jax bring-up or a
# full CT ship) get the long bound; reads get short ones.
OP_TIMEOUTS = {
    "ready": 300.0,
    "probe": 5.0,
    "add_endpoint": 60.0,
    "policy_rev": 10.0,
    "has_identity": 10.0,
    "start_node": 300.0,
    "warm": 300.0,
    "start_serving": 300.0,
    "front_end": 30.0,
    "stop_serving": 300.0,
    "metrics": 30.0,
    "metricsmap": 30.0,
    "map_pressure": 30.0,
    "compile_stats": 30.0,
    "ct_snapshot": 300.0,
    "ct_merge": 300.0,
    "record_incident": 30.0,
    "publish_drops": 30.0,
    "obs_scrape": 30.0,
    "sysdump": 60.0,
    "slo": 30.0,
    "history": 30.0,
    "ack_flush": 10.0,
    "rotate_epoch": 30.0,
    "shutdown": 30.0,
}


def _jsonable(obj):
    """Recursively coerce numpy scalars/arrays out of a stats dict —
    control responses must serialize without caring which surface
    built them."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def connect_channels(host: str, port: int, name: str, token: str,
                     pubkey: Optional[str] = None
                     ) -> Tuple[socket.socket, socket.socket,
                                socket.socket]:
    """Dial the parent's listener three times (control, data, obs),
    each introducing itself with a hello frame — the parent matches
    hellos to its ``ProcessNode`` handles (spawn order is not
    arrival order).  ``pubkey`` (hex) rides the hello when the data
    channel is encrypted (ISSUE 18): the spawn handshake IS the key
    exchange — the parent pins this worker's X25519 pubkey before
    the first sealed frame flows.  The OBS channel (ISSUE 14)
    carries the relay's scrape/sysdump ops on its own socket +
    worker thread so a slow or timed-out scrape can NEVER desync
    the control stream the membership prober depends on —
    observability must not be able to get a healthy node declared
    dead."""
    socks = []
    for role in ("ctrl", "data", "obs"):
        s = socket.create_connection((host, port), timeout=30.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = {"hello": True, "node": name,
                 "role": role, "token": token}
        if pubkey is not None:
            hello["pubkey"] = pubkey
        send_json_frame(s, hello)
        socks.append(s)
    return socks[0], socks[1], socks[2]


class _NodeHost:
    """The worker's brain: owns the daemon and serves both channels.
    Single-process single-instance; built by :func:`node_host_main`."""

    def __init__(self, name: str, cfg_fields: dict, kv_addr,
                 crypto_kp=None, parent_pub: Optional[str] = None,
                 epoch: int = 0):
        # imports INSIDE the worker: a spawn child pays its own jax
        # init here, off the parent's critical path
        from ..agent.daemon import Daemon, DaemonConfig
        from ..kvstore.remote import RemoteKVStore
        from .membership import ClusterPolicySync

        self.name = name
        self.kv = RemoteKVStore([tuple(kv_addr)])
        cfg_fields = dict(cfg_fields)
        if crypto_kp is not None:
            # the encrypted data channel forces the node-encryption
            # plane ON with the SAME keypair the hello advertised:
            # the registry-published pubkey and the data-channel key
            # are one identity (key desync between the two planes
            # would be undebuggable)
            cfg_fields["enable_encryption"] = True
        self.daemon = Daemon(DaemonConfig(**cfg_fields),
                             kvstore=self.kv,
                             encryption_keypair=crypto_kp)
        self.policy_sync = ClusterPolicySync(self.kv, self.daemon)
        # -- encrypted data channel, worker half (ISSUE 18) ---------
        self._crypto = None
        self._crypto_grace_s = float(
            cfg_fields.get("cluster_epoch_grace_s", 2.0))
        if crypto_kp is not None and parent_pub is not None:
            from ..encryption import EncryptedChannel

            # epoch in the CONSTRUCTOR, not via rotate(): a
            # scale-out worker joining mid-history starts at the
            # cluster's current keys with zero rotations on its own
            # books
            self._crypto = EncryptedChannel(
                crypto_kp, bytes.fromhex(parent_pub),
                epoch=int(epoch))
        # data frames RECEIVED (transport thread only) — the NACK
        # ordinal space: TCP ordering makes our Nth receipt the
        # parent's Nth send, which is how a reject names a frame
        # whose sealed seq it cannot read
        self._rx_frames = 0
        self._crypto_rejected = 0  # transport thread writes; ops read
        self._crypto_replays = 0
        self._ctrl: Optional[socket.socket] = None
        self._data: Optional[socket.socket] = None
        self._obs: Optional[socket.socket] = None
        self._data_thread: Optional[threading.Thread] = None
        self._obs_thread: Optional[threading.Thread] = None
        self._final: Optional[dict] = None
        self._stopping = threading.Event()
        # -- ack coalescer (ISSUE 17): pending cumulative-ack state
        # for sequenced frames.  The ledger snapshot is taken on the
        # data thread RIGHT AFTER each admit, so a flush (from either
        # thread) sends counters that cover exactly the frames up to
        # _ack_seq — never rows the parent still holds in its window
        # (double-count would break the crash ledger).
        # guarded-by: _ack_lock: _ack_seq, _ack_frames, _ack_admitted,
        # guarded-by: _ack_lock: _ack_ledger, _ack_echoes, _acks_sent,
        # guarded-by: _ack_lock: _acks_coalesced, _frames_acked
        self._ack_lock = threading.Lock()
        self._ack_seq = 0
        self._ack_frames = 0
        self._ack_admitted = 0
        self._ack_ledger = (0, 0, 0, 0)
        self._ack_echoes: list = []
        self._acks_sent = 0
        self._acks_coalesced = 0
        self._frames_acked = 0
        self._ack_thread: Optional[threading.Thread] = None

    # -- data channel --------------------------------------------------
    def _data_loop(self) -> None:
        # thread-affinity: transport -- the worker's row hot path:
        # recv, decode, submit, ack.  Nothing else belongs here.
        from ..core.packets import unpack_rows_np

        sock = self._data
        runtime = self.daemon._serving["runtime"]
        st = runtime.stats
        ack_every = max(int(self.daemon.config.cluster_ack_every), 1)
        ch = self._crypto
        try:
            while True:
                payload = recv_frame(sock)
                if payload is None:
                    break
                if ch is not None:
                    # ISSUE 18: open/verify BEFORE decode — nothing
                    # unauthenticated ever reaches decode_rows or
                    # runtime.submit.  A failure is COUNTED and
                    # answered with the typed reject record (by
                    # receipt ordinal — the sealed seq is
                    # unreadable), never a worker death: the typed
                    # catch comes before the loop's generic
                    # channel-teardown handler.
                    self._rx_frames += 1
                    try:
                        payload = ch.open(payload)
                    except (DecryptError, InjectedFault) as exc:
                        reason = getattr(exc, "reason", "fault")
                        self._crypto_rejected += 1
                        if reason == "replay":
                            self._crypto_replays += 1
                        self._send_reject(self._rx_frames, reason)
                        continue
                rows, packed_meta, trace, seq = \
                    decode_rows_seq(payload)
                # ISSUE 14 span stitching: a traced frame gets its
                # worker-side stage stamps — recv (frame decoded)
                # and admit (runtime.submit returned) — echoed on
                # the ack.  One is-None branch when tracing is off.
                t_recv = time.monotonic() if trace is not None \
                    else 0.0
                if packed_meta is not None:
                    ep, dirn = packed_meta
                    rows = unpack_rows_np(rows, ep, dirn)
                admitted = runtime.submit(rows)
                # ledger counters read AFTER submit returned, so
                # this ack's `submitted` includes this frame's rows
                # — the invariant the parent's crash accounting
                # stands on.  Unlocked int reads (CPython-atomic,
                # monotonic): worst case the ack understates
                # verdicts by an in-flight batch, which the
                # crash-loss term absorbs by design
                echo = ((trace[0], t_recv, time.monotonic())
                        if trace is not None else None)
                if seq is None:
                    # legacy sync frame: the PR 13 per-frame ack,
                    # byte-identical when the channel is plaintext
                    # (window=1 degenerates to it); sealed when
                    # encrypted.  A seal fault here propagates: the
                    # parent is blocked on THIS reply, so the only
                    # contained answer is the channel-death path the
                    # pipelined tier already proves exact (EOF ->
                    # forwarder requeue -> counted by failover/stop)
                    blob = pack_ack(admitted, st.submitted,
                                    st.verdicts, st.shed,
                                    st.recovery_dropped, trace=echo)
                    if ch is not None:
                        blob = ch.seal(blob)
                    send_frame(sock, blob)
                    continue
                # sequenced frame (ISSUE 17): accumulate toward a
                # cumulative ack.  TCP delivers in order, so the
                # newest seq IS the highest contiguous one.  The
                # ledger snapshot taken here — on this thread, after
                # this admit — is what a flush sends for seq: it
                # covers exactly frames 1..seq, no more (a frame
                # admitted after it would inflate `submitted` past
                # what the parent retires, double-counting rows the
                # failover path also requeues).
                with self._ack_lock:
                    self._ack_seq = seq
                    self._ack_frames += 1
                    self._ack_admitted += admitted
                    self._ack_ledger = (st.submitted, st.verdicts,
                                        st.shed, st.recovery_dropped)
                    if echo is not None:
                        self._ack_echoes.append(echo)
                    do_flush = self._ack_frames >= ack_every
                if not do_flush:
                    # flush-on-drain: if the channel has NOTHING
                    # more buffered, ack NOW instead of riding the
                    # idle timer — at low load (one frame at a time)
                    # every frame acks immediately, sync-like, while
                    # a loaded channel (next frame already in the
                    # socket buffer) keeps coalescing at the cadence.
                    # The coalescer must not buy throughput by
                    # selling low-load latency
                    rd, _, _ = select.select([sock], [], [], 0)
                    do_flush = not rd
                if do_flush:
                    self._flush_acks()
                if self._ack_thread is None:
                    self._start_ack_flusher()
        except Exception:  # noqa: BLE001 — torn frame, dead fd, OR
            # a failed decode/submit/ack: the channel contract is
            # dead either way.  CLOSE the socket before exiting —
            # a silently-dead reader with an open fd would wedge
            # the parent's forwarder in its ack wait forever (the
            # close delivers EOF, the forwarder requeues the
            # in-flight chunk and parks suspect, and the loss is
            # counted by failover/stop instead of hidden)
            pass
        finally:
            shutdown_close(sock)

    def _flush_acks(self) -> None:
        # thread-affinity: transport, ackflush -- both the data
        # thread (ack_every reached) and the flush timer call this;
        # build + send under _ack_lock so two flushes can never put
        # their acks on the wire out of sequence order (the parent's
        # retire-up-to would regress)
        with self._ack_lock:
            if self._ack_frames == 0:
                return
            blob = pack_cum_ack(self._ack_seq, self._ack_frames,
                                self._ack_admitted, *self._ack_ledger,
                                echoes=tuple(self._ack_echoes))
            if self._crypto is not None:
                # seal BEFORE resetting the pending state: an
                # injected seal fault costs one flush, not one ack —
                # the counters stay pending and the next flush (or
                # the idle timer) sends a cumulative ack that covers
                # everything.  Deferred, never lost.
                try:
                    blob = self._crypto.seal(blob)
                except InjectedFault:
                    return
            self._acks_sent += 1
            self._acks_coalesced += self._ack_frames - 1
            self._frames_acked += self._ack_frames
            self._ack_frames = 0
            self._ack_admitted = 0
            self._ack_echoes = []
            send_frame(self._data, blob)

    def _send_reject(self, ordinal: int, reason: str) -> None:
        # thread-affinity: transport -- the data loop's reject
        # answer; serialized under _ack_lock with the coalescer's
        # flushes so a reject and a cumulative ack can never
        # interleave mid-wire
        blob = pack_crypto_reject(ordinal, reason)
        try:
            wire = self._crypto.seal(blob)
        except InjectedFault:
            # seal fault on the reject itself: ship it RAW — the
            # parent's open() fails it "short" (counted, outside the
            # desync class), and in sync mode the reply unblocks the
            # forwarder, which is the one job this frame must do
            wire = blob
        with self._ack_lock:
            send_frame(self._data, wire)

    def _start_ack_flusher(self) -> None:
        # thread-affinity: transport -- spawned lazily by the data
        # loop on the first sequenced frame; a sync-only channel
        # never pays for the thread
        self._ack_thread = threading.Thread(
            target=self._ack_flush_loop, daemon=True,
            name=f"nodehost-ackflush-{self.name}")
        self._ack_thread.start()

    def _ack_flush_loop(self) -> None:
        # thread-affinity: ackflush -- the flush-on-idle timer
        # (ISSUE 17): any pending cumulative ack goes on the wire
        # within cluster_ack_flush_ms even when the frame trickle
        # stays below ack_every — bounded ack latency is what keeps
        # low-load forward latency near the sync baseline
        flush_s = max(
            float(self.daemon.config.cluster_ack_flush_ms), 0.1) / 1e3
        while not self._stopping.is_set():
            time.sleep(flush_s)
            try:
                self._flush_acks()
            except Exception:  # noqa: BLE001 — dead data fd: the
                # channel is gone; the data loop (or close()) owns
                # the teardown, the timer just stops
                return

    # -- control ops ---------------------------------------------------
    def _op_ready(self, req: dict) -> dict:
        return {"ok": True, "node": self.name}

    def _op_probe(self, req: dict) -> dict:
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        return {"ok": rt is not None and rt.running}

    def _op_add_endpoint(self, req: dict) -> dict:
        ep = self.daemon.add_endpoint(req["name"], tuple(req["ips"]),
                                      list(req["labels"]))
        return {"id": int(ep.id)}

    def _op_policy_rev(self, req: dict) -> dict:
        return {"rev": int(self.policy_sync.applied_rev)}

    def _op_has_identity(self, req: dict) -> dict:
        ident = self.daemon.allocator.lookup_by_id(int(req["numeric"]))
        return {"ok": ident is not None}

    def _op_start_node(self, req: dict) -> dict:
        self.daemon.start()
        return {"ok": True}

    def _op_warm(self, req: dict) -> dict:
        """The bring-up warm discipline: the ONE shared recipe
        (``cluster.warm_serving_session`` — compile-key statics
        mirrored, packed+wide × full/masked), run on THIS worker's
        own jit cache (process caches don't share)."""
        from . import warm_serving_session

        ok = warm_serving_session(
            self.daemon, int(req["bucket"]), int(req.get("ep", 0)),
            int(req.get("trace_sample", 0)),
            int(req.get("ring_capacity", 1 << 15)))
        return {"ok": True, "packed": ok}

    def _op_start_serving(self, req: dict) -> dict:
        kw = dict(req.get("kwargs") or {})
        kw["ingress"] = True
        self.daemon.start_serving(**kw)
        self._data_thread = threading.Thread(
            target=self._data_loop, daemon=True,
            name=f"nodehost-data-{self.name}")
        self._data_thread.start()
        return {"ok": True}

    def _node_ledgers(self) -> dict:
        """The per-node halves of ``ClusterServing.ledgers()``:
        event / span / agg, read from the live serving session (or
        zeros when none)."""
        out = {}
        s = self.daemon._serving
        w = s.get("eventplane") if s is not None else None
        if w is not None:
            out["event"] = _jsonable(w.stats())
        tr = s.get("tracer") if s is not None else None
        if tr is not None:
            out["span"] = _jsonable(tr.stats())
        out["agg"] = _jsonable(self.daemon.analytics.stats())
        return out

    def _crypto_block(self) -> Optional[dict]:
        """The worker half of the encrypted channel's status surface
        (COUNTERS AND EPOCH ONLY — key material never leaves the
        channel object; CTA013 pins that)."""
        ch = self._crypto
        if ch is None:
            return None
        return {"epoch": ch.epoch, "sealed": ch.sealed,
                "opened": ch.opened,
                "rejected": self._crypto_rejected,
                "replays": self._crypto_replays,
                "rx-frames": self._rx_frames,
                "rotations": ch.rotations}

    def _op_front_end(self, req: dict) -> dict:
        if self._final is not None:
            return {"front-end": self._final.get("front-end"),
                    "ledgers": self._final.get("ledgers"),
                    "mode": self._final.get("mode"),
                    "l7": self._final.get("l7"),
                    "crypto": self._crypto_block()}
        s = self.daemon._serving
        rt = s.get("runtime") if s is not None else None
        lad = s.get("ladder") if s is not None else None
        l7 = self.daemon._l7plane
        return {
            "front-end": (_jsonable(rt.snapshot())
                          if rt is not None else None),
            "ledgers": self._node_ledgers(),
            "mode": lad.rung if lad is not None else None,
            "l7": (_jsonable(l7.stats()) if l7 is not None
                   else None),
            "crypto": self._crypto_block(),
        }

    def _op_stop_serving(self, req: dict) -> dict:
        # ledgers captured BEFORE stop_serving clears daemon._serving
        # (the everything-on gate closes them post-stop)
        ledgers = self._node_ledgers()
        s = self.daemon._serving
        lad = s.get("ladder") if s is not None else None
        mode = lad.rung if lad is not None else None
        final = self.daemon.stop_serving()
        self._final = {
            "front-end": _jsonable((final or {}).get("front-end")),
            "ledgers": ledgers,
            "mode": mode,
            "l7": _jsonable((final or {}).get("l7")),
            "crypto": self._crypto_block(),
        }
        return dict(self._final)

    def _op_metrics(self, req: dict) -> dict:
        """The worker's SELF-DESCRIBING metric surface: the full
        registry exposition text (ISSUE 14 — this op used to return
        the raw unlabeled metricsmap array, which made the worker's
        richest subsystem invisible behind the control channel; the
        raw array moved to the precisely-named ``metricsmap`` op for
        the CT-continuity proofs that genuinely want the decoded
        device counters)."""
        return {"text": self.daemon.registry.render()}

    def _op_metricsmap(self, req: dict) -> dict:
        return {"metrics": np.asarray(
            self.daemon.loader.metrics()).tolist()}

    def _op_obs_scrape(self, req: dict) -> dict:
        """One relay scrape — ``Daemon.obs_scrape_snapshot`` holds
        the one snapshot definition shared with thread-mode
        ``ClusterNode.obs_scrape``."""
        return _jsonable(self.daemon.obs_scrape_snapshot(
            cursor=int(req.get("cursor", 0)),
            flows=int(req.get("flows", 512)),
            top=int(req.get("top", 16))))

    def _op_sysdump(self, req: dict) -> dict:
        """Ship this worker's flight-recorder bundle (size-bounded,
        assembled in memory — works without a sysdump dir) for the
        parent's cluster sysdump archive."""
        return {"bundle": _jsonable(
            self.daemon.flightrec.collect_bundle(
                trigger=str(req.get("trigger", "cluster-sysdump"))))}

    def _op_slo(self, req: dict) -> dict:
        """This worker's SLO verdict — ``Daemon.slo_snapshot`` is the
        one node-stamped definition shared with thread-mode
        ``ClusterNode.slo``; the relay merges these into the
        cluster-wide verdict."""
        return _jsonable(self.daemon.slo_snapshot())

    def _op_history(self, req: dict) -> dict:
        """Windowed metrics history from this worker's ring —
        ``Daemon.history_snapshot`` is the shared definition."""
        series = req.get("series")
        return _jsonable(self.daemon.history_snapshot(
            series=list(series) if series is not None else None,
            since=float(req.get("since", 0.0))))

    def _op_map_pressure(self, req: dict) -> dict:
        return {"pressure": _jsonable(
            self.daemon.loader.map_pressure(self.daemon._now()))}

    def _op_compile_stats(self, req: dict) -> dict:
        return self.daemon.loader.compile_log.dispatch_summary()

    def _op_ct_snapshot(self, req: dict) -> dict:
        """Take + retain a CT snapshot and SHIP the rows to the
        parent — the parent-side replica is the failover replay
        source once SIGKILL has erased this process."""
        self.daemon.ct_snapshot_now(req.get("trigger", "cluster"))
        rows = self.daemon._ct_snap["rows"]
        return {"rows": rows_to_b64(rows)}

    def _op_ct_merge(self, req: dict) -> dict:
        """Merge foreign CT rows (a dead peer's replayed snapshot, or
        a scale-out donor's moved slots) with the live table — the
        PR 3 snapshot+concat+restore idiom."""
        rows = rows_from_b64(req["rows"])
        merged = np.concatenate([
            self.daemon.loader.ct_snapshot(), np.asarray(rows)])
        self.daemon.loader.ct_restore(merged)
        return {"merged": int(len(rows))}

    def _op_record_incident(self, req: dict) -> dict:
        self.daemon.record_incident(req["kind"], dict(req["rec"]))
        return {"ok": True}

    def _op_publish_drops(self, req: dict) -> dict:
        rows = (rows_from_b64(req["rows"])
                if req.get("rows") is not None else None)
        self.daemon._publish_cluster_drops(rows, int(req["count"]))
        return {"ok": True}

    def _op_ack_flush(self, req: dict) -> dict:
        """Force the ack coalescer to flush NOW and report its
        counters — the parent's drain paths (stop, scale-in quiesce)
        use it to collapse the flush-timer tail, and the stats ride
        ``transport_stats`` into the cluster exposition."""
        self._flush_acks()
        with self._ack_lock:
            return {"acks-sent": self._acks_sent,
                    "acks-coalesced": self._acks_coalesced,
                    "frames-acked": self._frames_acked}

    def _op_rotate_epoch(self, req: dict) -> dict:
        """The worker half of the cluster-wide key rotation
        (ISSUE 18), called FIRST (worker-first ordering): flush any
        pending cumulative ack under the OLD epoch, rotate the data
        channel (old epoch parked in its grace window so the
        parent's in-flight frames still open), and rotate the
        daemon's node-encryption plane to keep the registry epoch in
        step.  The control-channel ack IS the per-node rotation
        ack."""
        if self._crypto is None:
            raise ValueError(
                "rotate_epoch needs cluster_encrypt=True")
        epoch = int(req["epoch"])
        grace = float(req.get("grace_s", self._crypto_grace_s))
        self._flush_acks()
        self._crypto.rotate(epoch, grace_s=grace)
        if self.daemon.encryption is not None:
            self.daemon.encryption.rotate(epoch, grace_s=grace)
        return {"ok": True, "epoch": epoch}

    def _op_shutdown(self, req: dict) -> dict:
        self._stopping.set()
        return {"ok": True}

    _OPS = {
        "ready": _op_ready,
        "probe": _op_probe,
        "add_endpoint": _op_add_endpoint,
        "policy_rev": _op_policy_rev,
        "has_identity": _op_has_identity,
        "start_node": _op_start_node,
        "warm": _op_warm,
        "start_serving": _op_start_serving,
        "front_end": _op_front_end,
        "stop_serving": _op_stop_serving,
        "metrics": _op_metrics,
        "metricsmap": _op_metricsmap,
        "obs_scrape": _op_obs_scrape,
        "sysdump": _op_sysdump,
        "slo": _op_slo,
        "history": _op_history,
        "map_pressure": _op_map_pressure,
        "compile_stats": _op_compile_stats,
        "ct_snapshot": _op_ct_snapshot,
        "ct_merge": _op_ct_merge,
        "record_incident": _op_record_incident,
        "publish_drops": _op_publish_drops,
        "ack_flush": _op_ack_flush,
        "rotate_epoch": _op_rotate_epoch,
        "shutdown": _op_shutdown,
    }

    # -- the op loops ---------------------------------------------------
    # (named control_loop, not serve: the callgraph name-match
    # fallback would otherwise bind loader.serve call sites here)
    def _serve_ops(self, sock: socket.socket) -> None:
        # thread-affinity: api -- one strict request/response loop;
        # runs on the control thread AND (a second instance) on the
        # obs thread — the op table is shared, the sockets are not
        while not self._stopping.is_set():
            req = recv_json_frame(sock)
            if req is None:
                break  # peer hung up
            op = self._OPS.get(req.get("op"))
            if op is None:
                send_json_frame(sock, {
                    "e": f"unknown op {req.get('op')!r}"})
                continue
            try:
                resp = op(self, req)
            except Exception as exc:  # noqa: BLE001 — surface to
                # the parent, keep serving (its retry/abandon call)
                resp = {"e": f"{type(exc).__name__}: {exc}"}
            send_json_frame(sock, resp)

    def _obs_loop(self) -> None:
        # thread-affinity: api -- the worker's OBS plane: scrape and
        # sysdump ops on their own socket + thread, so a slow scrape
        # can neither desync the control stream nor park a probe
        # behind it (observability-induced node death — ISSUE 14
        # review finding).  A dead obs loop degrades scraping only;
        # the worker serves on.
        try:
            self._serve_ops(self._obs)
        except Exception:  # noqa: BLE001 — torn frame/dead fd: the
            pass  # obs channel is gone, nothing else is
        finally:
            shutdown_close(self._obs)

    def control_loop(self, ctrl: socket.socket, data: socket.socket,
                     obs: socket.socket) -> None:
        # thread-affinity: api -- the worker's control plane
        self._ctrl, self._data, self._obs = ctrl, data, obs
        self._obs_thread = threading.Thread(
            target=self._obs_loop, daemon=True,
            name=f"nodehost-obs-{self.name}")
        self._obs_thread.start()
        try:
            self._serve_ops(ctrl)
        finally:
            self.close()

    def close(self) -> None:
        self._stopping.set()
        shutdown_close(self._data)
        shutdown_close(self._obs)
        shutdown_close(self._ctrl)
        try:
            self.policy_sync.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        try:
            self.daemon.shutdown()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.kv.close()
        except Exception:  # noqa: BLE001
            pass


def node_host_main(host: str, port: int, token: str, name: str,
                   cfg_fields: dict, kv_addr,
                   parent_pub: Optional[str] = None,
                   epoch: int = 0) -> None:
    """The spawn target: dial home, build the daemon world, serve
    until the parent says shutdown (or the control channel dies —
    an orphaned worker must not outlive its cluster).  When
    ``parent_pub`` (hex) is given the data channel is ENCRYPTED
    (ISSUE 18): the worker mints its own X25519 keypair here — the
    private key never crosses a process boundary — advertises the
    pubkey in its hellos, and joins at the cluster's current key
    ``epoch``."""
    kp = None
    if parent_pub is not None:
        from ..encryption import NodeKeypair

        kp = NodeKeypair()
    ctrl, data, obs = connect_channels(
        host, port, name, token,
        pubkey=(kp.public.hex() if kp is not None else None))
    try:
        node = _NodeHost(name, cfg_fields, kv_addr, crypto_kp=kp,
                         parent_pub=parent_pub, epoch=int(epoch))
    except Exception as exc:  # noqa: BLE001 — a worker that cannot
        # build its daemon reports WHY before dying (the parent's
        # first RPC would otherwise just see EOF)
        try:
            send_json_frame(ctrl, {
                "e": f"worker bring-up failed: "
                     f"{type(exc).__name__}: {exc}"})
        except OSError:
            pass
        shutdown_close(data)
        shutdown_close(obs)
        shutdown_close(ctrl)
        raise
    node.control_loop(ctrl, data, obs)
