"""Node failover: drain a dead replica's flows onto a designated
peer with CT continuity.

Reference: upstream cilium survives node loss because connection
state lives WITH the flow's owner and ECMP re-steers; a stateful
serving tier must migrate that state explicitly.  This module extends
the PR 3 demotion proof (sharded -> single CT carry via snapshot +
restore) to NODE DEATH — and, since ISSUE 13, to REAL process death:

1. the dead node is crash-stopped.  A thread-mode replica's runtime
   is killed in-process (queued rows become counted recovery drops in
   ITS OWN ledger); a process-mode replica takes a real SIGKILL — no
   goodbye, no final snapshot — and its last data-channel ACK
   becomes its final ledger word, with the admitted-but-unresolved
   delta counted ``crash_dropped`` on the router
   (``ProcessNode.take_crash_loss`` ->
   ``router.account_crash_loss``).  A crash loses work; it never
   hides work;
2. a designated peer is chosen (next live node in ring order — the
   same deterministic choice a rendezvous hash would make for the
   freed slots);
3. the dead node's latest retained CT snapshot is REPLAYED into the
   peer, MERGED with the peer's own live CT
   (``node.ct_rows_for_failover()`` -> ``peer.merge_ct(rows)``:
   snapshot + concat + restore; flow-affine routing guarantees the
   two tables are disjoint, and the device re-hash resolves any
   residue) — so a reply for a connection established on the dead
   node passes the peer's egress enforcement through the CT fast
   path, exactly like a demotion survivor.  In process mode the
   replay source is the PARENT-RETAINED snapshot replica
   (``snapshot_now`` ships rows home) — the corpse's device memory
   died with its process, the multi-host truth thread mode could
   fake its way around (DIVERGENCES #26, retired);
4. the router re-pins the dead node's slots and migrates its queued
   chunks; rows the peer cannot absorb are counted
   ``failover_dropped``;
5. the whole episode is a named ``node-failover`` incident on the
   peer (flight recorder: sysdump bundle with ledger + membership
   state), and the blackout/detect latencies land in cluster stats
   for the bench to report.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


class FailoverOrchestrator:
    """Owns the failover sequence + the failover record history.
    Driven by membership's ``on_death`` (prober thread) or directly
    by ``ClusterServing.fail_node`` — control-plane contexts both."""

    # guarded-by: _lock: records

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self.records: List[dict] = []

    def fail_over(self, dead_name: str,
                  detail: Optional[dict] = None) -> dict:
        # thread-affinity: api
        """Run the full sequence for ``dead_name``; returns the
        failover record.  Idempotent per node: a second call for the
        same node only crash-stops it again (no-op) and re-pins
        nothing new."""
        c = self._cluster
        t0 = time.monotonic()
        dead = c.node(dead_name)
        dead.crash("declared dead by cluster membership")
        # a SIGKILLed worker's admitted-but-unresolved rows (last-ack
        # delta) close the ledger as crash_dropped; thread corpses
        # return 0 (their kill() sweeps everything counted)
        crash_lost = c.router.account_crash_loss(
            dead.take_crash_loss())
        peer = c.designated_peer(dead.idx)
        ct_entries = 0
        if peer is not None:
            rows = dead.ct_rows_for_failover()
            ct_entries = int(len(rows))
            if ct_entries:
                # merge, not replace: the peer keeps its own live
                # flows AND inherits the dead node's
                peer.merge_ct(rows)
        moved = c.router.fail_over(dead.idx,
                                   peer.idx if peer is not None
                                   else None)
        rec = {
            "dead": dead_name,
            "peer": peer.name if peer is not None else None,
            "blackout-ms": round((time.monotonic() - t0) * 1e3, 3),
            "detect-ms": (detail or {}).get("detect-ms"),
            "cause": (detail or {}).get("cause", ""),
            "ct-replayed-entries": ct_entries,
            "moved-rows": moved["moved"],
            "dropped-rows": moved["dropped"],
            "crash-dropped-rows": crash_lost,
            "at": time.time(),
        }
        with self._lock:
            self.records.append(rec)
        if peer is not None:
            from ..obs.flightrec import KIND_NODE_FAILOVER

            # the incident lands on the PEER (the dead node's flight
            # recorder died with it); capture runs on the recorder's
            # capture thread (thread mode) or inside the peer worker
            # (process mode), never this one
            peer.record_incident(KIND_NODE_FAILOVER, rec)
        return rec

    def snapshot(self) -> List[dict]:
        # thread-affinity: any
        with self._lock:
            return [dict(r) for r in self.records]
