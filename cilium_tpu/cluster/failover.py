"""Node failover: drain a dead replica's flows onto a designated
peer with CT continuity.

Reference: upstream cilium survives node loss because connection
state lives WITH the flow's owner and ECMP re-steers; a stateful
serving tier must migrate that state explicitly.  This module extends
the PR 3 demotion proof (sharded -> single CT carry via snapshot +
restore) to NODE DEATH:

1. the dead node is crash-stopped (its queued rows become counted
   recovery drops in ITS OWN ledger — a crash loses work, it never
   hides work);
2. a designated peer is chosen (next live node in ring order — the
   same deterministic choice a rendezvous hash would make for the
   freed slot);
3. the dead node's latest retained CT snapshot is REPLAYED into the
   peer, MERGED with the peer's own live CT (snapshot + concat +
   ``ct_restore``: flow-affine routing guarantees the two tables are
   disjoint, and the device re-hash resolves any residue) — so a
   reply for a connection established on the dead node passes the
   peer's egress enforcement through the CT fast path, exactly like
   a demotion survivor;
4. the router re-pins the dead node's slots and migrates its queued
   chunks; rows the peer cannot absorb are counted
   ``failover_dropped``;
5. the whole episode is a named ``node-failover`` incident on the
   peer (flight recorder: sysdump bundle with ledger + membership
   state), and the blackout/detect latencies land in cluster stats
   for the bench to report.

In-process deployment note: when the dead node never took a snapshot
(no periodic cadence configured), the orchestrator falls back to
reading the dead daemon's device CT directly — possible here because
"nodes" are threads sharing the host; a multi-host deployment gets
that only from the replicated snapshot artifact (DIVERGENCES:
threads-as-nodes).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


class FailoverOrchestrator:
    """Owns the failover sequence + the failover record history.
    Driven by membership's ``on_death`` (prober thread) or directly
    by ``ClusterServing.fail_node`` — control-plane contexts both."""

    # guarded-by: _lock: records

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self.records: List[dict] = []

    def fail_over(self, dead_name: str,
                  detail: Optional[dict] = None) -> dict:
        # thread-affinity: api
        """Run the full sequence for ``dead_name``; returns the
        failover record.  Idempotent per node: a second call for the
        same node only crash-stops it again (no-op) and re-pins
        nothing new."""
        c = self._cluster
        t0 = time.monotonic()
        dead = c.node(dead_name)
        dead.crash("declared dead by cluster membership")
        peer = c.designated_peer(dead.idx)
        ct_entries = 0
        if peer is not None:
            rows = self._dead_ct_rows(dead)
            ct_entries = int(len(rows))
            if ct_entries:
                # merge, not replace: the peer keeps its own live
                # flows AND inherits the dead node's.  ct_restore
                # re-hashes the union at the peer's capacity.
                merged = np.concatenate([
                    peer.daemon.loader.ct_snapshot(),
                    np.asarray(rows)])
                peer.daemon.loader.ct_restore(merged)
        moved = c.router.fail_over(dead.idx,
                                   peer.idx if peer is not None
                                   else None)
        rec = {
            "dead": dead_name,
            "peer": peer.name if peer is not None else None,
            "blackout-ms": round((time.monotonic() - t0) * 1e3, 3),
            "detect-ms": (detail or {}).get("detect-ms"),
            "cause": (detail or {}).get("cause", ""),
            "ct-replayed-entries": ct_entries,
            "moved-rows": moved["moved"],
            "dropped-rows": moved["dropped"],
            "at": time.time(),
        }
        with self._lock:
            self.records.append(rec)
        if peer is not None:
            from ..obs.flightrec import KIND_NODE_FAILOVER

            # the incident lands on the PEER (the dead node's flight
            # recorder died with it); capture runs on the recorder's
            # capture thread, never this one
            peer.daemon.record_incident(KIND_NODE_FAILOVER, rec)
        return rec

    @staticmethod
    def _dead_ct_rows(dead) -> np.ndarray:
        # thread-affinity: api
        """The dead node's latest retained CT snapshot; in-process
        fallback reads the corpse's device CT directly (module doc)."""
        snap = dead.daemon._ct_snap
        if snap is not None:
            return snap["rows"]
        try:
            return dead.daemon.loader.ct_snapshot()
        except Exception:  # noqa: BLE001 — an unreadable corpse CT
            # degrades to an empty replay: pre-failover connections
            # then re-establish instead of resuming (counted by the
            # policy plane, never silent)
            import numpy as _np

            from ..datapath.conntrack import ROW_WORDS

            return _np.zeros((0, ROW_WORDS), dtype=_np.uint32)

    def snapshot(self) -> List[dict]:
        # thread-affinity: any
        with self._lock:
            return [dict(r) for r in self.records]
