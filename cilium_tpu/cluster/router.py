"""The cluster front-end router: flow-affine steering across N
daemon replicas.

Reference: upstream clustermesh has no packet router — kube-proxy/XDP
ECMP spreads flows across nodes and each node's agent enforces
locally.  The serving tier needs the same property made explicit: a
front end that pins a connection (forward AND reply directions) to
ONE node, so that node's private CT owns the flow, while spreading
the aggregate across the cluster.  ``flow_shard_ids`` (the RSS
analogue the sharded single-node path already uses) supplies the
direction-invariant hash; this module adds the NODE layer on top:

- a fixed SLOT space (``slot_factor`` slots per initially-configured
  node) the hash maps into, and a mutable ``slot -> owner`` table so
  membership changes move EXACTLY the affected share
  (consistent-hashing-lite): failover re-pins only the dead node's
  slots, and live scale-out (ISSUE 13, ``cluster/scale.py``) steals
  a fair share of slots for the new node WITHOUT re-hashing anyone
  else's flows.  The slot count is a multiple of the initial node
  count, so the initial layout (slot ``s`` -> node ``s % n``) routes
  identically to the PR 8 direct ``hash % n`` scheme;
- a bounded per-node FORWARD QUEUE between the router and each
  node's admission queue — the cluster-level backpressure point.
  Overflow sheds by drop-tail, counted (``router_overflow``) and
  surfaced as ``REASON_CLUSTER_OVERFLOW`` DROP events through a live
  node's monitor plane, never silently;
- one forwarder thread per node draining its queue into
  ``node.submit`` (the "router" thread-affinity domain; in
  process-per-node mode the submit is a socket send+ack on the
  shared transport — the forwarder then also carries the
  ``transport`` domain).  Forward-path latency (enqueue ->
  delivered, queue wait + transport round trip) lands in a log2
  histogram for the bench's percentiles;
- ``fail_over``: re-pin a dead node's slots and migrate its queued
  (and requeued in-flight) chunks onto the peer; rows the peer's
  queue cannot absorb are counted ``failover_dropped``; rows a
  SIGKILLed worker process admitted but never verdicted are counted
  ``crash_dropped`` (``account_crash_loss`` — the process-mode
  ledger's honesty term, computed from the node's last data-channel
  ACK);
- ``freeze`` / ``resume`` + ``wait_quiesced``: the scale-out
  migration window — a frozen router parks submitters (bounded) while
  the forwarders drain, so a CT snapshot taken inside the window is
  complete for the slots about to move.

The cluster-wide no-silent-loss ledger this module anchors::

    submitted == sum(per-node accounted) + router_overflow
                 + failover_dropped + crash_dropped   (after stop)

where each node's own ledger (``submitted == verdicts + shed +
recovery_dropped``) accounts everything the router handed it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..serving import ServingError
from ..serving.stats import LatencyHistogram

# on_overflow(node_idx, retained rows or None, exact count): surface
# router sheds on a (live) node's monitor/metrics plane.  Called from
# forwarder threads and stop() — never from submit(), which only
# counts (the shed path must not pay event synthesis).
OverflowFn = Callable[[int, Optional[np.ndarray], int], None]

# Drop counters this module may increment.  The CTA008 checker pins
# every ``*_overflow`` / ``*_dropped`` increment in cluster/ to this
# tuple AND requires a ``cilium_cluster_<name>_total`` registry
# series per entry — a new drop site cannot ship uncounted.
DROP_COUNTERS = ("router_overflow", "failover_dropped",
                 "crash_dropped")

# bounded retention of shed rows for DROP-event surfacing (the count
# is exact either way — same discipline as admission sheds)
SHED_RETAIN = 512

# slots per initially-configured node (DaemonConfig
# cluster_slot_factor overrides): the granularity of failover re-pin
# and scale-out share stealing
SLOT_FACTOR = 16

# a frozen router (scale-out migration window) parks submitters at
# most this long before failing loudly — a stuck migration must not
# wedge every caller forever
FREEZE_DEADLINE_S = 30.0


class ClusterRouter:
    """Flow-affine steering + bounded forwarding for N node replicas.

    ``nodes`` are handles with ``.name``, ``.alive`` and
    ``.submit(rows) -> int`` (``ClusterNode`` / ``ProcessNode`` in
    production; tests pass fakes).  ``start()`` spawns one forwarder
    thread per node; ``stop(drain=True)`` forwards everything still
    queued before returning."""

    # Lock discipline: ONE lock (the condition's) guards the whole
    # routing state — the slot table flips atomically with the queue
    # migration during failover, so a torn read cannot route a chunk
    # to a node whose queue was already drained.
    # guarded-by: _lock: _slot_owner, _owner_arr, _chunks, _pending,
    # guarded-by: _lock: _oflow_rows, _oflow_n, _stopping, submitted,
    # guarded-by: _lock: router_overflow, failover_dropped, forwarded,
    # guarded-by: _lock: _suspect, crash_dropped, _frozen, _inflight,
    # guarded-by: _lock: forward_latency, _nchunks

    def __init__(self, nodes: Sequence, forward_depth: int,
                 on_overflow: Optional[OverflowFn] = None,
                 shed_retain: int = SHED_RETAIN,
                 slot_factor: int = SLOT_FACTOR,
                 trace_sample: int = 0, span_store=None):
        if not nodes:
            raise ValueError("cluster router needs at least one node")
        self.nodes = list(nodes)
        self.n_nodes = len(self.nodes)
        self.forward_depth = int(forward_depth)
        if self.forward_depth < 1:
            raise ValueError("forward_depth must be >= 1")
        slot_factor = int(slot_factor)
        if slot_factor < 1:
            raise ValueError("slot_factor must be >= 1")
        self._on_overflow = on_overflow
        self._shed_retain = int(shed_retain)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # slot s (the FIXED flow hash space) -> owning node index.
        # n_slots is a multiple of the initial node count, so the
        # initial s % n layout routes exactly like hash % n (PR 8
        # semantics); failover and scale-out mutate ownership only.
        # The numpy mirror serves the vectorized submit path; both
        # flip together under the lock.
        self.n_slots = slot_factor * self.n_nodes
        self._slot_owner: List[int] = [s % self.n_nodes
                                       for s in range(self.n_slots)]
        self._owner_arr = np.asarray(self._slot_owner, dtype=np.int64)
        self._chunks: List[list] = [[] for _ in self.nodes]
        self._pending = [0] * self.n_nodes
        # rows a forwarder popped and is delivering right now (the
        # quiesce condition: pending AND inflight both zero)
        self._inflight = [0] * self.n_nodes
        # per-node shed surfacing backlog (bounded rows, exact count)
        self._oflow_rows: List[list] = [[] for _ in self.nodes]
        self._oflow_n = [0] * self.n_nodes
        # a forwarder whose submit raised parks its node as suspect
        # until failover re-pins or stop() sweeps
        self._suspect = [False] * self.n_nodes
        self._frozen = False
        self._stopping = False
        self._threads: List[threading.Thread] = []
        self.submitted = 0
        self.router_overflow = 0
        self.failover_dropped = 0
        # rows a crashed (SIGKILLed) worker admitted but never
        # verdicted — see account_crash_loss
        self.crash_dropped = 0
        self.forwarded = [0] * self.n_nodes
        # enqueue -> delivered µs (queue wait + node submit / socket
        # round trip): the bench's forward-path percentiles
        self.forward_latency = LatencyHistogram()
        # ISSUE 14 cross-process span stitching: every trace_sample'th
        # APPENDED chunk carries a TraceCtx through the forward path
        # (frame + ack echo in process mode); completed spans land in
        # span_store (obs/relay.ClusterSpanStore).  0 = off — the
        # hot-path cost is one int compare per appended chunk.
        self._trace_sample = int(trace_sample)
        self.span_store = span_store
        self._nchunks = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # thread-affinity: api
        if self._threads:
            raise ServingError("cluster router already started")
        for i in range(self.n_nodes):
            self._spawn_forwarder(i)

    def _spawn_forwarder(self, idx: int) -> None:
        # thread-affinity: api
        # holds: nothing — callers serialize (start / add_node)
        t = threading.Thread(target=self._forward_loop, args=(idx,),
                             daemon=True,
                             name=f"cluster-fwd-{self.nodes[idx].name}")
        self._threads.append(t)
        t.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> dict:
        # thread-affinity: api
        """Stop the forwarders; with ``drain`` every queued chunk is
        offered to its (current) owner synchronously first — rows a
        dead owner can no longer take are counted
        ``failover_dropped``, so the ledger closes exactly."""
        with self._cv:
            self._stopping = True
            self._frozen = False
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads = []
        if drain:
            for idx in range(self.n_nodes):
                while True:
                    with self._cv:
                        if not self._chunks[idx]:
                            break
                        chunk, _t_enq, ctx = self._chunks[idx].pop(0)
                        self._pending[idx] -= len(chunk)
                    if ctx is not None and self.span_store is not None:
                        self.span_store.drop_span(ctx)  # span lost at stop
                    node = self.nodes[idx]
                    try:
                        node.submit(chunk)
                        with self._cv:
                            self.forwarded[idx] += len(chunk)
                    except Exception:  # noqa: BLE001 — a dead/terminal
                        # node at stop: its loss is counted, not raised
                        with self._cv:
                            self.failover_dropped += len(chunk)
        self._flush_overflow_all()
        return self.snapshot()

    # -- the enqueue path (the cluster tier's hot path) ----------------
    def submit(self, rows: np.ndarray) -> int:
        """Offer header rows; returns how many entered a forward
        queue.  Never blocks in steady state: per-node overflow sheds
        drop-tail, counted exactly (rows retained for DROP surfacing
        up to the retention bound); the one exception is a FROZEN
        router (a live scale-out migration window, bounded by
        ``FREEZE_DEADLINE_S``), which parks the caller until the slot
        table settles — blocking beats misrouting a flow whose CT is
        mid-migration.  Chunks are COPIED in — callers may reuse
        their buffers immediately.  (Thin unannotated wrapper: the
        annotated hot path is :meth:`_route` — a generic name like
        ``submit`` must not carry the ``router`` affinity or the
        call graph's name-match fallback would taint every other
        ``.submit`` call in the repo.)"""
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError(
                f"cluster submit wants [n, N_COLS] rows, got shape "
                f"{rows.shape}")
        return self._route(rows)

    def _route(self, rows: np.ndarray) -> int:
        # thread-affinity: router
        """The enqueue hot path: flow-hash + per-node bounded queue
        append, one lock window, no allocation beyond the admitted
        copies (CTA003 purity-scanned from here)."""
        from ..parallel.mesh import flow_shard_ids

        ids = flow_shard_ids(rows, self.n_slots)
        admitted = 0
        t_enq = time.monotonic()
        with self._cv:
            deadline = None
            while self._frozen and not self._stopping:
                if deadline is None:
                    deadline = time.monotonic() + FREEZE_DEADLINE_S
                self._cv.wait(0.05)
                # checked every lap, NOT only on wait timeout: a
                # suspect node's requeue path notify_all()s each
                # retry, and a notified wait would otherwise starve
                # the deadline forever
                if (self._frozen and not self._stopping
                        and time.monotonic() > deadline):
                    raise ServingError(
                        "cluster router frozen past the migration "
                        "deadline — scale-out wedged")
            if self._stopping:
                raise ServingError("cluster router is stopped")
            self.submitted += len(rows)
            owners = self._owner_arr[ids]
            for o in np.unique(owners):
                o = int(o)
                sub = rows[owners == o]
                space = self.forward_depth - self._pending[o]
                take = min(max(space, 0), len(sub))
                if take:
                    ctx = None
                    if self._trace_sample > 0 \
                            and self.span_store is not None:
                        if self._nchunks % self._trace_sample == 0:
                            ctx = self.span_store.allocate_span(
                                take, t_enq)
                        self._nchunks += 1
                    self._chunks[o].append(
                        (np.array(sub[:take], copy=True), t_enq,
                         ctx))
                    self._pending[o] += take
                    admitted += take
                lost = len(sub) - take
                if lost:
                    self.router_overflow += lost
                    self._oflow_n[o] += lost
                    room = self._shed_retain - sum(
                        len(r) for r in self._oflow_rows[o])
                    if room > 0:
                        self._oflow_rows[o].append(
                            np.array(sub[take:take + room], copy=True))
            self._cv.notify_all()
        return admitted

    # -- forwarders ----------------------------------------------------
    def _forward_loop(self, idx: int) -> None:
        # thread-affinity: router
        node = self.nodes[idx]
        while True:
            with self._cv:
                while (not self._stopping
                       and (not node.alive or self._suspect[idx]
                            or (not self._chunks[idx]
                                and not self._oflow_n[idx]))):
                    # parked: dead/suspect node (failover will steal
                    # the queue) or simply nothing to do
                    self._cv.wait(0.05)
                    if node.alive and self._suspect[idx]:
                        self._suspect[idx] = False  # healed
                if self._stopping:
                    return
                chunk = t_enq = ctx = None
                if self._chunks[idx]:
                    chunk, t_enq, ctx = self._chunks[idx].pop(0)
                    self._pending[idx] -= len(chunk)
                    self._inflight[idx] = len(chunk)
                oflow_rows, oflow_n = self._take_oflow_locked(idx)
            if chunk is not None:
                try:
                    if ctx is not None:
                        # span stitching: stamp the forward stage and
                        # ride the chunk; the node fills recv/admit
                        # (ack echo in process mode, direct stamps
                        # in thread mode)
                        ctx.node = node.name
                        ctx.t_fwd = time.monotonic()
                        node.submit(chunk, trace=ctx)
                    else:
                        node.submit(chunk)
                    with self._cv:
                        self.forwarded[idx] += len(chunk)
                        self._inflight[idx] = 0
                        self.forward_latency.record(
                            (time.monotonic() - t_enq) * 1e6)
                        self._cv.notify_all()
                    if ctx is not None:
                        ctx.t_ack = time.monotonic()
                        # commit counts an echo-less span as dropped
                        self.span_store.commit_span(ctx)
                except Exception:  # noqa: BLE001 — crashed/terminal
                    # node: requeue AT THE FRONT and park as suspect;
                    # failover's queue migration (or stop's drain)
                    # claims the chunk with its loss accounted
                    with self._cv:
                        self._chunks[idx].insert(0, (chunk, t_enq,
                                                     ctx))
                        self._pending[idx] += len(chunk)
                        self._inflight[idx] = 0
                        self._suspect[idx] = True
                        self._cv.notify_all()
            if oflow_n and self._on_overflow is not None:
                self._surface(idx, oflow_rows, oflow_n)

    def _take_oflow_locked(self, idx: int):
        # thread-affinity: router, api -- forwarder flush + the stop
        # path's final sweep; callers hold _lock
        # holds: _lock
        rows, self._oflow_rows[idx] = self._oflow_rows[idx], []
        n, self._oflow_n[idx] = self._oflow_n[idx], 0
        return rows, n

    def _surface(self, idx: int, rows_list: list, count: int) -> None:
        # thread-affinity: router, api
        rows = (np.concatenate(rows_list) if rows_list else None)
        try:
            self._on_overflow(idx, rows, count)
        except Exception:  # noqa: BLE001 — surfacing is best-effort;
            pass  # the exact count already lives in router_overflow

    def _flush_overflow_all(self) -> None:
        # thread-affinity: api
        for idx in range(self.n_nodes):
            with self._cv:
                rows_list, n = self._take_oflow_locked(idx)
            if n and self._on_overflow is not None:
                self._surface(idx, rows_list, n)

    # -- failover ------------------------------------------------------
    def fail_over(self, dead_idx: int,
                  peer_idx: Optional[int]) -> dict:
        # thread-affinity: api
        """Re-pin every slot the dead node owns onto ``peer_idx`` and
        migrate its queued chunks (including any chunk a forwarder
        requeued mid-crash).  Rows the peer's queue cannot absorb —
        or all of them when no peer is left — are counted
        ``failover_dropped``.  Atomic under the router lock: no
        submit can route into the dead queue mid-migration."""
        moved = dropped = 0
        with self._cv:
            for s in range(len(self._slot_owner)):
                if self._slot_owner[s] == dead_idx:
                    self._slot_owner[s] = (peer_idx if peer_idx
                                           is not None else dead_idx)
            self._owner_arr = np.asarray(self._slot_owner,
                                         dtype=np.int64)
            while self._chunks[dead_idx]:
                chunk, t_enq, ctx = self._chunks[dead_idx].pop(0)
                self._pending[dead_idx] -= len(chunk)
                take = 0
                if peer_idx is not None:
                    space = (self.forward_depth
                             - self._pending[peer_idx])
                    take = min(max(space, 0), len(chunk))
                if take:
                    # a WHOLLY-moved chunk keeps its trace ctx (the
                    # span completes on the peer); a split one drops
                    # it — half a chunk's hop timings would lie
                    self._chunks[peer_idx].append(
                        (chunk[:take], t_enq,
                         ctx if take == len(chunk) else None))
                    self._pending[peer_idx] += take
                    moved += take
                    if ctx is not None and take != len(chunk) \
                            and self.span_store is not None:
                        self.span_store.drop_span(ctx)
                elif ctx is not None and self.span_store is not None:
                    self.span_store.drop_span(ctx)
                lost = len(chunk) - take
                if lost:
                    self.failover_dropped += lost
                    dropped += lost
            # shed-surfacing backlog follows the flows to the peer
            # (the dead node's monitor plane is gone)
            if peer_idx is not None and self._oflow_n[dead_idx]:
                self._oflow_rows[peer_idx].extend(
                    self._oflow_rows[dead_idx])
                self._oflow_n[peer_idx] += self._oflow_n[dead_idx]
                self._oflow_rows[dead_idx] = []
                self._oflow_n[dead_idx] = 0
            self._suspect[dead_idx] = False
            self._cv.notify_all()
        return {"moved": moved, "dropped": dropped}

    def account_crash_loss(self, count: int) -> int:
        # thread-affinity: api
        """Count rows a crashed worker process ADMITTED (acked over
        the data channel) but never turned into verdicts — the delta
        between the last ack's ``submitted`` and its accounted
        counters (``cluster/process.py`` computes it; a SIGKILL
        leaves no other witness).  Returns the count, clamped at
        zero, so the cluster ledger closes exactly over the
        corpse."""
        count = max(int(count), 0)
        if count:
            with self._cv:
                self.crash_dropped += count
        return count

    # -- live scale-out (cluster/scale.py drives this) -----------------
    def freeze(self) -> None:
        # thread-affinity: api
        """Park new submits (bounded — see :meth:`submit`) while a
        migration recomputes slot ownership.  Forwarders keep
        draining, so :meth:`wait_quiesced` converges."""
        with self._cv:
            self._frozen = True

    def resume(self) -> None:
        # thread-affinity: api
        with self._cv:
            self._frozen = False
            self._cv.notify_all()

    def wait_quiesced(self, timeout: float = 30.0,
                      nodes: Optional[Sequence[int]] = None) -> bool:
        # thread-affinity: api
        """Block until the given nodes' forward queues are empty AND
        no chunk is mid-delivery — every row the router admitted has
        been DELIVERED to its node.  Delivered is not verdicted: rows
        may still sit in the node's own admission ring, so a caller
        that needs CT completeness (``cluster/scale.py``) must also
        wait for the node ledgers to catch up."""
        idxs = (list(nodes) if nodes is not None
                else list(range(self.n_nodes)))
        deadline = time.monotonic() + timeout
        with self._cv:
            while any(self._pending[i] or self._inflight[i]
                      for i in idxs):
                # deadline checked every lap (a notified wait must
                # not starve it — see _route's freeze park)
                if time.monotonic() > deadline:
                    return False
                self._cv.wait(0.05)
            return True

    def add_node(self, node) -> List[int]:
        # thread-affinity: api
        """Grow the router by one node: extend the per-node state,
        steal a fair share of slots (⌊n_slots / new_n⌋, taken
        round-robin from the current owners with the most slots so
        the layout stays balanced), flip the table atomically, and
        spawn the new forwarder.  Returns the moved slot ids — the
        caller (``cluster/scale.py``) migrates exactly those slots'
        CT.  Call FROZEN + quiesced: the atomic flip keeps routing
        correct either way, but CT continuity for moved flows needs
        the donors drained first."""
        with self._cv:
            new_idx = self.n_nodes
            self.nodes.append(node)
            self.n_nodes += 1
            self._chunks.append([])
            self._pending.append(0)
            self._inflight.append(0)
            self._oflow_rows.append([])
            self._oflow_n.append(0)
            self._suspect.append(False)
            self.forwarded.append(0)
            share = self.n_slots // self.n_nodes
            counts = {}
            for owner in self._slot_owner:
                counts[owner] = counts.get(owner, 0) + 1
            moved: List[int] = []
            while len(moved) < share:
                donor = max(counts, key=lambda o: (counts[o], -o))
                if counts[donor] <= 1:
                    break  # never strip a node's last slot
                for s in range(self.n_slots):
                    if self._slot_owner[s] == donor:
                        self._slot_owner[s] = new_idx
                        counts[donor] -= 1
                        moved.append(s)
                        break
            self._owner_arr = np.asarray(self._slot_owner,
                                         dtype=np.int64)
            self._cv.notify_all()
        if self._threads:  # started router: the new node forwards too
            self._spawn_forwarder(new_idx)
        return moved

    def slots_of(self, idx: int) -> List[int]:
        # thread-affinity: any
        with self._cv:
            return [s for s, o in enumerate(self._slot_owner)
                    if o == idx]

    # -- reading -------------------------------------------------------
    def pending_total(self) -> int:
        # thread-affinity: any
        with self._cv:
            return sum(self._pending) + sum(self._inflight)

    def snapshot(self) -> dict:
        # thread-affinity: any
        with self._cv:
            lat = self.forward_latency
            return {
                "submitted": self.submitted,
                "forwarded": list(self.forwarded),
                "pending": list(self._pending),
                "router-overflow": self.router_overflow,
                "failover-dropped": self.failover_dropped,
                "crash-dropped": self.crash_dropped,
                "n-slots": self.n_slots,
                "slot-owner": list(self._slot_owner),
                "forward-latency-us": {
                    "p50": lat.percentile(0.50),
                    "p95": lat.percentile(0.95),
                    "p99": lat.percentile(0.99),
                    "max": round(lat.max_us, 1),
                    "count": lat.count,
                },
                "trace": (self.span_store.span_stats()
                          if self.span_store is not None else None),
            }
